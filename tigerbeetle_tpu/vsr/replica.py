"""The VSR replica: consensus-driven replication of the device ledger.

Viewstamped Replication (Revisited) over the Storage/Network/Time seams
(reference: src/vsr/replica.zig — normal path handlers :1208-1538, view
change :1595-1924, repair :5248+, commit dispatch :3045-3103):

NORMAL PATH — the PRIMARY (view % replica_count) sequences client requests
into prepares: assigns op + batch-final timestamp (cluster clock, monotonic
clamped), hash-chains the header, journals it (WAL-before-ack), broadcasts;
BACKUPS verify the chain, journal, ack prepare_ok; at a majority quorum the
primary commits in op order through the StateMachine (the TPU device
ledger) and replies; backups commit when the commit number reaches them
(piggybacked + heartbeats). Client sessions are replicated state: register
ops flow through the log, duplicates are answered from the table.

VIEW CHANGE — backups that lose contact with the primary send
start_view_change for view+1; at a quorum of SVCs each sends do_view_change
(carrying its log suffix headers) to the new primary; the new primary picks
the best log (max log_view, then op), repairs missing prepares via
request_prepare, truncates its tail, then broadcasts start_view; backups
adopt the suffix, repairing the same way. Uncommitted ops that survive in
the chosen log commit in the new view (VSR's no-lost-commits invariant:
any op that reached a commit quorum is in a majority of logs, so the best
log contains it).

CLOCK — replicas ping each other; pongs return the peer's wall clock, and
Marzullo's algorithm over the offset intervals (vsr/clock.py) yields a
cluster-synchronized timestamp base (reference: src/vsr/clock.zig).

All transport is real wire bytes; all persistence goes through the Storage
seam; ticks through the Time seam — the deterministic cluster and the
simulator run this exact code.
"""

from __future__ import annotations

import dataclasses
import json as _json
from collections import deque
from time import perf_counter_ns

import numpy as np

from tigerbeetle_tpu.constants import ConfigCluster, ConfigProcess
from tigerbeetle_tpu.io.network import Network
from tigerbeetle_tpu.io.storage import Storage
from tigerbeetle_tpu.io.time import Time
from tigerbeetle_tpu.latency import (
    LEG_DISPATCH,
    LEG_FINALIZE,
    LEG_FUSE,
    LEG_QUORUM,
    LEG_WAIT,
    LEG_WAL,
    LatencyAnatomy,
)
from tigerbeetle_tpu.lsm.grid import GridBlockCorrupt
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.models.ledger import DeviceLedger
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE, Operation
from tigerbeetle_tpu.vsr.client_replies import ClientReplies
from tigerbeetle_tpu.vsr.clock import Clock
from tigerbeetle_tpu.vsr.durable import (
    check_config_fingerprint,
    persist_view,
    restore_from_snapshot,
    snapshot_to_superblock,
)
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header
from tigerbeetle_tpu.vsr.journal import Journal
from tigerbeetle_tpu.vsr.superblock import SuperBlock

# Tick-based timeout constants (reference: src/vsr/replica.zig:2479-2843
# timeout table; values here are in ticks of the Time seam).
HEARTBEAT_TICKS = 4  # primary: commit heartbeat cadence
PING_TICKS = 8  # clock sync cadence
VIEW_CHANGE_TICKS = 40  # backup: silence before starting a view change
RETRY_TICKS = 16  # view-change message retry cadence
GRID_SCRUB_TICKS = 8  # forest-block scrub cadence (reference: grid scrubber)
GRID_SCRUB_BLOCKS = 8  # acquired blocks verified per scrub pass
WAL_SWEEP_TICKS = 64  # in-place-fault WAL re-verify cadence (1 MiB/pass)
# Client tables whose JSON exceeds this inline into the superblock meta;
# larger ones (many-session ingress mode) spill to a checkpoint blob —
# the 64 KiB superblock copy must also hold the rest of the meta.
CLIENT_TABLE_INLINE_MAX = 24 * 1024

# CDC reply-ring retention: only create-op replies (sparse failure
# structs) are kept for resume-from-WAL; read replies are large and the
# change stream encodes no records for reads.
_CDC_RETAIN_OPS = (
    int(Operation.create_accounts), int(Operation.create_transfers)
)

# DVC suffix NACK marker: a synthetic header whose `operation` proves the
# sender's slot for that op is BLANK — it never prepared the op (the
# reference's blank header in protocol-aware recovery, src/vsr.zig:302-304).
# Valid state-machine operations are 128-131; VSR ops are < 128.
OP_NACK = 255


class Replica:
    def __init__(
        self,
        replica_index: int,
        replica_count: int,
        storage: Storage,
        network: Network,
        time: Time,
        cluster: ConfigCluster,
        process: ConfigProcess,
        mode: str = "auto",
        backend_factory=None,
        standby_count: int = 0,
        spill_io: str = "deferred",
        metrics=None,
        tracer=None,
    ):
        # Observability seams (tigerbeetle_tpu/metrics.py, tracer.py): one
        # registry and one tracer per replica, threaded into the journal,
        # the ledger backend and the spill pipeline below, so every stage
        # of the commit path reports into the SAME store. The default
        # registry is always live (counters are cheap ints); the default
        # tracer is the no-op `none` backend.
        self.metrics = metrics if metrics is not None else Metrics()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # Per-request critical-path attribution (tigerbeetle_tpu/
        # latency.py): sampled requests are stamped at every pipeline
        # leg and fold into the latency.* histograms at reply egress.
        # The clock is the TIME SEAM's monotonic — simulator replicas
        # stamp with virtual ticks, so seeded runs stay byte-identical
        # with stamping on (tests/test_latency.py pins it).
        self.latency = LatencyAnatomy(
            metrics=self.metrics, clock=time.monotonic
        )
        # optional metrics.FlightRecorder (the server loop installs and
        # drives it ~1/s); _on_request_stats ships its history when set
        self.flight_recorder = None
        self.replica = replica_index
        self.replica_count = replica_count
        # Standbys (reference: src/vsr/replica.zig:163-175): replicas with
        # index >= replica_count follow the log — they journal prepares
        # and commit — but never ack, never vote, and never count toward
        # any quorum; a warm spare for operator-driven replacement.
        self.standby_count = standby_count
        self.standby = replica_index >= replica_count
        self.network = network
        self.time = time
        self.cluster = cluster
        # With a forest block area in the layout, the device ledger spills
        # its cold transfer tail to an LSM forest in the grid zone's tail
        # (models/spill.py) — same wiring as the single-replica
        # DurableLedger; checkpoints carry the spill meta and state sync
        # ships the forest blocks (see _on_request_sync_checkpoint).
        self.forest = None
        if backend_factory is not None:
            backend = backend_factory()
        else:
            if storage.layout.forest_blocks:
                from tigerbeetle_tpu.lsm.grid import Grid
                from tigerbeetle_tpu.lsm.groove import Forest

                self.forest = Forest(Grid(
                    storage,
                    offset=storage.layout.forest_offset,
                    block_count=storage.layout.forest_blocks,
                ), memtable_max=getattr(process, "lsm_memtable_max", 2048))
            # The replica's spill/grid IO rides the SpillManager executor
            # seam instead of running inline in the commit path:
            # "deferred" (default) queues LSM insertion and runs it at the
            # tick boundary (models/spill.py DeferredSpillIO) — the commit
            # dispatch never executes LSM work, grid allocation order stays
            # the FIFO job order (deterministic across replicas, which
            # repair-by-address depends on), and seeded simulator runs
            # never depend on thread timing. "threaded" (production
            # servers, real time) moves the same jobs to a worker thread
            # for wall-clock overlap; the scrub pass skips a turn while
            # worker inserts are in flight (_scrub_grid).
            backend = DeviceLedger(cluster, process, mode=mode,
                                   forest=self.forest,
                                   spill_io=spill_io)
        if hasattr(backend, "prefetch_results"):
            # the replica drains results to serve replies: start copies at
            # dispatch (a fetch-free driver like the flagship bench must
            # NOT — see DeviceLedger.prefetch_results)
            backend.prefetch_results = True
        # Dual-commit follower plan (`--backend dual`, models/dual_ledger):
        # the native engine serves replies while the device applier follows
        # the committed op stream — this replica enqueues each create op at
        # commit FINALIZE (apply_commit), drains the applier before any
        # state-replacing transition, and feeds the applier's bounded-lag
        # excess into admission (ingress_occupancy / the _on_request cap).
        self._dual_apply = bool(getattr(backend, "dual_follower", False))
        self.ledger = backend
        # thread the observability seams through the stack: the backend's
        # staging fences, the spill pipeline (prefetch/admit/cycle spans)
        # and the WAL writes all report into this replica's registry
        if hasattr(backend, "instrument"):
            backend.instrument(self.metrics, self.tracer)
        else:
            spill = getattr(backend, "spill", None)
            if spill is not None and hasattr(spill, "instrument"):
                spill.instrument(self.metrics, self.tracer)
        self.sm = StateMachine(backend, cluster)
        self.journal = Journal(storage, cluster)
        self.journal.metrics = self.metrics
        self.journal.tracer = self.tracer
        self.superblock = SuperBlock(storage)
        self.client_replies = ClientReplies(storage, cluster)
        self.storage = storage
        self.clock = Clock(replica_index, replica_count, time)

        self.status = "recovering"
        self.view = 0
        self.log_view = 0  # latest view in which status was normal
        self.op = 0  # highest prepared op
        self.commit_min = 0  # highest committed op
        self.commit_max = 0  # highest known-committed op cluster-wide
        self.parent_checksum = 0  # checksum of prepare `self.op`
        self.commit_checksum = 0  # checksum of prepare `self.commit_min`
        self.checkpoint_op = 0

        # primary state
        self.pipeline: dict[int, dict] = {}  # op -> {header, body, oks}
        # replicated session state: client_id -> {session, request, reply}
        self.client_table: dict[int, dict] = {}
        # backup reorder buffer for out-of-order prepares
        self._pending_prepares: dict[int, tuple[Header, bytes]] = {}

        # repair state: ops whose prepares we asked peers for
        self._repair_wanted: set[int] = set()
        # last tick we asked a peer for a full checkpoint (rate limit)
        self._sync_request_tick = -RETRY_TICKS
        # Commit-stage overlap (reference: src/vsr/replica.zig:52-70
        # CommitStage; :3045-3103 commit_dispatch): with commit_window > 0,
        # device commits are DISPATCHED asynchronously (JAX async dispatch
        # — the launch is queued, the host returns immediately) and their
        # results drained later, so the journal write + broadcast of op N+1
        # overlap the device execution of op N. 0 = fully synchronous
        # (deterministic tests). The event loop calls flush_commits() when
        # idle; state-changing transitions (checkpoint, view change, state
        # sync) flush first.
        self.commit_window = 0
        # Group-commit fuse window (ns): with commit_window > 0, a
        # quorum-ready run of fewer than GROUP_MAX create_transfers
        # prepares may be HELD for up to this long — but only while
        # earlier commits are still in flight, so the engine never idles —
        # letting requests that arrive within the window coalesce into ONE
        # fused device dispatch per quorum run instead of a solo dispatch
        # per pump turn (reference: the commit pipeline overlaps stages
        # the same way, src/vsr/replica.zig:5102-5186). 0 disables the
        # hold; commit_window == 0 (deterministic tests) never defers.
        self.fuse_window_ns = 2_000_000
        self._fuse_started: int | None = None
        # Fuse-window AUTOTUNE (opt-in; the server CLI turns it on by
        # default): AIMD on hold outcomes — a hold that EXPIRES with its
        # run still short means arrivals are spaced wider than the window
        # (widen ×1.25); a run that fills to GROUP_MAX while a hold is
        # open means the window over-covers the arrival spacing (shrink
        # ×0.95 to shed hold latency). Bounded so a quiet wire cannot
        # climb the window into client-visible latency. Only active with
        # commit_window > 0 (deterministic harnesses never hold).
        self.fuse_autotune = False
        self.fuse_window_min_ns = 500_000
        self.fuse_window_max_ns = 8_000_000
        self._inflight: deque[dict] = deque()
        # grid repair state: forest-block addresses awaiting peer repair
        # (reference: src/vsr/grid_blocks_missing.zig)
        self._grid_missing: set[int] = set()
        self._scrub_cursor = 0
        self._wal_scrub_cursor = 1  # continuous WAL repair sweep position
        # group-commit observability (BENCH reports the hit rate): ops
        # committed via a fused device dispatch vs per-op fallback, plus
        # the group count (fused_ops / fused_groups = mean fusion width).
        # A registry-backed Mapping: readers keep dict access, the storage
        # lives in self.metrics (the shared pipeline registry).
        self.group_stats = self.metrics.group(
            "commit.group",
            # fuse_holds/fuse_expired instrument WHY a hit rate is what it
            # is: holds that expired short mean the window lost the race
            # against arrival spacing (the autotune's widen signal), while
            # a high hit rate with zero holds means runs formed without
            # deferral (the window is irrelevant, not well-tuned)
            # wave_ops/wave_dispatches: ops whose batch ran the
            # conflict-wave scheduler (dependent transfers executed as
            # dependency-ordered waves instead of a whole-batch serial
            # scan), and the total waves those ops dispatched
            ("fused_ops", "solo_ops", "fused_groups", "fuse_holds",
             "fuse_expired", "wave_ops", "wave_dispatches"),
        )
        # commit-pipeline timing histograms (metrics.py CATALOG for units)
        self._h_quorum = self.metrics.histogram("replica.quorum_wait_us")
        self._h_dispatch = self.metrics.histogram("replica.commit_dispatch_us")
        self._h_finalize = self.metrics.histogram("replica.commit_finalize_us")
        self._h_fuse = self.metrics.histogram("replica.fuse_hold_us")
        self._fuse_token = 0  # open fuse_hold trace span, if any
        # test/simulator observation hook: called on every committed prepare
        self.commit_hook = None
        # observation hook on every reply built at finalize (hash_log:
        # reply checksums capture result codes, so kernel nondeterminism
        # across runs surfaces even when the logs match)
        self.reply_hook = None
        # optional append-only disaster-recovery log (reference: src/aof.zig,
        # hooked before the reply at src/vsr/replica.zig:3643-3648)
        self.aof = None
        # CDC seam (tigerbeetle_tpu/cdc): cdc_hook(header, body, reply_body)
        # fires once per op at commit FINALIZE, in op order, with the reply
        # buffer the replica materialized for the client anyway — the
        # change-stream pump's live tail (no new d2h, no copies). With
        # cdc_retain on, the replies of the last journal_slot_count ops are
        # kept in cdc_replies (tiny: sparse failure structs, usually empty)
        # so a pump resuming from the WAL ring can rebuild exact records
        # for ops it missed while down.
        self.cdc_hook = None
        # Ingress gateway seam: called with the victim client id when a
        # register at clients_max evicts the oldest session, so the
        # gateway's session table tracks the replica's — without it,
        # evicted sessions on a still-open multiplexed connection would
        # pin the gateway's sessions_max cap forever (conn close never
        # fires while other sessions keep the connection alive).
        self.ingress_evict_hook = None
        # checkpoint state commitments (federation/commitment.py): when a
        # CommitmentLog is installed (cli --commitment-interval, the
        # federation harness, SimFederation), every boundary op's commit
        # dispatch folds the backend's state fingerprint into the chain;
        # the ring persists in checkpoint meta and ships via state sync.
        self.commitment_log = None
        self.cdc_retain = False
        self.cdc_replies: dict[int, bytes] = {}
        # Finalized-op watermark: with an async commit window, commit_min
        # advances at DISPATCH while replies materialize at finalize — a
        # pump bounded by commit_min would race ahead of the hook and
        # stream ops whose reply buffers don't exist yet. This is the
        # stream-safe bound: the highest op whose finalize has run (or
        # that a restore/state-sync declared executed elsewhere).
        self.cdc_commit_min = 0

        # Durable reply-slot free list (client_replies zone): maintained
        # incrementally so a register is O(1) — with the ingress gateway
        # multiplexing tens of thousands of sessions, the old per-register
        # scan over the whole client table was O(sessions^2) across a
        # connect storm. None = rebuild lazily from the table (set at
        # every point the table is wholesale replaced).
        self._reply_slots_free: list[int] | None = None

        # tick + view-change state
        self.ticks = 0
        self._primary_contact_tick = 0
        self._recover_tick = 0
        self._vc_tick = 0
        self._vc_retries = 0
        self.view_candidate = 0
        self._svc_votes: set[int] = set()
        self._dvc: dict[int, tuple[Header, list[Header]]] = {}
        self._adopt: dict[int, Header] | None = None  # op -> wanted header
        self._adopt_commit_max = 0

        network.attach(replica_index, self._on_message)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @property
    def primary_index(self) -> int:
        return self.view % self.replica_count

    @property
    def is_primary(self) -> bool:
        return self.replica == self.primary_index and self.status == "normal"

    @property
    def quorum_replication(self) -> int:
        return self.replica_count // 2 + 1

    @property
    def quorum_view_change(self) -> int:
        return self.replica_count // 2 + 1

    # -- ingress saturation signal + reply-slot allocator --------------

    def ingress_occupancy(self) -> tuple[int, int]:
        """(used, capacity) of the commit pipeline — the admission signal
        the ingress gateway's credit regulator reads every request (so it
        must stay O(1)). `used` counts quorum-pending pipeline entries
        plus dispatched-but-unfinalized commits beyond the steady async
        window; `capacity` is the same cap _on_request backpressures at,
        so the gateway sheds with a typed busy reply just before the
        replica would start dropping silently."""
        cap = max(
            self.cluster.pipeline_prepare_queue_max, 2 * self.commit_window
        )
        backlog = max(0, len(self._inflight) - max(1, self.commit_window))
        used = len(self.pipeline) + backlog
        if self._dual_apply:
            # dual-commit bounded-lag backpressure: device-applier lag
            # beyond its window counts as occupancy, so the credit
            # regulator sheds (typed busy replies) BEFORE the bounded
            # apply queue's put() would stall the event loop
            used += self.ledger.apply_lag_excess()
        return used, cap

    def _reply_slot_alloc(self) -> int | None:
        """Pop a free client_replies slot (None when every slot is owned
        — the session registers without durable reply persistence)."""
        if self._reply_slots_free is None:
            used = {
                e.get("slot") for e in self.client_table.values()
            } - {None}
            self._reply_slots_free = [
                i for i in range(self.client_replies.slot_count - 1, -1, -1)
                if i not in used
            ]
        return self._reply_slots_free.pop() if self._reply_slots_free else None

    def _reply_slot_release(self, slot: int | None) -> None:
        if slot is not None and self._reply_slots_free is not None:
            self._reply_slots_free.append(slot)

    def open(self) -> None:
        """Superblock -> snapshot -> WAL replay (same recovery as the
        single-replica DurableLedger, then join the cluster)."""
        state = self.superblock.open()
        check_config_fingerprint(state, self.cluster)
        restore_from_snapshot(
            self.storage, self.ledger, self.sm, self.ledger.process, state
        )
        self.client_table = {
            int(c): dict(e, reply=None)
            for c, e in self._load_client_table(state).items()
        }
        self._reply_slots_free = None  # rebuilt from the restored table
        self._restore_client_replies()
        persisted_view = int(state.meta.get("view", 0))
        persisted_log_view = int(state.meta.get("log_view", persisted_view))
        self.view = self.log_view = persisted_log_view
        self.checkpoint_op = state.commit_min
        self.commit_min = self.commit_max = self.op = state.commit_min
        self.cdc_commit_min = state.commit_min  # executed pre-restart
        if self.commitment_log is not None:
            # restore BEFORE the WAL-tail replay below: replayed boundary
            # ops re-record against the restored head (the persisted head
            # is the last boundary <= the checkpoint's commit_min, so the
            # replay's boundaries extend the chain contiguously)
            self.commitment_log.restore(state.meta.get("commitments"))
        self.parent_checksum = self.commit_checksum = state.commit_min_checksum
        recovered = self.journal.recover()
        op = state.commit_min + 1
        while op in recovered:
            header, body = self.journal.read_prepare(op)  # type: ignore
            if header.parent != self.parent_checksum:
                # Stale-timeline slot: a crash between OUT-OF-ORDER async
                # WAL writes (write N lost, write N+1 landed) leaves a gap;
                # after restart re-fills the gap on a new timeline, the
                # surviving higher slot no longer chains. No reply can have
                # left for it (replies finalize in op order, each waiting
                # its own WAL future), so the chain — and durability —
                # ends at the last op that chains.
                break
            if self.replica_count == 1 and not self.standby:
                # Single replica: every journaled op was committed (WAL is
                # written before execution, and there is no one else).
                self._commit_prepare(header, body)
                self.commit_min = self.commit_max = op
                self.commit_checksum = header.checksum
            # Multi-replica: the WAL tail is PREPARED, not necessarily
            # committed — rebuild the log head only; the cluster's commit
            # numbers (SV / heartbeats) drive execution through
            # _commit_up_to, and divergent tails get truncated by adoption.
            self.op = op
            self.parent_checksum = header.checksum
            op += 1
        if self.replica_count == 1 and not self.standby:
            # Destroy journal evidence above the replay head: slots beyond a
            # gap or chain break are unreachable stale timelines (never
            # acked — see the ordering argument above), and left in place
            # they would be re-filled piecemeal and crash-loop a SECOND
            # restart on the broken chain. Multi-replica keeps its tail:
            # acked prepares above a torn slot are DVC evidence that
            # protocol-aware recovery needs (adoption truncates instead).
            self.journal.invalidate_above(self.op)
        genesis = state.sequence == 1 and self.op == 0
        if self.replica_count == 1 or genesis:
            # Cold boot of a fresh cluster (or single replica): view 0 with
            # replica 0 as primary is the trusted starting point.
            self.status = "normal"
        else:
            # RESTART: our replayed log is only a candidate — we may have
            # missed commits (torn WAL tail) or whole views. Never resume as
            # primary on local evidence (reference: status=recovering until
            # a start_view arrives). Ask the presumed primary for an SV; the
            # recovering timeout forces a re-election if nobody answers.
            self.status = "recovering"
            self._recover_tick = self.ticks
            rsv = Header(
                command=int(Command.request_start_view), view=self.view
            )
            self._broadcast(rsv)
        self._primary_contact_tick = self.ticks
        # Crashed mid-view-change (view voted > last normal view): resume
        # the view change rather than acting normal in a view we never
        # finished entering (self-promotion would bypass the DVC quorum).
        if persisted_view > self.log_view:
            self._start_view_change(persisted_view)

    def checkpoint(self) -> None:
        """Durably snapshot the committed state AT commit_min (pipelined
        ops beyond it stay replayable in the WAL). The replicated client
        table rides in the snapshot meta — it is part of the replicated
        state (reference: src/vsr/superblock.zig ClientSessions trailer)."""
        with self.tracer.span("replica.checkpoint", op=self.commit_min), \
                self.metrics.histogram("replica.checkpoint_us").time():
            self._checkpoint()
        self.metrics.counter("replica.checkpoints").add()

    def _checkpoint(self) -> None:
        self.flush_commits()  # snapshot sees finalized client-table state
        if self._dual_apply:
            # dual-commit contract: the device applier drains to the
            # checkpoint's commit_min before the snapshot is cut, so the
            # checkpoint never races an in-flight device apply and the
            # applier's lag is re-bounded at every checkpoint
            self._drain_applier_checked("checkpoint")
        # Queued reply-slot writes must land before the client table (with
        # their checksums) is persisted: a crash after the superblock commit
        # but before a queued write would record a reply_checksum for bytes
        # that never hit disk — that session's duplicate requests would be
        # dropped forever (reply absent, request number already recorded).
        self.journal.drain_io()
        table = {
            str(c): {
                "session": e["session"],
                "request": e["request"],
                "slot": e.get("slot"),
                "reply_checksum": str(e.get("reply_checksum", 0)),
            }
            for c, e in self.client_table.items()
        }
        extra_meta = {"view": self.view, "log_view": self.log_view}
        if self.commitment_log is not None:
            # the chain rides checkpoint meta (and therefore state-sync
            # shipping): a restored/synced replica resumes the chain from
            # the last boundary at or before this checkpoint's commit_min
            extra_meta["commitments"] = self.commitment_log.snapshot()
        extra_blobs = None
        encoded = _json.dumps(table, sort_keys=True).encode()
        if len(encoded) > CLIENT_TABLE_INLINE_MAX:
            # many-session ingress mode: the table no longer fits the
            # 64 KiB superblock copy — spill it to a checkpoint blob in
            # the grid area (rides the same sync-shipping machinery;
            # _load_client_table reads it back by name)
            extra_meta["client_table_blob"] = True
            extra_blobs = [("client_table", encoded)]
        else:
            extra_meta["client_table"] = table
        snapshot_to_superblock(
            self.storage, self.ledger, self.sm, self.superblock,
            commit_min=self.commit_min,
            commit_min_checksum=self.commit_checksum,
            extra_meta=extra_meta,
            extra_blobs=extra_blobs,
        )
        self.checkpoint_op = self.commit_min

    def _load_client_table(self, state) -> dict:
        """The checkpointed client table: inline in the superblock meta,
        or — when a many-session table overflowed the copy — from its
        grid blob (written by _checkpoint, shipped by state sync)."""
        if not state.meta.get("client_table_blob"):
            return state.meta.get("client_table", {})
        from tigerbeetle_tpu import native
        from tigerbeetle_tpu.io.storage import Zone

        for ref in state.blobs:
            if ref.name == "client_table":
                raw = self.storage.read(Zone.grid, ref.offset, ref.size)
                if native.checksum(raw) != ref.checksum:
                    raise RuntimeError(
                        "client_table checkpoint blob: bad checksum"
                    )
                return _json.loads(raw.decode())
        raise RuntimeError(
            "checkpoint flags a client_table blob but the superblock "
            "references none"
        )

    def _drain_applier_checked(self, where: str) -> None:
        """Drain the dual-commit device applier and make a timeout LOUD:
        proceeding with applies still in flight breaks the
        drain-before-snapshot/restore contract, and a later parity
        failure at finalize would be undebuggable back to this cause
        without the record."""
        if not self.ledger.drain_applier():
            self.metrics.counter("shadow.drain_timeouts").add()
            import sys as _sys

            _sys.stderr.write(
                f"[dual] WARNING: device applier drain timed out at "
                f"{where} (lag {self.ledger.apply_lag_ops()} ops) — "
                "device parity is no longer assured for this run\n"
            )

    def _maybe_checkpoint(self, next_op: int) -> None:
        """WAL-wrap guard: never let a prepare overwrite an op that is not
        covered by a checkpoint (reference: src/vsr.zig:2003-2035 keeps a
        bar of headroom)."""
        if next_op - self.checkpoint_op >= self.cluster.checkpoint_interval:
            self.checkpoint()  # snapshots at commit_min
        assert next_op - self.checkpoint_op < self.cluster.journal_slot_count, (
            "WAL would wrap uncommitted ops: pipeline stuck"
        )

    # ------------------------------------------------------------------
    # ticks / timeouts
    # ------------------------------------------------------------------

    def tick(self) -> None:
        self.ticks += 1
        spill = getattr(self.ledger, "spill", None)
        if spill is not None:
            # run deferred LSM insert jobs (or reap finished worker jobs)
            # at the tick boundary — never inside the commit dispatch path
            try:
                spill.io_pump()
            except GridBlockCorrupt as e:
                # a threaded worker's settle hit a corrupt block: route it
                # to peer repair instead of crashing the event loop (the
                # staged rows keep serving fetches; the tree's compaction
                # debt resumes at the next settle once healed)
                if not self._request_block_repair([e.address]):
                    raise
        self.pump_commits()  # deferred group commits (event-loop safety)
        # finalize whatever results have LANDED (never block the tick on
        # in-flight device compute; the idle-loop flush and the next ticks
        # drain the rest as it lands)
        self.flush_commits(only_ready=True)
        if self.status == "normal":
            if self.is_primary:
                if self.ticks % HEARTBEAT_TICKS == 0:
                    h = Header(command=int(Command.commit), commit=self.commit_max)
                    self._broadcast(h)
                if self.ticks % RETRY_TICKS == 0 and self.pipeline:
                    # Prepare timeout: retransmit the oldest unacked prepare
                    # (its broadcast may have been lost; backups re-ack
                    # duplicates; reference: prepare_timeout).
                    entry = self.pipeline[min(self.pipeline)]
                    h, body = entry["header"], entry["body"]
                    for r in range(self.replica_count):
                        if r != self.replica and r not in entry["oks"]:
                            self.network.send(
                                self.replica, r, h.to_bytes() + body
                            )
            else:
                if self.ticks - self._primary_contact_tick > VIEW_CHANGE_TICKS:
                    self._start_view_change(self.view + 1)
            if self.ticks % PING_TICKS == 0:
                ping = Header(command=int(Command.ping), op=self.time.monotonic())
                self._broadcast(ping)
            if (
                self.forest is not None
                and self.replica_count > 1
                and self.ticks % GRID_SCRUB_TICKS == 0
            ):
                self._scrub_grid()
            if self.replica_count > 1 and self.ticks % GRID_SCRUB_TICKS == 0:
                self._scrub_wal()
            if self._grid_missing and self.ticks % RETRY_TICKS == 0:
                self._request_block_repair(())  # retransmit lost requests
            if (
                getattr(self, "_sync_payload_cache", None) is not None
                and self.ticks - self._sync_payload_tick > 4 * RETRY_TICKS
            ):
                # the full checkpoint image (tens of MiB) must not stay
                # pinned after the lagging replica finished its transfer
                self._sync_payload_cache = None
        elif self.status == "recovering":
            if self.ticks - self._recover_tick > VIEW_CHANGE_TICKS:
                # Nobody sent a start_view (the cluster may lack a primary):
                # force a re-election; best-log selection recovers commits.
                self._start_view_change(self.view + 1)
            elif self.ticks % RETRY_TICKS == 0:
                rsv = Header(
                    command=int(Command.request_start_view), view=self.view
                )
                self._broadcast(rsv)
        elif self.status == "view_change":
            if self.ticks - self._vc_tick > RETRY_TICKS:
                self._vc_retries += 1
                if self._adopt is not None and self._vc_retries < 4:
                    # Mid-adoption: re-request missing fills (lost packets),
                    # don't abandon the view change while it can progress.
                    self._vc_tick = self.ticks
                    self._repair_wanted.clear()
                    self._request_catchup_window()
                    for op, h in self._adopt.items():
                        got = self.journal.read_prepare(op)
                        if got is None or got[0].checksum != h.checksum:
                            self._request_prepare(op, self._adopt_src)
                elif self._vc_retries >= 2:
                    # The candidate view is not completing (its primary may
                    # be down too): escalate to the next view (reference:
                    # view_change_status_timeout increments the view).
                    self._start_view_change(self.view_candidate + 1)
                else:
                    self._vc_tick = self.ticks
                    svc = Header(
                        command=int(Command.start_view_change),
                        view=self.view_candidate,
                    )
                    self._broadcast(svc)
                    if len(self._svc_votes) >= self.quorum_view_change:
                        self._send_do_view_change()

    # ------------------------------------------------------------------
    # message dispatch
    # ------------------------------------------------------------------

    def _on_message(self, src, data: bytes) -> None:
        header = Header.from_bytes(data[:HEADER_SIZE])
        if not header.valid_checksum():
            return  # corrupt: drop (reference: message_bus checksum gate)
        body = data[HEADER_SIZE : header.size]
        if not header.valid_checksum_body(body):
            return
        cmd = Command(header.command)
        # Commands valid in any status:
        if cmd == Command.ping:
            pong = Header(
                command=int(Command.pong), op=header.op,
                timestamp=self.clock.realtime(),
            )
            self._send(header.replica, pong)
            return
        if cmd == Command.pong:
            self.clock.learn(
                header.replica, header.op, header.timestamp,
                self.time.monotonic(),
            )
            return
        if cmd == Command.ping_client:
            # Client view discovery (reference: src/vsr/replica.zig
            # on_ping_client): answer only in normal status — the pong's
            # view (stamped by _send) tells an idle client where the
            # primary is, so its next request targets the current view.
            if self.status == "normal" and header.client:
                pong = Header(
                    command=int(Command.pong_client), client=header.client
                )
                self._send(header.client, pong)
            return
        if cmd == Command.request_stats:
            self._on_request_stats(header)
            return
        if cmd == Command.mark:
            self._on_mark(header, body)
            return
        if cmd == Command.request_prepare:
            self._on_request_prepare(header)
            return
        if cmd == Command.request_blocks:
            self._on_request_blocks(header, body)
            return
        if cmd == Command.block:
            self._on_block(header, body)
            return
        if cmd == Command.request_sync_manifest:  # request full checkpoint
            self._on_request_sync_checkpoint(header)
            return
        if cmd == Command.sync_manifest:  # checkpoint (state + trailers)
            self._on_sync_checkpoint(header, body)
            return
        if cmd == Command.start_view_change:
            self._on_start_view_change(header)
            return
        if cmd == Command.do_view_change:
            self._on_do_view_change(header, body)
            return
        if cmd == Command.start_view:
            self._on_start_view(header, body)
            return
        if cmd == Command.request_start_view:
            self._on_request_start_view(header)
            return

        if self.status == "view_change":
            if header.view > self.view_candidate and cmd in (
                Command.prepare, Command.commit
            ):
                # the cluster moved past our candidate view: catch up via
                # the authoritative start_view instead of slow escalation
                rsv = Header(
                    command=int(Command.request_start_view), view=header.view
                )
                self._send(header.view % self.replica_count, rsv)
                return
            if cmd == Command.prepare:
                self._on_repair_prepare(header, body)
                return
        if self.status == "recovering":
            if cmd in (Command.prepare, Command.commit) and header.view >= self.view:
                # a live primary exists: ask it for the current start_view
                rsv = Header(
                    command=int(Command.request_start_view), view=header.view
                )
                self._send(header.view % self.replica_count, rsv)
            return
        if self.status != "normal":
            return
        # A message from a newer view: we missed a view change — catch up.
        if header.view > self.view and cmd in (Command.prepare, Command.commit):
            rsv = Header(command=int(Command.request_start_view), view=header.view)
            self._send(header.view % self.replica_count, rsv)
            return
        if cmd == Command.request:
            self._on_request(header, body)
        elif cmd == Command.prepare:
            self._on_prepare(header, body)
        elif cmd == Command.prepare_ok:
            self._on_prepare_ok(header)
        elif cmd == Command.commit:
            self._on_commit(header)

    def _send(self, dst, header: Header, body: bytes = b"") -> None:
        header.set_checksum_body(body)
        header.replica = self.replica
        if header.view == 0 and header.command != int(Command.start_view_change):
            header.view = self.view
        header.cluster = self.superblock.state.cluster if self.superblock.state else 0
        header.set_checksum()
        self.network.send(self.replica, dst, header.to_bytes() + body)

    def _broadcast(self, header: Header, body: bytes = b"") -> None:
        # standbys receive the replicated stream too (prepares, commits,
        # SVs); they just never answer with votes or acks
        for r in range(self.replica_count + self.standby_count):
            if r != self.replica:
                self._send(r, dataclasses.replace(header), body)

    # ------------------------------------------------------------------
    # primary: request -> prepare
    # ------------------------------------------------------------------

    def _on_request(self, header: Header, body: bytes) -> None:
        if not self.is_primary:
            return  # client retries against the right primary
        client = header.client
        entry = self.client_table.get(client)
        operation = Operation(header.operation)

        if operation == Operation.register:
            # A register retransmit must not create a second session — the
            # client's real session would be silently replaced and its next
            # request evicted (reference: duplicate register replies from
            # the client table).
            if entry is not None:
                if entry["reply"] is not None:
                    self.network.send(self.replica, client, entry["reply"])
                elif entry["request"] == 0:
                    # reply bytes were lost across a restart/state sync, but
                    # the session number IS the stored entry: reconstruct
                    reply = Header(
                        command=int(Command.reply),
                        client=client,
                        request=0,
                        op=entry["session"],
                        commit=entry["session"],
                        operation=int(Operation.register),
                    )
                    body_r = entry["session"].to_bytes(8, "little")
                    reply.set_checksum_body(body_r)
                    reply.replica = self.replica
                    reply.view = self.view
                    reply.set_checksum()
                    wire = reply.to_bytes() + body_r
                    entry["reply"] = wire
                    self.network.send(self.replica, client, wire)
                return
        else:
            if entry is None or header.context != entry["session"]:
                self._send_eviction(client)
                return
            if header.request <= entry["request"]:
                if header.request == entry["request"] and entry["reply"] is not None:
                    self.network.send(self.replica, client, entry["reply"])
                return  # duplicate/stale: drop (reply resent above)
        # Retransmission of a request still awaiting quorum: already in
        # the pipeline — preparing it again would execute it twice
        # (reference: pipeline_prepare_queue message_by_client check).
        # Dispatched-but-unfinalized commits (async window) are equally
        # in flight: the client table only learns the request at finalize.
        for entry_p in self.pipeline.values():
            h = entry_p["header"]
            if (
                h.client == client
                and h.request == header.request
                and h.operation == header.operation
            ):
                return
        for entry_i in self._inflight:
            h = entry_i["header"]
            if h.client == client and h.request == header.request:
                return

        # Pipeline backpressure (reference: pipeline_prepare_queue_max=8):
        # while commits stall (lost quorum, partition), new requests must
        # not grow the uncommitted tail without bound — the WAL headroom is
        # finite. The client retries. With a commit window the cap widens
        # to hold one full turn of deferred group commits (still far under
        # the WAL-wrap guard).
        cap = max(
            self.cluster.pipeline_prepare_queue_max, 2 * self.commit_window
        )
        # Dual-commit mode: device-applier lag beyond its window throttles
        # admission here too (gateway-less deployments) — the client
        # retries, the lag stays bounded, the apply queue never wedges the
        # event loop on a blocking put.
        lag_excess = self.ledger.apply_lag_excess() if self._dual_apply else 0
        if len(self.pipeline) + lag_excess >= cap:
            return

        # Latency anatomy: the request survived dedup/backpressure and
        # will become an op — open the sampled record (keyed by the
        # cluster-causal trace id; the id derivation is paid only for
        # sampled requests). ingress_admission closes here: gateway
        # arrival (or now) -> admission+dedup done.
        lat = self.latency
        lt = lat.open(header.trace()) if lat.want() else 0
        op = self.op + 1
        assert op not in self.pipeline
        self._maybe_checkpoint(op)
        if operation != Operation.register:
            # Timestamp base: cluster-synchronized wall clock, clamped
            # monotonic (reference: src/vsr/replica.zig:5121-5131).
            rt = self.clock.realtime_synchronized()
            if rt is None:
                rt = self.clock.realtime()
            self.sm.prepare_timestamp = max(self.sm.prepare_timestamp, rt)
            self.sm.prepare(operation, body)
        prepare = Header(
            parent=self.parent_checksum,
            client=client,
            context=header.checksum,  # checksum of the client's request
            request=header.request,
            op=op,
            commit=self.commit_max,
            timestamp=(
                self.sm.prepare_timestamp
                if operation != Operation.register
                else self.time.realtime()
            ),
            command=int(Command.prepare),
            operation=int(operation),
            view=self.view,
            cluster=self.superblock.state.cluster if self.superblock.state else 0,
            replica=self.replica,
        )
        # The prepare's body IS the request's body: reuse the checksum the
        # request carried (verified on receive) instead of re-hashing the
        # full 1 MiB per prepare.
        prepare.size = HEADER_SIZE + len(body)
        prepare.checksum_body = header.checksum_body
        prepare.set_checksum()
        if self.commit_window > 0 and self.replica_count == 1:
            # async WAL (reference: journal write IOPS): the reply waits
            # on this future at finalize — WAL-before-ack holds while the
            # 1 MiB O_DSYNC write overlaps device commits + other requests.
            # Single replica only: a multi-replica primary's self-vote in
            # `oks` is an implicit ack, and acks require a DURABLE prepare
            # (a backup acks only after its synchronous write) — counting
            # an un-landed write toward quorum could commit an op with
            # fewer than quorum durable copies.
            wal = self.journal.write_prepare_async(prepare, body)
        else:
            self.journal.write_prepare(prepare, body)
            wal = None
        if lt:
            # sync path: the completed WAL write; async path: the submit
            # (the durable wait lands in commit_finalize; the write's
            # own submit->durable time is the latency.wal_lane_us lane)
            lat.stamp(lt, LEG_WAL)
            if self.quorum_replication == 1:
                # the self-vote below IS the quorum: close the leg now
                lat.stamp(lt, LEG_QUORUM)
        self.op = op
        self.parent_checksum = prepare.checksum
        self.pipeline[op] = {"header": prepare, "body": body,
                             "oks": {self.replica}, "wal": wal, "lt": lt,
                             # quorum-wait accounting: broadcast -> quorum
                             "t": perf_counter_ns(),
                             # ingress anchor of the op's causal trace:
                             # the id every later span derives from the
                             # prepare's (client, context) pair
                             "qtok": self.tracer.start(
                                 "replica.quorum_wait", op=op,
                                 trace=self._tid(prepare))}
        # Stream prepares to standbys too (they journal + commit but never
        # ack — _ack_prepare declines): without this a standby would learn
        # each op only via a commit heartbeat plus one request_prepare round
        # trip, lagging unboundedly under sustained load (the reference
        # streams prepares to standbys).
        for r in range(self.replica_count + self.standby_count):
            if r != self.replica:
                self.network.send(self.replica, r, prepare.to_bytes() + body)
        if self.commit_window > 0 and self.replica_count == 1:
            # defer to the event loop's end-of-pump pump_commits(): every
            # request that arrived this turn then commits as ONE fused
            # group instead of k separate device launches
            return
        self._maybe_commit_pipeline()

    def _restore_client_replies(self) -> None:
        """Repopulate reply bytes from the client_replies zone (restart
        path). Slots are validated against the checkpointed reply
        checksum, so stale bytes (state sync adopted a foreign table; a
        torn write; a newer uncheckpointed reply) read as absent and the
        reply-lost fallbacks apply."""
        for entry in self.client_table.values():
            slot = entry.get("slot")
            want = int(entry.get("reply_checksum", 0) or 0)
            if slot is None or not want:
                continue
            wire = self.client_replies.read(slot, want)
            if wire is not None:
                entry["reply"] = wire

    def _send_eviction(self, client: int) -> None:
        h = Header(command=int(Command.eviction), client=client)
        self._send(client, h)

    # ------------------------------------------------------------------
    # backup: prepare -> prepare_ok
    # ------------------------------------------------------------------

    def _on_prepare(self, header: Header, body: bytes) -> None:
        # Repair fills (any view):
        if header.op in self._repair_wanted:
            if header.op == self.op + 1 and header.parent == self.parent_checksum:
                # catch-up beyond our log head, verified by the hash chain
                self.journal.write_prepare(header, body)
                self.op = header.op
                self.parent_checksum = header.checksum
                self._repair_wanted.discard(header.op)
                self._ack_prepare(header)
                self._commit_up_to(self.commit_max)  # continues / asks next
                # drain buffered out-of-order successors (a normal prepare
                # may have been parked while this gap filled)
                nxt = self._pending_prepares.pop(self.op + 1, None)
                if nxt is not None:
                    self._on_prepare(*nxt)
                return
            # in-log gap (faulty slot): verified against the expected
            # checksum from the redundant-header mirror
            want = self.journal.get_header(header.op)
            if want is not None and want.checksum == header.checksum:
                if self.journal.read_prepare(header.op) is None:
                    self.journal.write_prepare(header, body)
                self._repair_wanted.discard(header.op)
                self._commit_up_to(self.commit_max)
                return
            # Unresolvable by point repair: our uncommitted tail above
            # commit_min is stale (left over from an abandoned view) and the
            # fill doesn't chain. Re-adopt the whole log via start_view —
            # adoption truncates to the committed prefix and reverifies.
            self._repair_wanted.discard(header.op)
            if self.status == "normal" and not self.is_primary:
                rsv = Header(
                    command=int(Command.request_start_view), view=self.view
                )
                self._send(self.primary_index, rsv)
            return
        if header.view < self.view or self.is_primary:
            return
        self._primary_contact_tick = self.ticks
        if header.op <= self.op:
            self._ack_prepare(header)  # duplicate: re-ack
            self._commit_up_to(header.commit)
            return
        if header.op > self.op + 1:
            self._pending_prepares[header.op] = (header, body)
            self._request_prepare(header.op - 1, header.replica)
            return
        if header.parent != self.parent_checksum:
            return  # chain break: resolved by the view-change/repair layer
        self._maybe_checkpoint(header.op)
        self.journal.write_prepare(header, body)
        self.op = header.op
        self.parent_checksum = header.checksum
        self._ack_prepare(header)
        self._commit_up_to(header.commit)
        # drain any buffered successors
        nxt = self._pending_prepares.pop(self.op + 1, None)
        if nxt is not None:
            self._on_prepare(*nxt)

    def _ack_prepare(self, prepare: Header) -> None:
        if self.standby:
            return  # standbys follow; they never contribute to quorums
        ok = Header(
            command=int(Command.prepare_ok),
            op=prepare.op,
            context=prepare.checksum,
            client=prepare.client,
            request=prepare.request,
            timestamp=prepare.timestamp,
            operation=prepare.operation,
        )
        self._send(self.primary_index, ok)

    # ------------------------------------------------------------------
    # repair: fetching missing prepares
    # ------------------------------------------------------------------

    def _request_prepare(self, op: int, from_replica: int) -> None:
        self._repair_wanted.add(op)
        rp = Header(command=int(Command.request_prepare), op=op)
        self._send(from_replica, rp)

    def _on_request_prepare(self, header: Header) -> None:
        got = self.journal.read_prepare(header.op)
        if got is None:
            return
        p_header, body = got
        self.network.send(
            self.replica, header.replica, p_header.to_bytes() + body
        )

    # ------------------------------------------------------------------
    # live introspection (`tigerbeetle inspect live`, inspect.py)
    # ------------------------------------------------------------------

    def _on_request_stats(self, header: Header) -> None:
        """Serve the live [stats] snapshot over the wire: the metric
        registry plus the consensus state an operator asks about first.
        Answered in ANY status — a wedged replica is exactly the one
        worth inspecting — and routed back to the asking client id (the
        bus learned the peer from this very frame)."""
        self.metrics.counter("inspect.live_requests").add()
        snap = {
            "replica": self.replica,
            "status": self.status,
            "view": self.view,
            "op": self.op,
            "commit_min": self.commit_min,
            "commit_max": self.commit_max,
            "checkpoint_op": self.checkpoint_op,
            "pipeline": len(self.pipeline),
            "inflight": len(self._inflight),
            "sessions": len(self.client_table),
            "metrics": self.metrics.snapshot(),
            # per-request breakdowns of the slowest sampled requests
            # (latency.py top-K ring) — `inspect live` renders them
            "latency_slowest": self.latency.slowest(limit=16),
        }
        if self.commitment_log is not None:
            snap["commitments"] = self.commitment_log.stats_snapshot()
        da = getattr(self.ledger, "device_anatomy", None)
        if da is not None:
            ds = da.slowest(limit=8)
            if ds:
                # dual mode: the slowest sampled APPLY items with their
                # commit_wait sub-leg breakdowns (latency.py DeviceAnatomy)
                snap["device_slowest"] = ds
        if self.flight_recorder is not None:
            # the time-series ring: `inspect live --watch` renders the
            # per-interval deltas/rates as they accumulate
            snap["history"] = self.flight_recorder.history()
        body = _json.dumps(snap, sort_keys=True).encode()
        if HEADER_SIZE + len(body) > self.cluster.message_size_max:
            # shed detail in layers, never validity: the full history is
            # the biggest payload — try the newest slice, then drop it
            if "history" in snap:
                snap["history"] = snap["history"][-30:]
                body = _json.dumps(snap, sort_keys=True).encode()
            if HEADER_SIZE + len(body) > self.cluster.message_size_max:
                snap.pop("history", None)
                body = _json.dumps(snap, sort_keys=True).encode()
        if HEADER_SIZE + len(body) > self.cluster.message_size_max:
            # a registry too large for one frame loses its detail, never
            # its validity: the consensus state is the part that must land
            snap["metrics"] = {"truncated": True}
            body = _json.dumps(snap, sort_keys=True).encode()
        reply = Header(command=int(Command.stats), client=header.client)
        self._send(header.client or header.replica, reply, body)

    def _on_mark(self, header: Header, body: bytes) -> None:
        """Phase marker (the prodday harness, inspect.send_mark): stamp
        the named scenario phase into the flight recorder so every
        subsequent per-interval entry — and therefore the SLO scorer's
        history slices — carries it. Served in ANY status (the driver
        marks phase boundaries straight through kills and view changes)
        and acked with a small `stats` frame so the driver knows the
        boundary landed before it changes the offered load."""
        self.metrics.counter("inspect.marks").add()
        name = body.decode(errors="replace")[:256]
        snap: dict = {"marked": name, "replica": self.replica}
        if self.flight_recorder is not None:
            snap["t"] = self.flight_recorder.set_phase(name)
        ack = _json.dumps(snap, sort_keys=True).encode()
        reply = Header(command=int(Command.stats), client=header.client)
        self._send(header.client or header.replica, reply, ack)

    # ------------------------------------------------------------------
    # grid block repair: a corrupt forest block heals from any peer that
    # holds an intact copy — no full state sync needed (reference:
    # src/vsr/grid_blocks_missing.zig + src/vsr/grid.zig:731). Detection
    # is (a) a periodic scrub pass over acquired blocks and (b) lazy, at
    # the read that trips GridBlockCorrupt in the commit path (which then
    # stalls that op and retries once the block is healed).
    # ------------------------------------------------------------------

    def _request_block_repair(self, addresses) -> bool:
        """Record missing blocks and ask ONE peer (rotating on retries —
        broadcasting would draw (n-1) duplicate 128 KiB replies per block;
        the reference's grid_blocks_missing requests from one replica at a
        time too). Returns False when repair is impossible (no forest /
        single replica) — the caller should treat corruption as fatal."""
        if self.forest is None or self.replica_count == 1:
            return False
        self.metrics.counter("grid.repair_requests").add()
        self._grid_missing.update(addresses)
        body = b"".join(
            a.to_bytes(8, "little") for a in sorted(self._grid_missing)
        )
        self._repair_peer_rotation = getattr(self, "_repair_peer_rotation", 0) + 1
        # 1 + (rot mod n-1) ∈ [1, n-1], so the offset never lands on self
        peer = (
            self.replica + 1 + (self._repair_peer_rotation % (self.replica_count - 1))
        ) % self.replica_count
        rq = Header(command=int(Command.request_blocks))
        self._send(peer, rq, body)
        return True

    def _on_request_blocks(self, header: Header, body: bytes) -> None:
        if self.forest is None:
            return
        grid = self.forest.grid
        for i in range(0, len(body), 8):
            a = int.from_bytes(body[i : i + 8], "little")
            if not (1 <= a <= grid.block_count):
                continue
            raw = grid.read_block_raw(a)  # verified: never spread corruption
            if raw is None:
                continue
            reply = Header(command=int(Command.block), op=a)
            self._send(header.replica, reply, raw)

    def _on_block(self, header: Header, body: bytes) -> None:
        if self.forest is None or header.op not in self._grid_missing:
            return
        spill = getattr(self.ledger, "spill", None)
        if spill is not None and spill.io_pending():
            # a threaded worker may be mid-settle on grid state (a freed
            # address can be re-acquired mid-install): defer — the block
            # stays in _grid_missing and the tick-cadence retry re-requests
            return
        grid = self.forest.grid
        # A late duplicate reply must not overwrite an address that has
        # healed and since been released + reused — the stale bytes carry
        # a valid checksum, so the clobber would be silent.
        if grid.free_set.is_free(header.op) or grid.verify_block(header.op):
            self._grid_missing.discard(header.op)
        elif grid.install_block_raw(header.op, body):
            self._grid_missing.discard(header.op)
        else:
            return  # corrupt in flight: the tick retry re-requests
        if not self._grid_missing and self.status == "normal":
            # healed: retry whatever stalled on the corrupt block
            if self.is_primary:
                self._maybe_commit_pipeline()
            else:
                self._commit_up_to(self.commit_max)

    def _scrub_grid(self) -> None:
        """Verify a few acquired forest blocks per pass, round-robin
        (the reference's grid scrubber): corruption below the WAL is found
        and repaired from peers BEFORE a commit needs the block."""
        spill = getattr(self.ledger, "spill", None)
        if spill is not None and spill.io_pending():
            # inserts in flight: a threaded worker may be mid-write on a
            # freshly acquired block — verifying it now would misreport
            # corruption (deferred mode: the tick pump already emptied the
            # queue, so this never skips there)
            return
        grid = self.forest.grid
        checked = scanned = 0
        a = self._scrub_cursor
        n = grid.block_count
        corrupt = []
        while checked < GRID_SCRUB_BLOCKS and scanned < n:
            a = a % n + 1
            scanned += 1
            if grid.free_set.is_free(a):
                continue
            checked += 1
            if not grid.verify_block(a):
                corrupt.append(a)
        self._scrub_cursor = a
        if corrupt:
            self._request_block_repair(corrupt)

    def _scrub_wal(self) -> None:
        """Continuous WAL repair in NORMAL status (reference: the replica
        repairs faulty journal slots outside view changes,
        src/vsr/replica.zig:5248-5654 — not only during adoption): refetch
        every slot the recovery scan classified TORN (redundant header
        survives, body lost — vsr/journal.py recover), plus a slow
        round-robin sweep that re-verifies one live slot per pass to catch
        in-place media faults after recovery. Fills arrive via the
        _repair_wanted path in _on_prepare, verified against the mirror
        header's checksum."""
        # peer rotation includes the tick so a down peer doesn't pin an op
        def ask(op: int) -> None:
            rot = (op + self.ticks // RETRY_TICKS) % (self.replica_count - 1)
            self._request_prepare(
                op, (self.replica + 1 + rot) % self.replica_count
            )

        faulty = getattr(self.journal, "faulty", None)
        if faulty:
            for slot, op in list(faulty.items()):
                h = self.journal.get_header(op)
                if h is None or h.op != op:
                    # the ring wrapped: a newer op overwrote the slot — the
                    # torn op is beyond repair relevance (without this the
                    # scrub would re-request the superseded op forever)
                    del faulty[slot]
                    continue
                if self.journal.read_prepare(op) is not None:
                    del faulty[slot]  # healed (repair fill landed)
                    continue
                ask(op)  # re-request each pass: lost requests retry
        # slow sweep for IN-PLACE media faults (after recovery): one full
        # 1 MiB slot re-verify per WAL_SWEEP_TICKS — a deliberately low
        # cadence; the verify is a synchronous read on the event loop
        if self.ticks % WAL_SWEEP_TICKS != 0:
            return
        lo = max(1, self.op - self.cluster.journal_slot_count + 1)
        if lo > self.op:
            return
        op = self._wal_scrub_cursor
        if not (lo <= op <= self.op):
            op = lo
        h = self.journal.get_header(op)
        if h is not None and self.journal.read_prepare(op) is None:
            ask(op)
        self._wal_scrub_cursor = op + 1 if op < self.op else lo

    # ------------------------------------------------------------------
    # state sync: checkpoint shipping for replicas lagging beyond the WAL
    # (reference: src/vsr/sync.zig — a lagging replica jumps to a newer
    # checkpoint, then repairs the remaining WAL tail normally)
    # ------------------------------------------------------------------

    def _sync_checkpoint_payload(self) -> tuple[bytes, int] | None:
        """(full image, checksum) to ship: state + snapshot blobs +
        (spill) forest blocks. Cached per superblock sequence — rebuilding
        or re-hashing per chunk request would be O(image) each.

        With sync_payload_async (production default), the O(checkpoint)
        read+hash runs on a side thread and requests arriving mid-build get
        no reply (the lagging peer's tick-cadence retry is the backpressure)
        — serving a sync must never stall the event loop for the whole
        image (reference: src/vsr/sync.zig streams trailers in chunks).
        Deterministic harnesses set sync_payload_async=False (thread timing
        must not leak into seeded runs). Consistency: the blob areas of the
        live sequence are immutable (ping-pong), and forest-block reuse is
        staged until the NEXT checkpoint — a checkpoint advancing mid-build
        changes the sequence and the stale build is discarded."""
        state = self.superblock.state
        if state is None or state.commit_min == 0:
            return None
        spill = getattr(self.ledger, "spill", None)
        if spill is not None:
            # the image reads the forest block area: queued spill inserts
            # must land first (drained HERE, on the event loop — the side
            # thread must not touch the executor's job list)
            spill.io_drain()
        cached = getattr(self, "_sync_payload_cache", None)
        if cached is not None and cached[0] == state.sequence:
            self._sync_payload_tick = self.ticks
            return cached[1], cached[2]
        if getattr(self, "sync_payload_async", True):
            fut = getattr(self, "_sync_payload_fut", None)
            if fut is not None:
                if not fut.done():
                    return None  # still building: the peer retries
                self._sync_payload_fut = None
                try:
                    seq, full, checksum = fut.result()
                except Exception:
                    # a failed build (transient IO error on the side
                    # thread) must not crash the event loop to serve an
                    # OPTIONAL sync — drop it; the peer's retry rebuilds
                    return None
                if seq == state.sequence:
                    self._sync_payload_cache = (seq, full, checksum)
                    self._sync_payload_tick = self.ticks
                    return full, checksum
                # checkpoint advanced mid-build: fall through, rebuild
            # a daemon thread + bare Future (not a ThreadPoolExecutor):
            # replicas have no close() hook, and a pool's non-daemon worker
            # would outlive the replica and stall interpreter exit behind
            # an O(checkpoint) build
            import threading
            from concurrent.futures import Future

            fut = Future()

            def _build(state=state, fut=fut):
                try:
                    fut.set_result(self._build_sync_payload(state))
                except BaseException as e:  # surfaced (and dropped) above
                    fut.set_exception(e)

            threading.Thread(
                target=_build, daemon=True, name="sync-payload"
            ).start()
            self._sync_payload_fut = fut
            return None
        seq, full, checksum = self._build_sync_payload(state)
        self._sync_payload_cache = (seq, full, checksum)
        self._sync_payload_tick = self.ticks
        return full, checksum

    def _build_sync_payload(self, state) -> tuple[int, bytes, int]:
        from tigerbeetle_tpu.io.storage import Zone

        payload = state.to_bytes()
        blob_bytes = b"".join(
            self.storage.read(Zone.grid, ref.offset, ref.size)
            for ref in state.blobs
        )
        # With a spill store, ship the forest's acquired grid blocks too:
        # the checkpoint's spill meta references them by address, and grid
        # addresses are layout-relative, so the receiver installs them at
        # the same addresses in its own forest area.
        forest_section = b""
        if getattr(self.ledger, "spill", None) is not None:
            from tigerbeetle_tpu.lsm.grid import BLOCK_SIZE

            grid = self.ledger.spill.forest.grid
            fo = self.storage.layout.forest_offset
            blocks = [
                a for a in range(1, grid.block_count + 1)
                if not grid.free_set.is_free(a)
            ]
            parts = [len(blocks).to_bytes(4, "little")]
            for a in blocks:
                raw = self.storage.read(
                    Zone.grid, fo + (a - 1) * BLOCK_SIZE, BLOCK_SIZE
                )
                parts.append(a.to_bytes(8, "little") + raw)
            forest_section = b"".join(parts)
        full = (
            len(payload).to_bytes(8, "little") + payload + blob_bytes
            + forest_section
        )
        from tigerbeetle_tpu import native

        checksum = native.checksum(full)  # hashed ONCE per image, not per chunk
        return state.sequence, full, checksum

    @property
    def _sync_chunk_size(self) -> int:
        return self.cluster.message_size_max - HEADER_SIZE

    def _on_request_sync_checkpoint(self, header: Header) -> None:
        """Serve ONE bounded chunk of the checkpoint image (reference:
        src/vsr/sync.zig:9-56 — trailers ship in message-sized chunks, the
        receiver requests them progressively). header.op = chunk index.
        The reply carries commit=checkpoint op, timestamp=total size,
        parent=checksum(full image) so the receiver can detect a source
        checkpoint advancing mid-transfer and restart."""
        got = self._sync_checkpoint_payload()
        if got is None:
            return
        full, checksum = got
        state = self.superblock.state
        chunk_size = self._sync_chunk_size
        index = header.op
        if index * chunk_size >= len(full):
            return  # out of range (stale request for a shrunken image)
        chunk = full[index * chunk_size : (index + 1) * chunk_size]
        reply = Header(
            command=int(Command.sync_manifest),
            op=index,
            commit=state.commit_min,
            timestamp=len(full),
            parent=checksum,
        )
        self._send(header.replica, reply, chunk)

    def _on_sync_checkpoint(self, header: Header, body: bytes) -> None:
        """One CHUNK of a peer's checkpoint image. Gather until complete
        (requesting the next missing chunk each arrival — the transfer is
        self-clocking), verify the whole-image checksum, then install.
        A source whose checkpoint advanced mid-transfer changes the image
        checksum (header.parent): the gather restarts on the new image
        (reference: src/vsr/sync.zig stage machine with restart-on-
        target-change)."""
        adopting = (
            self.status in ("view_change", "recovering")
            and self._adopt is not None
        )
        # A NORMAL-status backup lagging beyond the primary's WAL also
        # jumps via checkpoint shipping (see _commit_up_to's escalation) —
        # installing a checkpoint with commit_min above our own only ever
        # replaces a committed prefix with a longer committed prefix.
        if not adopting and self.status != "normal":
            return
        if header.commit <= self.commit_min:
            return  # stale / not an improvement
        from tigerbeetle_tpu import native

        key = (header.parent, header.commit, header.timestamp)
        gather = getattr(self, "_sync_gather", None)
        if gather is None or gather["key"] != key:
            gather = {"key": key, "chunks": {}, "total": header.timestamp}
            self._sync_gather = gather
        gather["chunks"][header.op] = body
        chunk_size = self._sync_chunk_size
        n_chunks = (gather["total"] + chunk_size - 1) // chunk_size
        missing = next(
            (i for i in range(n_chunks) if i not in gather["chunks"]), None
        )
        if missing is not None:
            rq = Header(
                command=int(Command.request_sync_manifest), op=missing
            )
            self._send(header.replica, rq)
            return
        full = b"".join(gather["chunks"][i] for i in range(n_chunks))
        self._sync_gather = None
        if len(full) != gather["total"] or native.checksum(full) != header.parent:
            return  # torn/mixed image: the tick-cadence retry restarts
        self._install_sync_checkpoint(full)

    def _install_sync_checkpoint(self, body: bytes) -> None:
        """Adopt a peer's complete checkpoint image (we are too far behind
        for WAL repair)."""
        from tigerbeetle_tpu import native
        from tigerbeetle_tpu.io.storage import Zone
        from tigerbeetle_tpu.vsr.superblock import BlobRef, VSRState

        adopting = (
            self.status in ("view_change", "recovering")
            and self._adopt is not None
        )
        self.flush_commits()  # restore replaces the ledger state wholesale
        if self._dual_apply:
            # the device applier must quiesce before restore_bytes
            # replaces its tables (the install rides the apply queue, but
            # draining first bounds how much queued work the jump makes
            # moot and keeps the digest-ring reset unambiguous)
            self._drain_applier_checked("state-sync")
        n = int.from_bytes(body[:8], "little")
        remote = VSRState.from_bytes(body[8 : 8 + n])
        if remote.commit_min <= self.commit_min:
            return  # stale / not an improvement
        blob_raw = body[8 + n :]
        # verify + rewrite blobs into our own grid (other ping-pong area)
        own = self.superblock.state
        assert own is not None
        area = 1 - own.area
        area_size = self.storage.layout.snapshot_area_size
        off = area * area_size
        local_refs = []
        pos = 0
        for ref in remote.blobs:
            raw = blob_raw[pos : pos + ref.size]
            pos += ref.size
            if native.checksum(raw) != ref.checksum:
                return  # corrupt in flight: retry will refetch
            self.storage.write(Zone.grid, off, raw)
            local_refs.append(BlobRef(ref.name, off, ref.size, ref.checksum))
            off += (len(raw) + 4095) // 4096 * 4096
        if pos < len(blob_raw):
            # forest block section (spill store): install the source's
            # acquired blocks at the same layout-relative addresses in OUR
            # forest area; per-block payload checksums verify on first
            # read, and the spill meta's free set covers the address map
            if getattr(self.ledger, "spill", None) is None:
                return  # cannot adopt spilled state without a forest
            from tigerbeetle_tpu.lsm.grid import BLOCK_SIZE

            fo = self.storage.layout.forest_offset
            count = int.from_bytes(blob_raw[pos : pos + 4], "little")
            pos += 4
            blocks: list[tuple[int, bytes]] = []
            for _ in range(count):
                a = int.from_bytes(blob_raw[pos : pos + 8], "little")
                pos += 8
                raw = blob_raw[pos : pos + BLOCK_SIZE]
                pos += BLOCK_SIZE
                # Verify the block's embedded checksum BEFORE any install
                # (the blob path above does the same): a corrupt-in-flight
                # block adopted here would only surface later as a
                # read_block error mid-commit, with no refetch path. All
                # blocks verify before any write so a rejected checkpoint
                # never leaves the forest area half-replaced (addresses
                # are shared with the CURRENT checkpoint's references).
                from tigerbeetle_tpu.lsm.grid import Grid

                if Grid.validate_raw(raw) is None:
                    return  # corrupt in flight: retry will refetch
                blocks.append((a, raw))
            for a, raw in blocks:
                self.storage.write(Zone.grid, fo + (a - 1) * BLOCK_SIZE, raw)
            self.ledger.spill.forest.grid.cache.clear()
        self.storage.sync()
        meta = dict(remote.meta)
        # view durability is OURS, not the sync source's
        meta["view"] = max(
            int(meta.get("view", 0)), self.view_candidate, self.view
        )
        meta["log_view"] = self.log_view
        new_state = dataclasses.replace(
            remote,
            replica=self.replica,
            sequence=own.sequence + 1,
            area=area,
            blobs=local_refs,
            meta=meta,
        )
        self.superblock.checkpoint(new_state)
        restore_from_snapshot(
            self.storage, self.ledger, self.sm, self.ledger.process, new_state
        )
        self.client_table = {
            int(c): dict(e, reply=None)
            for c, e in self._load_client_table(new_state).items()
        }
        self._reply_slots_free = None  # rebuilt from the adopted table
        self._restore_client_replies()
        self.checkpoint_op = new_state.commit_min
        self.commit_min = self.commit_max = self.op = new_state.commit_min
        # the jumped ops executed elsewhere: unblock the CDC pump (it
        # declares whatever the journal no longer covers as a gap), and
        # prune reply-ring entries stranded below the jump — the
        # single-key eviction at finalize only ever pops op-slot_count
        # for CONSECUTIVE ops and would skip the jumped range forever
        self.cdc_commit_min = max(self.cdc_commit_min, new_state.commit_min)
        if self.cdc_replies:
            floor = new_state.commit_min - self.cluster.journal_slot_count
            self.cdc_replies = {
                k: v for k, v in self.cdc_replies.items() if k > floor
            }
        self.parent_checksum = self.commit_checksum = new_state.commit_min_checksum
        self._repair_wanted.clear()
        if adopting:
            # resume adoption from the new base
            self._catchup.clear()
            self._catchup_no_local = True  # local WAL predates the sync point
            self._vc_tick = self.ticks
            self._vc_retries = 0
            self._request_catchup_window()
            self._try_finish_view_change()
        # normal status: the next commit heartbeat resumes WAL catch-up
        # from the new checkpoint via _commit_up_to

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------

    def _on_prepare_ok(self, header: Header) -> None:
        if not self.is_primary:
            return
        entry = self.pipeline.get(header.op)
        if entry is None or entry["header"].checksum != header.context:
            return
        before = len(entry["oks"])
        entry["oks"].add(header.replica)
        if (
            before < self.quorum_replication
            and len(entry["oks"]) == self.quorum_replication
        ):
            # quorum_wait leg closes at the ack that COMPLETES the
            # quorum — transition-gated, because a duplicate re-ack
            # (retransmitted prepare) leaves len(oks) AT quorum and a
            # re-stamp would fold later legs' time into quorum_wait
            # (the _note_quorum accounting below fires later, after any
            # fuse hold — a different boundary)
            lt = entry.get("lt")
            if lt:
                self.latency.stamp(lt, LEG_QUORUM)
        self._maybe_commit_pipeline()

    # Max prepares fused into one group commit (the ledger pads smaller
    # runs into fixed-capacity scan kernels — see DeviceLedger.GROUP_KS).
    GROUP_MAX = 16

    def _spill_prefetch_body(self, header: Header, body: bytes) -> None:
        """Prefetch/commit overlap (models/spill.py): while op N's commit
        kernel runs, the spill IO executor gathers op N+1's referenced-
        spilled rows so its admit() finds them staged. Gated on an active
        spilled set — otherwise this is a free no-op per commit."""
        spill = getattr(self.ledger, "spill", None)
        if (
            spill is None
            or not spill.spilled
            or header.operation != int(Operation.create_transfers)
        ):
            return
        spill.prefetch_async(np.frombuffer(body, dtype=TRANSFER_DTYPE))

    def _tid(self, header: Header) -> int:
        """The op's cluster-causal trace id (vsr/header.py trace_id) for
        span tagging — 0 (untraced) when tracing is off, so the hot path
        never pays the hash for the no-op backend."""
        return header.trace() if self.tracer.enabled else 0

    def _drop_quorum_tokens(self) -> None:
        """Close the quorum-wait spans of pipeline entries about to be
        discarded (view change): without this a traced run leaks one open
        span per abandoned prepare into the dump. The histogram is NOT
        observed — these ops never reached quorum here."""
        for entry in self.pipeline.values():
            entry.pop("t", None)
            tok = entry.pop("qtok", 0)
            if tok:
                self.tracer.stop(tok)
            # abandoned prepares never reach egress: drop their open
            # latency records instead of leaking them to eviction
            self.latency.discard(entry.pop("lt", 0) or None)

    def _note_quorum(self, entry: dict) -> None:
        """Close a pipeline entry's quorum-wait accounting (histogram +
        trace span). Idempotent: the stall/retry paths can re-enter the
        commit for the same op."""
        t = entry.pop("t", None)
        if t is not None:
            self._h_quorum.observe((perf_counter_ns() - t) / 1000.0)
        tok = entry.pop("qtok", 0)
        if tok:
            self.tracer.stop(tok)

    def _maybe_commit_pipeline(self) -> None:
        committed = False
        while True:
            op = self.commit_min + 1
            entry = self.pipeline.get(op)
            if entry is None or len(entry["oks"]) < self.quorum_replication:
                break
            header, body = entry["header"], entry["body"]
            self._note_quorum(entry)
            try:
                if self.commit_window > 0:
                    if self._commit_group(op, header):
                        committed = True
                        continue
                    # overlapped: dispatch now, drain/reply on flush — the
                    # next request's journal write + broadcast run while
                    # the device executes this batch
                    d = self._commit_dispatch(header, body,
                                              lt=entry.get("lt", 0))
                    d["wal"] = entry.get("wal")
                    self._inflight.append(d)
                    self.group_stats.add("solo_ops")
                    self.flush_commits(keep=self.commit_window, only_ready=True)
                else:
                    lt = entry.get("lt", 0)
                    reply_wire = self._commit_prepare(header, body, lt=lt)
                    if reply_wire is not None:
                        if lt:
                            self.latency.egress(
                                lt, header.client, header.context
                            )
                        self.network.send(
                            self.replica, header.client, reply_wire
                        )
            except GridBlockCorrupt as e:
                # stall this op; retry when the block heals (_on_block)
                if not self._request_block_repair([e.address]):
                    raise  # single replica / no forest: unrecoverable
                break
            self.commit_min = self.commit_max = op
            self.commit_checksum = header.checksum
            del self.pipeline[op]
            committed = True
            # op's admit has run: start gathering op+1's spilled rows on
            # the IO executor while op's commit kernel executes
            nxt = self.pipeline.get(op + 1)
            if nxt is not None and len(nxt["oks"]) >= self.quorum_replication:
                self._spill_prefetch_body(nxt["header"], nxt["body"])
        if committed:
            # commit heartbeat so backups commit promptly (also sent on a
            # tick cadence)
            h = Header(command=int(Command.commit), commit=self.commit_max)
            self._broadcast(h)

    def _commit_group(self, first_op: int, first_header: Header) -> bool:
        """Group commit: fuse a run of quorum-ready create_transfers
        prepares into ONE device dispatch + ONE result fetch (reference
        pipelining collapsed onto the device the way the flagship
        benchmark K-fuses batches). Returns True if a group was
        dispatched; False -> the caller takes the per-op path."""
        if first_header.operation != int(Operation.create_transfers):
            return False
        run = []
        while len(run) < self.GROUP_MAX:
            e = self.pipeline.get(first_op + len(run))
            if (
                e is None
                or len(e["oks"]) < self.quorum_replication
                or e["header"].operation != int(Operation.create_transfers)
            ):
                break
            run.append(e)
            if self.commitment_log is not None and self.commitment_log.is_boundary(
                first_op + len(run) - 1
            ):
                # a commitment boundary ends its fused run: the group's
                # single device dispatch precedes every per-op
                # _commit_dispatch, so a mid-run boundary would
                # fingerprint state that already includes later ops
                break
        if len(run) < 2:
            return False
        handles = self.sm.commit_group_async(
            Operation.create_transfers,
            [(e["header"].timestamp, e["body"]) for e in run],
        )
        if handles is None:
            return False  # ineligible (hazard tier / spill / mode)
        for e, handle in zip(run, handles):
            h = e["header"]
            self._note_quorum(e)
            d = self._commit_dispatch(h, e["body"], handle=handle,
                                      lt=e.get("lt", 0))
            d["wal"] = e.get("wal")
            self._inflight.append(d)
            self.commit_min = self.commit_max = h.op
            self.commit_checksum = h.checksum
            del self.pipeline[h.op]
        self.group_stats.add("fused_ops", len(run))
        self.group_stats.add("fused_groups")
        self.flush_commits(keep=self.commit_window, only_ready=True)
        return True

    def _on_commit(self, header: Header) -> None:
        if header.view < self.view or self.is_primary:
            return
        self._primary_contact_tick = self.ticks
        self._commit_up_to(header.commit)

    def _commit_up_to(self, commit_max: int) -> None:
        self.commit_max = max(self.commit_max, commit_max)
        # Beyond-WAL lag: the ops we need have been overwritten in the
        # primary's ring (it keeps at most journal_slot_count, and
        # checkpoints every checkpoint_interval) — prepare repair cannot
        # progress; jump via checkpoint shipping instead (reference:
        # src/vsr/sync.zig — sync is not only a view-change concern).
        if (
            self.commit_max - self.commit_min
            >= self.cluster.checkpoint_interval
            and not self.is_primary
            # Rate limit: every commit heartbeat lands here while we lag,
            # and each request is answered with the FULL checkpoint —
            # unbounded amplification without a tick-cadence guard
            # (reference: sync requests ride timeouts, not messages).
            and self.ticks - self._sync_request_tick >= RETRY_TICKS
        ):
            self._sync_request_tick = self.ticks
            rq = Header(command=int(Command.request_sync_manifest))
            self._send(self.primary_index, rq)
            # fall through to WAL repair as well: at the boundary the
            # primary's checkpoint may not yet be ahead of our commit
            # (sync reply would be stale) while its ring still covers us
        while self.commit_min < self.commit_max:
            op = self.commit_min + 1
            if op > self.op:
                # Committed cluster-wide but we never prepared it (we were
                # down/partitioned): fetch it — the fill chains from our
                # head and advances self.op (lag catch-up; the reference's
                # state sync covers the beyond-one-WAL case).
                self._request_prepare(op, self.primary_index)
                return
            got = self.journal.read_prepare(op)
            if got is None:
                # journal gap (e.g. faulty slot): fetch from the primary
                self._request_prepare(op, self.primary_index)
                return
            header, body = got
            from tigerbeetle_tpu import constants as _constants

            if _constants.VERIFY and self.commit_checksum:
                # intensive tier (constants.VERIFY): the hash chain is
                # re-verified at the moment of commit, not only during
                # recovery — a journal slot swapped after its write (or a
                # repair that fetched the wrong timeline) dies here
                assert header.parent == self.commit_checksum, (
                    f"VERIFY: hash chain break at commit op {op}: "
                    f"parent {header.parent:#x} != "
                    f"commit_checksum {self.commit_checksum:#x}"
                )
            try:
                if self.commit_window > 0:
                    self._inflight.append(self._commit_dispatch(header, body))
                    self.flush_commits(keep=self.commit_window, only_ready=True)
                else:
                    self._commit_prepare(header, body)
            except GridBlockCorrupt as e:
                # stall; retry when the block heals (_on_block)
                if not self._request_block_repair([e.address]):
                    raise
                return
            self.commit_min = op
            self.commit_checksum = header.checksum
            pruned = self.pipeline.pop(op, None)  # prune if pipelined
            if pruned is not None:
                self._note_quorum(pruned)
            # backup-side prefetch/commit overlap: peek the next journaled
            # prepare (gated on a threaded executor + an active spilled
            # set — the read costs a WAL slot fetch, worthless when the
            # prefetch would no-op)
            spill = getattr(self.ledger, "spill", None)
            if (
                spill is not None and spill.spilled
                and spill.prefetch_enabled
                and self.commit_min < self.commit_max
            ):
                got2 = self.journal.read_prepare(op + 1)
                if got2 is not None:
                    self._spill_prefetch_body(got2[0], got2[1])

    def _commit_prepare(self, header: Header, body: bytes,
                        lt: int = 0) -> bytes | None:
        """Execute one prepare against the replicated state (identical on
        every replica — determinism is the consensus invariant). EVERY
        replica constructs and stores the reply in its client table
        (reference: src/vsr/client_replies.zig — replies are replicated so
        a post-view-change primary can answer duplicate requests); only the
        primary actually sends it. Returns the reply wire bytes."""
        return self._commit_finalize(
            self._commit_dispatch(header, body, lt=lt)
        )

    def _commit_dispatch(self, header: Header, body: bytes,
                         handle=None, lt: int = 0) -> dict:
        if lt:
            # fuse_hold leg: quorum reached -> dispatch entry (the
            # group-fuse hold + the end-of-pump deferral)
            self.latency.stamp(lt, LEG_FUSE)
        with self.tracer.span("replica.commit_dispatch", op=header.op,
                              trace=self._tid(header)), \
                self._h_dispatch.time():
            d = self._commit_dispatch_inner(header, body, handle)
        d["lt"] = lt
        if lt:
            self.latency.stamp(lt, LEG_DISPATCH)
        return d

    def _commit_dispatch_inner(self, header: Header, body: bytes,
                               handle=None) -> dict:
        """Stage 1: apply the prepare to the replicated state WITHOUT
        materializing device results (JAX async dispatch — create-op
        launches are queued and the host returns). Host-side effects that
        must be ordered (AOF, commit hooks, register sessions, the
        prepare-timestamp clamp) happen here, in op order. The
        state-machine dispatch runs FIRST: it may raise GridBlockCorrupt
        (spill reads), and the stall/retry path re-enters this method for
        the same op — AOF records and commit hooks must not duplicate.
        AOF still precedes the reply (sent at finalize)."""
        operation = Operation(header.operation)
        reply_body = None
        if handle is not None:
            # group commit already dispatched the state-machine work
            self.sm.prepare_timestamp = max(
                self.sm.prepare_timestamp, header.timestamp
            )
        elif operation == Operation.register:
            # At clients_max, evict the OLDEST session (lowest session
            # number — deterministic, so every replica evicts the same
            # one) and tell that client (reference:
            # src/vsr/replica.zig:3758-3860 + eviction command,
            # src/vsr.zig:136). Its slot is then free for the newcomer.
            prior = self.client_table.pop(header.client, None)
            if prior is not None:
                # Duplicate register EXECUTING (a view change can carry
                # the same client's register twice in the surviving log):
                # the re-insert below replaces the entry, so release its
                # slot or it leaks from the free list until the next
                # restart's rebuild (the old O(sessions) scan self-healed
                # here; the incremental list must be told). Popped BEFORE
                # the release/alloc: with the list still unbuilt (first
                # register after a restart), release is a no-op and the
                # lazy rebuild must not count the replaced entry's slot
                # as owned.
                self._reply_slot_release(prior.get("slot"))
            elif len(self.client_table) >= self.cluster.clients_max:
                victim = min(
                    self.client_table,
                    key=lambda c: self.client_table[c]["session"],
                )
                evicted = self.client_table.pop(victim)
                self._reply_slot_release(evicted.get("slot"))
                if self.is_primary:
                    self._send_eviction(victim)
                if self.ingress_evict_hook is not None:
                    self.ingress_evict_hook(victim)
            self.client_table[header.client] = {
                "session": header.op,
                "request": 0,
                "reply": None,
                # reply-persistence slot (reference: client_replies.zig);
                # None once every slot is owned (many-session ingress
                # mode: reply_slot_count < clients_max)
                "slot": self._reply_slot_alloc(),
            }
            reply_body = header.op.to_bytes(8, "little")  # session number
        else:
            handle = self.sm.commit_async(operation, header.timestamp, body)
            self.sm.prepare_timestamp = max(
                self.sm.prepare_timestamp, header.timestamp
            )
            # conflict-wave decision plumbed off the dispatch handle (the
            # backend's planner ran inside commit_async): surfaced as
            # commit.group.wave_* so the [stats] line and the bench can
            # attribute dependent-transfer ops to the wave path
            plan = self.sm.handle_plan(handle)
            if plan is not None and plan[0] == "waves":
                self.group_stats.add("wave_ops")
                self.group_stats.add("wave_dispatches", plan[1])
        clog = self.commitment_log
        if clog is not None and clog.is_boundary(header.op):
            # fold the backend's state fingerprint into the commitment
            # chain at dispatch: every op <= header.op has dispatched,
            # none after (group runs break at boundaries). Idempotent
            # across the stall/retry re-entry and WAL-tail replay — a
            # re-record with a different fingerprint raises naming this
            # checkpoint.
            clog.record(header.op, self.sm.backend.fingerprint())
        if self.commit_hook is not None:
            self.commit_hook(header, body)
        if self.aof is not None:
            self.aof.append(header, body)  # durable before the reply
        return {
            "header": header,
            "handle": handle,
            "reply_body": reply_body,
            "to_client": self.is_primary,
            # prepare body kept through finalize only for the CDC live
            # tail and the dual-commit device applier (references the
            # pipeline/journal hold anyway — but don't pin 1 MiB per
            # in-flight entry when neither consumer is on)
            "body": body
            if (self.cdc_hook is not None or self._dual_apply)
            else None,
        }

    def _commit_finalize(self, entry: dict) -> bytes | None:
        lt = entry.get("lt", 0)
        if lt:
            # commit_wait leg: dispatch exit -> finalize entry (async
            # commit window: the in-flight queue + device compute)
            self.latency.stamp(lt, LEG_WAIT)
        with self.tracer.span("replica.commit_finalize",
                              op=entry["header"].op,
                              trace=self._tid(entry["header"])), \
                self._h_finalize.time():
            wire = self._commit_finalize_inner(entry)
        if lt:
            self.latency.stamp(lt, LEG_FINALIZE)
        return wire

    def _commit_finalize_inner(self, entry: dict) -> bytes | None:
        """Stage 2: materialize the results (drains the device batch),
        build + store the reply, persist the client-replies slot."""
        header = entry["header"]
        wal = entry.get("wal")
        if wal is not None:
            wal.result()  # WAL durable before the reply leaves
        reply_body = entry["reply_body"]
        if reply_body is None:
            reply_body = self.sm.commit_finish(entry["handle"])
        reply = Header(
            command=int(Command.reply),
            client=header.client,
            context=header.context,
            request=header.request,
            op=header.op,
            commit=header.op,
            timestamp=header.timestamp,
            operation=header.operation,
        )
        reply.set_checksum_body(reply_body)
        reply.replica = self.replica
        reply.view = self.view
        reply.set_checksum()
        if self.reply_hook is not None:
            self.reply_hook(header, reply.checksum_body)
        if self.cdc_retain:
            # bounded reply ring for CDC resume-from-WAL: evict the op
            # that fell out of the journal ring this step. CREATE ops
            # only — their replies are tiny sparse failure structs; a
            # lookup's reply is a dense row dump up to message_size_max
            # that the change stream never reads (no records for reads)
            if header.operation in _CDC_RETAIN_OPS:
                self.cdc_replies[header.op] = reply_body
            self.cdc_replies.pop(
                header.op - self.cluster.journal_slot_count, None
            )
        if self.cdc_hook is not None:
            # once per op per process (finalize runs once; the dispatch
            # retry path never reaches here twice), in op order (the
            # in-flight queue drains FIFO)
            self.cdc_hook(header, entry.get("body"), reply_body)
        if (
            self._dual_apply
            and header.operation in _CDC_RETAIN_OPS  # the two create ops
            and isinstance(entry["handle"], tuple)
        ):
            # Dual-commit apply seam: the device applier follows the
            # COMMITTED op stream — enqueue exactly once, at finalize
            # (reply built, WAL durable), in op order, with the native
            # engine's dense codes for the host side of the hash-log
            # ring. Zero-copy: the rows view aliases the prepare body
            # bytes and the codes array is the one the engine filled.
            self.ledger.apply_commit(
                header.op,
                Operation(header.operation),
                header.timestamp,
                np.frombuffer(
                    entry["body"],
                    dtype=ACCOUNT_DTYPE
                    if header.operation == int(Operation.create_accounts)
                    else TRANSFER_DTYPE,
                ),
                entry["handle"][1].codes,
                prepare_checksum=header.checksum,
                trace=self._tid(header),
                # device-apply lag is a PARALLEL lane of the anatomy
                # (the reply does not wait for it): sampled ops carry
                # their enqueue stamp so the apply loop can observe
                # enqueue->upload into latency.device_apply_lag_us
                lat_ns=perf_counter_ns() if entry.get("lt") else 0,
            )
        if (
            self._dual_apply
            and self.commitment_log is not None
            and self.commitment_log.is_boundary(header.op)
        ):
            # commitment probe: finalizes run in op order, so the device
            # applier's queue holds exactly the creates <= this boundary
            # when the probe lands — the apply thread stashes the device
            # twin's lazy fingerprint there; finalize() compares it
            # against the chain's host fingerprint per checkpoint.
            fp = self.commitment_log.fingerprint_at(header.op)
            if fp is not None and hasattr(self.ledger, "commitment_probe"):
                self.ledger.commitment_probe(header.op, fp)
        self.cdc_commit_min = header.op
        wire = reply.to_bytes() + reply_body
        tentry = self.client_table.get(header.client)
        if tentry is not None:
            tentry["request"] = header.request
            tentry["reply"] = wire
            tentry["reply_checksum"] = reply.checksum
            if tentry.get("slot") is not None:
                # persist so a post-restart primary can answer a duplicate
                # with the ORIGINAL bytes (reference: client_replies.zig);
                # in window mode the O_DSYNC slot write rides the FIFO IO
                # worker — reply repair tolerates a lost tail write (the
                # checksum-validated restore reads it as absent)
                if self.commit_window > 0:
                    self.journal.submit_io(
                        self.client_replies.write, tentry["slot"], wire
                    )
                else:
                    self.client_replies.write(tentry["slot"], wire)
        return wire

    @staticmethod
    def _handle_ready(h) -> bool:
        """Readiness probe for a commit handle (shared by the event loop's
        commits_ready and the non-blocking flush)."""
        if h is None or isinstance(h, bytes):
            return True
        p = h[1]
        if hasattr(p, "is_ready"):
            return bool(p.is_ready())
        probe = getattr(p, "summary", None)
        if probe is None and getattr(p, "group", None) is not None:
            probe = p.group.summary
        if probe is None:
            probe = p.results
        is_ready = getattr(probe, "is_ready", None)
        return bool(is_ready()) if is_ready is not None else True

    def _entry_ready(self, entry: dict) -> bool:
        wal = entry.get("wal")
        if wal is not None and not wal.done():
            return False  # finalize would block on the WAL fsync
        return self._handle_ready(entry["handle"])

    def flush_commits(self, keep: int = 0, only_ready: bool = False) -> None:
        """Finalize queued async commits (oldest first) until at most
        `keep` remain in flight. The event loop calls this when the bus has
        no more incoming frames; _maybe_commit_pipeline calls it with
        keep=commit_window AND only_ready=True — the dispatch path must not
        BLOCK on its own group's device compute (that serialized recv of
        the next window behind execution of this one). A hard cap of
        4x keep still blocks to bound the in-flight window."""
        if only_ready:
            hard_cap = 4 * keep if keep else (1 << 30)
            while (
                len(self._inflight) > keep
                and (
                    self._entry_ready(self._inflight[0])
                    or len(self._inflight) > hard_cap
                )
            ):
                entry = self._inflight.popleft()
                wire = self._commit_finalize(entry)
                if wire is not None and entry["to_client"]:
                    lt = entry.get("lt", 0)
                    if lt:
                        h = entry["header"]
                        self.latency.egress(lt, h.client, h.context)
                    self.network.send(
                        self.replica, entry["header"].client, wire
                    )
            return
        n_final = len(self._inflight) - keep
        if n_final <= 0:
            return
        if n_final > 1:
            # one device->host round trip for the whole window, not one
            # per batch (high-latency transports)
            self.sm.commit_finish_many([
                e["handle"]
                for e in list(self._inflight)[:n_final]
                if e["handle"] is not None
            ])
        while len(self._inflight) > keep:
            entry = self._inflight.popleft()
            wire = self._commit_finalize(entry)
            if wire is not None and entry["to_client"]:
                lt = entry.get("lt", 0)
                if lt:
                    h = entry["header"]
                    self.latency.egress(lt, h.client, h.context)
                self.network.send(self.replica, entry["header"].client, wire)

    def pump_commits(self) -> None:
        """Event-loop hook: commit whatever reached quorum during this
        pump turn (deferred from _on_request so same-turn arrivals fuse
        into one group dispatch). A short quorum-ready run may additionally
        be HELD for up to fuse_window_ns (see _fuse_hold) so that requests
        arriving a few hundred microseconds apart still coalesce into one
        fused dispatch — the difference between a ~0.4 and a ~0.9 group-
        commit hit rate under concurrent session clients."""
        if not (self.status == "normal" and self.is_primary and self.pipeline):
            self._fuse_clear()
            return
        if self._fuse_hold():
            return
        self._maybe_commit_pipeline()

    def _fuse_clear(self) -> None:
        """End the fuse-window hold: close its trace span and record the
        hold duration (Time-seam clock, so deterministic harnesses stay
        deterministic)."""
        if self._fuse_started is not None:
            self._h_fuse.observe(
                (self.time.monotonic() - self._fuse_started) / 1000.0
            )
        self._fuse_started = None
        if self._fuse_token:
            self.tracer.stop(self._fuse_token)
            self._fuse_token = 0

    def _fuse_hold(self) -> bool:
        """True while the fuse window is holding a short quorum-ready run
        of create_transfers prepares open for more arrivals. Never holds
        when the engine is idle (_inflight empty): deferral then buys no
        fusion worth starving the engine for. The hold is bounded by
        fuse_window_ns from the run's first deferral."""
        if (
            self.commit_window <= 0
            or self.fuse_window_ns <= 0
            or not self._inflight
        ):
            self._fuse_clear()
            return False
        run = 0
        first = self.commit_min + 1
        while run < self.GROUP_MAX:
            e = self.pipeline.get(first + run)
            if (
                e is None
                or len(e["oks"]) < self.quorum_replication
                or e["header"].operation != int(Operation.create_transfers)
            ):
                break
            run += 1
        if run == 0 or run >= self.GROUP_MAX:
            if run >= self.GROUP_MAX and self._fuse_started is not None \
                    and self.fuse_autotune:
                # the held run filled before the window expired: the
                # window over-covers the arrival spacing — shed a little
                # hold latency (multiplicative-decrease half of AIMD)
                self.fuse_window_ns = max(
                    self.fuse_window_min_ns, int(self.fuse_window_ns * 0.95)
                )
            self._fuse_clear()
            return False
        now = self.time.monotonic()
        if self._fuse_started is None:
            self._fuse_started = now
            self.group_stats.add("fuse_holds")
            # tagged with the FIRST held op's trace id: clicking the op
            # in Perfetto shows the hold it waited out
            self._fuse_token = self.tracer.start(
                "replica.fuse_hold", run=run,
                trace=self._tid(self.pipeline[first]["header"]),
            )
            return True
        if now - self._fuse_started < self.fuse_window_ns:
            return True
        # hold EXPIRED with the run still short: the window lost the race
        # against this workload's arrival spacing (the r05 driver's 0.46
        # hit rate vs 0.85 in the CPU A/B was exactly this, invisible
        # without the counter) — record it, and autotune widens
        self.group_stats.add("fuse_expired")
        if self.fuse_autotune:
            self.fuse_window_ns = min(
                self.fuse_window_max_ns, int(self.fuse_window_ns * 1.25)
            )
        self._fuse_clear()
        return False

    def commits_ready(self) -> bool:
        """True when the NEWEST in-flight commit's device results are
        computed — batches execute in order, so the whole window is then
        fetchable in one transfer. The event loop uses this to defer
        flushes until one round trip can drain everything (fetching
        mid-compute would serialize a round trip per batch)."""
        if not self._inflight:
            return False
        return self._handle_ready(self._inflight[-1]["handle"])

    # ------------------------------------------------------------------
    # view change (reference: src/vsr/replica.zig:1595-1924)
    # ------------------------------------------------------------------

    def _start_view_change(self, new_view: int) -> None:
        assert new_view > self.view
        if self.standby:
            # a standby cannot vote a view in; it re-syncs via the
            # authoritative start_view instead
            rsv = Header(
                command=int(Command.request_start_view), view=new_view
            )
            self._send(new_view % self.replica_count, rsv)
            self._primary_contact_tick = self.ticks
            self._recover_tick = self.ticks
            return
        if self.status == "view_change" and new_view <= self.view_candidate:
            return
        self.flush_commits()  # no async commits across a status change
        self.status = "view_change"
        self.view_candidate = new_view
        self._svc_votes = {self.replica}
        self._dvc = {}
        self._adopt = None
        self._catchup = {}
        self._drop_quorum_tokens()
        self.pipeline = {}
        self._pending_prepares = {}
        self._repair_wanted.clear()
        self._vc_tick = self.ticks
        self._vc_retries = 0
        # Durable BEFORE voting: a crash-restart must not regress into an
        # abandoned view and form an intersecting quorum there.
        persist_view(self.superblock, new_view, self.log_view)
        svc = Header(command=int(Command.start_view_change), view=new_view)
        self._broadcast(svc)
        self._check_svc_quorum()

    def _on_start_view_change(self, header: Header) -> None:
        if self.standby or header.view <= self.view:
            return
        if self.status != "view_change" or header.view > self.view_candidate:
            self._start_view_change(header.view)
        if header.view == self.view_candidate:
            self._svc_votes.add(header.replica)
            self._check_svc_quorum()

    def _check_svc_quorum(self) -> None:
        if (
            self.status == "view_change"
            and len(self._svc_votes) >= self.quorum_view_change
        ):
            self._send_do_view_change()

    def _suffix_headers(self) -> list[Header]:
        """Headers of ops (commit_min, op] — the log suffix an SV carries.
        Only REAL headers (never nack markers — a backup would adopt one as
        a real header and wedge waiting for a prepare whose checksum can
        never match). A suffix op whose BODY is torn (in-place media fault
        after adoption verified it) still contributes its redundant-ring
        header — authoritative evidence — and we repair the body from
        backups rather than crashing (any acker can serve it; SV receivers
        independently fetch bodies from every peer in _begin_adoption)."""
        out = []
        for op in range(self.commit_min + 1, self.op + 1):
            got = self.journal.read_prepare(op)
            if got is not None:
                out.append(got[0])
                continue
            h = self.journal.get_header(op)
            assert h is not None, f"SV suffix op {op}: no journal evidence"
            out.append(h)
            for r in range(self.replica_count):
                if r != self.replica:
                    self._request_prepare(op, r)
        return out

    def _dvc_suffix_headers(self) -> tuple[list[Header], int]:
        """(suffix, head) for a DVC: the log evidence in the JOURNAL —
        NOT the in-memory head, which an earlier unfinished adoption may
        have truncated to commit_min while acked prepares still sit intact
        in the WAL (advertising only self.op there would falsely nack
        them). Per op:

        - readable prepare -> its header;
        - TORN slot (redundant header survives, body lost) -> that header:
          authoritative, peers repair the body after adoption (protocol-
          aware recovery, reference: src/vsr.zig:302-304);
        - BLANK slot -> an explicit NACK marker, counted toward the nack
          quorum that authorizes truncation.

        The scan extends past self.op while journal evidence continues; a
        run of blanks longer than the pipeline depth terminates it (the
        primary never has more than pipeline_prepare_queue_max prepares in
        flight, so a longer gap cannot hide acked ops)."""
        out: list[Header] = []
        head = self.commit_min
        gap_max = self.cluster.pipeline_prepare_queue_max
        op = self.commit_min
        limit = self.commit_min + self.cluster.journal_slot_count
        pending: list[Header] = []
        while op < limit:
            op += 1
            # the in-memory redundant-header mirror is authoritative for
            # slot EVIDENCE (valid and torn slots both carry their header;
            # the valid/torn distinction only matters for body repair,
            # which happens after adoption) — no prepare-ring reads here
            h = self.journal.get_header(op)
            if h is not None:
                out.extend(pending)
                pending = []
                out.append(h)
                head = op
                continue
            if len(pending) >= gap_max and op > self.op:
                break  # gap too long to hide acked ops: the log ends
            nack = Header(
                command=int(Command.prepare), op=op, operation=OP_NACK
            )
            nack.set_checksum_body(b"")
            nack.set_checksum()
            pending.append(nack)
        head = max(head, self.op)
        # markers for trailing blanks up to our known head still count
        out.extend(m for m in pending if m.op <= head)
        return out, head

    def _send_do_view_change(self) -> None:
        new_primary = self.view_candidate % self.replica_count
        suffix, head = self._dvc_suffix_headers()
        body = b"".join(h.to_bytes() for h in suffix)
        # DVC fields (reference: do_view_change sets request=log_view,
        # commit=commit_min, op=log head; the suffix headers ride the body).
        dvc = Header(
            command=int(Command.do_view_change),
            view=self.view_candidate,
            request=self.log_view,
            op=head,
            commit=self.commit_min,
            parent=self.commit_checksum,
            timestamp=self.checkpoint_op,  # my WAL covers (this, op]
        )
        if new_primary == self.replica:
            self._record_dvc(self.replica, dvc, suffix)
        else:
            self._send(new_primary, dvc, body)

    def _on_do_view_change(self, header: Header, body: bytes) -> None:
        if header.view % self.replica_count != self.replica:
            return
        if header.view <= self.view or header.view < self.view_candidate:
            return  # stale DVC (that view change already completed)
        if self.status != "view_change" or header.view > self.view_candidate:
            self._start_view_change(header.view)
        suffix = [
            Header.from_bytes(body[i : i + HEADER_SIZE])
            for i in range(0, len(body), HEADER_SIZE)
        ]
        self._record_dvc(header.replica, header, suffix)

    def _record_dvc(self, replica: int, header: Header, suffix: list[Header]):
        self._dvc[replica] = (header, suffix)
        if self._adopt is not None or len(self._dvc) < self.quorum_view_change:
            return
        # Choose the best log: max (log_view, op) (reference: :2845-2977
        # primary_receive_do_view_change), then MERGE per op with nack
        # accounting (protocol-aware recovery, reference:
        # src/vsr.zig:302-304): an op survives if any best-log_view DVC
        # carries its header (torn bodies repair later); it truncates only
        # under a NACK QUORUM proving no replication quorum ever acked it;
        # otherwise the change waits for more DVCs — guessing could drop
        # an acked op (data loss) or resurrect a superseded one.
        best_replica, (best_h, _) = max(
            self._dvc.items(),
            key=lambda kv: (kv[1][0].request, kv[1][0].op),
        )
        # Nack soundness rests on the WAL durability order (journal.py):
        # the redundant header is durable BEFORE an op is ever acked, so an
        # acked op's header survives a torn body and its slot reports TORN
        # (header, no nack), never BLANK. A false nack therefore requires
        # post-durability media corruption of BOTH rings' sectors on one
        # replica COMBINED with the loss of every other acker — beyond-f
        # faults, the same residual the reference accepts (its simulator
        # fault atlas guarantees one surviving copy cluster-wide,
        # reference: src/testing/storage.zig:1-25).
        best_log_view = best_h.request
        base = best_h.commit
        op_max = max(h.op for h, _ in self._dvc.values())
        commit_max = max(h.commit for h, _ in self._dvc.values())
        nack_quorum = self.replica_count - self.quorum_replication + 1
        merged: dict[int, Header] = {}
        undecided_op = None
        for op in range(base + 1, op_max + 1):
            header_for_op = None
            nacks = 0
            for _r, (h, sfx) in self._dvc.items():
                if h.op < op or op <= h.commit:
                    if h.op < op:
                        nacks += 1  # implicit nack: log head below op
                    continue
                m = next((x for x in sfx if x.op == op), None)
                if m is None or m.operation == OP_NACK:
                    nacks += 1
                elif h.request == best_log_view and header_for_op is None:
                    # headers are unique per (log_view, op): any best-
                    # log_view copy is THE header (lower log_views may hold
                    # superseded prepares and must not contribute)
                    header_for_op = m
            if header_for_op is not None:
                merged[op] = header_for_op
            elif nacks >= nack_quorum and op > commit_max:
                break  # provably never acked by a quorum: truncate here
            else:
                # No surviving header, and either no nack quorum OR a DVC
                # proves the op COMMITTED (op <= commit_max, in which case
                # nacks are contradictory evidence — truncating would drop
                # an executed op and diverge): refuse to guess.
                undecided_op = op
                break
        if undecided_op is not None:
            if len(self._dvc) < self.replica_count:
                # Wait: a further DVC can still decide this op. If the
                # missing replicas are down, the change re-runs on timeout
                # with the same inputs — a deliberate LIVENESS sacrifice:
                # with evidence destroyed on the live set, guessing either
                # way risks dropping or resurrecting a possible commit
                # (PAR blocks rather than guesses; service resumes when a
                # decisive replica returns).
                return
            raise RuntimeError(
                f"view change: op {undecided_op} unrecoverable — no "
                f"surviving header, {nacks} nacks "
                f"(quorum {nack_quorum}), commit_max {commit_max}; "
                "a possible commit would be lost (protocol-aware recovery "
                "refuses to guess)"
            )
        self._begin_adoption(
            base=base,
            suffix=merged,
            commit_max=commit_max,
            src=best_replica,
            tip=best_h.parent,  # checksum of the op at `base`
            src_checkpoint=best_h.timestamp,
        )

    # -- adoption: two phases shared by the new primary (from DVCs) and
    # backups (from SV). Phase 1: chain catch-up of COMMITTED ops up to the
    # suffix base (hash-chain-verified fills from `src`). Phase 2: the
    # suffix itself, checksum-verified against the adopted headers. --

    def _begin_adoption(self, base: int, suffix: dict[int, Header],
                        commit_max: int, src: int, tip: int,
                        src_checkpoint: int = 0) -> None:
        self._adopt = suffix
        self._adopt_base = base
        self._adopt_tip = tip  # expected checksum of the prepare at `base`
        self._adopt_commit_max = max(commit_max, base)
        self._adopt_src = src
        self._adopt_src_checkpoint = src_checkpoint
        self._catchup: dict[int, tuple[Header, bytes]] = {}
        self._catchup_no_local = False
        # Truncate the log head to the committed prefix: our uncommitted
        # tail may diverge from the chosen log (its journal rows remain and
        # are revalidated by checksum below; the state machine never saw
        # them — only committed ops execute).
        self.op = self.commit_min
        self.parent_checksum = self.commit_checksum
        self._fast_forward(limit=base)
        self._verify_catchup_tip()
        self._request_catchup_window()
        for op, h in suffix.items():
            if op <= self.commit_min:
                continue  # our committed prefix already covers it
            got = self.journal.read_prepare(op)
            if got is None or got[0].checksum != h.checksum:
                # Ask EVERY peer (not just the best-log source): the
                # adopted header may cover a slot whose BODY is torn on
                # the source itself (nack merge keeps such ops — any
                # replica that acked the prepare can serve it; fills are
                # checksum-verified so duplicates are harmless).
                for r in range(self.replica_count):
                    if r != self.replica:
                        self._request_prepare(op, r)
        self._try_finish_view_change()

    CATCHUP_WINDOW = 32

    def _request_catchup_window(self) -> None:
        """Pipeline catch-up fetches (serial round trips would make a long
        catch-up slower than the view-change timeout — livelock)."""
        if self._adopt_src == self.replica:
            return
        if self.commit_min < self._adopt_src_checkpoint:
            # Too far behind: the ops we need predate the source's
            # checkpoint (its WAL ring no longer covers them), and filling
            # more than a ring's worth would overwrite our own fills — jump
            # via state sync (checkpoint shipping) instead. commit_min (not
            # the advancing op) is the stable lag measure: the source's
            # guard bounds (src_op - src_checkpoint) within one ring, so
            # once we sync to its checkpoint every remaining fill fits
            # distinct slots.
            if self.ticks - self._sync_request_tick >= RETRY_TICKS:
                self._sync_request_tick = self.ticks
                rq = Header(command=int(Command.request_sync_manifest))
                self._send(self._adopt_src, rq)
            return
        hi = min(self._adopt_base, self.op + self.CATCHUP_WINDOW)
        for o in range(self.op + 1, hi + 1):
            if o not in self._repair_wanted and o not in self._catchup:
                self._request_prepare(o, self._adopt_src)

    def _verify_catchup_tip(self) -> None:
        """Our LOCAL chain up to the suffix base may include prepares the
        cluster discarded (we were the old primary) — locally consistent
        but wrong. The DVC/SV carries the true checksum of the op at the
        base (`tip`); on mismatch, restart catch-up from the committed
        prefix fetching everything from the source (remote fills overwrite
        the stale rows and are chain-verified from commit_checksum)."""
        if (
            self.op < self._adopt_base
            or self._adopt_base == 0
            or self._adopt_base <= self.commit_min  # we're at/ahead of base:
            # our committed prefix subsumes it (quorum intersection)
        ):
            return
        if self.parent_checksum != self._adopt_tip:
            self._catchup_no_local = True
            self.op = self.commit_min
            self.parent_checksum = self.commit_checksum
            self._repair_wanted.clear()
            self._catchup.clear()

    def _drain_catchup(self) -> None:
        while self.op < self._adopt_base:
            if not self._catchup_no_local:
                self._fast_forward(limit=self._adopt_base)
                self._verify_catchup_tip()
            got = self._catchup.pop(self.op + 1, None)
            if got is None:
                break
            header, body = got
            if header.parent != self.parent_checksum:
                # stale/wrong fill: re-request
                self._repair_wanted.discard(header.op)
                self._request_prepare(header.op, self._adopt_src)
                break
            self.journal.write_prepare(header, body)
            self.op = header.op
            self.parent_checksum = header.checksum
        if self.op >= self._adopt_base:
            self._verify_catchup_tip()

    def _fast_forward(self, limit: int) -> None:
        """Advance the log head through locally-journaled ops that chain
        correctly (avoids refetching what we already hold)."""
        while self.op < limit:
            got = self.journal.read_prepare(self.op + 1)
            if got is None or got[0].parent != self.parent_checksum:
                return
            self.op += 1
            self.parent_checksum = got[0].checksum

    def _on_repair_prepare(self, header: Header, body: bytes) -> None:
        """A prepare arriving while in view_change: either a chain catch-up
        fill below the suffix base or an adopted suffix prepare. Any
        accepted fill counts as view-change progress (resets the retry/
        escalation timer — a long catch-up must not be abandoned)."""
        if self._adopt is None:
            return
        if header.op <= self._adopt_base:
            if header.op <= self.op:
                return  # already have it
            self._repair_wanted.discard(header.op)
            self._catchup[header.op] = (header, body)
            self._vc_tick = self.ticks
            self._vc_retries = 0
            self._drain_catchup()
            self._request_catchup_window()
            self._try_finish_view_change()
            return
        want = self._adopt.get(header.op)
        if want is None or want.checksum != header.checksum:
            return
        self.journal.write_prepare(header, body)
        self._repair_wanted.discard(header.op)
        self._vc_tick = self.ticks
        self._vc_retries = 0
        self._try_finish_view_change()

    def _adoption_complete(self) -> bool:
        assert self._adopt is not None
        anchor = max(self.commit_min, self._adopt_base)
        if self.op < anchor:
            return False  # catch-up still in flight
        if (
            self._adopt_base > self.commit_min
            and self._adopt_base > 0
            and self.parent_checksum != self._adopt_tip
        ):
            return False  # local tail was stale; refetch in flight
        for op, h in self._adopt.items():
            if op <= self.commit_min:
                continue  # already committed; consistent by quorum math
            got = self.journal.read_prepare(op)
            if got is None or got[0].checksum != h.checksum:
                return False
        return True

    def _try_finish_view_change(self) -> None:
        if self._adopt is None or not self._adoption_complete():
            return
        new_primary = self.view_candidate % self.replica_count
        if new_primary == self.replica:
            self._finish_view_change(primary=True)
        else:
            self._finish_view_change(primary=False)

    def _finish_view_change(self, primary: bool) -> None:
        assert self._adopt is not None
        # The adopted log head: suffix ops above our committed prefix win;
        # otherwise whichever of (base, commit_min) is further.
        ops = sorted(o for o in self._adopt if o > self.commit_min)
        if ops:
            self.op = ops[-1]
            self.parent_checksum = self._adopt[ops[-1]].checksum
        elif self._adopt_base > self.commit_min:
            self.op = self._adopt_base
            self.parent_checksum = self._adopt_tip
        else:
            self.op = self.commit_min
            self.parent_checksum = self.commit_checksum
        self.view = self.view_candidate
        self.log_view = self.view
        persist_view(self.superblock, self.view, self.log_view)
        self.status = "normal"
        self._primary_contact_tick = self.ticks
        adopt_commit_max = self._adopt_commit_max
        self._adopt = None
        self._dvc = {}
        self._repair_wanted.clear()
        # The quorum decided the log ends at self.op: destroy journal
        # evidence above it, or the next _dvc_suffix_headers scan would
        # re-advertise superseded headers under our NEW log_view and a
        # truncated prepare could shadow a committed op (see
        # Journal.invalidate_above).
        self.journal.invalidate_above(self.op)
        if primary:
            suffix = self._suffix_headers()
            sv = Header(
                command=int(Command.start_view),
                view=self.view,
                op=self.op,
                commit=self.commit_min,
                parent=self.commit_checksum,  # checksum of op `commit`
                timestamp=self.checkpoint_op,  # my WAL covers (this, op]
            )
            self._broadcast(sv, b"".join(h.to_bytes() for h in suffix))
            # Commit the known-committed prefix FIRST, then refill the
            # pipeline with only the still-uncommitted tail (a stale
            # committed entry would poison retransmission and quorum
            # counting).
            self._commit_up_to(adopt_commit_max)
            for op in range(self.commit_min + 1, self.op + 1):
                got = self.journal.read_prepare(op)
                assert got is not None
                h, body = got
                self.pipeline[op] = {
                    "header": h, "body": body, "oks": {self.replica}
                }
        else:
            self._commit_up_to(adopt_commit_max)
            # Re-ack the adopted-but-uncommitted tail so the new primary
            # can reach quorum and commit it in the new view.
            for op in range(self.commit_min + 1, self.op + 1):
                got = self.journal.read_prepare(op)
                if got is not None:
                    self._ack_prepare(got[0])

    def _on_start_view(self, header: Header, body: bytes) -> None:
        if header.view < self.view:
            return
        if header.view == self.view and (
            self.is_primary or header.replica != self.primary_index
        ):
            return  # same-view SV only from the view's primary (requested
            # re-adoption: a backup with a stale tail asks for one)
        suffix = [
            Header.from_bytes(body[i : i + HEADER_SIZE])
            for i in range(0, len(body), HEADER_SIZE)
        ]
        self.flush_commits()  # no async commits across a status change
        self.status = "view_change"
        self.view_candidate = header.view
        self._drop_quorum_tokens()
        self.pipeline = {}
        self._pending_prepares = {}
        self._repair_wanted.clear()
        self._vc_tick = self.ticks  # fresh adoption: reset retry state so
        self._vc_retries = 0  # stale counters can't abandon it instantly
        persist_view(self.superblock, header.view, self.log_view)
        self._begin_adoption(
            base=header.commit,
            suffix={h.op: h for h in suffix},
            commit_max=header.commit,
            src=header.replica,
            tip=header.parent,
            src_checkpoint=header.timestamp,
        )

    def _on_request_start_view(self, header: Header) -> None:
        # Serve any requester at or below our view (a recovering/stale
        # replica catches up from the authoritative current SV).
        if not self.is_primary or header.view > self.view:
            return
        suffix = self._suffix_headers()
        sv = Header(
            command=int(Command.start_view),
            view=self.view,
            op=self.op,
            commit=self.commit_min,
            parent=self.commit_checksum,  # checksum of op `commit`
            timestamp=self.checkpoint_op,  # my WAL covers (this, op]
        )
        self._send(
            header.replica, sv, b"".join(h.to_bytes() for h in suffix)
        )
