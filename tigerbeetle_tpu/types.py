"""Core data model: Account, Transfer, flags, result codes.

Byte-layout-compatible with the reference's extern structs
(reference: src/tigerbeetle.zig:7-104 — 128-byte little-endian, no padding).
u128 fields are stored as two little-endian u64 limbs (lo, hi), which matches
the reference's in-memory representation on little-endian targets.

The numpy structured dtypes here are the wire format AND the host-side store
format; device kernels consume/produce the same fields as struct-of-arrays
u64/u32 columns.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np

from tigerbeetle_tpu.constants import U64_MAX, U128_MAX

# --- flags (reference: src/tigerbeetle.zig:42-62, 91-104) ---


class AccountFlags(enum.IntFlag):
    linked = 1 << 0
    debits_must_not_exceed_credits = 1 << 1
    credits_must_not_exceed_debits = 1 << 2

    @staticmethod
    def padding_mask() -> int:
        return 0xFFFF & ~0b111


class TransferFlags(enum.IntFlag):
    linked = 1 << 0
    pending = 1 << 1
    post_pending_transfer = 1 << 2
    void_pending_transfer = 1 << 3
    balancing_debit = 1 << 4
    balancing_credit = 1 << 5

    @staticmethod
    def padding_mask() -> int:
        return 0xFFFF & ~0b111111


# --- result codes (reference: src/tigerbeetle.zig:109-229) ---
# Error codes are ordered by descending precedence; the numeric values are part
# of the wire protocol and must match the reference exactly.


class CreateAccountResult(enum.IntEnum):
    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_field = 4
    reserved_flag = 5
    id_must_not_be_zero = 6
    id_must_not_be_int_max = 7
    flags_are_mutually_exclusive = 8
    debits_pending_must_be_zero = 9
    debits_posted_must_be_zero = 10
    credits_pending_must_be_zero = 11
    credits_posted_must_be_zero = 12
    ledger_must_not_be_zero = 13
    code_must_not_be_zero = 14
    exists_with_different_flags = 15
    exists_with_different_user_data_128 = 16
    exists_with_different_user_data_64 = 17
    exists_with_different_user_data_32 = 18
    exists_with_different_ledger = 19
    exists_with_different_code = 20
    exists = 21


class CreateTransferResult(enum.IntEnum):
    ok = 0
    linked_event_failed = 1
    linked_event_chain_open = 2
    timestamp_must_be_zero = 3
    reserved_flag = 4
    id_must_not_be_zero = 5
    id_must_not_be_int_max = 6
    flags_are_mutually_exclusive = 7
    debit_account_id_must_not_be_zero = 8
    debit_account_id_must_not_be_int_max = 9
    credit_account_id_must_not_be_zero = 10
    credit_account_id_must_not_be_int_max = 11
    accounts_must_be_different = 12
    pending_id_must_be_zero = 13
    pending_id_must_not_be_zero = 14
    pending_id_must_not_be_int_max = 15
    pending_id_must_be_different = 16
    timeout_reserved_for_pending_transfer = 17
    amount_must_not_be_zero = 18
    ledger_must_not_be_zero = 19
    code_must_not_be_zero = 20
    debit_account_not_found = 21
    credit_account_not_found = 22
    accounts_must_have_the_same_ledger = 23
    transfer_must_have_the_same_ledger_as_accounts = 24
    pending_transfer_not_found = 25
    pending_transfer_not_pending = 26
    pending_transfer_has_different_debit_account_id = 27
    pending_transfer_has_different_credit_account_id = 28
    pending_transfer_has_different_ledger = 29
    pending_transfer_has_different_code = 30
    exceeds_pending_transfer_amount = 31
    pending_transfer_has_different_amount = 32
    pending_transfer_already_posted = 33
    pending_transfer_already_voided = 34
    pending_transfer_expired = 35
    exists_with_different_flags = 36
    exists_with_different_debit_account_id = 37
    exists_with_different_credit_account_id = 38
    exists_with_different_amount = 39
    exists_with_different_pending_id = 40
    exists_with_different_user_data_128 = 41
    exists_with_different_user_data_64 = 42
    exists_with_different_user_data_32 = 43
    exists_with_different_timeout = 44
    exists_with_different_code = 45
    exists = 46
    overflows_debits_pending = 47
    overflows_credits_pending = 48
    overflows_debits_posted = 49
    overflows_credits_posted = 50
    overflows_debits = 51
    overflows_credits = 52
    overflows_timeout = 53
    exceeds_credits = 54
    exceeds_debits = 55


class Operation(enum.IntEnum):
    """State machine operations (reference: src/state_machine.zig:208-214).

    Values < 128 are reserved for VSR (reference: src/constants.zig:38
    vsr_operations_reserved); state-machine ops start at 128.
    """

    # VSR-reserved (reference: src/vsr.zig:158-230):
    reserved = 0
    root = 1
    register = 2
    reconfigure = 3
    # State machine:
    create_accounts = 128
    create_transfers = 129
    lookup_accounts = 130
    lookup_transfers = 131


# --- wire-format structured dtypes (128 bytes each, little-endian) ---

ACCOUNT_DTYPE = np.dtype(
    [
        ("id_lo", "<u8"),
        ("id_hi", "<u8"),
        ("debits_pending_lo", "<u8"),
        ("debits_pending_hi", "<u8"),
        ("debits_posted_lo", "<u8"),
        ("debits_posted_hi", "<u8"),
        ("credits_pending_lo", "<u8"),
        ("credits_pending_hi", "<u8"),
        ("credits_posted_lo", "<u8"),
        ("credits_posted_hi", "<u8"),
        ("user_data_128_lo", "<u8"),
        ("user_data_128_hi", "<u8"),
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("reserved", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)
assert ACCOUNT_DTYPE.itemsize == 128

TRANSFER_DTYPE = np.dtype(
    [
        ("id_lo", "<u8"),
        ("id_hi", "<u8"),
        ("debit_account_id_lo", "<u8"),
        ("debit_account_id_hi", "<u8"),
        ("credit_account_id_lo", "<u8"),
        ("credit_account_id_hi", "<u8"),
        ("amount_lo", "<u8"),
        ("amount_hi", "<u8"),
        ("pending_id_lo", "<u8"),
        ("pending_id_hi", "<u8"),
        ("user_data_128_lo", "<u8"),
        ("user_data_128_hi", "<u8"),
        ("user_data_64", "<u8"),
        ("user_data_32", "<u4"),
        ("timeout", "<u4"),
        ("ledger", "<u4"),
        ("code", "<u2"),
        ("flags", "<u2"),
        ("timestamp", "<u8"),
    ]
)
assert TRANSFER_DTYPE.itemsize == 128

CREATE_ACCOUNTS_RESULT_DTYPE = np.dtype([("index", "<u4"), ("result", "<u4")])
CREATE_TRANSFERS_RESULT_DTYPE = np.dtype([("index", "<u4"), ("result", "<u4")])
assert CREATE_ACCOUNTS_RESULT_DTYPE.itemsize == 8


def split_u128(x: int) -> tuple[int, int]:
    assert 0 <= x <= U128_MAX
    return x & U64_MAX, x >> 64


def join_u128(lo: int, hi: int) -> int:
    return (int(hi) << 64) | int(lo)


# --- host-side record classes (exact-integer semantics for the oracle) ---


@dataclasses.dataclass
class Account:
    """reference: src/tigerbeetle.zig:7-40."""

    id: int = 0
    debits_pending: int = 0
    debits_posted: int = 0
    credits_pending: int = 0
    credits_posted: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    reserved: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    def debits_exceed_credits(self, amount: int) -> bool:
        # reference: src/tigerbeetle.zig:31-34
        return bool(self.flags & AccountFlags.debits_must_not_exceed_credits) and (
            self.debits_pending + self.debits_posted + amount > self.credits_posted
        )

    def credits_exceed_debits(self, amount: int) -> bool:
        # reference: src/tigerbeetle.zig:36-39
        return bool(self.flags & AccountFlags.credits_must_not_exceed_debits) and (
            self.credits_pending + self.credits_posted + amount > self.debits_posted
        )

    def to_np(self) -> np.ndarray:
        return accounts_to_np([self])

    @staticmethod
    def from_np(row: np.ndarray) -> "Account":
        return Account(
            id=join_u128(row["id_lo"], row["id_hi"]),
            debits_pending=join_u128(row["debits_pending_lo"], row["debits_pending_hi"]),
            debits_posted=join_u128(row["debits_posted_lo"], row["debits_posted_hi"]),
            credits_pending=join_u128(row["credits_pending_lo"], row["credits_pending_hi"]),
            credits_posted=join_u128(row["credits_posted_lo"], row["credits_posted_hi"]),
            user_data_128=join_u128(row["user_data_128_lo"], row["user_data_128_hi"]),
            user_data_64=int(row["user_data_64"]),
            user_data_32=int(row["user_data_32"]),
            reserved=int(row["reserved"]),
            ledger=int(row["ledger"]),
            code=int(row["code"]),
            flags=int(row["flags"]),
            timestamp=int(row["timestamp"]),
        )


@dataclasses.dataclass
class Transfer:
    """reference: src/tigerbeetle.zig:64-89."""

    id: int = 0
    debit_account_id: int = 0
    credit_account_id: int = 0
    amount: int = 0
    pending_id: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    timeout: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    def to_np(self) -> np.ndarray:
        return transfers_to_np([self])

    @staticmethod
    def from_np(row: np.ndarray) -> "Transfer":
        return Transfer(
            id=join_u128(row["id_lo"], row["id_hi"]),
            debit_account_id=join_u128(row["debit_account_id_lo"], row["debit_account_id_hi"]),
            credit_account_id=join_u128(
                row["credit_account_id_lo"], row["credit_account_id_hi"]
            ),
            amount=join_u128(row["amount_lo"], row["amount_hi"]),
            pending_id=join_u128(row["pending_id_lo"], row["pending_id_hi"]),
            user_data_128=join_u128(row["user_data_128_lo"], row["user_data_128_hi"]),
            user_data_64=int(row["user_data_64"]),
            user_data_32=int(row["user_data_32"]),
            timeout=int(row["timeout"]),
            ledger=int(row["ledger"]),
            code=int(row["code"]),
            flags=int(row["flags"]),
            timestamp=int(row["timestamp"]),
        )


def accounts_to_np(accounts: list[Account]) -> np.ndarray:
    out = np.zeros(len(accounts), dtype=ACCOUNT_DTYPE)
    for i, a in enumerate(accounts):
        out[i]["id_lo"], out[i]["id_hi"] = split_u128(a.id)
        out[i]["debits_pending_lo"], out[i]["debits_pending_hi"] = split_u128(a.debits_pending)
        out[i]["debits_posted_lo"], out[i]["debits_posted_hi"] = split_u128(a.debits_posted)
        out[i]["credits_pending_lo"], out[i]["credits_pending_hi"] = split_u128(
            a.credits_pending
        )
        out[i]["credits_posted_lo"], out[i]["credits_posted_hi"] = split_u128(a.credits_posted)
        out[i]["user_data_128_lo"], out[i]["user_data_128_hi"] = split_u128(a.user_data_128)
        out[i]["user_data_64"] = a.user_data_64
        out[i]["user_data_32"] = a.user_data_32
        out[i]["reserved"] = a.reserved
        out[i]["ledger"] = a.ledger
        out[i]["code"] = a.code
        out[i]["flags"] = a.flags
        out[i]["timestamp"] = a.timestamp
    return out


def transfers_to_np(transfers: list[Transfer]) -> np.ndarray:
    out = np.zeros(len(transfers), dtype=TRANSFER_DTYPE)
    for i, t in enumerate(transfers):
        out[i]["id_lo"], out[i]["id_hi"] = split_u128(t.id)
        out[i]["debit_account_id_lo"], out[i]["debit_account_id_hi"] = split_u128(
            t.debit_account_id
        )
        out[i]["credit_account_id_lo"], out[i]["credit_account_id_hi"] = split_u128(
            t.credit_account_id
        )
        out[i]["amount_lo"], out[i]["amount_hi"] = split_u128(t.amount)
        out[i]["pending_id_lo"], out[i]["pending_id_hi"] = split_u128(t.pending_id)
        out[i]["user_data_128_lo"], out[i]["user_data_128_hi"] = split_u128(t.user_data_128)
        out[i]["user_data_64"] = t.user_data_64
        out[i]["user_data_32"] = t.user_data_32
        out[i]["timeout"] = t.timeout
        out[i]["ledger"] = t.ledger
        out[i]["code"] = t.code
        out[i]["flags"] = t.flags
        out[i]["timestamp"] = t.timestamp
    return out
