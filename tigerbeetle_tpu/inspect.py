"""`tigerbeetle inspect` — offline data-file and live-state introspection.

The reference ships `tigerbeetle inspect` (reference:
src/tigerbeetle/inspect.zig): when something is wrong ON DISK, the
operator decodes the data file directly — superblock copies with checksum
verdicts, WAL ring slots with torn-write diagnosis, client-reply slots,
the grid free set, and the LSM manifest — without starting (or being able
to start) a replica. This is that tool over our zones
(io/storage.py: superblock | wal_headers | wal_prepares | client_replies
| grid), plus a LIVE mode that asks a running replica for its
[stats]-registry snapshot over the wire (Command.request_stats).

Every decoder is a pure read: nothing here ever writes to the data file,
so inspecting a corrupt file cannot make it worse. Reports are plain
dicts (the CLI renders them as text or `--json`), so tests assert on the
same structures operators read.

Geometry: the fixed zones (superblock, WAL rings, client replies) derive
from the cluster config the file was formatted with; the grid zone is
whatever remains of the file, so only non-default `--clients-max` /
`--client-reply-slots` need to be repeated (the same contract as
`start`). The config fingerprint in the superblock meta cross-checks the
guess.
"""

from __future__ import annotations

import json

from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.io.storage import SECTOR_SIZE, Storage, Zone, ZoneLayout
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header
from tigerbeetle_tpu.vsr.superblock import SuperBlock, VSRState

# operation u8 -> display name (unknown values print as the raw byte)
_OP_NAMES = {int(op): op.name for op in Operation}
_EVENT_OPS = (
    int(Operation.create_accounts), int(Operation.create_transfers)
)


def open_storage(path: str, cluster: ConfigCluster,
                 forest_blocks: int = 0):
    """Open a data file for inspection, inferring the grid-zone size from
    the file size (the fixed zones are determined by the cluster config;
    the grid is the remainder)."""
    import os

    from tigerbeetle_tpu.io.storage import FileStorage

    probe = ZoneLayout(cluster, grid_size=1 << 20)
    fixed = probe.total_size - probe.sizes[Zone.grid]
    file_size = os.path.getsize(path)
    grid_size = file_size - fixed
    if grid_size <= 0:
        raise RuntimeError(
            f"{path}: {file_size} bytes is smaller than the fixed zones "
            f"({fixed} bytes) for this cluster config — wrong "
            "--clients-max/--client-reply-slots?"
        )
    layout = ZoneLayout(cluster, grid_size=grid_size,
                        forest_blocks=forest_blocks)
    return FileStorage(path, layout, create=False)


# ----------------------------------------------------------------------
# superblock
# ----------------------------------------------------------------------


def inspect_superblock(storage: Storage) -> dict:
    """Decode all redundant superblock copies independently (the quorum
    open would hide a corrupt copy; the operator wants per-copy
    verdicts), then report the quorum winner."""
    copies = []
    decoded: list[VSRState | None] = []
    for copy in range(ZoneLayout.SUPERBLOCK_COPIES):
        raw = storage.read(
            Zone.superblock,
            copy * ZoneLayout.SUPERBLOCK_COPY_SIZE,
            ZoneLayout.SUPERBLOCK_COPY_SIZE,
        )
        st, verdict = SuperBlock.decode_copy(raw)
        decoded.append(st)
        rec: dict = {
            "copy": copy,
            "magic_ok": verdict != "bad magic",
            "checksum_ok": verdict == "valid",
            "verdict": verdict,
        }
        copies.append(rec)
        if st is None:
            continue
        rec.update(
            cluster=st.cluster, replica=st.replica, sequence=st.sequence,
            commit_min=st.commit_min,
            commit_min_checksum=f"{st.commit_min_checksum:x}",
            view=int(st.meta.get("view", 0)),
            log_view=int(st.meta.get("log_view", 0)),
            area=st.area,
            blobs=[
                {"name": b.name, "offset": b.offset, "size": b.size,
                 "checksum": f"{b.checksum:x}"}
                for b in st.blobs
            ],
        )
    # the SAME quorum rule the replica opens with (SuperBlock owns it)
    state, n_copies = SuperBlock.quorum_winner(decoded)
    return {
        "copies": copies,
        "quorum": state.sequence if state is not None else None,
        "quorum_copies": n_copies,
        "state": state,
    }


def _open_state(storage: Storage) -> VSRState | None:
    return inspect_superblock(storage)["state"]


# ----------------------------------------------------------------------
# WAL rings
# ----------------------------------------------------------------------


def _classify_slot(slot: int, praw: bytes, rraw: bytes,
                   cluster: ConfigCluster) -> dict:
    """One WAL slot's evidence from BOTH rings — the same decision matrix
    as Journal.recover (reference: src/vsr/journal.zig:374-535), but
    reported instead of acted on."""
    slot_count = cluster.journal_slot_count
    p_header = Header.from_bytes(praw[:HEADER_SIZE])
    p_checksum_ok = (
        p_header.valid_checksum() and p_header.command == Command.prepare
        and p_header.size <= cluster.message_size_max
    )
    p_body_ok = p_checksum_ok and p_header.valid_checksum_body(
        praw[HEADER_SIZE : p_header.size]
    )
    p_here = p_body_ok and p_header.op % slot_count == slot
    r_header = Header.from_bytes(rraw)
    r_ok = (
        r_header.valid_checksum() and r_header.command == Command.prepare
        and r_header.op % slot_count == slot
    )
    rec: dict = {"slot": slot}
    if p_checksum_ok:
        rec["prepare"] = {
            "op": p_header.op, "size": p_header.size,
            "operation": _OP_NAMES.get(p_header.operation,
                                       p_header.operation),
            "checksum": f"{p_header.checksum:x}",
            "parent": f"{p_header.parent:x}",
            "header_ok": True, "body_ok": p_body_ok,
        }
    if r_ok:
        rec["redundant"] = {
            "op": r_header.op, "checksum": f"{r_header.checksum:x}",
        }
    if p_body_ok and not p_here:
        rec["class"] = "misdirected"  # valid prepare, wrong slot
    elif p_here and (not r_ok or r_header.op <= p_header.op):
        rec["class"] = (
            "valid" if r_ok and r_header.op == p_header.op
            else "torn_header"
        )
    elif r_ok:
        # redundant header is the newest evidence; the body is lost
        rec["class"] = "wrap_stale" if p_here else (
            "torn_prepare" if p_checksum_ok or any(praw[:HEADER_SIZE])
            else "faulty"
        )
        rec["lost_op"] = r_header.op
    elif p_checksum_ok and not p_body_ok:
        rec["class"] = "torn_prepare"  # header landed, body torn, no mirror
    else:
        rec["class"] = "blank"
    return rec


def inspect_wal(storage: Storage, cluster: ConfigCluster,
                state: VSRState | None = None) -> dict:
    """Scan both WAL rings slot by slot; classify each and diagnose the
    replayable tail: starting at the checkpoint (superblock commit_min),
    walk the hash chain op by op and report where — and WHY — it ends
    (the torn-tail diagnosis: chain_end + chain_break)."""
    if state is None:
        state = _open_state(storage)
    msg_max = cluster.message_size_max
    raw_headers = storage.read(
        Zone.wal_headers, 0,
        (cluster.journal_slot_count * HEADER_SIZE + SECTOR_SIZE - 1)
        // SECTOR_SIZE * SECTOR_SIZE,
    )
    slots = []
    stats: dict[str, int] = {}
    by_op: dict[int, dict] = {}
    for slot in range(cluster.journal_slot_count):
        praw = storage.read(Zone.wal_prepares, slot * msg_max, msg_max)
        rec = _classify_slot(
            slot, praw,
            raw_headers[slot * HEADER_SIZE : (slot + 1) * HEADER_SIZE],
            cluster,
        )
        stats[rec["class"]] = stats.get(rec["class"], 0) + 1
        if rec["class"] != "blank":
            slots.append(rec)
        p = rec.get("prepare")
        # only prepares sitting in THEIR OWN slot are replay evidence: a
        # misdirected write's body is intact but recovery reads slot
        # op % slot_count, which holds something else — indexing it here
        # would make the chain walk call a torn log "replayable"
        if (
            p is not None and p["body_ok"]
            and rec["class"] in ("valid", "torn_header")
        ):
            by_op[p["op"]] = p
    report: dict = {"slots": slots, "stats": stats}
    if state is not None:
        # torn-tail diagnosis: walk the hash chain from the checkpoint;
        # where it stops, say WHY — a torn/faulty/misdirected slot naming
        # this op is damage, anything else is just the end of the log
        by_slot = {s["slot"]: s for s in slots}
        chain = state.commit_min_checksum
        op = state.commit_min + 1
        report["checkpoint_op"] = state.commit_min
        report["chain_break"] = None
        while True:
            p = by_op.get(op)
            if p is None:
                s = by_slot.get(op % cluster.journal_slot_count)
                damaged = s is not None and (
                    s.get("lost_op") == op
                    or s["class"] == "misdirected"
                    or (
                        s.get("prepare", {}).get("op") == op
                        and not s["prepare"]["body_ok"]
                    )
                )
                if damaged:
                    report["chain_break"] = {
                        "op": op, "slot": s["slot"], "why": s["class"],
                    }
                else:
                    # the op's own slot says nothing, but a MISDIRECTED
                    # copy of it elsewhere proves the op existed and its
                    # write landed in the wrong place — that is damage,
                    # not the end of the log
                    stray = next(
                        (x for x in slots
                         if x["class"] == "misdirected"
                         and x.get("prepare", {}).get("op") == op),
                        None,
                    )
                    if stray is not None:
                        report["chain_break"] = {
                            "op": op, "slot": stray["slot"],
                            "why": "misdirected (found in wrong slot)",
                        }
                break
            if int(p["parent"], 16) != chain:
                report["chain_break"] = {
                    "op": op, "slot": op % cluster.journal_slot_count,
                    "why": "parent checksum mismatch (stale timeline)",
                }
                break
            chain = int(p["checksum"], 16)
            op += 1
        report["chain_end"] = op - 1
    return report


def inspect_wal_op(storage: Storage, cluster: ConfigCluster,
                   op: int) -> dict:
    """Dump ONE prepare from the WAL ring: full header fields, checksum
    verdicts, and a body summary (event count + first/last ids for the
    create ops)."""
    msg_max = cluster.message_size_max
    slot = op % cluster.journal_slot_count
    praw = storage.read(Zone.wal_prepares, slot * msg_max, msg_max)
    header = Header.from_bytes(praw[:HEADER_SIZE])
    rec: dict = {"op": op, "slot": slot}
    if not header.valid_checksum():
        rec["verdict"] = "slot header fails its checksum"
        return rec
    if header.op != op:
        rec["verdict"] = f"slot holds op {header.op} (ring wrapped)"
        rec["slot_op"] = header.op
        return rec
    body = praw[HEADER_SIZE : header.size]
    body_ok = header.valid_checksum_body(body)
    rec.update(
        verdict="valid" if body_ok else "body checksum mismatch (torn)",
        header={
            "checksum": f"{header.checksum:x}",
            "checksum_body": f"{header.checksum_body:x}",
            "parent": f"{header.parent:x}",
            "client": f"{header.client:x}",
            "context": f"{header.context:x}",
            "request": header.request,
            "cluster": header.cluster,
            "view": header.view,
            "op": header.op,
            "commit": header.commit,
            "timestamp": header.timestamp,
            "size": header.size,
            "replica": header.replica,
            "operation": _OP_NAMES.get(header.operation, header.operation),
        },
        trace=f"{header.trace():x}",  # the op's cluster-causal trace id
    )
    if header.operation in _EVENT_OPS and body_ok and len(body) >= 128:
        events = len(body) // 128
        first_id = int.from_bytes(body[0:16], "little")
        last = body[(events - 1) * 128 :]
        rec["body"] = {
            "events": events,
            "first_id": f"{first_id:x}",
            "last_id": f"{int.from_bytes(last[0:16], 'little'):x}",
        }
    return rec


# ----------------------------------------------------------------------
# client replies + client table
# ----------------------------------------------------------------------


def inspect_replies(storage: Storage, cluster: ConfigCluster) -> dict:
    """Decode every client-reply slot (reference: client_replies.zig):
    a valid slot holds the wire reply (header + body) last persisted for
    the session that owns it."""
    msg_max = cluster.message_size_max
    slots = []
    for slot in range(cluster.reply_slot_count):
        raw = storage.read(Zone.client_replies, slot * msg_max, msg_max)
        header = Header.from_bytes(raw[:HEADER_SIZE])
        if not (
            header.valid_checksum()
            and header.command == int(Command.reply)
            and header.size <= msg_max
        ):
            continue
        body_ok = header.valid_checksum_body(
            raw[HEADER_SIZE : header.size]
        )
        slots.append({
            "slot": slot,
            "client": f"{header.client:x}",
            "request": header.request,
            "op": header.op,
            "size": header.size,
            "operation": _OP_NAMES.get(header.operation, header.operation),
            "checksum": f"{header.checksum:x}",
            "body_ok": body_ok,
        })
    return {"slot_count": cluster.reply_slot_count, "slots": slots}


def inspect_client_table(storage: Storage,
                         state: VSRState | None = None) -> dict:
    """The checkpointed client table: inline in the superblock meta, or
    (many-session ingress mode) spilled to its grid blob — decoded with
    the blob's checksum verdict."""
    from tigerbeetle_tpu import native

    if state is None:
        state = _open_state(storage)
    if state is None:
        return {"error": "no superblock quorum"}
    rec: dict = {"source": "inline"}
    table = state.meta.get("client_table")
    if state.meta.get("client_table_blob"):
        rec["source"] = "grid blob"
        ref = next(
            (b for b in state.blobs if b.name == "client_table"), None
        )
        if ref is None:
            return dict(rec, error="blob flagged but not referenced")
        raw = storage.read(Zone.grid, ref.offset, ref.size)
        rec["checksum_ok"] = native.checksum(raw) == ref.checksum
        if not rec["checksum_ok"]:
            return dict(rec, error="blob checksum mismatch")
        table = json.loads(raw.decode())
    if table is None:
        return dict(rec, sessions=0, entries=[])
    entries = [
        {
            "client": f"{int(c):x}",
            "session": e["session"],
            "request": e["request"],
            "slot": e.get("slot"),
            "reply_checksum": e.get("reply_checksum", "0"),
        }
        for c, e in sorted(table.items(), key=lambda kv: int(kv[0]))
    ]
    return dict(rec, sessions=len(entries), entries=entries)


# ----------------------------------------------------------------------
# grid + LSM forest
# ----------------------------------------------------------------------


def inspect_grid(storage: Storage, cluster: ConfigCluster,
                 state: VSRState | None = None) -> dict:
    """The grid zone: checkpoint blob references (with checksum
    verdicts), the two ping-pong snapshot areas, and — when the file
    carries an LSM forest — the free set plus a verify scan over every
    acquired block."""
    from tigerbeetle_tpu import native

    if state is None:
        state = _open_state(storage)
    layout = storage.layout
    rec: dict = {
        "snapshot_area_size": layout.snapshot_area_size,
        "forest_offset": layout.forest_offset,
        "forest_blocks": layout.forest_blocks,
    }
    if state is None:
        return dict(rec, error="no superblock quorum")
    rec["area"] = state.area
    rec["blobs"] = [
        {
            "name": b.name, "offset": b.offset, "size": b.size,
            "checksum_ok": native.checksum(
                storage.read(Zone.grid, b.offset, b.size)
            ) == b.checksum,
        }
        for b in state.blobs
    ]
    spill = state.meta.get("spill")
    if spill and layout.forest_blocks:
        from tigerbeetle_tpu.lsm.grid import BLOCK_SIZE, Grid
        from tigerbeetle_tpu.vsr.free_set import FreeSet

        free_set = FreeSet.decode(
            bytes.fromhex(spill["manifest"]["free_set"]),
            layout.forest_blocks,
        )
        acquired = [
            a for a in range(1, layout.forest_blocks + 1)
            if not free_set.is_free(a)
        ]
        corrupt = [
            a for a in acquired
            if Grid.validate_raw(storage.read(
                Zone.grid, layout.forest_offset + (a - 1) * BLOCK_SIZE,
                BLOCK_SIZE,
            )) is None
        ]
        rec["free_set"] = {
            "blocks": layout.forest_blocks,
            "free": free_set.count_free(),
            "acquired": len(acquired),
            "corrupt": corrupt,
        }
        rec["spilled_count"] = spill.get("spilled_count", 0)
        rec["spilled_blocks"] = spill.get("spilled_blocks", [])
    elif spill:
        rec["note"] = (
            "checkpoint carries spill meta but no --forest-blocks was "
            "given: pass the forest geometry to decode the free set"
        )
    return rec


# groove display names by tree id (lsm/groove.py tree_ids, which mirror
# reference src/state_machine.zig:67-100)
def _tree_names() -> dict[int, str]:
    from tigerbeetle_tpu.lsm.groove import (
        ACCOUNT_TREE_IDS,
        POSTED_TREE_ID,
        TRANSFER_TREE_IDS,
    )

    names = {}
    for field, tid in ACCOUNT_TREE_IDS.items():
        names[tid] = f"accounts.{field}"
    for field, tid in TRANSFER_TREE_IDS.items():
        names[tid] = f"transfers.{field}"
    names[POSTED_TREE_ID] = "posted"
    return names


def inspect_lsm(storage: Storage, cluster: ConfigCluster,
                state: VSRState | None = None) -> dict:
    """LSM manifest/table summaries per groove: replay the manifest-log
    block chain (lsm/manifest_log.py) and report, per tree and level,
    the live tables with entry counts and key ranges."""
    if state is None:
        state = _open_state(storage)
    if state is None:
        return {"error": "no superblock quorum"}
    spill = state.meta.get("spill")
    if not spill:
        return {"note": "no spill/LSM state in this checkpoint"}
    if not storage.layout.forest_blocks:
        return {
            "error": "checkpoint carries LSM state; pass --forest-blocks "
            "matching the replica's layout to decode it"
        }
    from tigerbeetle_tpu.lsm.grid import Grid
    from tigerbeetle_tpu.lsm.manifest_log import ManifestLog

    grid = Grid(storage, offset=storage.layout.forest_offset,
                block_count=storage.layout.forest_blocks)
    mlog = ManifestLog(grid)
    levels = mlog.restore(spill["manifest"]["manifest_log"])
    names = _tree_names()
    trees = []
    for tid in sorted(levels):
        per_level = []
        for lv in sorted(levels[tid]):
            infos = levels[tid][lv]
            if not infos:
                continue
            per_level.append({
                "level": lv,
                "tables": len(infos),
                "entries": sum(t.entry_count for t in infos),
                "key_min": min(t.key_min for t in infos).hex(),
                "key_max": max(t.key_max for t in infos).hex(),
                "addresses": [t.index_address for t in infos],
            })
        if per_level:
            trees.append({
                "tree_id": tid,
                "name": names.get(tid, f"tree {tid}"),
                "levels": per_level,
            })
    return {
        "manifest_blocks": spill["manifest"]["manifest_log"]["blocks"],
        "manifest_events": spill["manifest"]["manifest_log"]["events"],
        "trees": trees,
    }


# ----------------------------------------------------------------------
# checkpoint state commitments (federation/commitment.py)
# ----------------------------------------------------------------------


def inspect_commitments_offline(storage: Storage) -> dict:
    """Decode the checkpointed commitment chain from the data file's
    superblock meta (written by Replica._checkpoint when the server runs
    with --commitment-interval). Offline truth: what the LAST checkpoint
    durably published — the live chain may be ahead by up to one
    checkpoint interval of WAL tail."""
    state = _open_state(storage)
    data = state.meta.get("commitments") if state is not None else None
    if not data:
        return {
            "enabled": False,
            "note": "no commitment chain in the checkpoint meta "
                    "(server not started with --commitment-interval, or "
                    "no checkpoint has run yet)",
        }
    return {
        "enabled": True,
        "interval": int(data["interval"]),
        "head_op": int(data["head_op"]),
        "head": int(data["head"]),
        "checkpoints": [
            [int(op), int(c), int(prev)]
            for op, c, prev, _t in data["entries"]
        ],
    }


def commitments_from_stats(stats: dict) -> dict:
    """The live chain out of a [stats] registry snapshot (inspect_live /
    cmd_start's _on_term line both carry the same key)."""
    snap = stats.get("commitments")
    if not snap:
        return {
            "enabled": False,
            "note": "server has no commitment chain "
                    "(start with --commitment-interval N)",
        }
    return {
        "enabled": True,
        "interval": int(snap["interval"]),
        "head_op": int(snap["head_op"]),
        "head": int(snap["head"]),
        "checkpoints": [
            [int(op), int(c), int(prev)] for op, c, prev in snap["recent"]
        ],
    }


def verify_commitment_stream(path: str) -> dict:
    """External-consumer verification of a region's CDC stream: replay
    every change record through a fresh oracle and re-derive the
    commitment chain at every `commitment` record. The stream must start
    at op 1 (an AOF-backed tail never gaps). A tampered stream or a
    forged commitment fails AT the divergent checkpoint, named in the
    report — this is the trust boundary a settlement counterparty
    checks before accepting a region's stream.

    The JSONL file has at-least-once framing: a crashed streamer resumes
    from its durable cursor (duplicate op groups) and a SIGKILL mid-write
    tears a tail line that the next incarnation's append glues onto.
    Committed history never changes, so dedup is first-wins per record
    identity — (op, ix) for events, op for commitment records — and
    unparseable glue lines are skipped and counted (their ops arrive
    again intact with the redelivery)."""
    from tigerbeetle_tpu.federation.commitment import StreamVerifier

    events: dict = {}       # op -> {ix: record}
    commitments: dict = {}  # op -> record
    gaps: dict = {}         # start op -> record
    torn = redelivered = 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1
                continue
            kind = rec.get("kind")
            if kind == "gap":
                gaps.setdefault(int(rec["from"]), rec)
            elif kind == "commitment":
                if int(rec["op"]) in commitments:
                    redelivered += 1
                else:
                    commitments[int(rec["op"])] = rec
            elif kind in ("account", "transfer"):
                group = events.setdefault(int(rec["op"]), {})
                if int(rec.get("ix", 0)) in group:
                    redelivered += 1
                else:
                    group[int(rec.get("ix", 0))] = rec
    v = StreamVerifier()
    for op in sorted(set(events) | set(commitments) | set(gaps)):
        if op in gaps:
            v.feed(gaps[op])
        group = events.get(op, {})
        for ix in sorted(group):
            v.feed(group[ix])
        if op in commitments:
            v.feed(commitments[op])
    report = v.report()
    report["stream"] = path
    report["torn_lines"] = torn
    report["redelivered_records"] = redelivered
    return report


# ----------------------------------------------------------------------
# live mode
# ----------------------------------------------------------------------

INSPECT_CLIENT_ID = 0x7453_4550_534E_49  # "INSPECt" — above replica range


def inspect_live(host: str, port: int, timeout: float = 5.0) -> dict:
    """Ask a RUNNING replica for its [stats]-registry snapshot: connect
    as a one-shot client, send a request_stats frame, parse the stats
    reply (vsr/replica.py _on_request_stats). Works in any replica
    status — a wedged server still answers from its event loop."""
    import socket

    req = Header(
        command=int(Command.request_stats), client=INSPECT_CLIENT_ID
    )
    req.set_checksum_body(b"")
    req.set_checksum()
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(req.to_bytes())
        buf = b""
        while True:
            if len(buf) >= HEADER_SIZE:
                header = Header.from_bytes(buf[:HEADER_SIZE])
                if not (HEADER_SIZE <= header.size <= (1 << 20)):
                    # garbage framing: the wrong port / not a replica —
                    # error out instead of spinning on a 0-size frame
                    raise RuntimeError(
                        f"{host}:{port} is not speaking the VSR wire "
                        f"format (frame size {header.size})"
                    )
                if len(buf) >= header.size:
                    frame, buf = buf[: header.size], buf[header.size :]
                    if header.command == int(Command.stats):
                        if not header.valid_checksum():
                            raise RuntimeError(
                                "stats reply failed its checksum"
                            )
                        return json.loads(
                            frame[HEADER_SIZE : header.size].decode()
                        )
                    continue  # other traffic (e.g. an eviction): skip
            chunk = s.recv(1 << 16)
            if not chunk:
                raise RuntimeError(
                    "server closed the connection without a stats reply"
                )
            buf += chunk


def send_mark(host: str, port: int, name: str,
              timeout: float = 5.0) -> dict:
    """Stamp a scenario-phase marker into a RUNNING replica's flight
    recorder (vsr/replica.py _on_mark): the prodday driver calls this at
    every phase boundary so recorder history slices per phase. Same
    one-shot framing as inspect_live; returns the ack ({"marked": name,
    "t": <recorder time base>}) once the mark landed."""
    import socket

    req = Header(command=int(Command.mark), client=INSPECT_CLIENT_ID)
    body = name.encode()
    req.set_checksum_body(body)
    req.set_checksum()
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(req.to_bytes() + body)
        buf = b""
        while True:
            if len(buf) >= HEADER_SIZE:
                header = Header.from_bytes(buf[:HEADER_SIZE])
                if not (HEADER_SIZE <= header.size <= (1 << 20)):
                    raise RuntimeError(
                        f"{host}:{port} is not speaking the VSR wire "
                        f"format (frame size {header.size})"
                    )
                if len(buf) >= header.size:
                    frame, buf = buf[: header.size], buf[header.size :]
                    if header.command == int(Command.stats):
                        if not header.valid_checksum():
                            raise RuntimeError(
                                "mark ack failed its checksum"
                            )
                        return json.loads(
                            frame[HEADER_SIZE : header.size].decode()
                        )
                    continue  # other traffic: skip
            chunk = s.recv(1 << 16)
            if not chunk:
                raise RuntimeError(
                    "server closed the connection without a mark ack"
                )
            buf += chunk


def _watch_line(e: dict) -> str:
    """One flight-recorder entry as a compact rates line: committed
    ops/s, frames/s, sheds/s, and the interval's windowed p99 for the
    end-to-end + commit-dispatch latency histograms (the per-interval
    evidence a cumulative snapshot buries)."""
    dt = e.get("dt") or 1.0
    c = e.get("counters", {})
    h = e.get("histograms", {})

    def rate(name):
        return c.get(name, 0) / dt

    parts = [
        f"t={e.get('t', 0):.1f}s",
        f"ops/s={rate('server.ops_committed'):.0f}",
        f"frames/s={rate('bus.frames'):.0f}",
    ]
    if e.get("phase"):
        # scenario phase (prodday `mark` markers): which part of the
        # scripted timeline this interval belongs to
        parts.insert(1, f"phase={e['phase']}")
    shed = rate("ingress.shed")
    if shed:
        parts.append(f"sheds/s={shed:.0f}")
    for short, name in (
        ("e2e", "latency.e2e_us"),
        ("dispatch", "replica.commit_dispatch_us"),
    ):
        w = h.get(name)
        if w:
            parts.append(f"{short}_p99={w['p99']:.0f}us")
    # the interval's dominant latency leg (largest windowed total):
    # "where did this second's milliseconds go"
    best, best_total = None, 0.0
    for name, w in h.items():
        if name.startswith("latency.") and name != "latency.e2e_us" \
                and not name.endswith(("lag_us", "lane_us")):
            total = w["count"] * w.get("mean", 0.0)
            if total > best_total:
                best, best_total = name, total
    if best:
        parts.append(
            f"dominant={best[len('latency.'):-len('_us')]}"
            f"({best_total / 1000.0:.1f}ms)"
        )
    gauges = e.get("gauges", {})
    lag = gauges.get("shadow.device_lag_ops")
    if lag:
        parts.append(f"apply_lag={lag}")
    # device columns (dual mode): applier queue depth, h2d throughput,
    # dispatch rate, compile events (a nonzero here mid-run is the
    # .jax_cache pathology), windowed device-busy p99, and the
    # interval's dominant commit_wait sub-leg
    qd = gauges.get("device.queue_depth")
    if qd:
        parts.append(f"dev_q={qd}")
    h2d = rate("device.h2d_bytes")
    if h2d:
        parts.append(f"h2d={h2d / 1e6:.1f}MB/s")
    disp = rate("device.dispatches")
    if disp:
        parts.append(f"disp/s={disp:.0f}")
    compiles = c.get("device.compiles", 0)
    if compiles:
        parts.append(f"compiles={compiles}")
    busy = h.get("device.device_busy_us")
    if busy:
        parts.append(f"dev_busy_p99={busy['p99']:.0f}us")
    dbest, dbest_total = None, 0.0
    for name, w in h.items():
        if name.startswith("device.") and name.endswith("_us") \
                and name != "device.apply_e2e_us":
            total = w["count"] * w.get("mean", 0.0)
            if total > dbest_total:
                dbest, dbest_total = name, total
    if dbest:
        parts.append(
            f"dev_dominant={dbest[len('device.'):-len('_us')]}"
            f"({dbest_total / 1000.0:.1f}ms)"
        )
    return "  ".join(parts)


def watch_live(host: str, port: int, interval_s: float = 1.0,
               count: int = 0, out=None, as_json: bool = False,
               sleep=None) -> int:
    """`inspect live --watch <sec>`: poll the running replica's [stats]
    snapshot on a cadence and print the flight-recorder entries that
    arrived since the previous poll — per-interval deltas/rates, one
    line each (or raw JSONL with as_json). Works against wedged
    replicas: request_stats is answered in any status. `count` bounds
    the polls (0 = until interrupted)."""
    import sys as _sys
    import time as _time

    out = out or _sys.stdout
    sleep = sleep or _time.sleep
    last_t = None
    polls = 0
    try:
        while True:
            report = inspect_live(host, port)
            entries = report.get("history") or []
            fresh = [
                e for e in entries
                if last_t is None or (e.get("t") or 0) > last_t
            ]
            if entries:
                last_t = max(e.get("t") or 0 for e in entries)
            if not entries and polls == 0:
                out.write(
                    "no flight-recorder history (server started with "
                    "--flight-interval-s 0?) — falling back to "
                    "consensus state only\n"
                )
            for e in fresh:
                if as_json:
                    json.dump(e, out, sort_keys=True,
                              separators=(",", ":"))
                    out.write("\n")
                else:
                    out.write(_watch_line(e) + "\n")
            if not fresh and not as_json:
                out.write(
                    f"status={report.get('status')} "
                    f"commit={report.get('commit_min')} (no new history)\n"
                )
            out.flush()
            polls += 1
            if count and polls >= count:
                return 0
            sleep(interval_s)
    except KeyboardInterrupt:
        return 0


# ----------------------------------------------------------------------
# rendering
# ----------------------------------------------------------------------


def _print_kv(prefix: str, d: dict, out) -> None:
    for k, v in d.items():
        out.write(f"{prefix}{k}: {v}\n")


def render(topic: str, report: dict, out) -> None:
    """Human rendering of one topic's report dict (the `--json` path
    prints the dict itself)."""
    if topic == "superblock":
        for c in report["copies"]:
            head = f"copy {c['copy']}: {c['verdict']}"
            if c.get("checksum_ok"):
                head += (
                    f" — sequence {c['sequence']}, commit_min "
                    f"{c['commit_min']}, view {c['view']}"
                    f"/{c['log_view']}, area {c['area']}, "
                    f"{len(c['blobs'])} blob(s)"
                )
            out.write(head + "\n")
            for b in c.get("blobs", ()):
                out.write(
                    f"    blob {b['name']}: offset {b['offset']} "
                    f"size {b['size']} checksum {b['checksum']}\n"
                )
        if report["quorum"] is None:
            out.write("QUORUM: NONE — data file unopenable\n")
        else:
            out.write(
                f"quorum: sequence {report['quorum']} "
                f"({report['quorum_copies']}/"
                f"{ZoneLayout.SUPERBLOCK_COPIES} copies)\n"
            )
    elif topic == "wal":
        out.write(f"slot classes: {report['stats']}\n")
        for s in report["slots"]:
            line = f"slot {s['slot']:5d}  {s['class']:12s}"
            p = s.get("prepare")
            if p is not None:
                line += (
                    f" op {p['op']} {p['operation']} size {p['size']}"
                    f" body_ok={p['body_ok']}"
                )
            elif "lost_op" in s:
                line += f" lost op {s['lost_op']} (body unrecoverable here)"
            out.write(line + "\n")
        if "chain_end" in report:
            out.write(
                f"replayable chain: checkpoint op "
                f"{report['checkpoint_op']} -> op {report['chain_end']}\n"
            )
            if report.get("chain_break"):
                b = report["chain_break"]
                out.write(
                    f"TORN TAIL: chain breaks at op {b['op']} "
                    f"(slot {b['slot']}): {b['why']}\n"
                )
    elif topic == "replies":
        out.write(
            f"{len(report['slots'])}/{report['slot_count']} reply "
            "slots hold a valid reply\n"
        )
        for s in report["slots"]:
            out.write(
                f"slot {s['slot']:4d}: client {s['client']} request "
                f"{s['request']} op {s['op']} {s['operation']} "
                f"body_ok={s['body_ok']}\n"
            )
    elif topic == "grid":
        _print_kv("", {k: v for k, v in report.items()
                       if k not in ("blobs", "free_set")}, out)
        for b in report.get("blobs", ()):
            out.write(
                f"blob {b['name']}: offset {b['offset']} size {b['size']} "
                f"checksum_ok={b['checksum_ok']}\n"
            )
        fs = report.get("free_set")
        if fs:
            out.write(
                f"free set: {fs['acquired']} acquired / {fs['free']} free "
                f"of {fs['blocks']} blocks; corrupt: "
                f"{fs['corrupt'] or 'none'}\n"
            )
    elif topic == "lsm":
        if "trees" not in report:
            _print_kv("", report, out)
            return
        out.write(
            f"manifest log: {len(report['manifest_blocks'])} block(s), "
            f"{report['manifest_events']} event(s)\n"
        )
        for t in report["trees"]:
            out.write(f"{t['name']} (tree {t['tree_id']}):\n")
            for lv in t["levels"]:
                out.write(
                    f"    L{lv['level']}: {lv['tables']} table(s), "
                    f"{lv['entries']} entries, keys "
                    f"[{lv['key_min']}, {lv['key_max']}]\n"
                )
    elif topic == "client-table":
        _print_kv("", {k: v for k, v in report.items()
                       if k != "entries"}, out)
        for e in report.get("entries", ()):
            out.write(
                f"client {e['client']}: session {e['session']} request "
                f"{e['request']} slot {e['slot']}\n"
            )
    elif topic == "commitments":
        if "ok" in report:  # stream-verify mode
            verdict = "VERIFIED" if report["ok"] else "REJECTED"
            out.write(
                f"{verdict}: {report.get('stream', '')} — "
                f"{report['checked']} checkpoint(s), "
                f"{report['ops_replayed']} op(s) replayed\n"
            )
            if report.get("head_op"):
                out.write(
                    f"chain head: op {report['head_op']} = "
                    f"{report['head']:#018x}\n"
                )
            if report.get("first_divergent") is not None:
                out.write(
                    f"FIRST DIVERGENT CHECKPOINT: op "
                    f"{report['first_divergent']}\n"
                )
            if report.get("error"):
                out.write(f"error: {report['error']}\n")
        elif not report.get("enabled"):
            out.write(f"commitments disabled: {report.get('note', '')}\n")
        else:
            out.write(
                f"interval {report['interval']}, chain head: op "
                f"{report['head_op']} = {report['head']:#018x}\n"
            )
            for op, c, prev in report.get("checkpoints", ()):
                out.write(f"op {op}: {c:#018x} (prev {prev:#018x})\n")
    else:  # wal-op dumps, live snapshots, anything structured
        json.dump(report, out, indent=1, sort_keys=True)
        out.write("\n")
