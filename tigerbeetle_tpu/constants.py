"""Cluster/process configuration constants.

Mirrors the reference's two-tier comptime config (reference: src/config.zig:130-144
ConfigCluster, :73-121 ConfigProcess; derived values src/constants.zig). Cluster
values are consensus-affecting and must match across replicas; process values are
per-replica tuning knobs.
"""

from __future__ import annotations

import dataclasses
import os

# Intensive online-verification tier (reference: src/constants.zig:592
# `constants.verify` compiles extra invariant checks into hot paths).
# TB_VERIFY=1 enables: LSM level-invariant audits after every compaction,
# journal read-after-write verification, replica hash-chain re-checks at
# commit, and periodic conservation audits in the oracle state machine.
# Tests may also toggle `constants.VERIFY` directly; hot paths read it at
# check time, not import time.
VERIFY = os.environ.get("TB_VERIFY", "0") == "1"

U64_MAX = (1 << 64) - 1
U128_MAX = (1 << 128) - 1

NS_PER_S = 1_000_000_000

# --- wire sizes (reference: src/constants.zig:167-168, src/config.zig:137) ---
HEADER_SIZE = 128
MESSAGE_SIZE_MAX = 1 << 20  # 1 MiB
MESSAGE_BODY_SIZE_MAX = MESSAGE_SIZE_MAX - HEADER_SIZE

ACCOUNT_SIZE = 128
TRANSFER_SIZE = 128

# The max batch size: (1 MiB - 128 B) / 128 B = 8191 in this snapshot
# (reference: src/state_machine.zig:46-65 operation_batch_max,
# src/benchmark.zig:52-59 @divExact). Note BASELINE.md's benchmark protocol
# quotes batch=8190; BENCH_BATCH follows the protocol, BATCH_MAX the formula.
BATCH_MAX = MESSAGE_BODY_SIZE_MAX // TRANSFER_SIZE
assert BATCH_MAX == 8191
BENCH_BATCH = 8190

# Device kernels pad every batch to a static shape (XLA: static shapes only).
BATCH_PAD = 8192
assert BATCH_PAD >= BATCH_MAX


@dataclasses.dataclass(frozen=True)
class ConfigCluster:
    """Consensus-affecting constants (reference: src/config.zig:130-144)."""

    cluster_id: int = 0
    replica_count: int = 1
    message_size_max: int = MESSAGE_SIZE_MAX
    journal_slot_count: int = 1024
    clients_max: int = 32
    # Durable reply slots (client_replies zone), decoupled from
    # clients_max for the ingress gateway's many-session mode: each slot
    # costs message_size_max on disk, so 10k+ multiplexed sessions cannot
    # each own one. 0 = clients_max (every session gets a slot — the
    # pre-ingress behavior). Sessions beyond the slot count register with
    # slot=None: their duplicate requests after a restart fall back to
    # the reply-lost paths instead of replaying cached reply bytes.
    client_reply_slots: int = 0
    pipeline_prepare_queue_max: int = 8
    view_change_headers_suffix_max: int = 8 + 1
    quorum_replication_max: int = 3
    block_size: int = 1 << 17  # 128 KiB grid blocks
    lsm_levels: int = 7
    lsm_growth_factor: int = 8
    lsm_batch_multiple: int = 64  # ops per "bar" (checkpoint interval unit)

    @property
    def batch_max(self) -> int:
        return (self.message_size_max - HEADER_SIZE) // TRANSFER_SIZE

    @property
    def reply_slot_count(self) -> int:
        return self.client_reply_slots or self.clients_max

    @property
    def checkpoint_interval(self) -> int:
        # reference: src/vsr.zig:2003-2035 Checkpoint arithmetic.
        return self.journal_slot_count - self.lsm_batch_multiple

    def fingerprint(self) -> int:
        """Checksum of the consensus-affecting constants. Stored in the
        superblock at format and verified on open, so replicas built with
        mismatched cluster configs cannot silently join one cluster
        (reference: src/config.zig:167-179 cluster-config checksum)."""
        import json

        from tigerbeetle_tpu import native

        payload = json.dumps(dataclasses.asdict(self), sort_keys=True)
        return native.checksum(payload.encode())


@dataclasses.dataclass(frozen=True)
class ConfigProcess:
    """Per-replica tuning (reference: src/config.zig:73-121)."""

    tick_ms: int = 10
    # Device table capacities (slots; power of two). The analog of the
    # reference's cache_entries_accounts/transfers + grid cache: here the
    # full working store is HBM-resident.
    account_slots_log2: int = 20  # 1M account slots
    transfer_slots_log2: int = 24  # 16.7M transfer slots
    # Sequential-repair scan capacity for the hybrid kernel (Tier B).
    repair_slots: int = 1024
    journal_iops_read_max: int = 8
    journal_iops_write_max: int = 8
    # LSM forest mutable-table budget (rows buffered before a flush packs
    # them into grid blocks; reference: table_memory sizing via config).
    lsm_memtable_max: int = 2048


DEFAULT_CLUSTER = ConfigCluster()
DEFAULT_PROCESS = ConfigProcess()

# Small configs for tests/simulator (reference: src/config.zig:232-272 test_min).
TEST_CLUSTER = ConfigCluster(journal_slot_count=64, lsm_batch_multiple=4)
TEST_PROCESS = ConfigProcess(account_slots_log2=10, transfer_slots_log2=12)
