"""Shared artifact provenance: the fields every in-session artifact
(`BENCH_r0N.json`, `PRODDAY_r0N.json`) must carry so no number can be
mistaken for a rig number and no two emitters can drift.

The driver's artifacts (r01-r05) ran on the TPU v5e rig; everything
produced in-session runs on the CPU sandbox, so each artifact stamps:

- the platform block (backend, machine, python, an explicit
  not-rig-comparable note),
- segment health (`segments_incomplete`: a null in the summary must
  read as "segment failed", never "measured zero"),
- the compile-cache story (`.jax_cache` size at run start / run end /
  artifact assembly, plus the in-process compile-sentinel totals — a
  poisoned cache is the known sandbox pathology, see models/ledger.py
  and the tests/conftest.py guard).

`scripts/make_bench_artifact.py` and the prodday emitter
(`scripts/prodday.py`) both build their wrapper through
`wrap_artifact()`; only the `parsed` payload and the incomplete-segment
rules differ per artifact kind.
"""

from __future__ import annotations

import os
import platform as _platform

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def jax_cache_bytes(repo: str | None = None) -> int:
    """Current on-disk size of the persistent compilation cache."""
    cache = os.path.join(repo or _REPO, ".jax_cache")
    total = 0
    for root, _dirs, files in os.walk(cache):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def platform_block(backend: str = "cpu",
                   note: str = "in-session CPU sandbox run; "
                               "not rig-comparable") -> dict:
    """Off-rig provenance: absolute tps from a sandbox run is NOT
    comparable to the rig rounds; same-run ratios, spreads, parity
    booleans and pass/fail verdicts are the quotable signals."""
    return {
        "backend": backend,
        "machine": _platform.machine(),
        "python": _platform.python_version(),
        "note": note,
    }


def jax_cache_block(parsed: dict) -> dict:
    """The run's recompile story: cache size at run start/end (recorded
    by the run itself) plus at artifact assembly — cache churn between
    run and packaging is itself visible."""
    return {
        "bytes_at_artifact": jax_cache_bytes(),
        "bytes_run_start": parsed.get("jax_cache_bytes_start"),
        "bytes_run_end": parsed.get("jax_cache_bytes_end"),
        "compile_sentinel": parsed.get("compile_sentinel"),
    }


def wrap_artifact(cmd: str, rc: int, env: str, tail: str, parsed: dict,
                  segments_incomplete: list[str], n: int = 1,
                  backend: str = "cpu") -> dict:
    """The common driver-shaped wrapper {n, cmd, rc, platform, env,
    tail, segments_incomplete, jax_cache, parsed}."""
    return {
        "n": n,
        "cmd": cmd,
        "rc": int(rc),
        "platform": platform_block(backend=backend),
        "env": env,
        "tail": tail,
        "segments_incomplete": segments_incomplete,
        "jax_cache": jax_cache_block(parsed),
        "parsed": parsed,
    }
