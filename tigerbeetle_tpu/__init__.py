"""tigerbeetle_tpu — a TPU-native double-entry accounting database framework.

A ground-up redesign of the capabilities of TigerBeetle (reference:
/root/reference, Zig) for TPU hardware:

- The batched ledger commit path (create_accounts / create_transfers /
  lookup_* — reference src/state_machine.zig) executes as JAX kernels over
  struct-of-arrays batches, with the account + transfer stores resident in
  HBM as open-addressing hash tables.
- u128 balances/ids are exact two-limb (2 x u64) arithmetic on device.
- Batches with no intra-batch conflicts take a fully vectorized path; batches
  with serial dependencies (duplicate ids, linked chains, balancing
  transfers, balance-limit accounts, in-batch pending references) fall back
  to an exact sequential lax.scan kernel. Result codes are bit-exact vs. the
  reference state machine in both paths.
- Multi-chip scaling shards the HBM tables over a `jax.sharding.Mesh`
  (see tigerbeetle_tpu.parallel).

The surrounding systems layers (VSR consensus, WAL/superblock durability,
LSM indexes, message bus, deterministic simulator) live in vsr/, lsm/, io/,
testing/ as host-side runtime around the device state machine.

NOTE: importing this package enables jax_enable_x64 (u64 limbs are the
native word of the whole framework).
"""

import os as _os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the 8192-batch commit kernels take tens
# of seconds to compile (remote compile on tunneled TPUs), and every server
# process the bench/tests spawn used to pay that again. With the cache, the
# first process compiles and every later one loads from disk in <1s —
# including the dual-mode device shadow, whose in-window compile otherwise
# stalls the reply path once the shadow queue fills. TB_JAX_CACHE=''
# disables; default lives inside the repo (gitignored).
_cache = _os.environ.get("TB_JAX_CACHE")
if _cache is None:
    _repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    _cache = (
        _os.path.join(_repo, ".jax_cache")
        if _os.access(_repo, _os.W_OK)  # source checkout
        # installed package (site-packages may be read-only): user cache
        else _os.path.join(
            _os.path.expanduser("~"), ".cache", "tigerbeetle_tpu", "jax"
        )
    )
if _cache:
    try:
        jax.config.update("jax_compilation_cache_dir", _cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # older jax without the knob: compiles stay per-process

from tigerbeetle_tpu import constants, types  # noqa: E402,F401

__version__ = "0.1.0"
