"""Struct-driven CLI parsing.

The reference derives its whole CLI from structs at compile time
(reference: src/flags.zig — field name -> --flag, defaults from the
struct, `fatal` helpers; src/tigerbeetle/cli.zig:54-116 builds the
command surface from them). The Python analog: a dataclass per command,
parsed by introspection —

    @dataclasses.dataclass
    class Start:
        addresses: str          # required (no default): --addresses=...
        replica: int = 0        # optional with default
        verbose: bool = False   # presence flag: --verbose
        path: str = positional("data file")  # positional argument

    args = flags.parse(Start, argv)

Field name `snake_case` maps to `--kebab-case`. Unknown flags, missing
required flags, and malformed values exit via `fatal` (the reference's
behavior: print one line, exit 1).
"""

from __future__ import annotations

import dataclasses
import sys
from typing import get_type_hints


def positional(help_: str = ""):
    """Marks a dataclass field as a positional argument."""
    return dataclasses.field(
        default=dataclasses.MISSING, metadata={"positional": True, "help": help_}
    )


def fatal(msg: str) -> "NoReturn":  # noqa: F821
    sys.stderr.write(f"error: {msg}\n")
    raise SystemExit(1)


def _kebab(name: str) -> str:
    return name.replace("_", "-")


def usage(spec_cls) -> str:
    """Generated per-command help: the dataclass IS the flag surface."""
    hints = get_type_hints(spec_cls)
    flags_out, pos_out = [], []
    for f in dataclasses.fields(spec_cls):
        typ = hints[f.name].__name__
        if f.metadata.get("positional"):
            pos_out.append(f"  <{f.name}>  {f.metadata.get('help', '')}")
            continue
        default = (
            "required"
            if f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
            else f"default: {f.default!r}"
        )
        comment = (f.metadata or {}).get("help", "")
        flags_out.append(
            f"  --{_kebab(f.name)} <{typ}>  ({default}) {comment}".rstrip()
        )
    return "\n".join(["flags:"] + flags_out + ["arguments:"] + pos_out) + "\n"


def parse(spec_cls, argv: list[str]):
    """Parse argv into an instance of the dataclass `spec_cls`."""
    assert dataclasses.is_dataclass(spec_cls)
    if any(a in ("-h", "--help") for a in argv):
        sys.stdout.write(usage(spec_cls))
        raise SystemExit(0)
    hints = get_type_hints(spec_cls)
    by_flag: dict[str, dataclasses.Field] = {}
    positionals: list[dataclasses.Field] = []
    for f in dataclasses.fields(spec_cls):
        if f.metadata.get("positional"):
            positionals.append(f)
        else:
            by_flag["--" + _kebab(f.name)] = f

    values: dict[str, object] = {}
    pos_seen: list[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        i += 1
        if not arg.startswith("--"):
            pos_seen.append(arg)
            continue
        name, eq, inline = arg.partition("=")
        f = by_flag.get(name)
        if f is None:
            fatal(f"unknown flag {name}")
        typ = hints[f.name]
        if typ is bool:
            if eq:
                fatal(f"{name} takes no value")
            values[f.name] = True
            continue
        if eq:
            raw = inline
        else:
            if i >= len(argv):
                fatal(f"{name} requires a value")
            raw = argv[i]
            i += 1
        try:
            values[f.name] = typ(raw)
        except ValueError:
            fatal(f"{name}: invalid {typ.__name__} {raw!r}")

    if len(pos_seen) > len(positionals):
        fatal(f"unexpected argument {pos_seen[len(positionals)]!r}")
    for f, raw in zip(positionals, pos_seen):
        try:
            values[f.name] = hints[f.name](raw)
        except ValueError:
            fatal(f"{f.name}: invalid {hints[f.name].__name__} {raw!r}")

    for f in dataclasses.fields(spec_cls):
        if f.name in values:
            continue
        if (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        ):
            what = f.name if f.metadata.get("positional") else "--" + _kebab(f.name)
            fatal(f"missing required {what}")
    return spec_cls(**values)
