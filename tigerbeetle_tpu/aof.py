"""Append-only file: out-of-band disaster-recovery log.

The reference's AOF (reference: src/aof.zig:23-70): every committed prepare
is appended — sector-aligned records with a magic + header + body — BEFORE
the reply is sent (hooked at src/vsr/replica.zig:3643-3648), so even a
total loss of the data file can be replayed into a fresh cluster.

Record layout: [magic u64][size u64][header 128B][body][zero pad to 4KiB].
The header's own dual checksums authenticate the record; a torn tail record
simply fails validation and ends the replay.
"""

from __future__ import annotations

import os
import sys

from tigerbeetle_tpu.vsr.header import HEADER_SIZE, Command, Header

MAGIC = 0x6165_675F_746F_6265  # record marker
SECTOR = 4096


class AOF:
    def __init__(self, path: str):
        self.path = path
        self.fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND | os.O_DSYNC,
                          0o644)

    def append(self, header: Header, body: bytes) -> None:
        assert header.command == Command.prepare
        assert header.size == HEADER_SIZE + len(body)
        record = (
            MAGIC.to_bytes(8, "little")
            + header.size.to_bytes(8, "little")
            + header.to_bytes()
            + body
        )
        pad = (-len(record)) % SECTOR
        data = record + b"\x00" * pad
        done = 0
        while done < len(data):  # short writes would tear the record AND
            done += os.write(self.fd, data[done:])  # misalign every later one

    def close(self) -> None:
        if self.fd >= 0:
            os.close(self.fd)
            self.fd = -1


def replay(path: str):
    """Yield (header, body) for every valid record, stopping at the last
    intact one (reference: AOF replay tool, src/aof.zig).

    A crash mid-append leaves a torn tail: a partial magic/size prefix, a
    header cut short, a body cut short, or intact bytes whose checksums
    no longer authenticate. Every such shape STOPS the replay at the last
    valid record — never raises — and leaves one warning on stderr (the
    operator should know the log ends in a tear rather than cleanly; the
    replayed prefix is still the complete durable history, because the
    torn record's reply can never have left the replica: the AOF append
    completes before the reply is sent)."""
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + 16 + HEADER_SIZE <= len(data):
        if int.from_bytes(data[off : off + 8], "little") != MAGIC:
            break
        size = int.from_bytes(data[off + 8 : off + 16], "little")
        if size < HEADER_SIZE or off + 16 + size > len(data):
            break
        header = Header.from_bytes(data[off + 16 : off + 16 + HEADER_SIZE])
        body = data[off + 16 + HEADER_SIZE : off + 16 + size]
        if not header.valid_checksum() or not header.valid_checksum_body(body):
            break
        yield header, body
        off += 16 + size
        off += (-off) % SECTOR
    if off < len(data):
        sys.stderr.write(
            f"aof: {path}: torn/corrupt tail record at offset {off} "
            f"({len(data) - off} trailing bytes ignored); replay stops "
            "at the last valid record\n"
        )
