"""End-to-end benchmark driver: the BASELINE protocol through the FULL
system.

The reference measures its headline number by formatting a data file,
starting a real replica process, and driving create_transfers through a
client over the wire at batch=8190 (reference: scripts/benchmark.sh:34-78,
src/benchmark.zig:23-73: 10k accounts, 10M transfers, batch latency
percentiles printed at the end). This module is that harness for the TPU
build: a real `tigerbeetle_tpu start` server process (WAL on, consensus
path, TCP), driven by native session clients.

Unlike the reference's single sequential client, several clients each keep
one request in flight (the replica's commit window overlaps their journal
writes and device commits — reference: src/vsr/replica.zig:52-70); pass
clients=1 for the strictly sequential protocol.

Used by bench.py (reported as `durable_tps` alongside the kernel flagship
number) and by tests/test_process.py's smoke test (tiny sizes, CPU
backend).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE, Operation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH = 8190  # (1 MiB - 128 B) / 128 B


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def kill_process_group(proc) -> None:
    """Last-resort sweep of a server's WHOLE process group (the server is
    spawned with start_new_session=True so pgid == its pid). Idempotent;
    safe after a normal wait()."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass


def _accounts_body(start_id: int, count: int) -> bytes:
    arr = np.zeros(count, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + count, dtype=np.uint64)
    arr["ledger"] = 1
    arr["code"] = 1
    return arr.tobytes()


def _transfers_body(rng, start_id: int, count: int, n_accounts: int,
                    flags: int = 0) -> bytes:
    arr = np.zeros(count, dtype=TRANSFER_DTYPE)
    # id_order=reversed (reference: src/benchmark.zig:66-73 default)
    arr["id_lo"] = np.arange(
        start_id + count - 1, start_id - 1, -1, dtype=np.uint64
    )
    dr = rng.integers(1, n_accounts + 1, size=count, dtype=np.uint64)
    off = rng.integers(1, n_accounts, size=count, dtype=np.uint64)
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = (dr - 1 + off) % n_accounts + 1
    arr["amount_lo"] = 1
    arr["ledger"] = 1
    arr["code"] = 1
    arr["flags"] = flags
    return arr.tobytes()


def _post_body(pend_body: bytes, start_id: int) -> bytes:
    """Full-amount posts of every pending transfer in `pend_body`
    (two-phase second leg; reference: src/state_machine.zig:907-1014)."""
    pend = np.frombuffer(pend_body, dtype=TRANSFER_DTYPE)
    arr = np.zeros(len(pend), dtype=TRANSFER_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + len(pend), dtype=np.uint64)
    arr["pending_id_lo"] = pend["id_lo"]
    arr["pending_id_hi"] = pend["id_hi"]
    arr["flags"] = 4  # post_pending_transfer
    return arr.tobytes()


class _BenchClient:
    """One session: its own TCP connection + vsr Client, one request in
    flight, per-batch latency recorded. Retries belong to the client
    RUNTIME (timeout/backoff state machine, vsr/client.py): the driver
    maps wall time onto its ticks and otherwise only pumps."""

    def __init__(self, client_id: int, port: int):
        from tigerbeetle_tpu.io.message_bus import TCPMessageBus
        from tigerbeetle_tpu.vsr.client import Client, WallTicker

        self.bus = TCPMessageBus([("127.0.0.1", port)], client_id)
        self.client = Client(client_id, self.bus, replica_count=1)
        # 0.1s ticks x 30-tick base = first retry ~3s, exponential after
        self.ticker = WallTicker(self.client, tick_s=0.1)
        self.sent_at = 0.0
        self.latencies_ms: list[float] = []
        self.replies: list[bytes] = []

    def pump(self) -> None:
        self.bus.pump(timeout=0.0)

    def wait_reply(self, deadline_s: float = 120.0) -> tuple:
        t0 = time.monotonic()
        while not self.client.done:
            self.pump()
            now = time.monotonic()
            if now - t0 > deadline_s:
                raise TimeoutError("benchmark client: no reply")
            self.ticker.advance(now)  # the runtime owns retransmits
            if not self.client.done:
                time.sleep(0.0001)
        return self.client.take_reply()

    def register(self) -> None:
        self.client.register()
        self.wait_reply()


def run_e2e(
    n_accounts: int = 10_000,
    n_transfers: int = 1_000_000,
    batch: int = BATCH,
    clients: int = 16,
    warmup_batches: int = 2,
    jax_platform: str | None = None,
    tmpdir: str | None = None,
    server_args: tuple[str, ...] = (),
    backend: str = "native",
    workload: str = "simple",
    driver: str = "python",
    trace: str | None = None,
    cdc_slow_us: int | None = None,
    log=None,
) -> dict:
    """Format, start a real replica, drive the protocol, return metrics.

    The server process owns the accelerator; this process stays host-only
    (numpy + sockets) so both can run on a machine with one TPU chip."""
    log = log or (lambda *_: None)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_bench_")
        tmpdir = tmp.name
    path = os.path.join(tmpdir, "bench.tigerbeetle")
    port = free_port()

    slots_log2 = 14
    warm_est = warmup_batches + 16 + 4 + 2 + 1  # singles + group rounds
    while n_transfers + warm_est * batch > (1 << slots_log2) // 2:
        slots_log2 += 1
    acct_log2 = max(14, (n_accounts * 2 + 2).bit_length())

    # prepend (not replace) PYTHONPATH: the TPU runtime may be provided by
    # a site dir already on it
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1", path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    # Own process group (start_new_session): teardown kills the whole group
    # so a wedged server (or anything it forked) cannot outlive the bench
    # and skew later timings. The server also carries a parent-death
    # watchdog (cli._install_parent_death_watchdog) for the paths where
    # this harness itself is SIGKILLed.
    # --trace: the server dumps its commit-pipeline spans (fuse hold,
    # journal writes, commit dispatch/finalize, shadow uploads) as Chrome
    # trace events on SIGTERM; run_e2e loads them back so the bench can
    # merge them into one Perfetto-loadable file.
    server_trace = os.path.join(tmpdir, "server_trace.json") if trace else None
    trace_args = ("--trace", server_trace) if server_trace else ()
    # CDC A/B mode: a live change-stream pump with a deliberately slow
    # (non-blocking, refusing) sink — the acceptance run proving the live
    # tail backpressures the PUMP and never the commit path. The server's
    # [stats] registry snapshot carries cdc.lag_ops /
    # cdc.backpressure_pauses back out.
    cdc_args: tuple[str, ...] = ()
    if cdc_slow_us is not None:
        cdc_args = (
            "--cdc-jsonl", os.path.join(tmpdir, "cdc.jsonl"),
            "--cdc-slow-us", str(cdc_slow_us),
        )
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         "--addresses", f"127.0.0.1:{port}",
         "--account-slots-log2", str(acct_log2),
         "--transfer-slots-log2", str(slots_log2),
         "--backend", backend,
         *trace_args, *cdc_args, *server_args, path],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        while True:  # skip [boot] trace lines until ready (TPU init)
            line = proc.stdout.readline()
            if "listening" in line:
                break
            if not line:
                raise RuntimeError("bench server died before listening")
            log(line.rstrip())
        log(f"server up on :{port} (slots 2^{slots_log2})")

        # Keep draining server output: an unread pipe fills and BLOCKS the
        # server's next print (debug mode would wedge the whole benchmark).
        server_stats: dict = {}

        def _drain_stdout():
            import json as _json

            for out in proc.stdout:
                line = out.rstrip()
                if line.startswith("[stats] "):
                    try:
                        server_stats.update(_json.loads(line[8:]))
                    except ValueError:
                        pass
                log("[server]", line)

        drain_thread = threading.Thread(target=_drain_stdout, daemon=True)
        drain_thread.start()
        if driver == "async":
            result = _drive_async(
                port, n_accounts, n_transfers, batch, clients,
                warmup_batches, log, workload=workload,
            )
        else:
            result = _drive(
                proc, port, n_accounts, n_transfers, batch, clients,
                warmup_batches, log, workload=workload,
            )
        # SIGTERM makes the server emit its [stats] line (group-commit hit
        # rate etc.); after exit the pipe hits EOF, so joining the drain
        # thread is deterministic (no sleep race). Dual mode drains the
        # device shadow and compiles+runs the fingerprint kernels at
        # shutdown — off the clock, but the wait must cover it.
        proc.terminate()
        try:
            # dual modes: must outlast DualLedger.finalize's own drain
            # timeout (600s) or a slow-but-legal verification is killed
            # mid-flight and the [stats] line is lost
            dual = "+" in backend or backend == "dual"
            proc.wait(timeout=650 if dual else 10)
        except subprocess.TimeoutExpired:
            pass
        drain_thread.join(timeout=5)
        if server_stats:
            result["server_stats"] = server_stats
            g = server_stats.get("group", {})
            total = g.get("fused_ops", 0) + g.get("solo_ops", 0)
            if total:
                result["group_commit_hit_rate"] = round(
                    g.get("fused_ops", 0) / total, 4
                )
                if g.get("fused_groups"):
                    result["group_fuse_width"] = round(
                        g["fused_ops"] / g["fused_groups"], 2
                    )
                # fuse-window diagnostics: holds that expired short vs
                # holds at all, and the window the run ended at (autotune
                # moves it) — a low hit rate is attributable, not a mystery
                result["group_fuse_holds"] = g.get("fuse_holds", 0)
                result["group_fuse_expired"] = g.get("fuse_expired", 0)
            fuse = server_stats.get("fuse", {})
            if fuse:
                result["fuse_window_us"] = fuse.get("window_us")
                result["fuse_autotune"] = fuse.get("autotune")
            loop = server_stats.get("loop", {})
            if loop:
                result["loop_us_per_batch"] = loop.get("us_per_batch")
            if "metrics" in server_stats:
                # the server's full registry snapshot (counters + timing
                # histogram percentiles) — sourced from the same store as
                # the loop/group numbers above
                result["server_metrics"] = server_stats["metrics"]
                if cdc_slow_us is not None:
                    m = server_stats["metrics"]
                    result["cdc_lag_ops"] = m.get("gauges", {}).get(
                        "cdc.lag_ops"
                    )
                    result["cdc_backpressure_pauses"] = m.get(
                        "counters", {}
                    ).get("cdc.backpressure_pauses")
                    result["cdc_ops_streamed"] = m.get(
                        "counters", {}
                    ).get("cdc.ops")
            if "device_shadow" in server_stats:
                result["device_shadow"] = server_stats["device_shadow"]
                sh = server_stats["device_shadow"].get("shadow") or {}
                if sh.get("upload_overlap") is not None:
                    result["shadow_upload_overlap"] = sh["upload_overlap"]
                # dual (follower) mode: the end-of-run hash-log ring
                # check + the applier's lag/overlap gauges
                hl = server_stats["device_shadow"].get("hash_log")
                if hl is not None:
                    result["device_hash_log_ok"] = hl.get("ok")
                gauges = server_stats.get("metrics", {}).get("gauges", {})
                if "shadow.device_lag_ops" in gauges:
                    result["device_lag_ops"] = gauges[
                        "shadow.device_lag_ops"
                    ]
                if "shadow.device_apply_overlap" in gauges:
                    result["device_apply_overlap"] = gauges[
                        "shadow.device_apply_overlap"
                    ]
        if server_trace and os.path.exists(server_trace):
            import json as _json

            try:
                with open(server_trace) as f:
                    result["trace_events"] = _json.load(f)["traceEvents"]
            except (ValueError, KeyError, OSError):
                pass  # a torn dump must not sink the run's numbers
        return result
    finally:
        if proc.poll() is None:
            proc.terminate()  # SIGTERM first: lets a profiling run dump
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        kill_process_group(proc)
        if own_tmp:
            tmp.cleanup()


def _drive_async(port, n_accounts, n_transfers, batch, clients,
                 warmup_batches, log, workload: str = "simple") -> dict:
    """Drive the protocol through the ASYNC packet ABI (native/tb_client.cc
    tb_client_async_*): ONE client process, one AsyncNativeClient whose
    session pool keeps `clients` requests in flight — the reference's
    packet/completion model replacing the Python per-session loop
    (reference: src/clients/c/tb_client/packet.zig)."""
    import threading as _threading

    from tigerbeetle_tpu.client_ffi import AsyncNativeClient, NativeClient
    from tigerbeetle_tpu.state_machine import decode_results

    rng = np.random.default_rng(42)
    addresses = f"127.0.0.1:{port}"
    ctl = NativeClient(addresses)  # blocking control-plane session

    t0 = time.monotonic()
    next_id = 1
    while next_id <= n_accounts:
        n = min(batch, n_accounts - next_id + 1)
        assert ctl._request(
            Operation.create_accounts, _accounts_body(next_id, n)
        ) == b"", "account create failed"
        next_id += n
    log(f"{n_accounts} accounts in {time.monotonic() - t0:.1f}s")

    ac = AsyncNativeClient(addresses, sessions=clients)
    log(f"async client up: {clients} pooled sessions")
    try:
        # -- build bodies (workload gen off the clock) --
        n_batches = (n_transfers + batch - 1) // batch
        nid = 1_000_000
        if workload == "two_phase":
            pends, posts = [], []
            for _ in range((n_batches + 1) // 2):
                pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
                nid += batch
                pends.append(pend)
                posts.append(_post_body(pend, nid))
                nid += batch
            waves = [pends, posts]
            posted_batches = len(posts)
        else:
            bodies = []
            for _ in range(n_batches):
                bodies.append(_transfers_body(rng, nid, batch, n_accounts))
                nid += batch
            waves = [bodies]
            posted_batches = len(bodies)

        # -- warmup (kernel compiles / cache warm): singles, then a full
        # concurrent burst so fused group paths compile before the clock --
        op = Operation.create_transfers
        warm = 0
        for _ in range(warmup_batches):
            pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
            nid += batch
            assert ac.submit(op, pend).result(timeout=600) == b""
            post = _post_body(pend, nid)
            nid += batch
            assert ac.submit(op, post).result(timeout=600) == b""
            warm += 2
        burst = [
            _transfers_body(rng, nid + i * batch, batch, n_accounts)
            for i in range(clients)
        ]
        nid += clients * batch
        for f in [ac.submit(op, b) for b in burst]:
            assert f.result(timeout=600) == b""
        warm += clients
        # warmup posted amounts: each pend+post pair posts ONE batch's
        # amounts (the pend batch itself only moves pending), plus the
        # simple burst batches
        posted_batches += warmup_batches + clients
        log(f"warmup done ({warm} batches); timing "
            f"{sum(len(w) for w in waves)} batches")

        # -- timed: submit with a bounded window (the pool keeps `clients`
        # requests on the wire; the window keeps its queue fed without
        # turning latency into pure queue depth) --
        sem = _threading.Semaphore(clients * 2)
        lat_ms: list[float] = []
        lat_lock = _threading.Lock()
        failures = 0
        t_start = time.monotonic()
        for wave in waves:
            futs = []
            for body in wave:
                sem.acquire()
                t_sub = time.monotonic()

                def _done(_f, t=t_sub):
                    with lat_lock:
                        lat_ms.append((time.monotonic() - t) * 1e3)
                    sem.release()

                fut = ac.submit(op, body)
                fut.add_done_callback(_done)
                futs.append(fut)
            for f in futs:  # wave barrier (two_phase: posts follow pends)
                failures += len(decode_results(f.result(timeout=600), op))
        wall = time.monotonic() - t_start
        n_timed = sum(len(w) for w in waves) * batch
        assert failures == 0, f"{failures} transfers failed"
    finally:
        ac.close()

    # -- conservation over the wire (blocking control session) --
    total = posted_batches * batch
    dpo = cpo = found = 0
    ids = list(range(1, n_accounts + 1))
    for i in range(0, len(ids), 8000):
        accounts = ctl.lookup_accounts(ids[i : i + 8000])
        found += len(accounts)
        dpo += sum(a.debits_posted for a in accounts)
        cpo += sum(a.credits_posted for a in accounts)
    assert found == n_accounts, (found, n_accounts)
    assert dpo == cpo == total, (dpo, cpo, total)
    log(f"conservation verified: {total} transfers, dpo==cpo=={total}")
    ctl.close()

    lat = np.percentile(lat_ms if lat_ms else [float("nan")],
                        [0, 25, 50, 75, 100])
    return {
        "durable_tps": round(n_timed / wall, 1) if wall else 0.0,
        "n_transfers": n_timed,
        "wall_s": round(wall, 2),
        "clients": clients,
        "driver": "async_abi",
        "latency_ms_p00_p25_p50_p75_p100": [round(float(x), 2) for x in lat],
    }


def _drive(proc, port, n_accounts, n_transfers, batch, clients,
           warmup_batches, log, workload: str = "simple") -> dict:
    from tigerbeetle_tpu.state_machine import decode_results

    rng = np.random.default_rng(42)
    sessions = [_BenchClient(0xB0000 + i, port) for i in range(clients)]
    for s in sessions:
        s.register()
    log(f"{clients} session(s) registered")

    # -- accounts (absorbs the create_accounts compile) --
    t0 = time.monotonic()
    next_id = 1
    while next_id <= n_accounts:
        n = min(batch, n_accounts - next_id + 1)
        sessions[0].client.request(
            Operation.create_accounts, _accounts_body(next_id, n)
        )
        _h, body = sessions[0].wait_reply()
        assert body == b"", "account create failed"
        next_id += n
    log(f"{n_accounts} accounts in {time.monotonic() - t0:.1f}s")

    # -- warmup rounds: singles compile the per-batch kernel; k
    # simultaneous batches compile each fused group kernel (k=8/4/2) —
    # lazily compiling those mid-run would stall the timed phase for
    # tens of seconds each (device backend; the native engine just warms
    # its caches) --
    from tigerbeetle_tpu.models.ledger import DeviceLedger

    group_rounds = sorted(
        {min(g, clients) for g in DeviceLedger.GROUP_KS if clients >= 2},
        reverse=True,
    )
    group_rounds = [k for k in group_rounds if k >= 2]
    rounds = [1] * warmup_batches + group_rounds
    total_warm = sum(rounds)

    # -- build all bodies up front (workload gen off the clock), split
    # into PER-SESSION queues. two_phase: each session alternates a
    # pending batch with the full-amount posts of ITS OWN previous batch
    # (the session's one-in-flight protocol orders post after pend) --
    id_stride = (n_transfers // clients + 3 * batch) * 2
    per_session: list[list[bytes]] = [[] for _ in sessions]
    n_total_batches = (n_transfers + batch - 1) // batch + total_warm
    posted_batches = 0  # batches that land posted amounts (conservation)
    for i, _s in enumerate(sessions):
        nid = 1_000_000 + i * id_stride
        share = n_total_batches // clients + (
            1 if i < n_total_batches % clients else 0
        )
        q = per_session[i]
        if workload == "two_phase":
            while len(q) < share:
                pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
                nid += batch
                q.append(pend)
                if len(q) < share:
                    q.append(_post_body(pend, nid))
                    nid += batch
                    posted_batches += 1
        else:
            for _ in range(share):
                q.append(_transfers_body(rng, nid, batch, n_accounts))
                nid += batch
                posted_batches += 1

    # warmup: pull evenly from the per-session queues (two_phase pairs
    # stay in order within a session)
    warm_done = 0
    for k in rounds:
        active = [
            (s, q) for s, q in zip(sessions, per_session) if q
        ][: max(k, 1)]
        for s, q in active:
            s.client.request(Operation.create_transfers, q.pop(0))
        for s, _q in active:
            _h, body = s.wait_reply(deadline_s=600.0)  # compiles are slow
            assert body == b"", decode_results(
                body, Operation.create_transfers
            )[:3]
            warm_done += 1
    n_work = sum(len(q) for q in per_session)
    log(f"warmup done ({warm_done} batches, rounds {rounds}); "
        f"timing {n_work} batches")

    # -- timed phase: each session keeps one batch in flight --
    import selectors as _selectors

    # One wakeup selector over every session's socket: the idle path blocks
    # until ANY reply bytes arrive instead of sleep-polling (time.sleep's
    # ~0.5 ms real granularity dominated the driver and starved the server).
    wake = _selectors.DefaultSelector()
    for s in sessions:
        for conn in s.bus.conns.values():
            try:
                wake.register(conn.sock, _selectors.EVENT_READ)
            except (KeyError, ValueError):
                pass
    lat_ms: list[float] = []
    failures = 0
    inflight: dict[int, float] = {}
    t_start = time.monotonic()
    for s, q in zip(sessions, per_session):
        if q:
            s.client.request(Operation.create_transfers, q.pop(0))
            inflight[s.client.client_id] = time.monotonic()
    deadline = t_start + max(600.0, n_transfers / 1000)
    done_batches = 0
    while inflight:
        progressed = False
        for s, q in zip(sessions, per_session):
            cid = s.client.client_id
            if cid not in inflight:
                continue
            s.pump()
            if s.client.reply is None:
                # a loss under backpressure retransmits via the client
                # runtime's own timeout ladder
                s.ticker.advance(time.monotonic())
                continue
            _h, body = s.client.take_reply()
            lat_ms.append(
                (time.monotonic() - inflight.pop(cid)) * 1e3
            )
            failures += len(decode_results(body, Operation.create_transfers))
            done_batches += 1
            progressed = True
            if q:
                s.client.request(Operation.create_transfers, q.pop(0))
                inflight[cid] = time.monotonic()
        if not progressed:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"benchmark stalled at batch {done_batches}/{n_work}"
                )
            # reconcile registrations: a dropped+redialed connection has a
            # NEW socket that must wake the idle path too
            regged = {k.fileobj for k in wake.get_map().values()}
            current = {
                c.sock for s in sessions for c in s.bus.conns.values()
            }
            for sock in current - regged:
                try:
                    wake.register(sock, _selectors.EVENT_READ)
                except (KeyError, ValueError, OSError):
                    pass
            for sock in regged - current:
                try:
                    wake.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
            wake.select(timeout=0.002)  # woken by the first reply bytes
    wake.close()
    wall = time.monotonic() - t_start
    n_timed = done_batches * batch
    assert failures == 0, f"{failures} transfers failed"
    # conservation total: every POSTED batch moves amount=1 per event
    # (simple batches post directly; two_phase pend batches only move
    # pending amounts, released when their post batch lands)
    total = posted_batches * batch
    return _verify_and_report(
        sessions[0], n_accounts, total, wall, n_timed, lat_ms, clients, log
    )


# ---------------------------------------------------------------------
# ingress: 10k multiplexed sessions through the gateway
# ---------------------------------------------------------------------


class _MuxSession:
    """One logical session multiplexed over a shared (demux) bus
    connection, driven by the client RUNTIME: busy sheds back off on the
    decorrelated ladder, losses retransmit on the timeout ladder — the
    driver only advances the ticker and harvests replies."""

    __slots__ = ("client", "ticker", "sent_at", "events")

    def __init__(self, client_id: int, bus):
        from tigerbeetle_tpu.vsr.client import Client, WallTicker

        # 5ms ticks: busy retries land at 10-320ms (decorrelated), the
        # loss ladder starts at 200ms (40 ticks) and caps at 4x
        self.client = Client(
            client_id, bus, replica_count=1,
            request_timeout_ticks=40, max_backoff_exponent=2,
            ping_ticks=0,  # 10k idle sessions must not ping-storm
        )
        self.ticker = WallTicker(self.client, tick_s=0.005)
        self.sent_at = 0.0
        self.events = 0  # events this session has in flight

    def poll(self, now: float) -> bool:
        """Drive one in-flight request: True once its reply landed.
        Retry cadence lives in the Client's runtime config now
        (request_timeout_ticks), not here."""
        c = self.client
        if c.done:
            return True
        if c.in_flight is None:
            return False
        self.ticker.advance(now)
        return c.done


def run_ingress_sessions(
    n_sessions: int = 10_000,
    conns: int = 16,
    n_accounts: int = 512,
    baseline_sessions: int = 10,
    driver_batches: int = 30,
    batch: int = 512,
    bg_window: int = 32,
    sat_window: int = 256,
    sat_batches: int = 120,
    reg_window: int = 512,
    reply_slots: int = 64,
    jax_platform: str | None = "cpu",
    tmpdir: str | None = None,
    log=None,
) -> dict:
    """The ingress_sessions bench segment: `n_sessions` LOGICAL sessions
    multiplexed over `conns` TCP connections against one gateway-fronted
    replica (native backend — ingress is a host-path measurement).

    Phases:
    A. baseline: `baseline_sessions` sessions drive `driver_batches`
       batches each; per-batch latency p99 is the 10-session reference.
    B. live: ALL `n_sessions` sessions register (the connect storm —
       every register is a consensus op through admission), then the
       same driver workload runs while a rotating background window
       keeps distant sessions active. p99 here vs A is the acceptance
       ratio (<= 2x with 10k live sessions).
    C. saturation: `sat_window` sessions keep full batches in flight
       concurrently — far past the pipeline cap, so the regulator sheds
       (typed busy replies, client backoff-retry). Event throughput here
       vs B shows shedding protects the pipeline instead of collapsing
       it.

    Conservation is verified over the wire at the end (every acked
    transfer moved amount=1)."""
    import json as _json
    from collections import deque

    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.types import Operation

    log = log or (lambda *_: None)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_ingress_")
        tmpdir = tmp.name
    path = os.path.join(tmpdir, "ingress.tigerbeetle")
    port = free_port()
    clients_max = n_sessions + 64
    slots_log2 = 14
    total_est = (
        (baseline_sessions + bg_window) * driver_batches * batch * 4
        + sat_batches * batch + n_sessions
    )
    while total_est > (1 << slots_log2) // 2:
        slots_log2 += 1

    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform
    session_args = (
        "--clients-max", str(clients_max),
        "--client-reply-slots", str(reply_slots),
    )
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         *session_args, path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         "--addresses", f"127.0.0.1:{port}",
         "--account-slots-log2", str(max(14, (n_accounts * 2 + 2).bit_length())),
         "--transfer-slots-log2", str(slots_log2),
         "--backend", "native", "--ingress", *session_args, path],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    buses: list[TCPMessageBus] = []
    try:
        while True:
            line = proc.stdout.readline()
            if "listening" in line:
                break
            if not line:
                raise RuntimeError("ingress server died before listening")
            log(line.rstrip())
        log(f"server up on :{port} ({n_sessions} sessions over {conns} conns)")
        server_stats: dict = {}

        def _drain_stdout():
            for out in proc.stdout:
                line = out.rstrip()
                if line.startswith("[stats] "):
                    try:
                        server_stats.update(_json.loads(line[8:]))
                    except ValueError:
                        pass
                log("[server]", line)

        drain_thread = threading.Thread(target=_drain_stdout, daemon=True)
        drain_thread.start()

        # demux buses: one TCP connection each, N sessions' Clients per
        # bus dispatching by the reply frame's client id
        buses = [
            TCPMessageBus(
                [("127.0.0.1", port)], 0xC0DE0000 + b, demux=True
            )
            for b in range(conns)
        ]

        def pump_all() -> None:
            for b in buses:
                b.pump(timeout=0.0)

        rng = np.random.default_rng(7)
        next_id = [1_000_000]

        def transfer_body(count: int) -> bytes:
            body = _transfers_body(rng, next_id[0], count, n_accounts)
            next_id[0] += count
            return body

        def register_all(sessions, deadline_s: float) -> float:
            """Bounded-window registration storm; returns wall seconds."""
            t0 = time.monotonic()
            pending = deque(sessions)
            active: list[_MuxSession] = []
            while pending or active:
                now = time.monotonic()
                if now - t0 > deadline_s:
                    raise TimeoutError(
                        f"registration stalled: {len(pending)} pending "
                        f"{len(active)} active"
                    )
                while pending and len(active) < reg_window:
                    s = pending.popleft()
                    s.client.register()
                    s.sent_at = now
                    active.append(s)
                pump_all()
                still = []
                for s in active:
                    if s.poll(now):
                        s.client.take_reply()
                        assert s.client.session != 0
                    else:
                        still.append(s)
                active = still
            return time.monotonic() - t0

        def run_phase(drivers, bodies, deadline_s: float,
                      background=None, lat_ms=None) -> tuple[int, float]:
            """Each driver keeps one body from the shared deque in
            flight (busy -> backoff resend). `background` (sessions,
            window): a rotating window of single-transfer requests over
            the whole live set. Returns (events acked, wall seconds)."""
            t0 = time.monotonic()
            events = 0

            def take_ok_reply(s, prefix):
                _h, body = s.client.take_reply()
                if body != b"":
                    from tigerbeetle_tpu.state_machine import decode_results

                    raise AssertionError(
                        f"{prefix}: "
                        f"{decode_results(body, Operation.create_transfers)[:4]} "
                        f"(reply client={_h.client:#x} req={_h.request} "
                        f"operation={_h.operation} op={_h.op} "
                        f"events={s.events})"
                    )

            inflight: dict[int, _MuxSession] = {}
            bg_inflight: list[_MuxSession] = []
            bg_iter = None
            if background is not None:
                bg_sessions, bg_cap = background

                def bg_cycle():
                    while True:
                        yield from bg_sessions

                bg_iter = bg_cycle()
            idle = [s for s in drivers]
            while bodies or inflight or bg_inflight:
                now = time.monotonic()
                if now - t0 > deadline_s:
                    raise TimeoutError(
                        f"ingress phase stalled: {len(bodies)} bodies "
                        f"{len(inflight)} inflight"
                    )
                while bodies and idle:
                    s = idle.pop()
                    body = bodies.popleft()
                    s.events = len(body) // 128
                    s.client.request(Operation.create_transfers, body)
                    s.sent_at = now
                    inflight[s.client.client_id] = s
                if bg_iter is not None and bodies:
                    scanned = 0  # bounded: never spin hunting an idle session
                    while len(bg_inflight) < bg_cap and scanned < 4 * bg_cap:
                        s = next(bg_iter)
                        scanned += 1
                        if (
                            s.client.in_flight is not None
                            or s.client.session == 0
                        ):
                            continue
                        s.events = 1
                        s.client.request(
                            Operation.create_transfers, transfer_body(1)
                        )
                        s.sent_at = now
                        bg_inflight.append(s)
                pump_all()
                for cid in list(inflight):
                    s = inflight[cid]
                    if s.poll(now):
                        take_ok_reply(s, "transfer failed")
                        events += s.events
                        if lat_ms is not None:
                            lat_ms.append((time.monotonic() - s.sent_at) * 1e3)
                        del inflight[cid]
                        idle.append(s)
                still_bg = []
                for s in bg_inflight:
                    if s.poll(now):
                        take_ok_reply(s, "bg transfer failed")
                        events += s.events
                    else:
                        still_bg.append(s)
                bg_inflight = still_bg
            return events, time.monotonic() - t0

        # -- build sessions: drivers first, then the long tail --
        all_sessions = [
            _MuxSession(0xB0000000 + i, buses[i % conns])
            for i in range(n_sessions)
        ]
        drivers = all_sessions[:baseline_sessions]

        # -- phase A: 10-session baseline --
        reg_s0 = register_all(drivers, deadline_s=120.0)
        s0 = drivers[0]
        next_acct = 1
        while next_acct <= n_accounts:
            k = min(BATCH, n_accounts - next_acct + 1)
            s0.client.request(
                Operation.create_accounts, _accounts_body(next_acct, k)
            )
            s0.sent_at = time.monotonic()
            t_acct = time.monotonic()
            while not s0.poll(time.monotonic()):
                pump_all()
                if time.monotonic() - t_acct > 120:
                    raise TimeoutError("account create stalled")
            _h, body = s0.client.take_reply()
            assert body == b"", "account create failed"
            next_acct += k
        warm = deque(transfer_body(batch) for _ in range(4))
        run_phase(drivers, warm, deadline_s=300.0)  # warm engine caches
        lat_a: list[float] = []
        bodies = deque(
            transfer_body(batch)
            for _ in range(baseline_sessions * driver_batches)
        )
        ev_a, wall_a = run_phase(
            drivers, bodies, deadline_s=600.0, lat_ms=lat_a
        )
        p99_a = float(np.percentile(lat_a, 99))
        log(f"baseline: {ev_a} events in {wall_a:.2f}s p99={p99_a:.2f}ms")

        # -- phase B: the full session population goes live --
        reg_s = register_all(
            all_sessions[baseline_sessions:],
            deadline_s=max(300.0, n_sessions / 20),
        )
        log(f"{n_sessions} sessions registered in {reg_s0 + reg_s:.1f}s")
        lat_b: list[float] = []
        bodies = deque(
            transfer_body(batch)
            for _ in range(baseline_sessions * driver_batches)
        )
        ev_b, wall_b = run_phase(
            drivers, bodies, deadline_s=600.0,
            background=(all_sessions[baseline_sessions:], bg_window),
            lat_ms=lat_b,
        )
        p99_b = float(np.percentile(lat_b, 99))
        tps_b = ev_b / wall_b if wall_b else 0.0
        log(f"live: {ev_b} events in {wall_b:.2f}s p99={p99_b:.2f}ms")

        # -- phase C: deliberate saturation (shed expected) --
        busy_before = sum(s.client.busy_replies for s in all_sessions)
        sat = all_sessions[:sat_window]
        bodies = deque(transfer_body(batch) for _ in range(sat_batches))
        ev_c, wall_c = run_phase(sat, bodies, deadline_s=600.0)
        tps_c = ev_c / wall_c if wall_c else 0.0
        busy_replies = (
            sum(s.client.busy_replies for s in all_sessions) - busy_before
        )
        log(f"saturated: {ev_c} events in {wall_c:.2f}s "
            f"busy_replies={busy_replies}")

        # -- conservation over the wire --
        from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids

        total = ev_a + ev_b + ev_c + batch * 4  # + warmup
        s0 = drivers[0]
        dpo = cpo = found = 0
        for i in range(0, n_accounts, 8000):
            ids = list(range(1 + i, 1 + min(i + 8000, n_accounts)))
            s0.client.request(Operation.lookup_accounts, encode_ids(ids))
            s0.sent_at = time.monotonic()
            t0 = time.monotonic()
            while not s0.poll(time.monotonic()):
                pump_all()
                if time.monotonic() - t0 > 120:
                    raise TimeoutError("conservation lookup stalled")
            _h, body = s0.client.take_reply()
            arr = decode_accounts(body)
            found += len(arr)
            dpo += int(arr["debits_posted_lo"].sum())
            cpo += int(arr["credits_posted_lo"].sum())
        assert found == n_accounts, (found, n_accounts)
        assert dpo == cpo == total, (dpo, cpo, total)
        log(f"conservation verified: {total} transfers")

        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        drain_thread.join(timeout=5)
        out = {
            "sessions": n_sessions,
            "conns": conns,
            "register_s": round(reg_s0 + reg_s, 2),
            "baseline_sessions": baseline_sessions,
            "p99_baseline_ms": round(p99_a, 2),
            "p99_live_ms": round(p99_b, 2),
            "p99_ratio": round(p99_b / p99_a, 3) if p99_a else None,
            "tps_live": round(tps_b, 1),
            "tps_saturated": round(tps_c, 1),
            "tps_saturated_ratio": (
                round(tps_c / tps_b, 3) if tps_b else None
            ),
            "busy_replies": busy_replies,
            "n_transfers": total,
        }
        m = server_stats.get("metrics", {})
        if m:
            c = m.get("counters", {})
            out["ingress_shed"] = c.get("ingress.shed", 0)
            out["ingress_admitted"] = c.get("ingress.admitted", 0)
            out["ingress_retransmits"] = c.get("ingress.retransmits", 0)
            out["ingress_sessions_gauge"] = m.get("gauges", {}).get(
                "ingress.sessions"
            )
        return out
    finally:
        for b in buses:
            try:
                b.sel.close()
            except Exception:
                pass
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        kill_process_group(proc)
        if own_tmp:
            tmp.cleanup()


# ---------------------------------------------------------------------
# frontier: offered-load ladder vs latency against a live server
# ---------------------------------------------------------------------


def run_frontier(
    steps=(20_000, 50_000, 100_000, 200_000),
    step_s: float = 6.0,
    batch: int = 2048,
    sessions: int = 32,
    conns: int = 4,
    n_accounts: int = 512,
    backend: str = "dual",
    sample_every: int = 1,
    warmup_batches: int = 4,
    drain_s: float = 60.0,
    jax_platform: str | None = None,
    tmpdir: str | None = None,
    log=None,
) -> dict:
    """The load/latency FRONTIER segment (ROADMAP item 4's artifact):
    step offered load across a ladder against one live gateway-fronted
    server and report, per step, offered vs achieved tps, client-side
    p50/p95/p99, the typed-shed rate, and the DOMINANT critical-path leg
    from the server's per-request latency anatomy (latency.py) — "where
    do the milliseconds go as load rises", the artifact that picks the
    first target of the latency attack.

    The driver is OPEN-LOOP: submissions are scheduled at the offered
    rate, queue when every session is busy, and each request's latency
    is measured from its SCHEDULED time — so saturation shows up as
    rising latency (no coordinated omission), and typed busy sheds ride
    the client runtime's backoff ladder like production traffic. Server-
    side numbers come from live [stats] wire snapshots taken between
    steps (inspect_live): counter deltas give the step's sheds, and
    latency.* histogram deltas give its dominant leg.

    The final snapshot's slowest-request breakdown proves the
    decomposition ACCOUNTS for the time: legs are consecutive stamp
    intervals, so sum(legs) must be within rounding of e2e
    (`breakdown_accounted_ratio`, asserted by the frontier smoke)."""
    import json as _json
    from collections import deque

    from tigerbeetle_tpu.inspect import inspect_live
    from tigerbeetle_tpu.io.message_bus import TCPMessageBus
    from tigerbeetle_tpu.latency import (
        device_leg_totals,
        dominant_leg,
        leg_totals,
    )

    log = log or (lambda *_: None)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_frontier_")
        tmpdir = tmp.name
    path = os.path.join(tmpdir, "frontier.tigerbeetle")
    port = free_port()
    total_est = int(
        sum(r * step_s for r in steps) * 1.5
        + (warmup_batches + 4) * batch + sessions * batch
    )
    slots_log2 = 15
    while total_est > (1 << slots_log2) // 2:
        slots_log2 += 1
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform
    session_args = ("--clients-max", str(sessions + 16))
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1",
         *session_args, path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         "--addresses", f"127.0.0.1:{port}",
         "--account-slots-log2",
         str(max(14, (n_accounts * 2 + 2).bit_length())),
         "--transfer-slots-log2", str(slots_log2),
         "--backend", backend, "--ingress",
         "--latency-sample-every", str(sample_every),
         *session_args, path],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    buses: list[TCPMessageBus] = []
    try:
        while True:
            line = proc.stdout.readline()
            if "listening" in line:
                break
            if not line:
                raise RuntimeError("frontier server died before listening")
            log(line.rstrip())
        log(f"server up on :{port} backend={backend} ladder={list(steps)}")
        server_stats: dict = {}

        def _drain_stdout():
            for out_line in proc.stdout:
                s = out_line.rstrip()
                if s.startswith("[stats] "):
                    try:
                        server_stats.update(_json.loads(s[8:]))
                    except ValueError:
                        pass
                log("[server]", s)

        drain_thread = threading.Thread(target=_drain_stdout, daemon=True)
        drain_thread.start()

        buses = [
            TCPMessageBus([("127.0.0.1", port)], 0xF0000000 + b, demux=True)
            for b in range(conns)
        ]

        def pump_all() -> None:
            for b in buses:
                b.pump(timeout=0.0)

        fleet = [
            _MuxSession(0xF1000000 + i, buses[i % conns])
            for i in range(sessions)
        ]
        # registration (bounded window, reusing the runtime's retries)
        t0 = time.monotonic()
        pending = deque(fleet)
        active: list[_MuxSession] = []
        while pending or active:
            now = time.monotonic()
            if now - t0 > 120:
                raise TimeoutError("frontier registration stalled")
            while pending and len(active) < 64:
                s = pending.popleft()
                s.client.register()
                active.append(s)
            pump_all()
            active = [s for s in active if not (
                s.poll(now) and (s.client.take_reply() or True)
            )]
        rng = np.random.default_rng(11)
        next_id = [1_000_000]

        def transfer_body(count: int) -> bytes:
            body = _transfers_body(rng, next_id[0], count, n_accounts)
            next_id[0] += count
            return body

        def drive_one(s: _MuxSession, op, body, deadline=120.0) -> bytes:
            s.client.request(op, body)
            t_req = time.monotonic()
            while not s.poll(time.monotonic()):
                pump_all()
                if time.monotonic() - t_req > deadline:
                    raise TimeoutError("frontier control request stalled")
            _h, rbody = s.client.take_reply()
            return rbody

        next_acct = 1
        while next_acct <= n_accounts:
            k = min(8190, n_accounts - next_acct + 1)
            assert drive_one(
                fleet[0], Operation.create_accounts,
                _accounts_body(next_acct, k),
            ) == b"", "account create failed"
            next_acct += k
        for _ in range(warmup_batches):  # engine/kernel warm, off the clock
            assert drive_one(
                fleet[0], Operation.create_transfers, transfer_body(batch)
            ) == b""
        log(f"{sessions} sessions + {n_accounts} accounts ready")

        def counters(snap: dict) -> dict:
            return snap.get("metrics", {}).get("counters", {})

        out_steps: list[dict] = []
        acked_total = 0
        by_id = {s.client.client_id: s for s in fleet}
        # in flight ACROSS steps: a drain-timeout leaves requests on the
        # wire, and the next step must neither double-submit on a busy
        # session (the client asserts one in-flight request) nor count
        # the stale replies into its own numbers (value None = stale).
        inflight: dict[int, float | None] = {}  # client_id -> due time
        for rate in steps:
            # stamp the ladder step as a flight-recorder phase: the
            # server's per-interval history slices by step exactly the
            # way a prodday timeline slices by phase (prodday.py
            # slice_history), so a frontier run's recorder entries
            # carry which offered rate produced them
            try:
                from tigerbeetle_tpu.inspect import send_mark

                send_mark("127.0.0.1", port, f"step:{rate}", timeout=2.0)
            except (OSError, RuntimeError, ValueError):
                pass  # observability only: a missed mark never fails a step
            snap0 = inspect_live("127.0.0.1", port)
            interval = batch / rate
            t_start = time.monotonic()
            t_end = t_start + step_s
            due = t_start
            backlog: deque[float] = deque()  # scheduled-but-unsubmitted
            idle = [s for s in fleet if s.client.client_id not in inflight]
            lat_ms: list[float] = []
            offered = acked_win = failures = 0
            while True:
                now = time.monotonic()
                if now >= t_end and not inflight and not backlog:
                    break
                if now - t_end > drain_s:
                    break  # overloaded step: stop draining, report as-is
                while due <= now and due < t_end:
                    backlog.append(due)
                    offered += batch
                    due += interval
                while backlog and idle and now < t_end + drain_s:
                    s = idle.pop()
                    due_t = backlog.popleft()
                    s.client.request(
                        Operation.create_transfers, transfer_body(batch)
                    )
                    inflight[s.client.client_id] = due_t
                if now >= t_end:
                    backlog.clear()  # never submitted: offered, not acked
                pump_all()
                for cid in list(inflight):
                    s = by_id[cid]
                    if s.poll(now):
                        _h, rbody = s.client.take_reply()
                        if rbody != b"":
                            failures += 1
                        due_t = inflight.pop(cid)
                        idle.append(s)
                        acked_total += batch
                        if due_t is None:
                            continue  # a prior step's straggler
                        # latency is recorded for EVERY request scheduled
                        # in the window, even those completing during the
                        # drain — dropping the late ones would understate
                        # p99 exactly at the knee (coordinated omission
                        # through the back door); only window THROUGHPUT
                        # is bounded to the step itself
                        lat_ms.append((now - due_t) * 1e3)
                        if now < t_end:
                            acked_win += batch
            # whatever is still on the wire belongs to no later step
            for cid in inflight:
                inflight[cid] = None
            wall = min(time.monotonic() - t_start, step_s)
            snap1 = inspect_live("127.0.0.1", port)
            c0, c1 = counters(snap0), counters(snap1)
            sheds = c1.get("ingress.shed", 0) - c0.get("ingress.shed", 0)
            admitted = (
                c1.get("ingress.admitted", 0)
                - c0.get("ingress.admitted", 0)
            )
            leg, share = dominant_leg(
                leg_totals(snap0.get("metrics", {})),
                leg_totals(snap1.get("metrics", {})),
            )
            # the commit_wait DECOMPOSITION (device anatomy): which
            # applier sub-leg dominated this step — the "why" behind a
            # commit_wait-dominated knee
            dleg, dshare = dominant_leg(
                device_leg_totals(snap0.get("metrics", {})),
                device_leg_totals(snap1.get("metrics", {})),
            )
            pct = (
                np.percentile(lat_ms, [50, 95, 99])
                if lat_ms else [float("nan")] * 3
            )
            step = {
                "offered_tps": rate,
                "achieved_tps": round(acked_win / wall, 1) if wall else 0.0,
                "offered_events": offered,
                "acked_events_in_window": acked_win,
                "p50_ms": round(float(pct[0]), 3),
                "p95_ms": round(float(pct[1]), 3),
                "p99_ms": round(float(pct[2]), 3),
                "sheds": sheds,
                "shed_rate": (
                    round(sheds / (sheds + admitted), 4)
                    if sheds + admitted else 0.0
                ),
                "dominant_leg": leg,
                "dominant_leg_share": share,
                "dominant_device_subleg": dleg,
                "dominant_device_subleg_share": dshare,
                "failures": failures,
            }
            out_steps.append(step)
            log(f"step {rate}/s: achieved {step['achieved_tps']}/s "
                f"p50={step['p50_ms']}ms p99={step['p99_ms']}ms "
                f"shed_rate={step['shed_rate']} dominant={leg}"
                + (f" device={dleg}" if dleg else ""))
            assert failures == 0, f"{failures} transfer batches failed"

        # decomposition accounting proof: the slowest sampled request's
        # legs are consecutive intervals and must sum to its e2e
        final = inspect_live("127.0.0.1", port)
        breakdown = None
        slowest = final.get("latency_slowest") or []
        if slowest:
            rec = slowest[0]
            legs_sum = sum(rec.get("legs", {}).values())
            breakdown = {
                "e2e_us": rec.get("e2e_us"),
                "legs": rec.get("legs"),
                "dominant": rec.get("dominant"),
                "sum_legs_us": round(legs_sum, 3),
                "accounted_ratio": (
                    round(legs_sum / rec["e2e_us"], 4)
                    if rec.get("e2e_us") else None
                ),
            }
        # device-granularity accounting proof: the slowest sampled APPLY
        # item's sub-legs are consecutive and must sum to its span
        # exactly (accounted_ratio 1.0 — the commit_wait decomposition)
        device_breakdown = None
        dev_slowest = final.get("device_slowest") or []
        if dev_slowest:
            drec = dev_slowest[0]
            dsum = sum(drec.get("legs", {}).values())
            device_breakdown = {
                "apply_e2e_us": drec.get("e2e_us"),
                "legs": drec.get("legs"),
                "dominant": drec.get("dominant"),
                "sum_legs_us": round(dsum, 3),
                "accounted_ratio": (
                    round(dsum / drec["e2e_us"], 4)
                    if drec.get("e2e_us") else None
                ),
            }
        achieved = [s["achieved_tps"] for s in out_steps]
        peak = max(achieved) if achieved else 0.0
        knee = None
        for s in out_steps:
            if s["achieved_tps"] < 0.9 * s["offered_tps"]:
                knee = s["offered_tps"]
                break
        proc.terminate()
        try:
            proc.wait(timeout=650 if backend == "dual" else 30)
        except subprocess.TimeoutExpired:
            pass
        drain_thread.join(timeout=5)
        out = {
            "backend": backend,
            "batch": batch,
            "step_s": step_s,
            "sessions": sessions,
            "sample_every": sample_every,
            "steps": out_steps,
            "peak_achieved_tps": peak,
            "saturation_offered_tps": knee,
            "breakdown": breakdown,
            "device_breakdown": device_breakdown,
            "acked_events": acked_total,
        }
        if backend == "dual" and server_stats:
            shadow = server_stats.get("device_shadow") or {}
            out["device_shadow_verified"] = shadow.get("verified")
        return out
    finally:
        for b in buses:
            try:
                b.sel.close()
            except Exception:
                pass
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        kill_process_group(proc)
        if own_tmp:
            tmp.cleanup()


def _verify_and_report(session, n_accounts, total, wall, n_timed, lat_ms,
                       clients, log) -> dict:
    from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids

    dpo = cpo = found = 0
    ids = list(range(1, n_accounts + 1))
    for i in range(0, len(ids), 8000):
        chunk = ids[i : i + 8000]
        session.client.request(Operation.lookup_accounts, encode_ids(chunk))
        _h, body = session.wait_reply()
        arr = decode_accounts(body)
        found += len(arr)
        dpo += int(arr["debits_posted_lo"].sum())
        cpo += int(arr["credits_posted_lo"].sum())
    assert found == n_accounts, (found, n_accounts)
    assert dpo == cpo == total, (dpo, cpo, total)
    log(f"conservation verified: {total} transfers, dpo==cpo=={total}")

    lat = np.percentile(lat_ms if lat_ms else [float("nan")],
                        [0, 25, 50, 75, 100])
    return {
        "durable_tps": round(n_timed / wall, 1) if wall else 0.0,
        "n_transfers": n_timed,
        "wall_s": round(wall, 2),
        "clients": clients,
        "latency_ms_p00_p25_p50_p75_p100": [round(float(x), 2) for x in lat],
    }
