"""End-to-end benchmark driver: the BASELINE protocol through the FULL
system.

The reference measures its headline number by formatting a data file,
starting a real replica process, and driving create_transfers through a
client over the wire at batch=8190 (reference: scripts/benchmark.sh:34-78,
src/benchmark.zig:23-73: 10k accounts, 10M transfers, batch latency
percentiles printed at the end). This module is that harness for the TPU
build: a real `tigerbeetle_tpu start` server process (WAL on, consensus
path, TCP), driven by native session clients.

Unlike the reference's single sequential client, several clients each keep
one request in flight (the replica's commit window overlaps their journal
writes and device commits — reference: src/vsr/replica.zig:52-70); pass
clients=1 for the strictly sequential protocol.

Used by bench.py (reported as `durable_tps` alongside the kernel flagship
number) and by tests/test_process.py's smoke test (tiny sizes, CPU
backend).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE, Operation

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCH = 8190  # (1 MiB - 128 B) / 128 B


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def kill_process_group(proc) -> None:
    """Last-resort sweep of a server's WHOLE process group (the server is
    spawned with start_new_session=True so pgid == its pid). Idempotent;
    safe after a normal wait()."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError, OSError):
        pass


def _accounts_body(start_id: int, count: int) -> bytes:
    arr = np.zeros(count, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + count, dtype=np.uint64)
    arr["ledger"] = 1
    arr["code"] = 1
    return arr.tobytes()


def _transfers_body(rng, start_id: int, count: int, n_accounts: int,
                    flags: int = 0) -> bytes:
    arr = np.zeros(count, dtype=TRANSFER_DTYPE)
    # id_order=reversed (reference: src/benchmark.zig:66-73 default)
    arr["id_lo"] = np.arange(
        start_id + count - 1, start_id - 1, -1, dtype=np.uint64
    )
    dr = rng.integers(1, n_accounts + 1, size=count, dtype=np.uint64)
    off = rng.integers(1, n_accounts, size=count, dtype=np.uint64)
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = (dr - 1 + off) % n_accounts + 1
    arr["amount_lo"] = 1
    arr["ledger"] = 1
    arr["code"] = 1
    arr["flags"] = flags
    return arr.tobytes()


def _post_body(pend_body: bytes, start_id: int) -> bytes:
    """Full-amount posts of every pending transfer in `pend_body`
    (two-phase second leg; reference: src/state_machine.zig:907-1014)."""
    pend = np.frombuffer(pend_body, dtype=TRANSFER_DTYPE)
    arr = np.zeros(len(pend), dtype=TRANSFER_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + len(pend), dtype=np.uint64)
    arr["pending_id_lo"] = pend["id_lo"]
    arr["pending_id_hi"] = pend["id_hi"]
    arr["flags"] = 4  # post_pending_transfer
    return arr.tobytes()


class _BenchClient:
    """One session: its own TCP connection + vsr Client, one request in
    flight, per-batch latency recorded."""

    def __init__(self, client_id: int, port: int):
        from tigerbeetle_tpu.io.message_bus import TCPMessageBus
        from tigerbeetle_tpu.vsr.client import Client

        self.bus = TCPMessageBus([("127.0.0.1", port)], client_id)
        self.client = Client(client_id, self.bus, replica_count=1)
        self.sent_at = 0.0
        self.latencies_ms: list[float] = []
        self.replies: list[bytes] = []

    def pump(self) -> None:
        self.bus.pump(timeout=0.0)

    def wait_reply(self, deadline_s: float = 120.0) -> tuple:
        t0 = last_send = time.monotonic()
        while self.client.reply is None:
            self.pump()
            now = time.monotonic()
            if now - t0 > deadline_s:
                raise TimeoutError("benchmark client: no reply")
            if now - last_send > 5.0 and self.client.in_flight is not None:
                self.client.resend()  # request/reply lost: retransmit
                last_send = now
            if self.client.reply is None:
                time.sleep(0.0001)
        return self.client.take_reply()

    def register(self) -> None:
        self.client.register()
        self.wait_reply()


def run_e2e(
    n_accounts: int = 10_000,
    n_transfers: int = 1_000_000,
    batch: int = BATCH,
    clients: int = 16,
    warmup_batches: int = 2,
    jax_platform: str | None = None,
    tmpdir: str | None = None,
    server_args: tuple[str, ...] = (),
    backend: str = "native",
    workload: str = "simple",
    driver: str = "python",
    trace: str | None = None,
    cdc_slow_us: int | None = None,
    log=None,
) -> dict:
    """Format, start a real replica, drive the protocol, return metrics.

    The server process owns the accelerator; this process stays host-only
    (numpy + sockets) so both can run on a machine with one TPU chip."""
    log = log or (lambda *_: None)
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_bench_")
        tmpdir = tmp.name
    path = os.path.join(tmpdir, "bench.tigerbeetle")
    port = free_port()

    slots_log2 = 14
    warm_est = warmup_batches + 16 + 4 + 2 + 1  # singles + group rounds
    while n_transfers + warm_est * batch > (1 << slots_log2) // 2:
        slots_log2 += 1
    acct_log2 = max(14, (n_accounts * 2 + 2).bit_length())

    # prepend (not replace) PYTHONPATH: the TPU runtime may be provided by
    # a site dir already on it
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform
    fmt = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format",
         "--cluster", "0", "--replica", "0", "--replica-count", "1", path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert fmt.returncode == 0, fmt.stderr
    # Own process group (start_new_session): teardown kills the whole group
    # so a wedged server (or anything it forked) cannot outlive the bench
    # and skew later timings. The server also carries a parent-death
    # watchdog (cli._install_parent_death_watchdog) for the paths where
    # this harness itself is SIGKILLed.
    # --trace: the server dumps its commit-pipeline spans (fuse hold,
    # journal writes, commit dispatch/finalize, shadow uploads) as Chrome
    # trace events on SIGTERM; run_e2e loads them back so the bench can
    # merge them into one Perfetto-loadable file.
    server_trace = os.path.join(tmpdir, "server_trace.json") if trace else None
    trace_args = ("--trace", server_trace) if server_trace else ()
    # CDC A/B mode: a live change-stream pump with a deliberately slow
    # (non-blocking, refusing) sink — the acceptance run proving the live
    # tail backpressures the PUMP and never the commit path. The server's
    # [stats] registry snapshot carries cdc.lag_ops /
    # cdc.backpressure_pauses back out.
    cdc_args: tuple[str, ...] = ()
    if cdc_slow_us is not None:
        cdc_args = (
            "--cdc-jsonl", os.path.join(tmpdir, "cdc.jsonl"),
            "--cdc-slow-us", str(cdc_slow_us),
        )
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         "--addresses", f"127.0.0.1:{port}",
         "--account-slots-log2", str(acct_log2),
         "--transfer-slots-log2", str(slots_log2),
         "--backend", backend,
         *trace_args, *cdc_args, *server_args, path],
        cwd=REPO, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        while True:  # skip [boot] trace lines until ready (TPU init)
            line = proc.stdout.readline()
            if "listening" in line:
                break
            if not line:
                raise RuntimeError("bench server died before listening")
            log(line.rstrip())
        log(f"server up on :{port} (slots 2^{slots_log2})")

        # Keep draining server output: an unread pipe fills and BLOCKS the
        # server's next print (debug mode would wedge the whole benchmark).
        server_stats: dict = {}

        def _drain_stdout():
            import json as _json

            for out in proc.stdout:
                line = out.rstrip()
                if line.startswith("[stats] "):
                    try:
                        server_stats.update(_json.loads(line[8:]))
                    except ValueError:
                        pass
                log("[server]", line)

        drain_thread = threading.Thread(target=_drain_stdout, daemon=True)
        drain_thread.start()
        if driver == "async":
            result = _drive_async(
                port, n_accounts, n_transfers, batch, clients,
                warmup_batches, log, workload=workload,
            )
        else:
            result = _drive(
                proc, port, n_accounts, n_transfers, batch, clients,
                warmup_batches, log, workload=workload,
            )
        # SIGTERM makes the server emit its [stats] line (group-commit hit
        # rate etc.); after exit the pipe hits EOF, so joining the drain
        # thread is deterministic (no sleep race). Dual mode drains the
        # device shadow and compiles+runs the fingerprint kernels at
        # shutdown — off the clock, but the wait must cover it.
        proc.terminate()
        try:
            # dual mode: must outlast DualLedger.finalize's own drain
            # timeout (600s) or a slow-but-legal verification is killed
            # mid-flight and the [stats] line is lost
            proc.wait(timeout=650 if "+" in backend else 10)
        except subprocess.TimeoutExpired:
            pass
        drain_thread.join(timeout=5)
        if server_stats:
            result["server_stats"] = server_stats
            g = server_stats.get("group", {})
            total = g.get("fused_ops", 0) + g.get("solo_ops", 0)
            if total:
                result["group_commit_hit_rate"] = round(
                    g.get("fused_ops", 0) / total, 4
                )
                if g.get("fused_groups"):
                    result["group_fuse_width"] = round(
                        g["fused_ops"] / g["fused_groups"], 2
                    )
            loop = server_stats.get("loop", {})
            if loop:
                result["loop_us_per_batch"] = loop.get("us_per_batch")
            if "metrics" in server_stats:
                # the server's full registry snapshot (counters + timing
                # histogram percentiles) — sourced from the same store as
                # the loop/group numbers above
                result["server_metrics"] = server_stats["metrics"]
                if cdc_slow_us is not None:
                    m = server_stats["metrics"]
                    result["cdc_lag_ops"] = m.get("gauges", {}).get(
                        "cdc.lag_ops"
                    )
                    result["cdc_backpressure_pauses"] = m.get(
                        "counters", {}
                    ).get("cdc.backpressure_pauses")
                    result["cdc_ops_streamed"] = m.get(
                        "counters", {}
                    ).get("cdc.ops")
            if "device_shadow" in server_stats:
                result["device_shadow"] = server_stats["device_shadow"]
                sh = server_stats["device_shadow"].get("shadow") or {}
                if sh.get("upload_overlap") is not None:
                    result["shadow_upload_overlap"] = sh["upload_overlap"]
        if server_trace and os.path.exists(server_trace):
            import json as _json

            try:
                with open(server_trace) as f:
                    result["trace_events"] = _json.load(f)["traceEvents"]
            except (ValueError, KeyError, OSError):
                pass  # a torn dump must not sink the run's numbers
        return result
    finally:
        if proc.poll() is None:
            proc.terminate()  # SIGTERM first: lets a profiling run dump
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        kill_process_group(proc)
        if own_tmp:
            tmp.cleanup()


def _drive_async(port, n_accounts, n_transfers, batch, clients,
                 warmup_batches, log, workload: str = "simple") -> dict:
    """Drive the protocol through the ASYNC packet ABI (native/tb_client.cc
    tb_client_async_*): ONE client process, one AsyncNativeClient whose
    session pool keeps `clients` requests in flight — the reference's
    packet/completion model replacing the Python per-session loop
    (reference: src/clients/c/tb_client/packet.zig)."""
    import threading as _threading

    from tigerbeetle_tpu.client_ffi import AsyncNativeClient, NativeClient
    from tigerbeetle_tpu.state_machine import decode_results

    rng = np.random.default_rng(42)
    addresses = f"127.0.0.1:{port}"
    ctl = NativeClient(addresses)  # blocking control-plane session

    t0 = time.monotonic()
    next_id = 1
    while next_id <= n_accounts:
        n = min(batch, n_accounts - next_id + 1)
        assert ctl._request(
            Operation.create_accounts, _accounts_body(next_id, n)
        ) == b"", "account create failed"
        next_id += n
    log(f"{n_accounts} accounts in {time.monotonic() - t0:.1f}s")

    ac = AsyncNativeClient(addresses, sessions=clients)
    log(f"async client up: {clients} pooled sessions")
    try:
        # -- build bodies (workload gen off the clock) --
        n_batches = (n_transfers + batch - 1) // batch
        nid = 1_000_000
        if workload == "two_phase":
            pends, posts = [], []
            for _ in range((n_batches + 1) // 2):
                pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
                nid += batch
                pends.append(pend)
                posts.append(_post_body(pend, nid))
                nid += batch
            waves = [pends, posts]
            posted_batches = len(posts)
        else:
            bodies = []
            for _ in range(n_batches):
                bodies.append(_transfers_body(rng, nid, batch, n_accounts))
                nid += batch
            waves = [bodies]
            posted_batches = len(bodies)

        # -- warmup (kernel compiles / cache warm): singles, then a full
        # concurrent burst so fused group paths compile before the clock --
        op = Operation.create_transfers
        warm = 0
        for _ in range(warmup_batches):
            pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
            nid += batch
            assert ac.submit(op, pend).result(timeout=600) == b""
            post = _post_body(pend, nid)
            nid += batch
            assert ac.submit(op, post).result(timeout=600) == b""
            warm += 2
        burst = [
            _transfers_body(rng, nid + i * batch, batch, n_accounts)
            for i in range(clients)
        ]
        nid += clients * batch
        for f in [ac.submit(op, b) for b in burst]:
            assert f.result(timeout=600) == b""
        warm += clients
        # warmup posted amounts: each pend+post pair posts ONE batch's
        # amounts (the pend batch itself only moves pending), plus the
        # simple burst batches
        posted_batches += warmup_batches + clients
        log(f"warmup done ({warm} batches); timing "
            f"{sum(len(w) for w in waves)} batches")

        # -- timed: submit with a bounded window (the pool keeps `clients`
        # requests on the wire; the window keeps its queue fed without
        # turning latency into pure queue depth) --
        sem = _threading.Semaphore(clients * 2)
        lat_ms: list[float] = []
        lat_lock = _threading.Lock()
        failures = 0
        t_start = time.monotonic()
        for wave in waves:
            futs = []
            for body in wave:
                sem.acquire()
                t_sub = time.monotonic()

                def _done(_f, t=t_sub):
                    with lat_lock:
                        lat_ms.append((time.monotonic() - t) * 1e3)
                    sem.release()

                fut = ac.submit(op, body)
                fut.add_done_callback(_done)
                futs.append(fut)
            for f in futs:  # wave barrier (two_phase: posts follow pends)
                failures += len(decode_results(f.result(timeout=600), op))
        wall = time.monotonic() - t_start
        n_timed = sum(len(w) for w in waves) * batch
        assert failures == 0, f"{failures} transfers failed"
    finally:
        ac.close()

    # -- conservation over the wire (blocking control session) --
    total = posted_batches * batch
    dpo = cpo = found = 0
    ids = list(range(1, n_accounts + 1))
    for i in range(0, len(ids), 8000):
        accounts = ctl.lookup_accounts(ids[i : i + 8000])
        found += len(accounts)
        dpo += sum(a.debits_posted for a in accounts)
        cpo += sum(a.credits_posted for a in accounts)
    assert found == n_accounts, (found, n_accounts)
    assert dpo == cpo == total, (dpo, cpo, total)
    log(f"conservation verified: {total} transfers, dpo==cpo=={total}")
    ctl.close()

    lat = np.percentile(lat_ms if lat_ms else [float("nan")],
                        [0, 25, 50, 75, 100])
    return {
        "durable_tps": round(n_timed / wall, 1) if wall else 0.0,
        "n_transfers": n_timed,
        "wall_s": round(wall, 2),
        "clients": clients,
        "driver": "async_abi",
        "latency_ms_p00_p25_p50_p75_p100": [round(float(x), 2) for x in lat],
    }


def _drive(proc, port, n_accounts, n_transfers, batch, clients,
           warmup_batches, log, workload: str = "simple") -> dict:
    from tigerbeetle_tpu.state_machine import decode_results

    rng = np.random.default_rng(42)
    sessions = [_BenchClient(0xB0000 + i, port) for i in range(clients)]
    for s in sessions:
        s.register()
    log(f"{clients} session(s) registered")

    # -- accounts (absorbs the create_accounts compile) --
    t0 = time.monotonic()
    next_id = 1
    while next_id <= n_accounts:
        n = min(batch, n_accounts - next_id + 1)
        sessions[0].client.request(
            Operation.create_accounts, _accounts_body(next_id, n)
        )
        _h, body = sessions[0].wait_reply()
        assert body == b"", "account create failed"
        next_id += n
    log(f"{n_accounts} accounts in {time.monotonic() - t0:.1f}s")

    # -- warmup rounds: singles compile the per-batch kernel; k
    # simultaneous batches compile each fused group kernel (k=8/4/2) —
    # lazily compiling those mid-run would stall the timed phase for
    # tens of seconds each (device backend; the native engine just warms
    # its caches) --
    from tigerbeetle_tpu.models.ledger import DeviceLedger

    group_rounds = sorted(
        {min(g, clients) for g in DeviceLedger.GROUP_KS if clients >= 2},
        reverse=True,
    )
    group_rounds = [k for k in group_rounds if k >= 2]
    rounds = [1] * warmup_batches + group_rounds
    total_warm = sum(rounds)

    # -- build all bodies up front (workload gen off the clock), split
    # into PER-SESSION queues. two_phase: each session alternates a
    # pending batch with the full-amount posts of ITS OWN previous batch
    # (the session's one-in-flight protocol orders post after pend) --
    id_stride = (n_transfers // clients + 3 * batch) * 2
    per_session: list[list[bytes]] = [[] for _ in sessions]
    n_total_batches = (n_transfers + batch - 1) // batch + total_warm
    posted_batches = 0  # batches that land posted amounts (conservation)
    for i, _s in enumerate(sessions):
        nid = 1_000_000 + i * id_stride
        share = n_total_batches // clients + (
            1 if i < n_total_batches % clients else 0
        )
        q = per_session[i]
        if workload == "two_phase":
            while len(q) < share:
                pend = _transfers_body(rng, nid, batch, n_accounts, flags=2)
                nid += batch
                q.append(pend)
                if len(q) < share:
                    q.append(_post_body(pend, nid))
                    nid += batch
                    posted_batches += 1
        else:
            for _ in range(share):
                q.append(_transfers_body(rng, nid, batch, n_accounts))
                nid += batch
                posted_batches += 1

    # warmup: pull evenly from the per-session queues (two_phase pairs
    # stay in order within a session)
    warm_done = 0
    for k in rounds:
        active = [
            (s, q) for s, q in zip(sessions, per_session) if q
        ][: max(k, 1)]
        for s, q in active:
            s.client.request(Operation.create_transfers, q.pop(0))
        for s, _q in active:
            _h, body = s.wait_reply(deadline_s=600.0)  # compiles are slow
            assert body == b"", decode_results(
                body, Operation.create_transfers
            )[:3]
            warm_done += 1
    n_work = sum(len(q) for q in per_session)
    log(f"warmup done ({warm_done} batches, rounds {rounds}); "
        f"timing {n_work} batches")

    # -- timed phase: each session keeps one batch in flight --
    import selectors as _selectors

    # One wakeup selector over every session's socket: the idle path blocks
    # until ANY reply bytes arrive instead of sleep-polling (time.sleep's
    # ~0.5 ms real granularity dominated the driver and starved the server).
    wake = _selectors.DefaultSelector()
    for s in sessions:
        for conn in s.bus.conns.values():
            try:
                wake.register(conn.sock, _selectors.EVENT_READ)
            except (KeyError, ValueError):
                pass
    lat_ms: list[float] = []
    failures = 0
    inflight: dict[int, float] = {}
    t_start = time.monotonic()
    for s, q in zip(sessions, per_session):
        if q:
            s.client.request(Operation.create_transfers, q.pop(0))
            inflight[s.client.client_id] = time.monotonic()
    deadline = t_start + max(600.0, n_transfers / 1000)
    done_batches = 0
    resent: dict[int, float] = {}
    while inflight:
        progressed = False
        for s, q in zip(sessions, per_session):
            cid = s.client.client_id
            if cid not in inflight:
                continue
            s.pump()
            if s.client.reply is None:
                now = time.monotonic()
                if (
                    now - inflight[cid] > 5.0
                    and now - resent.get(cid, 0.0) > 5.0
                    and s.client.in_flight is not None
                ):
                    s.client.resend()  # lost under backpressure: retry
                    resent[cid] = now
                continue
            _h, body = s.client.take_reply()
            lat_ms.append(
                (time.monotonic() - inflight.pop(cid)) * 1e3
            )
            failures += len(decode_results(body, Operation.create_transfers))
            done_batches += 1
            progressed = True
            if q:
                s.client.request(Operation.create_transfers, q.pop(0))
                inflight[cid] = time.monotonic()
        if not progressed:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"benchmark stalled at batch {done_batches}/{n_work}"
                )
            # reconcile registrations: a dropped+redialed connection has a
            # NEW socket that must wake the idle path too
            regged = {k.fileobj for k in wake.get_map().values()}
            current = {
                c.sock for s in sessions for c in s.bus.conns.values()
            }
            for sock in current - regged:
                try:
                    wake.register(sock, _selectors.EVENT_READ)
                except (KeyError, ValueError, OSError):
                    pass
            for sock in regged - current:
                try:
                    wake.unregister(sock)
                except (KeyError, ValueError, OSError):
                    pass
            wake.select(timeout=0.002)  # woken by the first reply bytes
    wake.close()
    wall = time.monotonic() - t_start
    n_timed = done_batches * batch
    assert failures == 0, f"{failures} transfers failed"
    # conservation total: every POSTED batch moves amount=1 per event
    # (simple batches post directly; two_phase pend batches only move
    # pending amounts, released when their post batch lands)
    total = posted_batches * batch
    return _verify_and_report(
        sessions[0], n_accounts, total, wall, n_timed, lat_ms, clients, log
    )


def _verify_and_report(session, n_accounts, total, wall, n_timed, lat_ms,
                       clients, log) -> dict:
    from tigerbeetle_tpu.state_machine import decode_accounts, encode_ids

    dpo = cpo = found = 0
    ids = list(range(1, n_accounts + 1))
    for i in range(0, len(ids), 8000):
        chunk = ids[i : i + 8000]
        session.client.request(Operation.lookup_accounts, encode_ids(chunk))
        _h, body = session.wait_reply()
        arr = decode_accounts(body)
        found += len(arr)
        dpo += int(arr["debits_posted_lo"].sum())
        cpo += int(arr["credits_posted_lo"].sum())
    assert found == n_accounts, (found, n_accounts)
    assert dpo == cpo == total, (dpo, cpo, total)
    log(f"conservation verified: {total} transfers, dpo==cpo=={total}")

    lat = np.percentile(lat_ms if lat_ms else [float("nan")],
                        [0, 25, 50, 75, 100])
    return {
        "durable_tps": round(n_timed / wall, 1) if wall else 0.0,
        "n_transfers": n_timed,
        "wall_s": round(wall, 2),
        "clients": clients,
        "latency_ms_p00_p25_p50_p75_p100": [round(float(x), 2) for x in lat],
    }
