"""The device ledger: TigerBeetle's state machine as JAX kernels over HBM.

This is the TPU-native redesign of the reference's hot path (reference:
src/state_machine.zig:508-698 commit/execute): the account and transfer stores
are HBM-resident open-addressing hash tables (ops/hashtable.py) and a whole
prepare batch commits in one jitted step.

Two execution tiers live inside the same compiled function, dispatched by a
device-computed hazard predicate via lax.cond:

- **Fast tier (vectorized)**: all lookups, validation, and application run
  data-parallel over the batch. Sound only when the batch is free of serial
  hazards — no linked chains, no post/void or balancing events, no duplicate
  ids, no touched account with balance-limit flags, and no u128 overflow even
  at the batch-final balances (all fast-tier balance deltas are non-negative,
  so per-prefix overflow is impossible iff final overflow is). Balance deltas
  are accumulated as 32-bit digit scatter-adds (sums of <= 2^13 events of
  2^32-bounded digits fit u64 exactly) and carried into the u128 balances in
  one elementwise renormalization pass.
- **Serial tier (lax.scan)**: an exact, event-at-a-time kernel with the full
  semantics — linked-chain rollback via an undo log (reference:
  src/state_machine.zig:612-698 + src/lsm/groove.zig:990-1010 scopes),
  two-phase post/void (reference: :907-1014), balancing clamps, in-batch
  duplicate ids.

Both tiers call the same validation ladders (models/validate.py), so result
codes are bit-exact against the oracle (models/oracle.py) on every path.

The reference's `posted` groove (reference: src/state_machine.zig:185-198) is
the `fulfill` column of the pending transfer's row (1:1 by construction).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import (
    DEFAULT_CLUSTER,
    DEFAULT_PROCESS,
    ConfigCluster,
    ConfigProcess,
)
from tigerbeetle_tpu.models import validate
from tigerbeetle_tpu.models.validate import (
    F_LINKED,
    F_PENDING,
    F_POST,
    F_VOID,
)
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.ops import u128
from tigerbeetle_tpu.types import Operation

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

# Flags that force the serial tier (linked | post | void | balancing_debit |
# balancing_credit). Only no-flag and pending-only events are fast-tier-safe.
_SLOW_FLAGS = 0b111101

_U64_COLS_ACCT = (
    "key_lo", "key_hi",
    "dp_lo", "dp_hi", "dpo_lo", "dpo_hi", "cp_lo", "cp_hi", "cpo_lo", "cpo_hi",
    "ud128_lo", "ud128_hi", "ud64", "ts",
)
_U32_COLS_ACCT = ("ud32", "ledger", "code", "flags")

_U64_COLS_XFER = (
    "key_lo", "key_hi",
    "dr_lo", "dr_hi", "cr_lo", "cr_hi",
    "amt_lo", "amt_hi", "pid_lo", "pid_hi",
    "ud128_lo", "ud128_hi", "ud64", "ts",
)
_U32_COLS_XFER = ("ud32", "timeout", "ledger", "code", "flags", "fulfill")

_BALANCE_COLS = ("dp", "dpo", "cp", "cpo")


def init_state(process: ConfigProcess = DEFAULT_PROCESS) -> dict:
    """Allocate the device ledger state. Tables have capacity+1 rows: the last
    row is the write dump for masked scatters (never read)."""
    a_rows = (1 << process.account_slots_log2) + 1
    t_rows = (1 << process.transfer_slots_log2) + 1
    acct = {c: jnp.zeros(a_rows, dtype=U64) for c in _U64_COLS_ACCT}
    acct.update({c: jnp.zeros(a_rows, dtype=U32) for c in _U32_COLS_ACCT})
    xfer = {c: jnp.zeros(t_rows, dtype=U64) for c in _U64_COLS_XFER}
    xfer.update({c: jnp.zeros(t_rows, dtype=U32) for c in _U32_COLS_XFER})
    return {
        "acct": acct,
        "xfer": xfer,
        "acct_claim": jnp.full(a_rows, ht.CLAIM_FREE, dtype=U32),
        "xfer_claim": jnp.full(t_rows, ht.CLAIM_FREE, dtype=U32),
        "commit_ts": jnp.uint64(0),
        "acct_count": jnp.uint64(0),
        "xfer_count": jnp.uint64(0),
    }


def _row(tbl: dict, slot) -> dict:
    return {k: v[slot] for k, v in tbl.items()}


# --- host <-> device batch conversion ---


def _pad(a: np.ndarray, n_pad: int) -> np.ndarray:
    if len(a) == n_pad:
        return a
    out = np.zeros(n_pad, dtype=a.dtype)
    out[: len(a)] = a
    return out


def transfers_to_batch(arr: np.ndarray, n_pad: int) -> dict:
    """Wire-format structured array (types.TRANSFER_DTYPE) -> SoA device batch."""
    a = _pad(arr, n_pad)
    return {
        "id_lo": jnp.asarray(a["id_lo"]), "id_hi": jnp.asarray(a["id_hi"]),
        "dr_lo": jnp.asarray(a["debit_account_id_lo"]),
        "dr_hi": jnp.asarray(a["debit_account_id_hi"]),
        "cr_lo": jnp.asarray(a["credit_account_id_lo"]),
        "cr_hi": jnp.asarray(a["credit_account_id_hi"]),
        "amt_lo": jnp.asarray(a["amount_lo"]), "amt_hi": jnp.asarray(a["amount_hi"]),
        "pid_lo": jnp.asarray(a["pending_id_lo"]), "pid_hi": jnp.asarray(a["pending_id_hi"]),
        "ud128_lo": jnp.asarray(a["user_data_128_lo"]),
        "ud128_hi": jnp.asarray(a["user_data_128_hi"]),
        "ud64": jnp.asarray(a["user_data_64"]),
        "ud32": jnp.asarray(a["user_data_32"]),
        "timeout": jnp.asarray(a["timeout"]),
        "ledger": jnp.asarray(a["ledger"]),
        "code": jnp.asarray(a["code"].astype(np.uint32)),
        "flags": jnp.asarray(a["flags"].astype(np.uint32)),
        "ts": jnp.asarray(a["timestamp"]),
    }


def accounts_to_batch(arr: np.ndarray, n_pad: int) -> dict:
    a = _pad(arr, n_pad)
    return {
        "id_lo": jnp.asarray(a["id_lo"]), "id_hi": jnp.asarray(a["id_hi"]),
        "dp_lo": jnp.asarray(a["debits_pending_lo"]),
        "dp_hi": jnp.asarray(a["debits_pending_hi"]),
        "dpo_lo": jnp.asarray(a["debits_posted_lo"]),
        "dpo_hi": jnp.asarray(a["debits_posted_hi"]),
        "cp_lo": jnp.asarray(a["credits_pending_lo"]),
        "cp_hi": jnp.asarray(a["credits_pending_hi"]),
        "cpo_lo": jnp.asarray(a["credits_posted_lo"]),
        "cpo_hi": jnp.asarray(a["credits_posted_hi"]),
        "ud128_lo": jnp.asarray(a["user_data_128_lo"]),
        "ud128_hi": jnp.asarray(a["user_data_128_hi"]),
        "ud64": jnp.asarray(a["user_data_64"]),
        "ud32": jnp.asarray(a["user_data_32"]),
        "reserved": jnp.asarray(a["reserved"]),
        "ledger": jnp.asarray(a["ledger"]),
        "code": jnp.asarray(a["code"].astype(np.uint32)),
        "flags": jnp.asarray(a["flags"].astype(np.uint32)),
        "ts": jnp.asarray(a["timestamp"]),
    }


def ids_to_batch(ids: list[int], n_pad: int) -> dict:
    lo = np.zeros(n_pad, dtype=np.uint64)
    hi = np.zeros(n_pad, dtype=np.uint64)
    for i, x in enumerate(ids):
        lo[i], hi[i] = types.split_u128(x)
    return {"id_lo": jnp.asarray(lo), "id_hi": jnp.asarray(hi)}


# --- duplicate-id detection (device) ---


def _has_duplicate_ids(id_lo, id_hi, valid):
    """True iff two valid lanes share an id. Invalid lanes sort last via a
    third key and are excluded from adjacency comparison."""
    inv = (~valid).astype(U32)
    inv_s, hi_s, lo_s = jax.lax.sort((inv, id_hi, id_lo), num_keys=3)
    both_valid = (inv_s[1:] == 0) & (inv_s[:-1] == 0)
    dup = both_valid & (hi_s[1:] == hi_s[:-1]) & (lo_s[1:] == lo_s[:-1])
    return jnp.any(dup)


# --- per-batch balance delta accumulation (fast tier) ---


def _digit_accumulate(n_rows, slot_masked_list, d0_list, d1_list):
    """Scatter-add per-event u64 deltas as two 32-bit digits. Returns (acc0,
    acc1) u64 accumulators of n_rows. Each event's delta fits u64 (fast tier
    rejects amt_hi != 0); digit sums of <= 2^13 events fit u64 exactly."""
    acc0 = jnp.zeros(n_rows, dtype=U64)
    acc1 = jnp.zeros(n_rows, dtype=U64)
    for slot, d0, d1 in zip(slot_masked_list, d0_list, d1_list):
        acc0 = acc0.at[slot].add(d0)
        acc1 = acc1.at[slot].add(d1)
    return acc0, acc1


def _apply_digits(lo, hi, acc0, acc1):
    """balance' = balance + (acc0 + acc1 * 2^32), exact, with overflow flag."""
    thirty_two = jnp.uint64(32)
    lo_add = acc0 + ((acc1 & jnp.uint64(0xFFFFFFFF)) << thirty_two)
    carry1 = (lo_add < acc0).astype(U64)
    hi_add = acc1 >> thirty_two
    new_lo, new_hi, over_a = u128.add(lo, hi, lo_add, hi_add)
    new_hi2 = new_hi + carry1
    over_b = new_hi2 < new_hi
    return new_lo, new_hi2, over_a | over_b


# --- kernel factory ---


class LedgerKernels:
    """Compiled commit kernels closed over the table geometry.

    `mode` selects dispatch: "auto" (hazard-predicated lax.cond, production),
    "serial" (always the exact scan; parity testing), "fast" (always the
    vectorized tier; only sound on hazard-free batches — parity testing).
    """

    def __init__(self, process: ConfigProcess = DEFAULT_PROCESS):
        self.process = process
        self.a_log2 = process.account_slots_log2
        self.t_log2 = process.transfer_slots_log2
        self.a_dump = jnp.int32(1 << self.a_log2)
        self.t_dump = jnp.int32(1 << self.t_log2)
        self.commit_transfers = jax.jit(
            self._commit_transfers, static_argnames=("mode",), donate_argnums=(0,)
        )
        self.commit_accounts = jax.jit(
            self._commit_accounts, static_argnames=("mode",), donate_argnums=(0,)
        )
        self.lookup_accounts = jax.jit(self._lookup_accounts)
        self.lookup_transfers = jax.jit(self._lookup_transfers)

    # -- shared lookups --

    def _acct_lookup(self, acct, key_lo, key_hi):
        return ht.lookup(key_lo, key_hi, acct["key_lo"], acct["key_hi"], self.a_log2)

    def _xfer_lookup(self, xfer, key_lo, key_hi):
        return ht.lookup(key_lo, key_hi, xfer["key_lo"], xfer["key_hi"], self.t_log2)

    # ------------------------------------------------------------------
    # create_transfers
    # ------------------------------------------------------------------

    def _commit_transfers(self, state, ev, n, timestamp, mode: str = "auto"):
        """Returns (state', results u32 [B])."""
        B = ev["flags"].shape[0]
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        ev_a = {**ev, "ts": ts_vec}  # timestamps assigned (reference: :645)

        if mode == "serial":
            return self._serial_transfers(state, ev, n, timestamp)

        acct, xfer = state["acct"], state["xfer"]
        dr_slot, dr_found = self._acct_lookup(acct, ev["dr_lo"], ev["dr_hi"])
        cr_slot, cr_found = self._acct_lookup(acct, ev["cr_lo"], ev["cr_hi"])
        ex_slot, ex_found = self._xfer_lookup(xfer, ev["id_lo"], ev["id_hi"])
        dr = _row(acct, dr_slot)
        cr = _row(acct, cr_slot)
        ex = _row(xfer, ex_slot)

        r0 = jnp.where(ev["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r0 = validate.transfer_common(ev, r0)
        r, amt_lo, amt_hi = validate.validate_simple_transfer(
            r0, ev_a, dr, cr, dr_found, cr_found, ex, ex_found
        )
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        # Hazard predicate — any condition the vectorized tier cannot honor.
        h_flags = jnp.any(valid & ((ev["flags"] & jnp.uint32(_SLOW_FLAGS)) != 0))
        h_dup = _has_duplicate_ids(ev["id_lo"], ev["id_hi"], valid)
        h_amt = jnp.any(ok & (ev["amt_hi"] != 0))
        limit_bits = jnp.uint32(validate.A_DR_LIMIT | validate.A_CR_LIMIT)
        h_limit = jnp.any(ok & (((dr["flags"] | cr["flags"]) & limit_bits) != 0))

        # Per-account batch totals as 32-bit digit scatter-adds.
        pending = ok & ((ev["flags"] & jnp.uint32(F_PENDING)) != 0)
        posted = ok & ~pending
        mask32 = jnp.uint64(0xFFFFFFFF)
        d0 = amt_lo & mask32
        d1 = amt_lo >> jnp.uint64(32)
        a_rows = (1 << self.a_log2) + 1

        def msk(cond, slot):
            return jnp.where(cond, slot, self.a_dump)

        new_bal = {}
        overflow = jnp.zeros((), dtype=bool)
        for col, cond, slot in (
            ("dp", pending, dr_slot),
            ("dpo", posted, dr_slot),
            ("cp", pending, cr_slot),
            ("cpo", posted, cr_slot),
        ):
            acc0, acc1 = _digit_accumulate(a_rows, [msk(cond, slot)], [d0], [d1])
            lo, hi, over = _apply_digits(acct[col + "_lo"], acct[col + "_hi"], acc0, acc1)
            new_bal[col + "_lo"] = lo
            new_bal[col + "_hi"] = hi
            overflow = overflow | jnp.any(over[: 1 << self.a_log2])
        hazard = h_flags | h_dup | h_amt | h_limit | overflow

        def fast_branch(state):
            acct2 = {**state["acct"], **new_bal}
            xfer2 = dict(state["xfer"])
            slots, k_lo, k_hi, claim = ht.insert_slots(
                ev["id_lo"], ev["id_hi"], ok,
                xfer2["key_lo"], xfer2["key_hi"], state["xfer_claim"], self.t_log2,
            )
            xfer2["key_lo"], xfer2["key_hi"] = k_lo, k_hi
            w = jnp.where(ok, slots, self.t_dump)
            for col, val in (
                ("dr_lo", ev["dr_lo"]), ("dr_hi", ev["dr_hi"]),
                ("cr_lo", ev["cr_lo"]), ("cr_hi", ev["cr_hi"]),
                ("amt_lo", amt_lo), ("amt_hi", amt_hi),
                ("pid_lo", ev["pid_lo"]), ("pid_hi", ev["pid_hi"]),
                ("ud128_lo", ev["ud128_lo"]), ("ud128_hi", ev["ud128_hi"]),
                ("ud64", ev["ud64"]), ("ud32", ev["ud32"]),
                ("timeout", ev["timeout"]), ("ledger", ev["ledger"]),
                ("code", ev["code"]), ("flags", ev["flags"]),
                ("ts", ts_vec), ("fulfill", jnp.zeros_like(ev["ud32"])),
            ):
                xfer2[col] = xfer2[col].at[w].set(val)
            any_ok = jnp.any(ok)
            last_ts = jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
            return {
                **state,
                "acct": acct2,
                "xfer": xfer2,
                "xfer_claim": claim,
                "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
                "xfer_count": state["xfer_count"] + jnp.sum(ok).astype(U64),
            }, r

        if mode == "fast":
            return fast_branch(state)
        return jax.lax.cond(
            hazard,
            lambda s: self._serial_transfers(s, ev, n, timestamp),
            fast_branch,
            state,
        )

    # -- exact serial tier --

    def _serial_transfers(self, state, ev, n, timestamp):
        B = ev["flags"].shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump, t_dump = self.a_dump, self.t_dump

        undo0 = {
            "kind": jnp.zeros(B, dtype=U32),
            "dr_slot": jnp.zeros(B, dtype=I32),
            "cr_slot": jnp.zeros(B, dtype=I32),
            "t_slot": jnp.zeros(B, dtype=I32),
            "p_slot": jnp.zeros(B, dtype=I32),
            "a_lo": jnp.zeros(B, dtype=U64),
            "a_hi": jnp.zeros(B, dtype=U64),
            "pa_lo": jnp.zeros(B, dtype=U64),
            "pa_hi": jnp.zeros(B, dtype=U64),
        }
        carry0 = (
            state["acct"], state["xfer"],
            jnp.zeros(B, dtype=U32),  # results
            undo0,
            jnp.int32(-1),  # chain_start
            jnp.zeros((), dtype=bool),  # chain_broken
            state["commit_ts"],
        )

        def step(carry, x):
            acct, xfer, results, undo, chain_start, chain_broken, commit_ts = carry
            i, e = x
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(F_LINKED)) != 0)

            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)

            ts = timestamp - n.astype(U64) + i.astype(U64) + jnp.uint64(1)
            e_a = {**e, "ts": ts}

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)  # linked_event_chain_open
            lad.set(active & chain_broken, 1)  # linked_event_failed
            lad.set(e["ts"] != 0, 3)  # timestamp_must_be_zero
            r0 = validate.transfer_common(e, lad.r)

            dr_slot, dr_found = self._acct_lookup(acct, e["dr_lo"], e["dr_hi"])
            cr_slot, cr_found = self._acct_lookup(acct, e["cr_lo"], e["cr_hi"])
            ex_slot, ex_found = self._xfer_lookup(xfer, e["id_lo"], e["id_hi"])
            p_slot, p_found = self._xfer_lookup(xfer, e["pid_lo"], e["pid_hi"])
            dr = _row(acct, dr_slot)
            cr = _row(acct, cr_slot)
            ex = _row(xfer, ex_slot)
            p = _row(xfer, p_slot)
            # The pending transfer's accounts (post/void path). Gated by
            # p_found in the validator; garbage rows otherwise.
            pdr_slot, _ = self._acct_lookup(acct, p["dr_lo"], p["dr_hi"])
            pcr_slot, _ = self._acct_lookup(acct, p["cr_lo"], p["cr_hi"])
            pdr = _row(acct, pdr_slot)
            pcr = _row(acct, pcr_slot)

            is_pv = (e["flags"] & jnp.uint32(F_POST | F_VOID)) != 0
            r_s, amt_s_lo, amt_s_hi = validate.validate_simple_transfer(
                r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
            )
            r_pv, amt_pv_lo, amt_pv_hi = validate.validate_post_void(
                r0, e_a, p, p_found, ex, ex_found
            )
            r = jnp.where(is_pv, r_pv, r_s)
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            amt_lo = jnp.where(is_pv, amt_pv_lo, amt_s_lo)
            amt_hi = jnp.where(is_pv, amt_pv_hi, amt_s_hi)
            is_post = is_pv & ((e["flags"] & jnp.uint32(F_POST)) != 0)
            is_pending = ~is_pv & ((e["flags"] & jnp.uint32(F_PENDING)) != 0)

            # --- apply ---
            free_slot = ht.probe_free_scalar(
                e["id_lo"], e["id_hi"], xfer["key_lo"], xfer["key_hi"], self.t_log2
            )
            w = jnp.where(ok, free_slot, t_dump)
            # Inserted row: the event itself (clamped amount), or the post/void
            # fulfillment row t2 with p-defaulted fields (reference: :975-990).
            zero64 = jnp.uint64(0)

            def dflt(t_lo, t_hi, p_lo, p_hi):
                z = u128.is_zero(t_lo, t_hi)
                return jnp.where(z, p_lo, t_lo), jnp.where(z, p_hi, t_hi)

            t2_ud128_lo, t2_ud128_hi = dflt(
                e["ud128_lo"], e["ud128_hi"], p["ud128_lo"], p["ud128_hi"]
            )
            row = {
                "key_lo": e["id_lo"], "key_hi": e["id_hi"],
                "dr_lo": jnp.where(is_pv, p["dr_lo"], e["dr_lo"]),
                "dr_hi": jnp.where(is_pv, p["dr_hi"], e["dr_hi"]),
                "cr_lo": jnp.where(is_pv, p["cr_lo"], e["cr_lo"]),
                "cr_hi": jnp.where(is_pv, p["cr_hi"], e["cr_hi"]),
                "amt_lo": amt_lo, "amt_hi": amt_hi,
                "pid_lo": e["pid_lo"], "pid_hi": e["pid_hi"],
                "ud128_lo": jnp.where(is_pv, t2_ud128_lo, e["ud128_lo"]),
                "ud128_hi": jnp.where(is_pv, t2_ud128_hi, e["ud128_hi"]),
                "ud64": jnp.where(is_pv & (e["ud64"] == 0), p["ud64"], e["ud64"]),
                "ud32": jnp.where(is_pv & (e["ud32"] == 0), p["ud32"], e["ud32"]),
                "timeout": jnp.where(is_pv, jnp.uint32(0), e["timeout"]),
                "ledger": jnp.where(is_pv, p["ledger"], e["ledger"]),
                "code": jnp.where(is_pv, p["code"], e["code"]),
                "flags": e["flags"],
                "ts": ts,
                "fulfill": jnp.uint32(0),
            }
            xfer = {k: v.at[w].set(row[k]) if k in row else v for k, v in xfer.items()}
            # Write key columns too (probe_free_scalar does not write).
            xfer["key_lo"] = xfer["key_lo"].at[w].set(e["id_lo"])
            xfer["key_hi"] = xfer["key_hi"].at[w].set(e["id_hi"])
            # Fulfillment mark on the pending row (posted groove insert,
            # reference: :992-996).
            fw = jnp.where(ok & is_pv, p_slot, t_dump)
            xfer["fulfill"] = xfer["fulfill"].at[fw].set(
                jnp.where(is_post, jnp.uint32(1), jnp.uint32(2))
            )

            # Balance application. Target accounts: the event's for simple,
            # the pending transfer's for post/void. dr != cr guaranteed.
            tgt_dr_slot = jnp.where(is_pv, pdr_slot, dr_slot)
            tgt_cr_slot = jnp.where(is_pv, pcr_slot, cr_slot)
            tdr = {k: jnp.where(is_pv, pdr[k], dr[k]) for k in dr}
            tcr = {k: jnp.where(is_pv, pcr[k], cr[k]) for k in cr}

            def upd(row_d, bal, add_cond, add_lo, add_hi, sub_cond, sub_lo, sub_hi):
                lo, hi = row_d[bal + "_lo"], row_d[bal + "_hi"]
                a_lo2, a_hi2, _ = u128.add(lo, hi, add_lo, add_hi)
                lo = jnp.where(add_cond, a_lo2, lo)
                hi = jnp.where(add_cond, a_hi2, hi)
                s_lo2, s_hi2, _ = u128.sub(lo, hi, sub_lo, sub_hi)
                lo = jnp.where(sub_cond, s_lo2, lo)
                hi = jnp.where(sub_cond, s_hi2, hi)
                return lo, hi

            false_ = jnp.zeros((), dtype=bool)
            # debits_pending: +amt (pending create) / -p.amount (post|void)
            dp_lo, dp_hi = upd(
                tdr, "dp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            # debits_posted: +amt (simple posted create, or post)
            dpo_add = (~is_pv & ~is_pending) | is_post
            dpo_lo, dpo_hi = upd(tdr, "dpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64)
            cp_lo, cp_hi = upd(
                tcr, "cp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            cpo_lo, cpo_hi = upd(tcr, "cpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64)

            dw = jnp.where(ok, tgt_dr_slot, a_dump)
            cw = jnp.where(ok, tgt_cr_slot, a_dump)
            acct = dict(acct)
            acct["dp_lo"] = acct["dp_lo"].at[dw].set(dp_lo)
            acct["dp_hi"] = acct["dp_hi"].at[dw].set(dp_hi)
            acct["dpo_lo"] = acct["dpo_lo"].at[dw].set(dpo_lo)
            acct["dpo_hi"] = acct["dpo_hi"].at[dw].set(dpo_hi)
            acct["cp_lo"] = acct["cp_lo"].at[cw].set(cp_lo)
            acct["cp_hi"] = acct["cp_hi"].at[cw].set(cp_hi)
            acct["cpo_lo"] = acct["cpo_lo"].at[cw].set(cpo_lo)
            acct["cpo_hi"] = acct["cpo_hi"].at[cw].set(cpo_hi)

            commit_ts = jnp.where(ok, ts, commit_ts)

            # --- undo log entry ---
            kind = jnp.where(
                ~ok,
                jnp.uint32(0),
                jnp.where(
                    is_pv,
                    jnp.where(is_post, jnp.uint32(3), jnp.uint32(4)),
                    jnp.where(is_pending, jnp.uint32(2), jnp.uint32(1)),
                ),
            )
            undo = {
                "kind": undo["kind"].at[i].set(kind),
                "dr_slot": undo["dr_slot"].at[i].set(tgt_dr_slot),
                "cr_slot": undo["cr_slot"].at[i].set(tgt_cr_slot),
                "t_slot": undo["t_slot"].at[i].set(free_slot),
                "p_slot": undo["p_slot"].at[i].set(p_slot),
                "a_lo": undo["a_lo"].at[i].set(amt_lo),
                "a_hi": undo["a_hi"].at[i].set(amt_hi),
                "pa_lo": undo["pa_lo"].at[i].set(p["amt_lo"]),
                "pa_hi": undo["pa_hi"].at[i].set(p["amt_hi"]),
            }

            # --- chain break: roll back [chain_start, i) ---
            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, tabs):
                acct, xfer = tabs
                kd = undo["kind"][k]
                applied = kd != 0
                k1 = kd == 1
                k2 = kd == 2
                k3 = kd == 3
                k4 = kd == 4
                drs = undo["dr_slot"][k]
                crs = undo["cr_slot"][k]
                tsl = undo["t_slot"][k]
                psl = undo["p_slot"][k]
                ua_lo, ua_hi = undo["a_lo"][k], undo["a_hi"][k]
                up_lo, up_hi = undo["pa_lo"][k], undo["pa_hi"][k]

                add_p = k3 | k4  # re-add p.amount to pending balances
                sub_a_pend = k2  # remove pending-create amount
                sub_a_post = k1 | k3  # remove posted amount

                def inv(lo, hi, addc, sublo, subhi, subc):
                    a_lo2, a_hi2, _ = u128.add(lo, hi, up_lo, up_hi)
                    lo = jnp.where(addc, a_lo2, lo)
                    hi = jnp.where(addc, a_hi2, hi)
                    s_lo2, s_hi2, _ = u128.sub(lo, hi, sublo, subhi)
                    lo = jnp.where(subc, s_lo2, lo)
                    hi = jnp.where(subc, s_hi2, hi)
                    return lo, hi

                dpl, dph = inv(
                    acct["dp_lo"][drs], acct["dp_hi"][drs], add_p, ua_lo, ua_hi, sub_a_pend
                )
                dpol, dpoh = inv(
                    acct["dpo_lo"][drs], acct["dpo_hi"][drs], false_, ua_lo, ua_hi, sub_a_post
                )
                cpl, cph = inv(
                    acct["cp_lo"][crs], acct["cp_hi"][crs], add_p, ua_lo, ua_hi, sub_a_pend
                )
                cpol, cpoh = inv(
                    acct["cpo_lo"][crs], acct["cpo_hi"][crs], false_, ua_lo, ua_hi, sub_a_post
                )
                dwk = jnp.where(applied, drs, a_dump)
                cwk = jnp.where(applied, crs, a_dump)
                acct = dict(acct)
                acct["dp_lo"] = acct["dp_lo"].at[dwk].set(dpl)
                acct["dp_hi"] = acct["dp_hi"].at[dwk].set(dph)
                acct["dpo_lo"] = acct["dpo_lo"].at[dwk].set(dpol)
                acct["dpo_hi"] = acct["dpo_hi"].at[dwk].set(dpoh)
                acct["cp_lo"] = acct["cp_lo"].at[cwk].set(cpl)
                acct["cp_hi"] = acct["cp_hi"].at[cwk].set(cph)
                acct["cpo_lo"] = acct["cpo_lo"].at[cwk].set(cpol)
                acct["cpo_hi"] = acct["cpo_hi"].at[cwk].set(cpoh)
                xfer = dict(xfer)
                twk = jnp.where(applied, tsl, t_dump)
                xfer["key_lo"] = xfer["key_lo"].at[twk].set(ht.TOMB)
                xfer["key_hi"] = xfer["key_hi"].at[twk].set(ht.TOMB)
                fwk = jnp.where(k3 | k4, psl, t_dump)
                xfer["fulfill"] = xfer["fulfill"].at[fwk].set(jnp.uint32(0))
                return acct, xfer

            acct, xfer = jax.lax.fori_loop(lo_k, i, undo_body, (acct, xfer))

            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)

            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)

            return (acct, xfer, results, undo, chain_start, chain_broken, commit_ts), None

        xs = (lanes, ev)
        (acct, xfer, results, _, _, _, commit_ts), _ = jax.lax.scan(step, carry0, xs)
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        # commit_ts advanced on at-the-time-ok events and, like the oracle's
        # scopes, is NOT restored by chain rollback — return the carry as-is.
        return {
            **state,
            "acct": acct,
            "xfer": xfer,
            "commit_ts": commit_ts,
            "xfer_count": state["xfer_count"] + ok_n,
        }, results

    # ------------------------------------------------------------------
    # create_accounts
    # ------------------------------------------------------------------

    def _commit_accounts(self, state, ev, n, timestamp, mode: str = "auto"):
        B = ev["flags"].shape[0]
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)

        if mode == "serial":
            return self._serial_accounts(state, ev, n, timestamp)

        acct = state["acct"]
        ex_slot, ex_found = self._acct_lookup(acct, ev["id_lo"], ev["id_hi"])
        ex = _row(acct, ex_slot)
        r0 = jnp.where(ev["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r = validate.validate_create_account(r0, ev, ex, ex_found)
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        h_flags = jnp.any(valid & ((ev["flags"] & jnp.uint32(F_LINKED)) != 0))
        h_dup = _has_duplicate_ids(ev["id_lo"], ev["id_hi"], valid)
        hazard = h_flags | h_dup

        def fast_branch(state):
            acct2 = dict(state["acct"])
            slots, k_lo, k_hi, claim = ht.insert_slots(
                ev["id_lo"], ev["id_hi"], ok,
                acct2["key_lo"], acct2["key_hi"], state["acct_claim"], self.a_log2,
            )
            acct2["key_lo"], acct2["key_hi"] = k_lo, k_hi
            w = jnp.where(ok, slots, self.a_dump)
            for col, val in (
                ("dp_lo", ev["dp_lo"]), ("dp_hi", ev["dp_hi"]),
                ("dpo_lo", ev["dpo_lo"]), ("dpo_hi", ev["dpo_hi"]),
                ("cp_lo", ev["cp_lo"]), ("cp_hi", ev["cp_hi"]),
                ("cpo_lo", ev["cpo_lo"]), ("cpo_hi", ev["cpo_hi"]),
                ("ud128_lo", ev["ud128_lo"]), ("ud128_hi", ev["ud128_hi"]),
                ("ud64", ev["ud64"]), ("ud32", ev["ud32"]),
                ("ledger", ev["ledger"]), ("code", ev["code"]),
                ("flags", ev["flags"]), ("ts", ts_vec),
            ):
                acct2[col] = acct2[col].at[w].set(val)
            any_ok = jnp.any(ok)
            last_ts = jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
            return {
                **state,
                "acct": acct2,
                "acct_claim": claim,
                "commit_ts": jnp.where(any_ok, last_ts, state["commit_ts"]),
                "acct_count": state["acct_count"] + jnp.sum(ok).astype(U64),
            }, r

        if mode == "fast":
            return fast_branch(state)
        return jax.lax.cond(
            hazard,
            lambda s: self._serial_accounts(s, ev, n, timestamp),
            fast_branch,
            state,
        )

    def _serial_accounts(self, state, ev, n, timestamp):
        B = ev["flags"].shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump = self.a_dump

        undo0 = {
            "slot": jnp.zeros(B, dtype=I32),
            "kind": jnp.zeros(B, dtype=U32),
        }
        carry0 = (
            state["acct"],
            jnp.zeros(B, dtype=U32),
            undo0,
            jnp.int32(-1),
            jnp.zeros((), dtype=bool),
            state["commit_ts"],
        )

        def step(carry, x):
            acct, results, undo, chain_start, chain_broken, commit_ts = carry
            i, e = x
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(F_LINKED)) != 0)
            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)
            ts = timestamp - n.astype(U64) + i.astype(U64) + jnp.uint64(1)

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)
            lad.set(active & chain_broken, 1)
            lad.set(e["ts"] != 0, 3)

            ex_slot, ex_found = self._acct_lookup(acct, e["id_lo"], e["id_hi"])
            ex = _row(acct, ex_slot)
            r = validate.validate_create_account(lad.r, e, ex, ex_found)
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            free_slot = ht.probe_free_scalar(
                e["id_lo"], e["id_hi"], acct["key_lo"], acct["key_hi"], self.a_log2
            )
            w = jnp.where(ok, free_slot, a_dump)
            acct = dict(acct)
            for col, val in (
                ("key_lo", e["id_lo"]), ("key_hi", e["id_hi"]),
                ("dp_lo", e["dp_lo"]), ("dp_hi", e["dp_hi"]),
                ("dpo_lo", e["dpo_lo"]), ("dpo_hi", e["dpo_hi"]),
                ("cp_lo", e["cp_lo"]), ("cp_hi", e["cp_hi"]),
                ("cpo_lo", e["cpo_lo"]), ("cpo_hi", e["cpo_hi"]),
                ("ud128_lo", e["ud128_lo"]), ("ud128_hi", e["ud128_hi"]),
                ("ud64", e["ud64"]), ("ud32", e["ud32"]),
                ("ledger", e["ledger"]), ("code", e["code"]),
                ("flags", e["flags"]), ("ts", ts),
            ):
                acct[col] = acct[col].at[w].set(val)
            commit_ts = jnp.where(ok, ts, commit_ts)

            undo = {
                "kind": undo["kind"].at[i].set(jnp.where(ok, jnp.uint32(5), jnp.uint32(0))),
                "slot": undo["slot"].at[i].set(free_slot),
            }

            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, acct):
                applied = undo["kind"][k] != 0
                sl = jnp.where(applied, undo["slot"][k], a_dump)
                acct = dict(acct)
                acct["key_lo"] = acct["key_lo"].at[sl].set(ht.TOMB)
                acct["key_hi"] = acct["key_hi"].at[sl].set(ht.TOMB)
                return acct

            acct = jax.lax.fori_loop(lo_k, i, undo_body, acct)
            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)
            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)
            return (acct, results, undo, chain_start, chain_broken, commit_ts), None

        (acct, results, _, _, _, commit_ts), _ = jax.lax.scan(step, carry0, (lanes, ev))
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        return {
            **state,
            "acct": acct,
            "commit_ts": commit_ts,
            "acct_count": state["acct_count"] + ok_n,
        }, results

    # ------------------------------------------------------------------
    # lookups (reference: src/state_machine.zig:701-736)
    # ------------------------------------------------------------------

    def _lookup_accounts(self, state, ids):
        slot, found = self._acct_lookup(state["acct"], ids["id_lo"], ids["id_hi"])
        return found, _row(state["acct"], slot)

    def _lookup_transfers(self, state, ids):
        slot, found = self._xfer_lookup(state["xfer"], ids["id_lo"], ids["id_hi"])
        return found, _row(state["xfer"], slot)


# ----------------------------------------------------------------------
# Host-facing state machine (the oracle-compatible driver interface)
# ----------------------------------------------------------------------


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


class DeviceLedger:
    """Host wrapper: owns the device state and mirrors the oracle's execute()
    API so the two are drop-in interchangeable in parity tests and in the VSR
    commit path (reference lifecycle: src/state_machine.zig:336-540
    prepare/commit; prefetch is subsumed by HBM residency)."""

    def __init__(
        self,
        cluster: ConfigCluster = DEFAULT_CLUSTER,
        process: ConfigProcess = DEFAULT_PROCESS,
        mode: str = "auto",
    ):
        self.cluster = cluster
        self.process = process
        self.mode = mode
        self.kernels = LedgerKernels(process)
        self.state = init_state(process)
        self.prepare_timestamp = 0
        self.pad_to: int | None = None  # fix the batch pad (bench: 8192)
        # Host-tracked occupancy for the load-factor guard (7/8 max). A full
        # table would make probe chains unbounded and inserts lossy; the
        # reference sizes its object pools statically for the same reason
        # (reference: src/static_allocator.zig, src/message_pool.zig:18-41).
        self._acct_used = 0
        self._xfer_used = 0
        self._acct_limit = (1 << process.account_slots_log2) * 7 // 8
        self._xfer_limit = (1 << process.transfer_slots_log2) * 7 // 8

    def prepare(self, operation: Operation, event_count: int) -> None:
        if operation in (Operation.create_accounts, Operation.create_transfers):
            self.prepare_timestamp += event_count

    def _pad_for(self, n: int) -> int:
        return self.pad_to if self.pad_to is not None else _next_pow2(n)

    def execute(self, operation, timestamp: int, events: list) -> list[tuple[int, int]]:
        dense = self.execute_dense(operation, timestamp, events)
        return [(i, c) for i, c in enumerate(dense) if c]

    def execute_dense(self, operation, timestamp: int, events: list) -> list[int]:
        n = len(events)
        n_pad = self._pad_for(n)
        assert n <= n_pad
        ts = jnp.uint64(timestamp)
        nn = jnp.int32(n)
        if operation == Operation.create_transfers:
            if self._xfer_used + n > self._xfer_limit:
                raise RuntimeError(
                    f"transfer table at load-factor limit "
                    f"({self._xfer_used}+{n} > {self._xfer_limit}): "
                    "grow ConfigProcess.transfer_slots_log2"
                )
            arr = events if isinstance(events, np.ndarray) else types.transfers_to_np(events)
            batch = transfers_to_batch(arr, n_pad)
            self.state, results = self.kernels.commit_transfers(
                self.state, batch, nn, ts, mode=self.mode
            )
        elif operation == Operation.create_accounts:
            if self._acct_used + n > self._acct_limit:
                raise RuntimeError(
                    f"account table at load-factor limit "
                    f"({self._acct_used}+{n} > {self._acct_limit}): "
                    "grow ConfigProcess.account_slots_log2"
                )
            arr = events if isinstance(events, np.ndarray) else types.accounts_to_np(events)
            batch = accounts_to_batch(arr, n_pad)
            self.state, results = self.kernels.commit_accounts(
                self.state, batch, nn, ts, mode=self.mode
            )
        else:
            raise AssertionError(operation)
        dense = [int(x) for x in np.asarray(results)[:n]]
        ok_n = sum(1 for c in dense if c == 0)
        if operation == Operation.create_transfers:
            self._xfer_used += ok_n
        else:
            self._acct_used += ok_n
        return dense

    def lookup_accounts(self, ids: list[int]) -> list[types.Account]:
        n_pad = self._pad_for(len(ids))
        found, rows = self.kernels.lookup_accounts(self.state, ids_to_batch(ids, n_pad))
        found = np.asarray(found)[: len(ids)]
        rows = {k: np.asarray(v)[: len(ids)] for k, v in rows.items()}
        out = []
        for i in range(len(ids)):
            if found[i]:
                out.append(_account_from_cols(rows, i))
        return out

    def lookup_transfers(self, ids: list[int]) -> list[types.Transfer]:
        n_pad = self._pad_for(len(ids))
        found, rows = self.kernels.lookup_transfers(self.state, ids_to_batch(ids, n_pad))
        found = np.asarray(found)[: len(ids)]
        rows = {k: np.asarray(v)[: len(ids)] for k, v in rows.items()}
        out = []
        for i in range(len(ids)):
            if found[i]:
                out.append(_transfer_from_cols(rows, i))
        return out

    # -- parity extraction --

    def extract(self):
        """Pull the full device state to host dicts (accounts, transfers,
        posted) for bit-exact comparison against the oracle."""
        acct = {k: np.asarray(v) for k, v in self.state["acct"].items()}
        xfer = {k: np.asarray(v) for k, v in self.state["xfer"].items()}
        accounts: dict[int, types.Account] = {}
        transfers: dict[int, types.Transfer] = {}
        posted: dict[int, int] = {}
        occ_a = _occupied(acct)
        for i in np.nonzero(occ_a)[0]:
            a = _account_from_cols(acct, i)
            accounts[a.id] = a
        occ_t = _occupied(xfer)
        for i in np.nonzero(occ_t)[0]:
            t = _transfer_from_cols(xfer, i)
            transfers[t.id] = t
            if xfer["fulfill"][i]:
                posted[int(xfer["ts"][i])] = int(xfer["fulfill"][i])
        return accounts, transfers, posted

    @property
    def commit_timestamp(self) -> int:
        return int(self.state["commit_ts"])


def _occupied(cols) -> np.ndarray:
    k_lo, k_hi = cols["key_lo"], cols["key_hi"]
    empty = (k_lo == 0) & (k_hi == 0)
    tomb = (k_lo == np.uint64(0xFFFFFFFFFFFFFFFF)) & (k_hi == np.uint64(0xFFFFFFFFFFFFFFFF))
    occ = ~empty & ~tomb
    occ[-1] = False  # dump row
    return occ


def _account_from_cols(c, i) -> types.Account:
    return types.Account(
        id=types.join_u128(c["key_lo"][i], c["key_hi"][i]),
        debits_pending=types.join_u128(c["dp_lo"][i], c["dp_hi"][i]),
        debits_posted=types.join_u128(c["dpo_lo"][i], c["dpo_hi"][i]),
        credits_pending=types.join_u128(c["cp_lo"][i], c["cp_hi"][i]),
        credits_posted=types.join_u128(c["cpo_lo"][i], c["cpo_hi"][i]),
        user_data_128=types.join_u128(c["ud128_lo"][i], c["ud128_hi"][i]),
        user_data_64=int(c["ud64"][i]),
        user_data_32=int(c["ud32"][i]),
        ledger=int(c["ledger"][i]),
        code=int(c["code"][i]),
        flags=int(c["flags"][i]),
        timestamp=int(c["ts"][i]),
    )


def _transfer_from_cols(c, i) -> types.Transfer:
    return types.Transfer(
        id=types.join_u128(c["key_lo"][i], c["key_hi"][i]),
        debit_account_id=types.join_u128(c["dr_lo"][i], c["dr_hi"][i]),
        credit_account_id=types.join_u128(c["cr_lo"][i], c["cr_hi"][i]),
        amount=types.join_u128(c["amt_lo"][i], c["amt_hi"][i]),
        pending_id=types.join_u128(c["pid_lo"][i], c["pid_hi"][i]),
        user_data_128=types.join_u128(c["ud128_lo"][i], c["ud128_hi"][i]),
        user_data_64=int(c["ud64"][i]),
        user_data_32=int(c["ud32"][i]),
        timeout=int(c["timeout"][i]),
        ledger=int(c["ledger"][i]),
        code=int(c["code"][i]),
        flags=int(c["flags"][i]),
        timestamp=int(c["ts"][i]),
    )
