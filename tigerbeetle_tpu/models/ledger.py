"""The device ledger: TigerBeetle's state machine as JAX kernels over HBM.

This is the TPU-native redesign of the reference's hot path (reference:
src/state_machine.zig:508-698 commit/execute): the account and transfer
stores are HBM-resident open-addressing hash tables whose rows ARE the
128-byte wire format (one [capacity+1, 32] u32 array per table — see
ops/hashtable.py for the probe design and why u32 rows are the fast layout
on TPU), and a whole prepare batch commits in one jitted step. Host batches
upload as a single bitcast of the wire bytes.

Two execution tiers, selected ON THE HOST before dispatch (the device
kernels are straight-line programs — no lax.cond dispatch, no while_loops;
see ops/hashtable.py for why data-dependent control flow is banned):

- **Fast tier (vectorized)**: all lookups, validation, and application run
  data-parallel over the batch. Sound only when the batch is free of serial
  hazards — no linked chains, no post/void or balancing events, no duplicate
  ids, no touched account with balance-limit flags, and no u128 overflow even
  at the batch-final balances (all fast-tier balance deltas are non-negative,
  so per-prefix overflow is impossible iff final overflow is). The HOST
  proves every one of these conditions before choosing this tier — see
  DeviceLedger._transfers_hazard (flags/dups from the batch itself, a
  limit-account id set, and an exact running amount-sum bound for overflow).
  Balance deltas accumulate as 16-bit digits in a persistent
  [capacity+1, 32] u32 scratch (4 balance fields x 8 digits; digit sums of
  <= 2^13 events stay < 2^30), and a touched-slot digit-carry pass folds
  them into the u128 balances — all in u32, no big-array traffic.
- **Serial tier (lax.scan)**: an exact, event-at-a-time kernel with the full
  semantics — linked-chain rollback via an undo log (reference:
  src/state_machine.zig:612-698 + src/lsm/groove.zig:990-1010 scopes),
  two-phase post/void (reference: :907-1014), balancing clamps, in-batch
  duplicate ids.

Between the two sits **conflict-wave scheduling** (HazardTracker.plan +
DeviceLedger._execute_waves): a batch with TRUE dependencies — duplicate
ids, post/voids of same-batch pendings, touches of balance-limit
accounts — is partitioned into dependency-ordered waves, each a masked
fast/fast_pv pass over the same uploaded batch, dispatched in one scanned
launch; only lanes the masked kernels cannot express (linked chains,
balancing, unresolvable pending refs against order-sensitive accounts,
chains deeper than WAVE_CAP) fall to a compacted serial residue. The wave
layout is a deterministic pure function of the batch bytes + tracker
state, so replicas and the simulator plan identically.

Both tiers call the same validation ladders (models/validate.py), so result
codes are bit-exact against the oracle (models/oracle.py) on every path.

**Fault protocol**: probe windows are finite (ops/hashtable.py), so a probe
chain or claim contention can — with ~2^-32 probability per op at the
enforced <= 1/2 load factor — exceed the window. The fast kernel detects
every such case BEFORE writing anything, turns the whole commit into a
no-op, and sets a sticky `fault` word in the state; once fault != 0, every
subsequent commit is also a no-op, so the device state stays exactly as of
the last good batch. The host checks the fault word (per batch on the sync
path, amortized on the async path) and raises. The serial kernel applies
as it scans and cannot un-apply, so its unresolved probes mark the fault
word as corrupting (FAULT_SERIAL) — with the 64-probe scalar window this is
a ~2^-64 event. The reference's analog is its assert-dense ReleaseSafe
discipline (reference: src/tigerbeetle.zig:263-266): fail loudly, never
corrupt silently.

The reference's `posted` groove (reference: src/state_machine.zig:185-198) is
the `fulfill` column alongside the transfer rows (1:1 by construction).
"""

from __future__ import annotations

import threading
from collections import deque
from time import perf_counter_ns, time  # vet: observability-only (compile sentinel)

import jax
import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import (
    DEFAULT_CLUSTER,
    DEFAULT_PROCESS,
    ConfigCluster,
    ConfigProcess,
)
from tigerbeetle_tpu.lsm import groove as groove_fields
from tigerbeetle_tpu.metrics import NULL_METRICS
from tigerbeetle_tpu.models import validate
from tigerbeetle_tpu.models.validate import (
    F_BAL_CR,
    F_BAL_DR,
    F_LINKED,
    F_PENDING,
    F_POST,
    F_VOID,
)
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.ops import u128
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.types import Operation

U64 = jnp.uint64
U32 = jnp.uint32
I32 = jnp.int32

# Flags that force the serial tier in the ALL-OR-NOTHING hazard check
# (sharded ledger): linked | post | void | balancing_debit |
# balancing_credit. Only no-flag and pending-only events are fast-tier-safe.
_SLOW_FLAGS = 0b111101


# ----------------------------------------------------------------------
# compile sentinel (every jit entry point in this module and
# dual_ledger.py routes through sentinel_jit)
# ----------------------------------------------------------------------

class CompileSentinel:
    """Process-wide XLA compile observer: every jit entry point wraps in
    a probe that detects executable-cache growth (a compile) and times
    it. A compile landing AFTER `mark_warm()` is a hot-path event — the
    long-documented `.jax_cache` sandbox pathology (a poisoned or absent
    persistent cache recompiling mid-serving) becomes a named counter
    (`device.compiles_post_warmup`) plus a bounded event log the SIGQUIT
    dump and flight recorder surface, instead of an inferred abort.

    Counts accumulate process-wide from import time; `instrument()`
    (called by DeviceLedger/DualLedger.instrument at setup) rebinds onto
    the replica's shared registry and carries the accumulated totals in,
    so warm-up compiles that predate the registry still show. Compiles
    can land on any thread (warm path on main, group steppers on the
    apply thread), hence the lock.  # vet: guarded-by=_lock
    """

    _EVENTS_MAX = 64  # bounded event log (SIGQUIT dump section)

    def __init__(self):
        self._lock = threading.Lock()
        self.metrics = NULL_METRICS
        self.warm = False
        self.total = 0
        self.post_warmup = 0
        self.per_name: dict[str, int] = {}
        self.events: deque = deque(maxlen=self._EVENTS_MAX)
        self._bind(NULL_METRICS)

    def _bind(self, m) -> None:
        self._c_total = m.counter("device.compiles")
        self._c_post = m.counter("device.compiles_post_warmup")
        self._h_ms = m.histogram("device.compile_ms")

    def instrument(self, metrics) -> None:
        """Re-bind onto a shared registry (the replica's); process-wide
        totals carry over because the fresh registry starts at zero and
        warm-up compiles predate it."""
        with self._lock:
            self.metrics = metrics
            self._bind(metrics)
            if self.total:
                self._c_total.add(self.total)
            if self.post_warmup:
                self._c_post.add(self.post_warmup)

    def mark_warm(self) -> None:
        """Everything compiled past this point is a hot-path event
        (called after kernel warm-up / at serving start)."""
        with self._lock:
            self.warm = True

    def note(self, name: str, ms: float) -> None:
        with self._lock:
            self.total += 1
            self.per_name[name] = self.per_name.get(name, 0) + 1
            self._c_total.add()
            self._h_ms.observe(ms)
            post = self.warm
            if post:
                self.post_warmup += 1
                self._c_post.add()
            self.events.append({
                "t": round(time(), 3),
                "fn": name,
                "ms": round(ms, 3),
                "post_warmup": post,
            })

    def snapshot(self) -> dict:
        """The [stats]/SIGQUIT section: totals + per-signature counts +
        the bounded event log (newest last)."""
        with self._lock:
            return {
                "total": self.total,
                "post_warmup": self.post_warmup,
                "warm": self.warm,
                "per_fn": dict(self.per_name),
                "events": list(self.events),
            }


COMPILE_SENTINEL = CompileSentinel()


class _SentinelJit:
    """One jit entry point under the sentinel. The steady-state cost is
    two executable-cache-size probes and one clock read per dispatch —
    noise against a kernel launch. A call that grew the cache compiled:
    its wall duration (trace + lower + compile + first dispatch) is the
    observed compile time."""

    __slots__ = ("fn", "name")

    def __init__(self, fn, name: str):
        self.fn = fn
        self.name = name

    def __call__(self, *args, **kwargs):
        fn = self.fn
        try:
            before = fn._cache_size()
        except Exception:  # not a PjitFunction (test double) — pass through
            return fn(*args, **kwargs)
        t0 = perf_counter_ns()
        out = fn(*args, **kwargs)
        if fn._cache_size() > before:
            COMPILE_SENTINEL.note(self.name, (perf_counter_ns() - t0) / 1e6)
        return out


def sentinel_jit(name: str, fn, **jit_kwargs):
    """jax.jit + compile sentinel — the only way this repo jits."""
    return _SentinelJit(jax.jit(fn, **jit_kwargs), name)

# ----------------------------------------------------------------------
# conflict-wave scheduling (HazardTracker.plan / DeviceLedger._execute_waves)
# ----------------------------------------------------------------------
# Deepest dependency chain the wave path executes; lanes past the cap fall
# to the serial residue (each wave costs a full-batch kernel pass, so past
# ~this depth the exact scan is cheaper anyway).
WAVE_CAP = 24
# Longest-path propagation sweeps before the planner gives up and takes
# the whole-batch serial escape hatch (multi-key entanglement deeper than
# this is adversarial, not a workload).
_WAVE_SWEEPS = 8
# Compiled wave-count variants: a plan's wave count pads up to the next
# bucket with all-false (no-op) masks so the scanned wave stepper compiles
# a handful of shapes, not one per observed depth.
_WAVE_BUCKETS = (2, 3, 4, 6, 8, 12, 16, WAVE_CAP)
_WAVE_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
# Distinct multiplier for the order-sensitive ACCOUNT key namespace; a
# cross-namespace hash collision with an id/pending-id key only ADDS a
# conflict edge (conservative), never drops one.
_WAVE_GOLDEN2 = np.uint64(0xC2B2AE3D27D4EB4F)

ROW_WORDS = 32  # 128-byte wire rows as u32 words

# Equality-query field specs: name -> (first u32 word, word count, halfword)
# — derived from the ONE declaration of the indexed field layouts
# (lsm/groove.py, mirroring the reference's secondary index trees,
# src/state_machine.zig:103-206 ids 1-24) so the device filter scan and the
# LSM index scan can never drift apart per field name.


def _query_words(index_fields) -> dict:
    out = {}
    for name, off, w in index_fields:
        assert off % 4 == 0 and w in (2, 4, 8, 16), (name, off, w)
        out[name] = (off // 4, max(w // 4, 1), w == 2)
    return out


_ACCOUNT_QUERY_WORDS = _query_words(groove_fields.ACCOUNT_INDEX_FIELDS)
_TRANSFER_QUERY_WORDS = _query_words(groove_fields.TRANSFER_INDEX_FIELDS)
# Query replies are message-bounded like every other reply (reference:
# src/state_machine.zig:59-64 — results must fit one message).
QUERY_LIMIT = 8192

# Sticky fault bits (see module docstring "Fault protocol").
FAULT_PROBE = 1  # fast-tier lookup window exhausted (batch was a no-op)
FAULT_CLAIM = 2  # fast-tier claim rounds exhausted (batch was a no-op)
FAULT_OVERFLOW = 4  # device-side overflow backstop tripped (batch was a no-op)
FAULT_SERIAL = 8  # serial-tier probe window exhausted — STATE IS CORRUPT
FAULT_CAPACITY = 16  # device-side load-factor guard tripped (batch no-op)

_FAULT_NAMES = (
    (FAULT_PROBE, "probe-window"),
    (FAULT_CLAIM, "claim-rounds"),
    (FAULT_OVERFLOW, "overflow-backstop"),
    (FAULT_SERIAL, "serial-probe"),
    (FAULT_CAPACITY, "capacity-guard"),
)


def raise_on_fault(fault: int, what: str) -> None:
    """Shared fault-word decoder (single-chip and sharded ledgers)."""
    if not fault:
        return
    bits = [name for bit, name in _FAULT_NAMES if fault & bit]
    corrupt = (
        " (serial tier: device state is CORRUPT)"
        if fault & FAULT_SERIAL
        else " (the faulting batch and everything after were no-ops)"
    )
    raise RuntimeError(
        f"{what} fault {fault:#x} [{', '.join(bits)}]{corrupt}: "
        "grow the table (slots_log2) or lower the load factor"
    )


# ----------------------------------------------------------------------
# state fingerprint + reply-code fold (the dual-commit parity seam)
#
# Order-independent digest over LIVE table rows: sum (mod 2^64) of a
# per-row hash of the 128-byte wire image. The native C++ engine implements
# the IDENTICAL function over its host tables (native/ledger.cc
# tb_ledger_fingerprint), so two parity-locked engines that processed the
# same prepares agree iff their logical row sets are bit-identical —
# regardless of slot layout (device open-addressing vs host table). Any
# constant below changes BOTH implementations or dual-commit verification
# breaks loudly.
# ----------------------------------------------------------------------

_FP_SEED = np.uint64(0x9E3779B97F4A7C15)
_FP_MUL = np.uint64(0xC2B2AE3D27D4EB4F)
_FP_ADD = np.uint64(0x165667B19E3779F9)
_FP_MIX1 = np.uint64(0xFF51AFD7ED558CCD)
_FP_MIX2 = np.uint64(0xC4CEB9FE1A85EC53)


def _fp_mix(x):
    x = (x ^ (x >> jnp.uint64(33))) * _FP_MIX1
    x = (x ^ (x >> jnp.uint64(33))) * _FP_MIX2
    return x ^ (x >> jnp.uint64(33))


def _fp_rows(rows):
    """[S, 32]-u32 table -> (u64 fp sum over live rows, u64 live count).
    Empty (key words all-0) and tombstone (all-0xFFFFFFFF) slots excluded,
    matching the native table's st[] == full predicate."""
    h = jnp.full(rows.shape[0], _FP_SEED, dtype=U64)
    for i in range(ROW_WORDS):
        h = h ^ (rows[:, i].astype(U64) * _FP_MUL)
        h = ((h << jnp.uint64(27)) | (h >> jnp.uint64(37))) * _FP_SEED + _FP_ADD
    h = _fp_mix(h)
    k4 = rows[:, :4]
    empty = (k4 == 0).all(axis=1)
    tomb = (k4 == 0xFFFFFFFF).all(axis=1)
    live = ~empty & ~tomb
    return (
        jnp.sum(jnp.where(live, h, jnp.uint64(0))),
        jnp.sum(live.astype(U64)),
    )


def state_fingerprint(state) -> dict:
    """Jittable digest of the device ledger (dual-commit verification).
    The trailing dump row (masked-scatter target, never read) is excluded —
    it holds garbage by design."""
    afp, alive = _fp_rows(state["acct_rows"][:-1])
    tfp, tlive = _fp_rows(state["xfer_rows"][:-1])
    return {
        "accounts_fp": afp,
        "transfers_fp": tfp,
        "accounts": alive,
        "transfers": tlive,
        "commit_timestamp": state["commit_ts"],
    }


def fold_reply_codes(chk, results, n):
    """Jittable running digest of the dense reply-code stream (the
    hash_log-style shadow check: the dual server folds every shadow batch's
    codes on DEVICE — no d2h — and compares one scalar at shutdown against
    the native engine's host-side fold). `results` is the packed
    [codes(n_pad), fault] vector from execute_async; lanes >= n are
    padding and excluded. Chained: order of batches is captured."""
    lane = jnp.arange(results.shape[0], dtype=jnp.int32)
    m = _fp_mix(
        results.astype(U64) * _FP_MUL
        + lane.astype(U64)
        + jnp.uint64(1)
    )
    batch_h = jnp.sum(jnp.where(lane < n, m, jnp.uint64(0)))
    return _fp_mix(chk ^ (batch_h + jnp.uint64(n).astype(U64)))


def fold_reply_codes_np(chk: int, codes: np.ndarray) -> int:
    """The numpy twin of fold_reply_codes for the native engine's dense
    codes (exact u64 wraparound semantics)."""
    with np.errstate(over="ignore"):
        def mix(x):
            x = (x ^ (x >> np.uint64(33))) * _FP_MIX1
            x = (x ^ (x >> np.uint64(33))) * _FP_MIX2
            return x ^ (x >> np.uint64(33))

        lane = np.arange(len(codes), dtype=np.uint64)
        m = mix(codes.astype(np.uint64) * _FP_MUL + lane + np.uint64(1))
        batch_h = np.sum(m, dtype=np.uint64)
        out = mix(np.uint64(chk) ^ (batch_h + np.uint64(len(codes))))
        return int(out)


def fp_rows_np(rows: np.ndarray) -> tuple:
    """The numpy twin of _fp_rows over 128-byte wire rows (structured
    ACCOUNT_DTYPE/TRANSFER_DTYPE arrays or raw [n, 32]-u32). The per-row
    hash is content-only and the reduction a commutative sum, so the
    oracle computes the same digest from its dict-ordered wire images as
    the device does from its open-addressed slots — this is what lets an
    external CDC consumer recompute checkpoint commitments."""
    if rows.dtype != np.uint32:
        rows = np.ascontiguousarray(rows).view(np.uint32)
    rows = rows.reshape(-1, ROW_WORDS)
    if len(rows) == 0:
        return 0, 0
    with np.errstate(over="ignore"):
        h = np.full(rows.shape[0], _FP_SEED, dtype=np.uint64)
        for i in range(ROW_WORDS):
            h = h ^ (rows[:, i].astype(np.uint64) * _FP_MUL)
            h = ((h << np.uint64(27)) | (h >> np.uint64(37))) * _FP_SEED + _FP_ADD
        h = (h ^ (h >> np.uint64(33))) * _FP_MIX1
        h = (h ^ (h >> np.uint64(33))) * _FP_MIX2
        h = h ^ (h >> np.uint64(33))
        k4 = rows[:, :4]
        live = ~(k4 == 0).all(axis=1) & ~(k4 == 0xFFFFFFFF).all(axis=1)
        return (
            int(np.sum(np.where(live, h, np.uint64(0)), dtype=np.uint64)),
            int(np.sum(live, dtype=np.uint64)),
        )


# ----------------------------------------------------------------------
# wire-row pack/unpack (word offsets = byte offsets / 4 of the extern
# structs, reference: src/tigerbeetle.zig:7-40 Account, :64-89 Transfer)
# ----------------------------------------------------------------------


def _w64(r, i: int):
    return r[..., i].astype(U64) | (r[..., i + 1].astype(U64) << jnp.uint64(32))


def _lohi(x):
    return (x & jnp.uint64(0xFFFFFFFF)).astype(U32), (x >> jnp.uint64(32)).astype(U32)


def unpack_transfer(r) -> dict:
    return {
        "id_lo": _w64(r, 0), "id_hi": _w64(r, 2),
        "dr_lo": _w64(r, 4), "dr_hi": _w64(r, 6),
        "cr_lo": _w64(r, 8), "cr_hi": _w64(r, 10),
        "amt_lo": _w64(r, 12), "amt_hi": _w64(r, 14),
        "pid_lo": _w64(r, 16), "pid_hi": _w64(r, 18),
        "ud128_lo": _w64(r, 20), "ud128_hi": _w64(r, 22),
        "ud64": _w64(r, 24),
        "ud32": r[..., 26],
        "timeout": r[..., 27],
        "ledger": r[..., 28],
        "code": r[..., 29] & jnp.uint32(0xFFFF),
        "flags": r[..., 29] >> jnp.uint32(16),
        "ts": _w64(r, 30),
    }


def pack_transfer(f) -> jnp.ndarray:
    words = []
    for key in ("id", "dr", "cr", "amt", "pid", "ud128"):
        lo0, lo1 = _lohi(f[key + "_lo"])
        hi0, hi1 = _lohi(f[key + "_hi"])
        words += [lo0, lo1, hi0, hi1]
    u0, u1 = _lohi(f["ud64"])
    words += [u0, u1, f["ud32"], f["timeout"], f["ledger"],
              (f["code"] & jnp.uint32(0xFFFF)) | (f["flags"] << jnp.uint32(16))]
    t0, t1 = _lohi(f["ts"])
    words += [t0, t1]
    return jnp.stack(words, axis=-1)


def unpack_account(r) -> dict:
    return {
        "id_lo": _w64(r, 0), "id_hi": _w64(r, 2),
        "dp_lo": _w64(r, 4), "dp_hi": _w64(r, 6),
        "dpo_lo": _w64(r, 8), "dpo_hi": _w64(r, 10),
        "cp_lo": _w64(r, 12), "cp_hi": _w64(r, 14),
        "cpo_lo": _w64(r, 16), "cpo_hi": _w64(r, 18),
        "ud128_lo": _w64(r, 20), "ud128_hi": _w64(r, 22),
        "ud64": _w64(r, 24),
        "ud32": r[..., 26],
        "reserved": r[..., 27],
        "ledger": r[..., 28],
        "code": r[..., 29] & jnp.uint32(0xFFFF),
        "flags": r[..., 29] >> jnp.uint32(16),
        "ts": _w64(r, 30),
    }


def pack_account(f) -> jnp.ndarray:
    words = []
    for key in ("id", "dp", "dpo", "cp", "cpo", "ud128"):
        lo0, lo1 = _lohi(f[key + "_lo"])
        hi0, hi1 = _lohi(f[key + "_hi"])
        words += [lo0, lo1, hi0, hi1]
    u0, u1 = _lohi(f["ud64"])
    words += [u0, u1, f["ud32"], f["reserved"], f["ledger"],
              (f["code"] & jnp.uint32(0xFFFF)) | (f["flags"] << jnp.uint32(16))]
    t0, t1 = _lohi(f["ts"])
    words += [t0, t1]
    return jnp.stack(words, axis=-1)


_TOMB_ROW = np.full(ROW_WORDS, 0xFFFFFFFF, dtype=np.uint32)


def key4_from_fields(f):
    lo0, lo1 = _lohi(f["id_lo"])
    hi0, hi1 = _lohi(f["id_hi"])
    return jnp.stack([lo0, lo1, hi0, hi1], axis=-1)


# ----------------------------------------------------------------------
# state
# ----------------------------------------------------------------------


def init_state(process: ConfigProcess = DEFAULT_PROCESS) -> dict:
    """Allocate the device ledger. Tables have capacity+1 rows: the last row
    is the write dump for masked scatters (never read). `bal_acc` is the
    persistent balance-digit accumulator (all-zero between commits). `fault`
    is the sticky fault word (0 = healthy; see module docstring)."""
    a_rows = (1 << process.account_slots_log2) + 1
    t_rows = (1 << process.transfer_slots_log2) + 1
    return {
        "acct_rows": jnp.zeros((a_rows, ROW_WORDS), dtype=U32),
        "xfer_rows": jnp.zeros((t_rows, ROW_WORDS), dtype=U32),
        "fulfill": jnp.zeros(t_rows, dtype=U32),
        "acct_claim": jnp.full(a_rows, ht.CLAIM_FREE, dtype=U32),
        "xfer_claim": jnp.full(t_rows, ht.CLAIM_FREE, dtype=U32),
        "bal_acc": jnp.zeros((a_rows, ROW_WORDS), dtype=U32),
        "commit_ts": jnp.uint64(0),
        "acct_count": jnp.uint64(0),
        "xfer_count": jnp.uint64(0),
        # ever-applied insert counters (rolled-back inserts INCLUDED: their
        # tombstones still lengthen probe chains) — the DEVICE-side
        # load-factor guard, independent of the host's estimate
        "acct_used_slots": jnp.uint64(0),
        "xfer_used_slots": jnp.uint64(0),
        "fault": jnp.uint32(0),
    }


# ----------------------------------------------------------------------
# host <-> device batch conversion (one bitcast upload)
# ----------------------------------------------------------------------


def _to_rows_np(arr: np.ndarray, n_pad: int) -> np.ndarray:
    out = np.zeros((n_pad, ROW_WORDS), dtype=np.uint32)
    out[: len(arr)] = arr.view(np.uint32).reshape(len(arr), ROW_WORDS)
    return out


def transfers_to_batch(arr: np.ndarray, n_pad: int) -> dict:
    """Wire-format structured array (types.TRANSFER_DTYPE) -> device batch."""
    return {"rows": jnp.asarray(_to_rows_np(arr, n_pad))}


def accounts_to_batch(arr: np.ndarray, n_pad: int) -> dict:
    return {"rows": jnp.asarray(_to_rows_np(arr, n_pad))}


def ids_to_batch(ids: list[int], n_pad: int) -> dict:
    k4 = np.zeros((n_pad, 4), dtype=np.uint32)
    for i, x in enumerate(ids):
        lo, hi = types.split_u128(x)
        k4[i] = (lo & 0xFFFFFFFF, lo >> 32, hi & 0xFFFFFFFF, hi >> 32)
    return {"key4": jnp.asarray(k4)}


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------


def _amount_digits(amt_lo, amt_hi):
    """u128 -> 8 x 16-bit digits (u32 lanes), little-endian."""
    ds = []
    for limb in (amt_lo, amt_hi):
        for j in range(4):
            ds.append(((limb >> jnp.uint64(16 * j)) & jnp.uint64(0xFFFF)).astype(U32))
    return jnp.stack(ds, axis=-1)  # [..., 8]


def _fold_digits(row32, acc32):
    """Fold a [.., 32] digit accumulator into a [.., 32] wire row's 4 balance
    fields (words 4..19) with 16-bit carry propagation. acc lanes: dp digits
    0..7, dpo 8..15, cp 16..23, cpo 24..31. Returns (new_row, overflow)."""
    new_words = [row32[..., i] for i in range(ROW_WORDS)]
    overflow = jnp.zeros(row32.shape[:-1], dtype=bool)
    for field in range(4):  # dp, dpo, cp, cpo at words 4+4f .. 7+4f
        w0 = 4 + 4 * field
        carry = jnp.zeros(row32.shape[:-1], dtype=U32)
        for k in range(4):  # 4 words x two 16-bit digits
            w = row32[..., w0 + k]
            d_lo = acc32[..., 8 * field + 2 * k]
            d_hi = acc32[..., 8 * field + 2 * k + 1]
            s_lo = (w & jnp.uint32(0xFFFF)) + d_lo + carry
            carry = s_lo >> jnp.uint32(16)
            s_hi = (w >> jnp.uint32(16)) + d_hi + carry
            carry = s_hi >> jnp.uint32(16)
            new_words[w0 + k] = (s_lo & jnp.uint32(0xFFFF)) | (s_hi << jnp.uint32(16))
        overflow = overflow | (carry != 0)
    return jnp.stack(new_words, axis=-1), overflow


def _fold_digits_signed(row32, acc32):
    """Signed variant of _fold_digits for the post/void fast tier: the
    accumulator lanes hold mod-2^32 sums of SIGNED 16-bit digits
    (subtractions contribute (-d) mod 2^32). |true sum| <= 8192*65535 <
    2^30, so bitcasting a lane to i32 recovers the exact signed value; the
    fold then runs in i64 with arithmetic-shift carries. A nonzero final
    carry means overflow (positive) or underflow (negative — impossible for
    host-proven batches: every subtraction is a distinct committed pending's
    amount already included in the balance; kept as the device backstop).
    Returns (new_row, bad)."""
    new_words = [row32[..., i] for i in range(ROW_WORDS)]
    bad = jnp.zeros(row32.shape[:-1], dtype=bool)
    I64 = jnp.int64
    for field in range(4):  # dp, dpo, cp, cpo at words 4+4f .. 7+4f
        w0 = 4 + 4 * field
        carry = jnp.zeros(row32.shape[:-1], dtype=I64)
        for k in range(4):
            w = row32[..., w0 + k]
            d_lo = jax.lax.bitcast_convert_type(
                acc32[..., 8 * field + 2 * k], jnp.int32
            ).astype(I64)
            d_hi = jax.lax.bitcast_convert_type(
                acc32[..., 8 * field + 2 * k + 1], jnp.int32
            ).astype(I64)
            s_lo = (w & jnp.uint32(0xFFFF)).astype(I64) + d_lo + carry
            carry = s_lo >> jnp.int64(16)
            s_hi = (w >> jnp.uint32(16)).astype(I64) + d_hi + carry
            carry = s_hi >> jnp.int64(16)
            new_words[w0 + k] = (
                (s_lo & jnp.int64(0xFFFF))
                | ((s_hi & jnp.int64(0xFFFF)) << jnp.int64(16))
            ).astype(U32)
        bad = bad | (carry != 0)
    return jnp.stack(new_words, axis=-1), bad


def _combined_overflow(new_rows_t):
    """Per-lane carry of the COMBINED debits_pending+debits_posted and
    credits_pending+credits_posted sums of folded account rows. Codes 51/52
    guard these sums (reference: src/state_machine.zig:856-861), not just each
    field: a batch mixing pending and posted amounts to one account can
    overflow dp+dpo with neither field's fold carrying. All fast-tier deltas
    are non-negative, so the batch-final combined sums overflow iff some
    prefix does — checking the folded rows is exact."""
    nr = unpack_account(new_rows_t)
    _, _, c_dr = u128.add(nr["dp_lo"], nr["dp_hi"], nr["dpo_lo"], nr["dpo_hi"])
    _, _, c_cr = u128.add(nr["cp_lo"], nr["cp_hi"], nr["cpo_lo"], nr["cpo_hi"])
    return c_dr | c_cr


def build_stored_transfer(e, p, is_pv, amt_lo, amt_hi, ts) -> dict:
    """The row a create_transfers event STORES: post/void events inherit the
    pending's routing fields, default their user data from it, and persist
    the resolved amount (reference: src/state_machine.zig:907-1014). Shared
    by the fast_pv kernel (batched) and the serial scan (per event) so the
    two tiers cannot drift."""

    def dflt128(t_lo, t_hi, q_lo, q_hi):
        z = u128.is_zero(t_lo, t_hi)
        return jnp.where(z, q_lo, t_lo), jnp.where(z, q_hi, t_hi)

    t2_ud128 = dflt128(e["ud128_lo"], e["ud128_hi"], p["ud128_lo"], p["ud128_hi"])
    return {
        "id_lo": e["id_lo"], "id_hi": e["id_hi"],
        "dr_lo": jnp.where(is_pv, p["dr_lo"], e["dr_lo"]),
        "dr_hi": jnp.where(is_pv, p["dr_hi"], e["dr_hi"]),
        "cr_lo": jnp.where(is_pv, p["cr_lo"], e["cr_lo"]),
        "cr_hi": jnp.where(is_pv, p["cr_hi"], e["cr_hi"]),
        "amt_lo": amt_lo, "amt_hi": amt_hi,
        "pid_lo": e["pid_lo"], "pid_hi": e["pid_hi"],
        "ud128_lo": jnp.where(is_pv, t2_ud128[0], e["ud128_lo"]),
        "ud128_hi": jnp.where(is_pv, t2_ud128[1], e["ud128_hi"]),
        "ud64": jnp.where(is_pv & (e["ud64"] == 0), p["ud64"], e["ud64"]),
        "ud32": jnp.where(is_pv & (e["ud32"] == 0), p["ud32"], e["ud32"]),
        "timeout": jnp.where(is_pv, jnp.uint32(0), e["timeout"]),
        "ledger": jnp.where(is_pv, p["ledger"], e["ledger"]),
        "code": jnp.where(is_pv, p["code"], e["code"]),
        "flags": e["flags"],
        "ts": ts,
    }


def _set_ts_words(rows, ts):
    t0, t1 = _lohi(ts)
    return jnp.concatenate(
        [rows[:, :30], t0[:, None], t1[:, None]], axis=1
    )


# ----------------------------------------------------------------------
# kernels
# ----------------------------------------------------------------------


_KERNELS_CACHE: dict = {}


def get_kernels(process: "ConfigProcess") -> "LedgerKernels":
    """One LedgerKernels per table geometry, process-wide. The kernels are
    stateless (closed over slot counts only); sharing them means a fresh
    DeviceLedger reuses every jitted function's compile cache — a
    median-of-N bench run or a 300-test CI session compiles each kernel
    ONCE instead of once per ledger."""
    k = _KERNELS_CACHE.get(process)
    if k is None:
        k = _KERNELS_CACHE[process] = LedgerKernels(process)
    return k


class LedgerKernels:
    """Compiled commit kernels closed over the table geometry.

    `mode` selects dispatch: "auto" (hazard-predicated lax.cond, production),
    "serial" (always the exact scan; parity testing), "fast" (always the
    vectorized tier; only sound on hazard-free batches — parity testing).
    """

    def __init__(self, process: ConfigProcess = DEFAULT_PROCESS):
        self.process = process
        self.a_log2 = process.account_slots_log2
        self.t_log2 = process.transfer_slots_log2
        # Python ints (embedded as literals) — capturing jnp scalars in the
        # kernels would poison dispatch (see ops/hashtable.py note).
        self.a_dump = 1 << self.a_log2
        self.t_dump = 1 << self.t_log2
        self.commit_transfers = sentinel_jit(
            "commit_transfers", self._commit_transfers,
            static_argnames=("mode",), donate_argnums=(0,),
        )
        self.commit_accounts = sentinel_jit(
            "commit_accounts", self._commit_accounts,
            static_argnames=("mode",), donate_argnums=(0,),
        )
        # Residue entry for the WAVE executor: the serial scan over a
        # compacted hazard residue with explicit per-event timestamps.
        self.commit_transfers_residue = sentinel_jit(
            "commit_transfers_residue",
            lambda state, ev, n: self._serial_transfers_core(
                state, ev["rows"], ev["ts"], n
            ),
            donate_argnums=(0,),
        )
        self.merge_results = sentinel_jit(
            "merge_results",
            lambda r_fast, r_res, idx: r_fast.at[idx].set(r_res, mode="drop"),
        )
        self.lookup_accounts = sentinel_jit("lookup_accounts", self._lookup_accounts)
        self.lookup_transfers = sentinel_jit("lookup_transfers", self._lookup_transfers)
        self._filters: dict = {}  # (table, field) -> jitted filter scan

    # ------------------------------------------------------------------
    # secondary-index queries: the TPU-native analog of the reference's
    # per-field index trees (reference: src/lsm/groove.zig:137-157) over
    # the RESIDENT store is a vectorized filter scan — the whole table is
    # in HBM, so an equality query is one fused compare+compact, no index
    # maintenance on the hot path. (Spilled rows use the LSM index trees,
    # lsm/groove.py; DeviceLedger.query_* merges the two.)
    # ------------------------------------------------------------------

    def filter_scan(self, table: str, field: str):
        """Jitted equality scan over a table: (rows, value_words u32[4]) ->
        (first QUERY_LIMIT matching rows in slot order, total match count)."""
        key = (table, field)
        if key in self._filters:
            return self._filters[key]
        spec = (_ACCOUNT_QUERY_WORDS if table == "acct" else
                _TRANSFER_QUERY_WORDS)[field]
        word0, nwords, halfword = spec
        dump = self.a_dump if table == "acct" else self.t_dump
        K = QUERY_LIMIT

        def scan(rows, val_words):
            occ = ht.occupied_mask(rows).at[dump].set(False)
            if halfword:
                m = (rows[:, word0] & jnp.uint32(0xFFFF)) == val_words[0]
            else:
                m = rows[:, word0] == val_words[0]
                for i in range(1, nwords):
                    m = m & (rows[:, word0 + i] == val_words[i])
            mask = occ & m
            total = jnp.sum(mask.astype(I32))
            rank = jnp.cumsum(mask.astype(I32)) - 1
            pos = jnp.where(mask & (rank < K), rank, K)
            idx = (
                jnp.full(K + 1, dump, dtype=I32)
                .at[pos]
                .set(jnp.arange(rows.shape[0], dtype=I32))[:K]
            )
            return rows[idx], total

        self._filters[key] = sentinel_jit(f"filter_{table}_{field}", scan)
        return self._filters[key]

    # ------------------------------------------------------------------
    # create_transfers
    # ------------------------------------------------------------------

    def _commit_transfers(self, state, ev, n, timestamp, mode: str = "fast"):
        """Returns (state', results u32 [B]). `mode` is chosen by the HOST:
        "fast" for host-proven hazard-free batches, "fast_pv" when the batch
        additionally carries fast-eligible post/void events (distinct,
        registry-known pendings, or waves ordered after their in-batch
        creators — see HazardTracker.plan), "serial" for the exact
        scan."""
        if mode == "serial":
            return self._serial_transfers(state, ev, n, timestamp)
        assert mode in ("fast", "fast_pv"), mode
        pv_mode = mode == "fast_pv"

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_transfer(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        if "mask" in ev:  # wave executor: only this wave's lanes are live
            valid = valid & ev["mask"]
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        e_a = {**e, "ts": ts_vec}

        acct_rows = state["acct_rows"]
        xfer_rows = state["xfer_rows"]
        # dr and cr probe the same table: fuse into one 2B-lane lookup.
        both_k4 = jnp.concatenate([rows_b[:, 4:8], rows_b[:, 8:12]], axis=0)
        both_slot, both_found, both_res = ht.lookup(both_k4, acct_rows, self.a_log2)
        both_rows = acct_rows[both_slot]
        dr_slot, cr_slot = both_slot[:B], both_slot[B:]
        dr_found, cr_found = both_found[:B], both_found[B:]
        dr_row, cr_row = both_rows[:B], both_rows[B:]
        ex_slot, ex_found, ex_res = ht.lookup(rows_b[:, :4], xfer_rows, self.t_log2)
        dr = unpack_account(dr_row)
        cr = unpack_account(cr_row)
        ex = unpack_transfer(xfer_rows[ex_slot])

        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r0 = validate.transfer_common(e, r0)
        r, amt_lo, amt_hi = validate.validate_simple_transfer(
            r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
        )

        # Unresolved probes among lanes that matter -> abort the whole batch
        # (fault protocol; writes below are gated on `proceed`).
        valid2 = jnp.concatenate([valid, valid])
        probe_bad = jnp.any(valid2 & ~both_res) | jnp.any(valid & ~ex_res)

        if pv_mode:
            # pending-transfer wave: p row + fulfill, then p's accounts
            is_pv = (e["flags"] & jnp.uint32(F_POST | F_VOID)) != 0
            p_slot, p_found, p_res = ht.lookup(
                rows_b[:, 16:20], xfer_rows, self.t_log2
            )
            p = unpack_transfer(xfer_rows[p_slot])
            p["fulfill"] = state["fulfill"][p_slot]
            p_both_k4 = jnp.concatenate([
                key4_from_fields({"id_lo": p["dr_lo"], "id_hi": p["dr_hi"]}),
                key4_from_fields({"id_lo": p["cr_lo"], "id_hi": p["cr_hi"]}),
            ], axis=0)
            pb_slot, pb_found, pb_res = ht.lookup(
                p_both_k4, acct_rows, self.a_log2
            )
            pb_rows = acct_rows[pb_slot]
            pdr_slot, pcr_slot = pb_slot[:B], pb_slot[B:]
            pdr_row, pcr_row = pb_rows[:B], pb_rows[B:]
            r_pv, amt_pv_lo, amt_pv_hi = validate.validate_post_void(
                r0, e_a, p, p_found, ex, ex_found
            )
            r = jnp.where(is_pv, r_pv, r)
            amt_lo = jnp.where(is_pv, amt_pv_lo, amt_lo)
            amt_hi = jnp.where(is_pv, amt_pv_hi, amt_hi)
            pvv = valid & is_pv
            probe_bad = (
                probe_bad
                | jnp.any(pvv & ~p_res)
                | jnp.any(jnp.concatenate([pvv, pvv]) & ~pb_res)
            )
        else:
            is_pv = jnp.zeros(B, dtype=bool)

        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        # Claim insert slots (pure claim phase; rows written below, after
        # gating). Keys are batch-unique and absent — host-proven.
        ins_slots, claim, ins_res = ht.claim_slots(
            rows_b[:, :4], ok, xfer_rows, state["xfer_claim"], self.t_log2
        )
        claim_bad = jnp.any(~ins_res)

        # Balance deltas: 16-bit digit scatter-add into the persistent
        # accumulator, then a touched-slot carry fold. acc lane layout:
        # dp 0..7 / dpo 8..15 / cp 16..23 / cpo 24..31.
        digits = _amount_digits(amt_lo, amt_hi)  # [B, 8]
        pending = ((e["flags"] & jnp.uint32(F_PENDING)) != 0)
        zeros8 = jnp.zeros_like(digits)
        if pv_mode:
            # signed digits: post/void SUBTRACTS the pending's amount from
            # the pending balances of the PENDING's accounts, and a post
            # adds the resolved amount to the posted balances
            is_post = is_pv & ((e["flags"] & jnp.uint32(F_POST)) != 0)
            p_digits = _amount_digits(p["amt_lo"], p["amt_hi"])
            neg_p = jnp.zeros_like(p_digits) - p_digits  # mod 2^32
            simple = ~is_pv
            pend8 = jnp.where((simple & pending)[:, None], digits, zeros8) + \
                jnp.where(is_pv[:, None], neg_p, zeros8)
            post8 = jnp.where((simple & ~pending)[:, None], digits, zeros8) + \
                jnp.where(is_post[:, None], digits, zeros8)
            dr_slot_eff = jnp.where(is_pv, pdr_slot, dr_slot)
            cr_slot_eff = jnp.where(is_pv, pcr_slot, cr_slot)
            dr_row_eff = jnp.where(is_pv[:, None], pdr_row, dr_row)
            cr_row_eff = jnp.where(is_pv[:, None], pcr_row, cr_row)
        else:
            pend8 = jnp.where(pending[:, None], digits, zeros8)
            post8 = jnp.where(pending[:, None], zeros8, digits)
            dr_slot_eff, cr_slot_eff = dr_slot, cr_slot
            dr_row_eff, cr_row_eff = dr_row, cr_row
        upd_dr = jnp.concatenate([pend8, post8, zeros8, zeros8], axis=-1)  # [B,32]
        upd_cr = jnp.concatenate([zeros8, zeros8, pend8, post8], axis=-1)
        slots_t = jnp.concatenate([
            jnp.where(ok, dr_slot_eff, self.a_dump),
            jnp.where(ok, cr_slot_eff, self.a_dump),
        ])
        upd = jnp.concatenate([upd_dr, upd_cr], axis=0)  # [2B, 32]
        acc = state["bal_acc"].at[slots_t].add(upd)
        acc_t = acc[slots_t]  # [2B, 32]
        old_rows_t = jnp.concatenate([dr_row_eff, cr_row_eff], axis=0)
        if pv_mode:
            new_rows_t, over_t = _fold_digits_signed(old_rows_t, acc_t)
        else:
            new_rows_t, over_t = _fold_digits(old_rows_t, acc_t)
        # Device-side backstop for the host's overflow bound (codes 51/52
        # combined-sum carries included — see _combined_overflow).
        over_bad = jnp.any(
            (over_t | _combined_overflow(new_rows_t)) & (slots_t != self.a_dump)
        )
        acc = acc.at[slots_t].set(jnp.zeros_like(upd))  # restore all-zero

        # Device-side load-factor guard (independent of the host estimate:
        # a desynced host must not re-expose unbounded probe densities).
        ok_n = jnp.sum(ok).astype(U64)
        cap_bad = state["xfer_used_slots"] + ok_n > np.uint64(self.t_dump // 2)
        fault = (
            state["fault"]
            | jnp.where(probe_bad, jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(claim_bad, jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(over_bad, jnp.uint32(FAULT_OVERFLOW), jnp.uint32(0))
            | jnp.where(cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0  # sticky: also no-ops every batch after a fault

        # --- application (every write gated on `proceed`) ---
        if pv_mode:
            ins_rows = pack_transfer(
                build_stored_transfer(e, p, is_pv, amt_lo, amt_hi, ts_vec)
            )
        else:
            ins_rows = _set_ts_words(rows_b, ts_vec)
        acct2 = acct_rows.at[jnp.where(proceed, slots_t, self.a_dump)].set(new_rows_t)
        w = jnp.where(proceed & ok, ins_slots, self.t_dump)
        xfer2 = xfer_rows.at[w].set(ins_rows)
        fulfill = state["fulfill"].at[w].set(jnp.uint32(0))
        if pv_mode:
            # mark the pendings resolved (distinct pendings: no conflicts)
            fw = jnp.where(proceed & ok & is_pv, p_slot, self.t_dump)
            fulfill = fulfill.at[fw].set(
                jnp.where(is_post, jnp.uint32(1), jnp.uint32(2))
            )
        applied = proceed & jnp.any(ok)
        # max, not set: wave execution dispatches this kernel out of lane
        # order (a later wave can hold EARLIER lanes), and the split-era
        # residue path already relied on max in the serial scan
        last_ts = jnp.maximum(
            state["commit_ts"], jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
        )
        return {
            **state,
            "acct_rows": acct2,
            "xfer_rows": xfer2,
            "fulfill": fulfill,
            "xfer_claim": claim,
            "bal_acc": acc,
            "commit_ts": jnp.where(applied, last_ts, state["commit_ts"]),
            "xfer_count": state["xfer_count"]
            + jnp.where(proceed, ok_n, jnp.uint64(0)),
            "xfer_used_slots": state["xfer_used_slots"]
            + jnp.where(proceed, ok_n, jnp.uint64(0)),
            "fault": fault,
        }, r

    # -- exact serial tier --

    def _serial_transfers(self, state, ev, n, timestamp):
        rows_b = ev["rows"]
        B = rows_b.shape[0]
        lane = jnp.arange(B, dtype=I32)
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)
        return self._serial_transfers_core(state, rows_b, ts_vec, n)

    def _serial_transfers_core(self, state, rows_b, ts_vec, n):
        """The exact scan. Timestamps are EXPLICIT per event: the full-batch
        path passes timestamp-n+i+1; the wave executor passes the residue
        events' ORIGINAL batch timestamps (compaction must not change them).
        """
        B = rows_b.shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump, t_dump = self.a_dump, self.t_dump
        tomb_row = _TOMB_ROW  # numpy: embeds as a literal
        # Entry gates: sticky fault + the device-side load-factor guard
        # (conservative: charges all n events; the scan applies as it goes
        # and cannot un-apply, so it must not START near the limit).
        cap_bad = state["xfer_used_slots"] + n.astype(U64) > np.uint64(
            self.t_dump // 2
        )
        fault0 = state["fault"] | jnp.where(
            cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0)
        )
        n = jnp.where(fault0 == 0, n, jnp.int32(0))

        undo0 = {
            "kind": jnp.zeros(B, dtype=U32),
            "dr_slot": jnp.zeros(B, dtype=I32),
            "cr_slot": jnp.zeros(B, dtype=I32),
            "t_slot": jnp.zeros(B, dtype=I32),
            "p_slot": jnp.zeros(B, dtype=I32),
            "a_lo": jnp.zeros(B, dtype=U64),
            "a_hi": jnp.zeros(B, dtype=U64),
            "pa_lo": jnp.zeros(B, dtype=U64),
            "pa_hi": jnp.zeros(B, dtype=U64),
        }
        carry0 = (
            state["acct_rows"], state["xfer_rows"], state["fulfill"],
            jnp.zeros(B, dtype=U32),  # results
            undo0,
            jnp.int32(-1),  # chain_start
            jnp.zeros((), dtype=bool),  # chain_broken
            state["commit_ts"],
            jnp.zeros((), dtype=bool),  # unresolved-probe accumulator
        )

        def step(carry, x):
            (acct_rows, xfer_rows, fulfill, results, undo, chain_start,
             chain_broken, commit_ts, probe_bad) = carry
            i, row_e, ts = x
            e = unpack_transfer(row_e)
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(F_LINKED)) != 0)

            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)

            e_a = {**e, "ts": ts}

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)  # linked_event_chain_open
            lad.set(active & chain_broken, 1)  # linked_event_failed
            lad.set(e["ts"] != 0, 3)  # timestamp_must_be_zero
            r0 = validate.transfer_common(e, lad.r)

            k4 = key4_from_fields
            W = ht.WINDOW_SCALAR
            dr_slot, dr_found, res1 = ht.lookup(
                k4({"id_lo": e["dr_lo"], "id_hi": e["dr_hi"]}), acct_rows,
                self.a_log2, window=W,
            )
            cr_slot, cr_found, res2 = ht.lookup(
                k4({"id_lo": e["cr_lo"], "id_hi": e["cr_hi"]}), acct_rows,
                self.a_log2, window=W,
            )
            ex_slot, ex_found, res3 = ht.lookup(
                row_e[:4], xfer_rows, self.t_log2, window=W
            )
            p_slot, p_found, res4 = ht.lookup(
                k4({"id_lo": e["pid_lo"], "id_hi": e["pid_hi"]}), xfer_rows,
                self.t_log2, window=W,
            )
            dr = unpack_account(acct_rows[dr_slot])
            cr = unpack_account(acct_rows[cr_slot])
            ex = unpack_transfer(xfer_rows[ex_slot])
            p = unpack_transfer(xfer_rows[p_slot])
            p["fulfill"] = fulfill[p_slot]
            # The pending transfer's accounts (post/void path); garbage rows
            # when ~p_found, gated by the validator.
            pdr_slot, _, res5 = ht.lookup(
                k4({"id_lo": p["dr_lo"], "id_hi": p["dr_hi"]}), acct_rows,
                self.a_log2, window=W,
            )
            pcr_slot, _, res6 = ht.lookup(
                k4({"id_lo": p["cr_lo"], "id_hi": p["cr_hi"]}), acct_rows,
                self.a_log2, window=W,
            )
            pdr = unpack_account(acct_rows[pdr_slot])
            pcr = unpack_account(acct_rows[pcr_slot])
            probe_bad = probe_bad | (
                active & ~(res1 & res2 & res3 & res4 & res5 & res6)
            )

            is_pv = (e["flags"] & jnp.uint32(F_POST | F_VOID)) != 0
            r_s, amt_s_lo, amt_s_hi = validate.validate_simple_transfer(
                r0, e_a, dr, cr, dr_found, cr_found, ex, ex_found
            )
            r_pv, amt_pv_lo, amt_pv_hi = validate.validate_post_void(
                r0, e_a, p, p_found, ex, ex_found
            )
            r = jnp.where(is_pv, r_pv, r_s)
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            amt_lo = jnp.where(is_pv, amt_pv_lo, amt_s_lo)
            amt_hi = jnp.where(is_pv, amt_pv_hi, amt_s_hi)
            is_post = is_pv & ((e["flags"] & jnp.uint32(F_POST)) != 0)
            is_pending = ~is_pv & ((e["flags"] & jnp.uint32(F_PENDING)) != 0)

            # --- build the row to insert (shared with the fast_pv tier) ---
            ins_row = pack_transfer(
                build_stored_transfer(e, p, is_pv, amt_lo, amt_hi, ts)
            )
            free_slot, free_ok = ht.probe_free(row_e[:4], xfer_rows, self.t_log2)
            probe_bad = probe_bad | (ok & ~free_ok)
            w = jnp.where(ok & free_ok, free_slot, t_dump)
            xfer_rows = xfer_rows.at[w].set(ins_row)
            fulfill = fulfill.at[w].set(jnp.uint32(0))
            fw = jnp.where(ok & is_pv, p_slot, t_dump)
            fulfill = fulfill.at[fw].set(
                jnp.where(is_post, jnp.uint32(1), jnp.uint32(2))
            )

            # --- balance application ---
            tgt_dr_slot = jnp.where(is_pv, pdr_slot, dr_slot)
            tgt_cr_slot = jnp.where(is_pv, pcr_slot, cr_slot)
            tdr = {k: jnp.where(is_pv, pdr[k], dr[k]) for k in dr}
            tcr = {k: jnp.where(is_pv, pcr[k], cr[k]) for k in cr}

            def upd(row_d, bal, add_cond, add_lo, add_hi, sub_cond, sub_lo, sub_hi):
                lo, hi = row_d[bal + "_lo"], row_d[bal + "_hi"]
                a_lo2, a_hi2, _ = u128.add(lo, hi, add_lo, add_hi)
                lo = jnp.where(add_cond, a_lo2, lo)
                hi = jnp.where(add_cond, a_hi2, hi)
                s_lo2, s_hi2, _ = u128.sub(lo, hi, sub_lo, sub_hi)
                lo = jnp.where(sub_cond, s_lo2, lo)
                hi = jnp.where(sub_cond, s_hi2, hi)
                return lo, hi

            false_ = jnp.zeros((), dtype=bool)
            zero64 = jnp.uint64(0)
            dpo_add = (~is_pv & ~is_pending) | is_post
            tdr["dp_lo"], tdr["dp_hi"] = upd(
                tdr, "dp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            tdr["dpo_lo"], tdr["dpo_hi"] = upd(
                tdr, "dpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64
            )
            tcr["cp_lo"], tcr["cp_hi"] = upd(
                tcr, "cp", is_pending, amt_lo, amt_hi, is_pv, p["amt_lo"], p["amt_hi"]
            )
            tcr["cpo_lo"], tcr["cpo_hi"] = upd(
                tcr, "cpo", dpo_add, amt_lo, amt_hi, false_, zero64, zero64
            )
            dw = jnp.where(ok, tgt_dr_slot, a_dump)
            cw = jnp.where(ok, tgt_cr_slot, a_dump)
            acct_rows = acct_rows.at[dw].set(pack_account(tdr))
            acct_rows = acct_rows.at[cw].set(pack_account(tcr))
            # max, not set: the wave executor's earlier waves may already
            # have committed later-lane timestamps
            commit_ts = jnp.where(ok, jnp.maximum(commit_ts, ts), commit_ts)

            # --- undo log entry ---
            kind = jnp.where(
                ~ok,
                jnp.uint32(0),
                jnp.where(
                    is_pv,
                    jnp.where(is_post, jnp.uint32(3), jnp.uint32(4)),
                    jnp.where(is_pending, jnp.uint32(2), jnp.uint32(1)),
                ),
            )
            undo = {
                "kind": undo["kind"].at[i].set(kind),
                "dr_slot": undo["dr_slot"].at[i].set(tgt_dr_slot),
                "cr_slot": undo["cr_slot"].at[i].set(tgt_cr_slot),
                "t_slot": undo["t_slot"].at[i].set(free_slot),
                "p_slot": undo["p_slot"].at[i].set(p_slot),
                "a_lo": undo["a_lo"].at[i].set(amt_lo),
                "a_hi": undo["a_hi"].at[i].set(amt_hi),
                "pa_lo": undo["pa_lo"].at[i].set(p["amt_lo"]),
                "pa_hi": undo["pa_hi"].at[i].set(p["amt_hi"]),
            }

            # --- chain break: roll back [chain_start, i) ---
            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, tabs):
                acct_rows, xfer_rows, fulfill = tabs
                kd = undo["kind"][k]
                applied = kd != 0
                k1, k2 = kd == 1, kd == 2
                k3, k4_ = kd == 3, kd == 4
                drs = undo["dr_slot"][k]
                crs = undo["cr_slot"][k]
                ua_lo, ua_hi = undo["a_lo"][k], undo["a_hi"][k]
                up_lo, up_hi = undo["pa_lo"][k], undo["pa_hi"][k]
                add_p = k3 | k4_
                sub_pend = k2
                sub_post = k1 | k3

                def inv(fields, bal, addc, subc, s_lo, s_hi):
                    lo, hi = fields[bal + "_lo"], fields[bal + "_hi"]
                    a_lo2, a_hi2, _ = u128.add(lo, hi, up_lo, up_hi)
                    lo = jnp.where(addc, a_lo2, lo)
                    hi = jnp.where(addc, a_hi2, hi)
                    s_lo2, s_hi2, _ = u128.sub(lo, hi, s_lo, s_hi)
                    lo = jnp.where(subc, s_lo2, lo)
                    hi = jnp.where(subc, s_hi2, hi)
                    return lo, hi

                fdr = unpack_account(acct_rows[drs])
                fcr = unpack_account(acct_rows[crs])
                fdr["dp_lo"], fdr["dp_hi"] = inv(fdr, "dp", add_p, sub_pend, ua_lo, ua_hi)
                fdr["dpo_lo"], fdr["dpo_hi"] = inv(fdr, "dpo", false_, sub_post, ua_lo, ua_hi)
                fcr["cp_lo"], fcr["cp_hi"] = inv(fcr, "cp", add_p, sub_pend, ua_lo, ua_hi)
                fcr["cpo_lo"], fcr["cpo_hi"] = inv(fcr, "cpo", false_, sub_post, ua_lo, ua_hi)
                dwk = jnp.where(applied, drs, a_dump)
                cwk = jnp.where(applied, crs, a_dump)
                acct_rows = acct_rows.at[dwk].set(pack_account(fdr))
                acct_rows = acct_rows.at[cwk].set(pack_account(fcr))
                twk = jnp.where(applied, undo["t_slot"][k], t_dump)
                xfer_rows = xfer_rows.at[twk].set(tomb_row)
                fwk = jnp.where(k3 | k4_, undo["p_slot"][k], t_dump)
                fulfill = fulfill.at[fwk].set(jnp.uint32(0))
                return acct_rows, xfer_rows, fulfill

            acct_rows, xfer_rows, fulfill = jax.lax.fori_loop(
                lo_k, i, undo_body, (acct_rows, xfer_rows, fulfill)
            )

            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)
            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)

            return (
                acct_rows, xfer_rows, fulfill, results, undo,
                chain_start, chain_broken, commit_ts, probe_bad,
            ), None

        (acct_rows, xfer_rows, fulfill, results, undo, _, _, commit_ts,
         probe_bad), _ = jax.lax.scan(step, carry0, (lanes, rows_b, ts_vec))
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        # Ever-applied inserts (rolled-back ones leave tombstones): the
        # undo log's kind stays set through rollback — exactly the count
        # the device-side load guard needs.
        applied_n = jnp.sum((undo["kind"] != 0).astype(U64))
        # commit_ts advanced on at-the-time-ok events and, like the oracle's
        # scopes, is NOT restored by chain rollback — return the carry as-is.
        # An unresolved probe mid-scan cannot be rolled back: FAULT_SERIAL
        # marks the state corrupt (host must discard it).
        return {
            **state,
            "acct_rows": acct_rows,
            "xfer_rows": xfer_rows,
            "fulfill": fulfill,
            "commit_ts": commit_ts,
            "xfer_count": state["xfer_count"] + ok_n,
            "xfer_used_slots": state["xfer_used_slots"] + applied_n,
            "fault": fault0
            | jnp.where(probe_bad, jnp.uint32(FAULT_SERIAL), jnp.uint32(0)),
        }, results

    # ------------------------------------------------------------------
    # create_accounts
    # ------------------------------------------------------------------

    def _commit_accounts(self, state, ev, n, timestamp, mode: str = "fast"):
        if mode == "serial":
            return self._serial_accounts(state, ev, n, timestamp)
        assert mode == "fast", mode

        rows_b = ev["rows"]
        B = rows_b.shape[0]
        e = unpack_account(rows_b)
        lane = jnp.arange(B, dtype=I32)
        valid = lane < n
        ts_vec = timestamp - n.astype(U64) + lane.astype(U64) + jnp.uint64(1)

        ex_slot, ex_found, ex_res = ht.lookup(
            rows_b[:, :4], state["acct_rows"], self.a_log2
        )
        ex = unpack_account(state["acct_rows"][ex_slot])
        r0 = jnp.where(e["ts"] != 0, jnp.uint32(3), jnp.uint32(0))
        r = validate.validate_create_account(r0, e, ex, ex_found)
        r = jnp.where(valid, r, jnp.uint32(0))
        ok = valid & (r == 0)

        probe_bad = jnp.any(valid & ~ex_res)
        ins_slots, claim, ins_res = ht.claim_slots(
            rows_b[:, :4], ok, state["acct_rows"], state["acct_claim"], self.a_log2
        )
        claim_bad = jnp.any(~ins_res)

        ok_n = jnp.sum(ok).astype(U64)
        cap_bad = state["acct_used_slots"] + ok_n > np.uint64(self.a_dump // 2)
        fault = (
            state["fault"]
            | jnp.where(probe_bad, jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(claim_bad, jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0

        ins_rows = _set_ts_words(rows_b, ts_vec)
        w = jnp.where(proceed & ok, ins_slots, self.a_dump)
        acct2 = state["acct_rows"].at[w].set(ins_rows)
        applied = proceed & jnp.any(ok)
        last_ts = jnp.max(jnp.where(ok, ts_vec, jnp.uint64(0)))
        return {
            **state,
            "acct_rows": acct2,
            "acct_claim": claim,
            "commit_ts": jnp.where(applied, last_ts, state["commit_ts"]),
            "acct_count": state["acct_count"]
            + jnp.where(proceed, ok_n, jnp.uint64(0)),
            "acct_used_slots": state["acct_used_slots"]
            + jnp.where(proceed, ok_n, jnp.uint64(0)),
            "fault": fault,
        }, r

    def _serial_accounts(self, state, ev, n, timestamp):
        rows_b = ev["rows"]
        B = rows_b.shape[0]
        lanes = jnp.arange(B, dtype=I32)
        a_dump = self.a_dump
        tomb_row = _TOMB_ROW  # numpy: embeds as a literal
        cap_bad = state["acct_used_slots"] + n.astype(U64) > np.uint64(
            self.a_dump // 2
        )
        fault0 = state["fault"] | jnp.where(
            cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0)
        )
        n = jnp.where(fault0 == 0, n, jnp.int32(0))

        undo0 = {
            "slot": jnp.zeros(B, dtype=I32),
            "kind": jnp.zeros(B, dtype=U32),
        }
        carry0 = (
            state["acct_rows"],
            jnp.zeros(B, dtype=U32),
            undo0,
            jnp.int32(-1),
            jnp.zeros((), dtype=bool),
            state["commit_ts"],
            jnp.zeros((), dtype=bool),  # unresolved-probe accumulator
        )

        def step(carry, x):
            (acct_rows, results, undo, chain_start, chain_broken, commit_ts,
             probe_bad) = carry
            i, row_e = x
            e = unpack_account(row_e)
            active = i < n
            linked = active & ((e["flags"] & jnp.uint32(F_LINKED)) != 0)
            opening = linked & (chain_start < 0)
            chain_start = jnp.where(opening, i, chain_start)
            in_chain = chain_start >= 0
            is_last = i == (n - 1)
            ts = timestamp - n.astype(U64) + i.astype(U64) + jnp.uint64(1)

            lad = validate.Ladder(jnp.uint32(0))
            lad.set(in_chain & is_last & linked, 2)
            lad.set(active & chain_broken, 1)
            lad.set(e["ts"] != 0, 3)

            ex_slot, ex_found, ex_res = ht.lookup(
                row_e[:4], acct_rows, self.a_log2, window=ht.WINDOW_SCALAR
            )
            ex = unpack_account(acct_rows[ex_slot])
            r = validate.validate_create_account(lad.r, e, ex, ex_found)
            r = jnp.where(active, r, jnp.uint32(0))
            ok = active & (r == 0)

            free_slot, free_ok = ht.probe_free(row_e[:4], acct_rows, self.a_log2)
            probe_bad = probe_bad | (active & ~ex_res) | (ok & ~free_ok)
            w = jnp.where(ok & free_ok, free_slot, a_dump)
            t0, t1 = _lohi(ts)
            ins_row = jnp.concatenate([row_e[:30], t0[None], t1[None]])
            acct_rows = acct_rows.at[w].set(ins_row)
            commit_ts = jnp.where(ok, ts, commit_ts)

            undo = {
                "kind": undo["kind"].at[i].set(jnp.where(ok, jnp.uint32(5), jnp.uint32(0))),
                "slot": undo["slot"].at[i].set(free_slot),
            }

            break_now = active & (r != 0) & in_chain & ~chain_broken
            lo_k = jnp.where(break_now, chain_start, i)

            def undo_body(k, acct_rows):
                applied = undo["kind"][k] != 0
                sl = jnp.where(applied, undo["slot"][k], a_dump)
                return acct_rows.at[sl].set(tomb_row)

            acct_rows = jax.lax.fori_loop(lo_k, i, undo_body, acct_rows)
            results = jnp.where(
                break_now & (lanes >= chain_start) & (lanes < i), jnp.uint32(1), results
            )
            results = results.at[i].set(r)
            chain_broken = chain_broken | break_now
            chain_end = in_chain & (~linked | (r == 2))
            chain_start = jnp.where(chain_end, jnp.int32(-1), chain_start)
            chain_broken = jnp.where(chain_end, False, chain_broken)
            return (acct_rows, results, undo, chain_start, chain_broken,
                    commit_ts, probe_bad), None

        (acct_rows, results, undo, _, _, commit_ts, probe_bad), _ = jax.lax.scan(
            step, carry0, (lanes, rows_b)
        )
        ok_n = jnp.sum((results == 0) & (lanes < n)).astype(U64)
        applied_n = jnp.sum((undo["kind"] != 0).astype(U64))
        return {
            **state,
            "acct_rows": acct_rows,
            "commit_ts": commit_ts,
            "acct_count": state["acct_count"] + ok_n,
            "acct_used_slots": state["acct_used_slots"] + applied_n,
            "fault": fault0
            | jnp.where(probe_bad, jnp.uint32(FAULT_SERIAL), jnp.uint32(0)),
        }, results

    # ------------------------------------------------------------------
    # lookups (reference: src/state_machine.zig:701-736)
    # ------------------------------------------------------------------

    def _lookup_accounts(self, state, ids):
        slot, found, res = ht.lookup(ids["key4"], state["acct_rows"], self.a_log2)
        # Per-lane resolve (NOT jnp.all): the padding lanes probe key 0,
        # whose single fixed window can fill with tombstones over time —
        # only the caller knows which lanes were requested.
        return found, state["acct_rows"][slot], res

    def _lookup_transfers(self, state, ids):
        slot, found, res = ht.lookup(ids["key4"], state["xfer_rows"], self.t_log2)
        return found, state["xfer_rows"][slot], res


# ----------------------------------------------------------------------
# Host-facing state machine (the oracle-compatible driver interface)
# ----------------------------------------------------------------------


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


class WavePlan:
    """Deterministic per-batch conflict-wave layout: `wave_of[i]` is event
    i's wave index (-1 = serial residue). Waves dispatch in index order
    through the masked fast/fast_pv kernel — wave w+1's table lookups see
    wave w's applied state, which is exactly the ordering the conflict
    edges demand — and the compacted residue runs the exact serial scan
    LAST (the entanglement closure proves it shares no ordering key with
    any wave lane, so last is as good as any position). The layout is a
    pure function of the batch bytes plus the tracker's committed-history
    state (no seeds, no wall clock, no unordered iteration), so every
    replica and the simulator plan the same batch identically."""

    __slots__ = ("wave_of", "n_waves", "has_pv", "residue_n")

    def __init__(self, wave_of: np.ndarray, n_waves: int, has_pv: bool):
        self.wave_of = wave_of
        self.n_waves = n_waves
        self.has_pv = has_pv  # any post/void among the wave lanes
        self.residue_n = int((wave_of < 0).sum())


class HazardTracker:
    """Host-side, EXACT fast-tier admission control. Tracks the two facts
    that cannot be read off a batch alone — balance-limit account ids and the
    running amount-sum overflow bound — plus the pending-accounts registry,
    and plans each batch's execution (fast / fast_pv / conflict waves /
    serial; see plan()). Shared by the single-chip DeviceLedger and the
    sharded ledger."""

    def __init__(self):
        # Ids of accounts created with balance-limit flags (account flags are
        # immutable after creation, so membership is stable). Kept as sorted
        # u64 limb columns so the hot-path membership test is vectorized.
        self.limit_account_ids: set[int] = set()
        self._limit_lo = np.empty(0, dtype=np.uint64)
        # Running sum of every transfer amount ever submitted. While this
        # exact upper bound on any balance stays < 2^127, no u128 balance sum
        # can overflow, so overflow codes 47-52 can only arise from per-event
        # validation against pre-batch balances — which the vectorized ladder
        # computes exactly.
        self.amount_sum = 0
        # Conservative superset of pending transfers ever submitted:
        # id -> (debit lo-limb, credit lo-limb). The wave planner needs
        # the accounts a post/void will touch (they are the PENDING's
        # accounts, not the event's own) to order them against
        # order-sensitive (limit/balancing) accounts.
        self.pending_accounts: dict[int, tuple[int, int]] = {}
        # Planner decision counters. New-style keys: fast / fast_pv /
        # serial / waves (batches through the wave path) /
        # wave_dispatches (total waves dispatched) / residue_events /
        # chain_len_max (deepest wave count seen). Legacy keys kept for
        # existing dashboards: every wave batch also counts as split /
        # split_pv (the retired split executor's partial-split buckets),
        # so fast + fast_pv + serial + split + split_pv still sums to
        # batches processed — DEPRECATED, read `waves` instead.
        self.plan_stats = {
            "fast": 0, "fast_pv": 0, "serial": 0, "waves": 0,
            "wave_dispatches": 0, "residue_events": 0, "chain_len_max": 0,
            "split": 0, "split_pv": 0,
        }

    @property
    def split_stats(self) -> dict:
        """DEPRECATED compat view: the pre-wave-planner stat surface.
        Same dict as plan_stats (a superset of the legacy keys), so
        `dict(hz.split_stats)` keeps working for every dashboard."""
        return self.plan_stats

    @staticmethod
    def has_dup_ids(arr: np.ndarray) -> bool:
        # Fast path: sort a 64-bit hash-fold of the u128 ids; if no two
        # hashes collide there are certainly no duplicate ids. Only on a
        # hash collision (~B^2/2^64 per batch) fall back to the exact
        # 16-byte comparison. Exact overall, ~15x cheaper than np.unique
        # over 16-byte voids on the hot path.
        with np.errstate(over="ignore"):
            h = arr["id_lo"] ^ (arr["id_hi"] * np.uint64(0x9E3779B97F4A7C15))
        h.sort()
        if not (h[1:] == h[:-1]).any():
            return False
        ids = np.ascontiguousarray(
            np.stack([arr["id_lo"], arr["id_hi"]], axis=1)
        ).view("V16")
        return len(np.unique(ids)) < len(arr)

    @staticmethod
    def _batch_amount_sum(arr: np.ndarray) -> int:
        """Exact u128 sum of every amount in the batch (u64 column sums
        cannot wrap: 2^13 values < 2^32 per 32-bit half)."""
        lo, hi = arr["amount_lo"], arr["amount_hi"]
        return (
            int(np.sum(lo & np.uint64(0xFFFFFFFF), dtype=np.uint64))
            + (int(np.sum(lo >> np.uint64(32), dtype=np.uint64)) << 32)
            + ((int(np.sum(hi & np.uint64(0xFFFFFFFF), dtype=np.uint64))
                + (int(np.sum(hi >> np.uint64(32), dtype=np.uint64)) << 32)) << 64)
        )

    def transfers_hazard(self, arr: np.ndarray) -> bool:
        """True if the batch needs the serial tier (all-or-nothing variant;
        the sharded ledger uses this — the single-chip ledger uses plan()).
        The running amount sum is an upper bound on any balance the store
        can hold: posts move pending to posted, voids remove, balancing
        clamps to available <= sum — counted for EVERY batch."""
        self.amount_sum += self._batch_amount_sum(arr)
        if self.amount_sum >= (1 << 127):
            return True  # conservative: overflow no longer provably impossible
        if (arr["flags"] & _SLOW_FLAGS).any():
            return True
        if self.has_dup_ids(arr):
            return True
        if self.limit_account_ids and self._touches_limit(arr).any():
            return True
        return False

    def accounts_hazard(self, arr: np.ndarray) -> bool:
        if (arr["flags"] & validate.A_LINKED).any():
            return True
        return self.has_dup_ids(arr)

    # ------------------------------------------------------------------
    # the WAVE decision (middle tier): order a batch's TRUE dependencies
    # into waves and close the serial residue under shared ORDERING KEYS
    # only — plain shared accounts commute and create no edges (the
    # split-era account-disjointness invariant is deliberately relaxed);
    # running waves-then-residue preserves exact semantics (see plan())
    # ------------------------------------------------------------------

    def note_pending(self, arr: np.ndarray) -> None:
        pen = (arr["flags"] & np.uint16(F_PENDING)) != 0
        if pen.any():
            for idl, idh, dl, cl in zip(
                arr["id_lo"][pen], arr["id_hi"][pen],
                arr["debit_account_id_lo"][pen],
                arr["credit_account_id_lo"][pen],
            ):
                self.pending_accounts[int(idl) | (int(idh) << 64)] = (
                    int(dl), int(cl),
                )
        # Bound the registry: a pending referenced by a post/void cannot be
        # meaningfully referenced again (idempotency paths fail without
        # touching balances) — evict it; a later stray reference moves that
        # lane to the residue (or the batch to serial), always sound.
        pv = (arr["flags"] & np.uint16(F_POST | F_VOID)) != 0
        if pv.any():
            for pl, ph in zip(
                arr["pending_id_lo"][pv], arr["pending_id_hi"][pv]
            ):
                self.pending_accounts.pop(int(pl) | (int(ph) << 64), None)

    def plan(self, arr: np.ndarray):
        """Per-batch tier decision, the conflict-wave planner: returns
        ("fast"|"fast_pv"|"serial", None) or ("waves", WavePlan).

        A deterministic (seed-free, sorted — a pure function of the batch
        bytes and this tracker's committed-history state) conflict index
        orders only the TRUE dependencies of a batch:

        - same-id groups (duplicate creates: exists-check order);
        - pending-id references (post/void after its in-batch creator;
          competing resolves of one pending in first-wins order);
        - order-sensitive ACCOUNTS: balance-limit accounts (their
          validation reads the running balance) and the accounts of
          balancing lanes (their clamp reads the running balance), so
          every touch of such an account is ordered. Plain hot accounts
          create NO edges — balance adds commute and non-limit validation
          never reads a balance, which is what lets a one-hot-account
          batch run in ~dependency-chain-length waves instead of a
          whole-batch serial scan.

        Lanes the masked fast/fast_pv kernels cannot express — linked
        chains (rollback), balancing (balance-dependent amount), and
        unresolvable pending references when order-sensitive accounts
        exist — form the serial RESIDUE, closed so it shares no ordering
        key with any wave lane (then running it after the waves preserves
        every cross ordering). Post/voids perform no limit checks
        themselves (reference: src/state_machine.zig:907-1014)."""
        # exact overflow bound, counted once per batch (see transfers_hazard)
        self.amount_sum += self._batch_amount_sum(arr)
        st = self.plan_stats
        if self.amount_sum >= (1 << 127):
            st["serial"] += 1
            return "serial", None

        B = len(arr)
        flags = arr["flags"]
        pv = (flags & np.uint16(F_POST | F_VOID)) != 0
        any_pv = bool(pv.any())
        bal = (flags & np.uint16(F_BAL_DR | F_BAL_CR)) != 0
        linked = (flags & np.uint16(F_LINKED)) != 0
        # whole chain runs: a linked run's terminator is the event AFTER it
        in_chain = linked.copy()
        in_chain[1:] |= linked[:-1]
        residue = in_chain | bal

        with np.errstate(over="ignore"):
            h_id = arr["id_lo"] ^ (arr["id_hi"] * _WAVE_GOLDEN)
        dup = self._dup_groups(h_id)

        # -- fast exits: hazard-free batches pay only what they always paid
        if not residue.any() and not dup.any():
            limit_touch = (
                self._touches_limit(arr)
                if self.limit_account_ids
                else None
            )
            if not any_pv:
                if limit_touch is None or not limit_touch.any():
                    st["fast"] += 1
                    return "fast", None
            else:
                with np.errstate(over="ignore"):
                    hp = arr["pending_id_lo"] ^ (
                        arr["pending_id_hi"] * _WAVE_GOLDEN
                    )
                # distinct pending refs, none created in this batch, no
                # limit-account touches by simple lanes: the whole batch
                # is one fast_pv wave (the kernel reads each pending's
                # truth — row, accounts, fulfill — from the table)
                hpc = hp.copy()
                hpc[~pv] = np.uint64(0) - np.arange(1, B + 1)[~pv].astype(
                    np.uint64
                )
                if (
                    not (self._dup_groups(hpc) & pv).any()
                    and not np.isin(hp[pv], h_id).any()
                    and (limit_touch is None or not (limit_touch & ~pv).any())
                ):
                    st["fast_pv"] += 1
                    return "fast_pv", None

        # -- general path: conflict index over ordering keys --
        with np.errstate(over="ignore"):
            h_pid = arr["pending_id_lo"] ^ (
                arr["pending_id_hi"] * _WAVE_GOLDEN
            )
        pv_idx = np.nonzero(pv)[0]

        # order-sensitive accounts (lo limbs; a collision only ADDS edges)
        sens = [self._limit_lo]
        if bal.any():
            sens.append(arr["debit_account_id_lo"][bal].astype(np.uint64))
            sens.append(arr["credit_account_id_lo"][bal].astype(np.uint64))
        sens_lo = np.unique(np.concatenate(sens))

        # pv lanes mutate their PENDING's accounts, not their own: resolve
        # those targets (registry, else the in-batch creator) so the
        # order-sensitive account edges are complete. Only needed when
        # order-sensitive accounts exist at all — otherwise pv balance
        # effects commute with everything and need no account edges.
        eff_dr = arr["debit_account_id_lo"].astype(np.uint64).copy()
        eff_cr = arr["credit_account_id_lo"].astype(np.uint64).copy()
        if len(pv_idx) and len(sens_lo):
            for i in pv_idx:
                pid = int(arr["pending_id_lo"][i]) | (
                    int(arr["pending_id_hi"][i]) << 64
                )
                if pid in (0, (1 << 128) - 1):
                    eff_dr[i] = 0  # invalid ref: fails with no effect
                    eff_cr[i] = 0
                    continue
                known = self.pending_accounts.get(pid)
                if known is not None:
                    eff_dr[i] = known[0] & ((1 << 64) - 1)
                    eff_cr[i] = known[1] & ((1 << 64) - 1)
                    continue
                cre = np.nonzero(h_id == h_pid[i])[0]
                if len(cre):
                    # in-batch creator(s): take the first's accounts; id-dup
                    # creators that disagree are unresolvable -> residue
                    eff_dr[i] = int(arr["debit_account_id_lo"][cre[0]])
                    eff_cr[i] = int(arr["credit_account_id_lo"][cre[0]])
                    if len(cre) > 1 and (
                        (arr["debit_account_id_lo"][cre] != eff_dr[i]).any()
                        or (arr["credit_account_id_lo"][cre] != eff_cr[i]).any()
                    ):
                        residue[i] = True
                else:
                    # unknown pending (e.g. registry evicted, or created
                    # before a restart): its balance targets cannot be
                    # proven clear of the order-sensitive set
                    eff_dr[i] = 0
                    eff_cr[i] = 0
                    residue[i] = True

        # (lane, key) conflict-edge list. Id keys only for lanes in a
        # duplicate group or referenced by a pv's pending id (a unique,
        # unreferenced id orders nothing).
        dup_or_ref = dup
        if len(pv_idx):
            dup_or_ref = dup | np.isin(h_id, h_pid[pv_idx])
        idk = np.nonzero(dup_or_ref)[0]
        lanes_e = [idk]
        keys_e = [h_id[idk]]
        if len(pv_idx):
            lanes_e.append(pv_idx)
            keys_e.append(h_pid[pv_idx])
        if len(sens_lo):
            with np.errstate(over="ignore"):
                for side in (eff_dr, eff_cr):
                    t_idx = np.nonzero(np.isin(side, sens_lo))[0]
                    if len(t_idx):
                        lanes_e.append(t_idx)
                        keys_e.append(side[t_idx] * _WAVE_GOLDEN2 + np.uint64(1))
        lane_e = np.concatenate(lanes_e)
        key_e = np.concatenate(keys_e)

        # -- residue entanglement closure: a wave lane sharing ANY ordering
        # key with a residue lane joins the residue (it runs LAST; a shared
        # key across that boundary would reorder a true dependency). Plain
        # account collisions never propagate — this closure is what keeps
        # hot accounts on the wave path.
        for _ in range(64):
            if not len(lane_e) or residue.all():
                break
            on_res = residue[lane_e]
            if not on_res.any():
                break
            tainted = np.unique(key_e[on_res])
            move = ~on_res & np.isin(key_e, tainted)
            if not move.any():
                break
            residue[lane_e[move]] = True
        else:
            st["serial"] += 1
            return "serial", None

        wl = ~residue
        if int(wl.sum()) < max(8, B // 8):
            # too little wave work to pay for the extra dispatches
            st["serial"] += 1
            return "serial", None

        # -- wave assignment: longest dependency chain ending at each lane.
        # Within one key group the lanes (in index order) form a chain
        # w'_t = max(w_t, w'_{t-1} + 1) = rank_t + cummax(w_s - rank_s);
        # a sweep applies every group's scan at once and scatter-maxes the
        # results back per lane; sweeps iterate to the multi-key fixpoint.
        wave = np.zeros(B, dtype=np.int64)
        m = wl[lane_e]
        el, ek = lane_e[m], key_e[m]
        if len(el):
            ko = np.lexsort((el, ek))
            el_k, ek_k = el[ko], ek[ko]
            E = len(el_k)
            grp_start = np.ones(E, dtype=bool)
            grp_start[1:] = ek_k[1:] != ek_k[:-1]
            gid = np.cumsum(grp_start) - 1
            pos = np.arange(E, dtype=np.int64)
            rank = pos - pos[grp_start][gid]
            off = gid * np.int64(2 * B + WAVE_CAP + 8)  # isolates groups
            lo_ = np.argsort(el_k, kind="stable")
            el_l = el_k[lo_]
            lane_start = np.ones(E, dtype=bool)
            lane_start[1:] = el_l[1:] != el_l[:-1]
            starts = np.nonzero(lane_start)[0]
            lanes_u = el_l[starts]
            for _ in range(_WAVE_SWEEPS):
                w_k = wave[el_k]
                w2 = rank + np.maximum.accumulate(w_k - rank + off) - off
                red = np.maximum.reduceat(w2[lo_], starts)
                if (red <= wave[lanes_u]).all():
                    break
                wave[lanes_u] = np.maximum(wave[lanes_u], red)
            else:
                st["serial"] += 1  # adversarial entanglement: escape hatch
                return "serial", None
            # depth cap: capped lanes fall to the residue. Sound without
            # re-running the closure — wave numbers are monotone along
            # every key chain, so any lane ordered AFTER a capped lane is
            # itself capped (also residue, in original order), and lanes
            # ordered before run in earlier waves, before the residue.
            over = wl & (wave >= WAVE_CAP)
            if over.any():
                residue |= over
                wl = ~residue
                if int(wl.sum()) < max(8, B // 8):
                    st["serial"] += 1
                    return "serial", None

        n_waves = int(wave[wl].max()) + 1 if wl.any() else 1
        has_res = bool(residue.any())
        if not has_res and n_waves == 1:
            name = "fast_pv" if any_pv else "fast"
            st[name] += 1
            return name, None
        wave_of = np.where(wl, wave, -1).astype(np.int32)
        plan = WavePlan(wave_of, n_waves, bool(pv[wl].any()))
        st["waves"] += 1
        st["wave_dispatches"] += n_waves
        st["residue_events"] += plan.residue_n
        st["chain_len_max"] = max(st["chain_len_max"], n_waves)
        # legacy dashboard keys (deprecated, see plan_stats): EVERY wave
        # batch counts toward split/split_pv so the legacy identity
        # fast + fast_pv + serial + split + split_pv == batches still
        # holds (a residue-free multi-wave batch is still a "partial
        # split" to an old reader: not whole-batch fast, not serial)
        st["split_pv" if plan.has_pv else "split"] += 1
        return "waves", plan

    @staticmethod
    def _dup_groups(h: np.ndarray) -> np.ndarray:
        """Lanes whose hash value occurs more than once (conservative)."""
        B = len(h)
        order = np.argsort(h, kind="stable")
        hs = h[order]
        dup_sorted = np.zeros(B, dtype=bool)
        if B > 1:
            eq = hs[1:] == hs[:-1]
            dup_sorted[1:] |= eq
            dup_sorted[:-1] |= eq
        dup = np.zeros(B, dtype=bool)
        dup[order] = dup_sorted
        return dup

    def _touches_limit(self, arr: np.ndarray) -> np.ndarray:
        lo2 = np.stack([arr["debit_account_id_lo"], arr["credit_account_id_lo"]])
        hi2 = np.stack([arr["debit_account_id_hi"], arr["credit_account_id_hi"]])
        pos = np.searchsorted(self._limit_lo, lo2)
        pos_c = np.minimum(pos, len(self._limit_lo) - 1)
        cand = self._limit_lo[pos_c] == lo2
        out = np.zeros(arr.shape[0], dtype=bool)
        if cand.any():
            for side in range(2):
                for i in np.nonzero(cand[side])[0]:
                    key = int(lo2[side][i]) | (int(hi2[side][i]) << 64)
                    if key in self.limit_account_ids:
                        out[i] = True
        return out

    def note_limit_accounts(self, arr: np.ndarray) -> None:
        limit_bits = validate.A_DR_LIMIT | validate.A_CR_LIMIT
        sel = (arr["flags"] & limit_bits) != 0
        if not sel.any():
            return
        new_lo = []
        for lo, hi in zip(arr["id_lo"][sel], arr["id_hi"][sel]):
            key = int(lo) | (int(hi) << 64)
            if key not in self.limit_account_ids:  # dedup: retries re-submit
                self.limit_account_ids.add(key)
                new_lo.append(lo)
        if new_lo:
            self._limit_lo = np.sort(
                np.concatenate([self._limit_lo, np.array(new_lo, dtype=np.uint64)])
            )


class HostLedgerBase:
    """Shared host-side driver surface of the single-chip and sharded
    ledgers: prepare-timestamp bookkeeping (reference:
    src/state_machine.zig:336-343) and the lookup wrappers (reference:
    src/state_machine.zig:701-736). Subclasses provide `state`,
    `kernels.lookup_accounts/lookup_transfers`, and optionally `pad_to`."""

    pad_to: int | None = None
    prepare_timestamp: int = 0

    def prepare(self, operation: Operation, event_count: int) -> None:
        if operation in (Operation.create_accounts, Operation.create_transfers):
            self.prepare_timestamp += event_count

    def _pad_for(self, n: int) -> int:
        return self.pad_to if self.pad_to is not None else _next_pow2(n)

    def _lookup(self, kernel, ids: list[int]):
        n_pad = self._pad_for(len(ids))
        found, rows, resolved = kernel(self.state, ids_to_batch(ids, n_pad))
        # resolved is a scalar (device kernel: jnp.all over its lanes) or
        # per-lane (sharded kernel) — only the REQUESTED lanes matter: the
        # padding lanes probe key 0, whose single fixed window can fill with
        # tombstones over time.
        res = np.asarray(resolved).reshape(-1)
        if not (res if res.size == 1 else res[: len(ids)]).all():
            raise RuntimeError("lookup probe-window overflow: grow the table")
        found = np.asarray(found)[: len(ids)]
        rows = np.asarray(rows)[: len(ids)]
        return found, rows

    def lookup_rows(self, operation: Operation, ids: list[int]) -> bytes:
        """Found objects' 128-byte wire rows, request order, missing skipped
        (reference: src/state_machine.zig:701-736) — the reply body, with no
        per-row Python object round-trip."""
        kernel = (
            self.kernels.lookup_accounts
            if operation == Operation.lookup_accounts
            else self.kernels.lookup_transfers
        )
        found, rows = self._lookup(kernel, ids)
        return rows[found].tobytes()

    def lookup_accounts(self, ids: list[int]) -> list[types.Account]:
        found, rows = self._lookup(self.kernels.lookup_accounts, ids)
        arr = np.frombuffer(rows.tobytes(), dtype=types.ACCOUNT_DTYPE)
        return [types.Account.from_np(arr[i]) for i in range(len(ids)) if found[i]]

    def lookup_transfers(self, ids: list[int]) -> list[types.Transfer]:
        found, rows = self._lookup(self.kernels.lookup_transfers, ids)
        arr = np.frombuffer(rows.tobytes(), dtype=types.TRANSFER_DTYPE)
        return [types.Transfer.from_np(arr[i]) for i in range(len(ids)) if found[i]]


def applied_insert_mask(dense: list[int], flags: np.ndarray) -> np.ndarray:
    """Which events inserted a row at their turn — INCLUDING inserts later
    rolled back by a chain break (rollback tombstones the slot, and
    tombstones still extend probe chains, so they count toward the non-empty
    slot density that the probe-window math bounds; see the load guard).

    Reconstructs the chain outcomes from the dense result codes: code 1
    (linked_event_failed) is only ever assigned by chain relabel/skip, and a
    broken chain reads [1, 1, .., breaker-code, 1, ..] — members strictly
    before the breaker were applied then rolled back."""
    n = len(dense)
    mask = np.zeros(n, dtype=bool)
    i = 0
    while i < n:
        if not (int(flags[i]) & 1):  # standalone event
            mask[i] = dense[i] == 0
            i += 1
            continue
        j = i  # chain: linked run + its first non-linked member (if any)
        while j < n and (int(flags[j]) & 1):
            j += 1
        end = min(j + 1, n)
        chain = dense[i:end]
        breaker = next((k for k, c in enumerate(chain) if c not in (0, 1)), None)
        if breaker is None:
            for k, c in enumerate(chain):
                mask[i + k] = c == 0
        else:
            mask[i : i + breaker] = True  # applied, then rolled back
        i = end
    return mask


class PendingGroup:
    """One fused device dispatch covering several batches (group commit):
    a single flat results array [k * n_pad + 1] (last word = fault),
    fetched ONCE for the whole group — the per-batch launch + transfer
    latency that dominates a high-latency transport is paid 1/k times.

    `summary` [k + 1] = per-slot failure counts + fault word, computed on
    device: the all-success steady state fetches THESE few words per group
    and never materializes the dense codes at all (the reply body for
    all-ok is empty; reference: src/tigerbeetle.zig:231-249 sparse
    results)."""

    __slots__ = ("results", "n_pad", "k", "host", "summary", "host_summary")

    def __init__(self, results, n_pad: int, k: int, summary=None):
        self.results = results
        self.n_pad = n_pad
        self.k = k
        self.host = None
        self.summary = summary
        self.host_summary = None

    def fetch(self):
        if self.host is None:
            self.host = np.asarray(self.results)
        return self.host

    def fetch_summary(self):
        if self.host_summary is None:
            self.host_summary = np.asarray(self.summary)
        return self.host_summary


class PendingBatch:
    """Handle for an asynchronously dispatched commit (results still on
    device). The driver's pipelining unit — the analog of one in-flight
    prepare in the reference's pipeline (reference:
    src/vsr/replica.zig:5102-5186, pipeline_prepare_queue_max=8)."""

    __slots__ = ("operation", "n", "results", "flags", "id_limbs", "dense",
                 "epoch", "group", "group_idx", "summary", "failures",
                 "codes_np", "plan")

    def __init__(self, operation, n, results, flags=None, id_limbs=None,
                 epoch=0, group=None, group_idx=0, summary=None, plan=None):
        self.operation = operation
        self.n = n
        self.results = results  # device u32 [n_pad + 1]; last = fault word
        self.flags = flags  # host u16 [n] (occupancy reconciliation)
        self.id_limbs = id_limbs  # host (lo, hi) u64 [n] (sharded reconcile)
        self.dense = None  # cached drain() result (drain is idempotent)
        self.epoch = epoch  # occupancy epoch at dispatch (spill reconcile)
        self.group = group  # PendingGroup when part of a fused dispatch
        self.group_idx = group_idx  # this batch's row within the group
        self.summary = summary  # device [count, fault]: the cheap drain
        self.failures = None  # failure count once drained
        self.codes_np = None  # dense codes np array (failure path only)
        # wave-planner decision plumbed to the commit dispatcher:
        # (decision str, wave count) for create_transfers, else None
        self.plan = plan


class DeviceLedger(HostLedgerBase):
    """Host wrapper: owns the device state and mirrors the oracle's execute()
    API so the two are drop-in interchangeable in parity tests and in the VSR
    commit path (reference lifecycle: src/state_machine.zig:336-540
    prepare/commit; prefetch is subsumed by HBM residency).

    `mode`:
    - "auto" (production): the host PROVES each batch hazard-free (see
      _transfers_hazard) and dispatches the vectorized kernel, else the exact
      serial kernel. Nothing data-dependent runs on device.
    - "fast" / "serial": force one tier (parity testing).
    """

    # observability seams (tigerbeetle_tpu/metrics.py, tracer.py);
    # instrument() re-points them at a shared registry — the group-staging
    # fence waits report there
    metrics = NULL_METRICS
    tracer = NULL_TRACER

    def instrument(self, metrics, tracer) -> None:
        self.metrics = metrics
        self.tracer = tracer
        # the compile sentinel rides the same registry rebind (warm-up
        # totals carry over; see CompileSentinel.instrument)
        COMPILE_SENTINEL.instrument(metrics)
        self._c_h2d = metrics.counter("device.h2d_bytes")
        if getattr(self, "spill", None) is not None:
            self.spill.instrument(metrics, tracer)

    def __init__(
        self,
        cluster: ConfigCluster = DEFAULT_CLUSTER,
        process: ConfigProcess = DEFAULT_PROCESS,
        mode: str = "auto",
        forest=None,
        spill_keep_frac: float = 0.25,
        spill_async_io: bool = True,
        spill_io=None,
    ):
        self.cluster = cluster
        self.process = process
        self.mode = mode
        self.kernels = get_kernels(process)
        self.state = init_state(process)
        self.prepare_timestamp = 0
        self.pad_to: int | None = None  # fix the batch pad (bench: 8192)
        # Optional LSM backing store: with a forest attached, the transfer
        # table spills its cold tail instead of raising at the load-factor
        # limit (models/spill.py — the bounded-memory story).
        self.spill = None
        self._occupancy_epoch = 0  # bumped by spill cycles (drain reconcile)
        if forest is not None:
            from tigerbeetle_tpu.models.spill import SpillManager

            # spill_io selects the IO executor behind the spill store:
            # None/"threaded" = real worker thread (production overlap),
            # "deferred" = deterministic event-loop-paced queue (the VSR
            # replica / simulator — see models/spill.py DeferredSpillIO),
            # or an executor instance.
            self.spill = SpillManager(self, forest, keep_frac=spill_keep_frac,
                                      async_io=spill_async_io, io=spill_io)
        # Host-tracked occupancy for the load-factor guard (1/2 max — the
        # probe-window unresolve probability is ~alpha^window, so alpha <= 1/2
        # with window 32 makes window overflow a ~2^-32 event; see
        # ops/hashtable.py). The reference sizes its object pools statically
        # for the same class of reason (reference: src/static_allocator.zig,
        # src/message_pool.zig:18-41).
        self._acct_used = 0
        self._xfer_used = 0
        self._acct_limit = (1 << process.account_slots_log2) // 2
        self._xfer_limit = (1 << process.transfer_slots_log2) // 2
        self.hazards = HazardTracker()
        # device-anatomy h2d seam: try_execute_group_async stamps the
        # upload-issued boundary here; the dual applier reads it to close
        # its h2d_stage sub-leg. Written and read on whichever thread
        # drives dispatch (the apply thread in dual mode), between the
        # dispatch call and its return — never concurrently.
        # vet: owner=device-shadow
        self.last_h2d_done_ns = 0
        self._c_h2d = self.metrics.counter("device.h2d_bytes")
        # Start each batch's device->host result copy AT DISPATCH so a
        # reply-serving driver (the VSR replica) drains landed buffers
        # instead of paying sync round trips. OPT-IN: on transports where
        # the first d2h permanently degrades dispatch (see bench.py), a
        # fetch-free driver (the flagship benchmark) must never trigger it.
        self.prefetch_results = False

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def execute(self, operation, timestamp: int, events: list) -> list[tuple[int, int]]:
        dense = self.execute_dense(operation, timestamp, events)
        return [(i, c) for i, c in enumerate(dense) if c]

    def execute_async(self, operation, timestamp: int, events) -> PendingBatch:
        """Dispatch a commit without any device->host synchronization.
        The caller materializes results later (results stay on device) and
        MUST call check_fault() at least once after the last drain.

        The occupancy guard charges the batch conservatively (+n, an upper
        bound on inserted rows); calling drain() reconciles it to the exact
        ever-applied count. An async driver that never drains keeps the
        conservative estimate — safe (guard can only fire early, never
        late)."""
        n = len(events)
        n_pad = self._pad_for(n)
        assert n <= n_pad
        ts = jnp.uint64(timestamp)
        nn = jnp.int32(n)
        if operation == Operation.create_transfers:
            arr = events if isinstance(events, np.ndarray) else types.transfers_to_np(events)
            if self.spill is not None:
                # spill the cold tail / reload referenced spilled rows so
                # the kernels' HBM lookups see the full store (spill.py)
                self.spill.admit(arr, n)
            if self._xfer_used + n > self._xfer_limit:
                raise RuntimeError(
                    f"transfer table at load-factor limit "
                    f"({self._xfer_used}+{n} > {self._xfer_limit}): "
                    "grow ConfigProcess.transfer_slots_log2"
                )
            if self.mode == "auto":
                decision, wave_plan = self.hazards.plan(arr)
            else:  # forced tier (parity tests); the amount bound is unused
                decision, wave_plan = self.mode, None
            self.hazards.note_pending(arr)
            if decision == "waves":
                results = self._execute_waves(
                    arr, n, n_pad, nn, ts, timestamp, wave_plan
                )
            else:
                batch = transfers_to_batch(arr, n_pad)
                self.state, results = self.kernels.commit_transfers(
                    self.state, batch, nn, ts, mode=decision
                )
            plan_info = (
                decision, wave_plan.n_waves if wave_plan is not None else 1
            )
            self._xfer_used += n
        elif operation == Operation.create_accounts:
            if self._acct_used + n > self._acct_limit:
                raise RuntimeError(
                    f"account table at load-factor limit "
                    f"({self._acct_used}+{n} > {self._acct_limit}): "
                    "grow ConfigProcess.account_slots_log2"
                )
            arr = events if isinstance(events, np.ndarray) else types.accounts_to_np(events)
            mode = self.mode
            if mode == "auto":
                mode = "serial" if self.hazards.accounts_hazard(arr) else "fast"
            self.hazards.note_limit_accounts(arr)
            batch = accounts_to_batch(arr, n_pad)
            self.state, results = self.kernels.commit_accounts(
                self.state, batch, nn, ts, mode=mode
            )
            plan_info = None
            self._acct_used += n
        else:
            raise AssertionError(operation)
        # Pack the fault word onto the results, compute the device-side
        # failure count, and START the summary's device->host copy now:
        # the all-success steady state drains TWO words per batch (count +
        # fault) off an already-landed buffer — no dense-codes transfer, no
        # per-event host loop, no sync round trip.
        results, summary = self._summarize_fn()(
            results, self.state["fault"], nn
        )
        if self.prefetch_results:
            try:
                summary.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass  # no async copy: drain pays the sync cost
        return PendingBatch(
            operation, n, results, flags=arr["flags"].copy(),
            epoch=self._occupancy_epoch, summary=summary, plan=plan_info,
        )

    def _summarize_fn(self):
        """Jitted (results, fault, n) -> (packed results+fault, [count,
        fault]): ONE dispatch for the post-kernel bookkeeping (the previous
        out-of-jit concatenate was its own XLA launch per batch). Cached on
        the SHARED kernels object so fresh ledgers reuse the compile."""
        fn = getattr(self.kernels, "_summarize_cache", None)
        if fn is None:
            def s(results, fault, n):
                res = results.astype(jnp.uint32)
                lane = jnp.arange(res.shape[0], dtype=jnp.int32)
                cnt = jnp.sum(
                    ((res != 0) & (lane < n)).astype(jnp.uint32)
                )
                f = fault.reshape(1).astype(jnp.uint32)
                packed = jnp.concatenate([res, f])
                return packed, jnp.concatenate([cnt.reshape(1), f])

            fn = self.kernels._summarize_cache = sentinel_jit("summarize", s)
        return fn

    def _wave_stepper(self, W: int, n_pad: int, mode: str):
        """Jitted dispatch of W dependency-ordered waves over ONE uploaded
        batch: a lax.scan over the wave masks traces the commit kernel
        ONCE regardless of W (the _group_stepper lesson), so a multi-wave
        batch pays a single launch, not one per wave. Each lane is active
        in exactly one wave and inactive lanes return code 0, so the
        per-wave results fold with an elementwise max. Cached on the
        SHARED kernels object; W is bucketed by the caller
        (_WAVE_BUCKETS) so only a handful of shapes ever compile."""
        cache = getattr(self.kernels, "_wave_cache", None)
        if cache is None:
            cache = self.kernels._wave_cache = {}
        fn = cache.get((W, n_pad, mode))
        if fn is None:
            kernels = self.kernels

            def step(state, rows, masks, n, timestamp):
                def body(st, mask):
                    st, r = kernels._commit_transfers(
                        st, {"rows": rows, "mask": mask}, n, timestamp,
                        mode=mode,
                    )
                    return st, r.astype(jnp.uint32)

                state, rs = jax.lax.scan(body, state, masks)
                return state, jnp.max(rs, axis=0)

            fn = cache[(W, n_pad, mode)] = sentinel_jit(
                f"wave_stepper_{W}x{n_pad}_{mode}", step, donate_argnums=(0,)
            )
        return fn

    def _execute_waves(self, arr, n, n_pad, nn, ts, timestamp: int, plan):
        """Conflict-scheduled wave execution (the HazardTracker.plan
        layout): the batch uploads ONCE, then the waves dispatch in
        dependency order through the masked fast/fast_pv kernel — wave
        w+1's table lookups see wave w's applied rows, the exact ordering
        the plan's conflict edges require — and the serial residue (if
        any) runs the exact scan COMPACTED (cost scales with residue
        size, not batch size) with its events' ORIGINAL timestamps;
        results scatter back to original lanes."""
        wave_of = plan.wave_of
        W = plan.n_waves
        mode = "fast_pv" if plan.has_pv else "fast"
        rows_dev = jnp.asarray(_to_rows_np(arr, n_pad))
        wl = wave_of[:n] >= 0
        m = self.metrics
        m.counter("waves.batches").add()
        m.histogram("waves.per_batch").observe(W)
        g = m.gauge("waves.chain_len_max")
        g.set(max(g.value, W))
        m.gauge("waves.occupancy").set(
            round(float(wl.sum()) / max(1, W * n), 4)
        )
        if W == 1:
            mask_np = np.zeros(n_pad, dtype=bool)
            mask_np[:n] = wl
            self.state, results = self.kernels.commit_transfers(
                self.state, {"rows": rows_dev, "mask": jnp.asarray(mask_np)},
                nn, ts, mode=mode,
            )
        else:
            Wp = next(b for b in _WAVE_BUCKETS if b >= W)
            masks = np.zeros((Wp, n_pad), dtype=bool)  # pad waves: no-ops
            masks[wave_of[:n][wl], np.nonzero(wl)[0]] = True
            self.state, results = self._wave_stepper(Wp, n_pad, mode)(
                self.state, rows_dev, jnp.asarray(masks), nn, ts
            )
        if plan.residue_n:
            m.counter("waves.residue_events").add(plan.residue_n)
            idx = np.nonzero(~wl)[0]
            n2 = len(idx)
            pad2 = _next_pow2(n2)
            rows2 = np.zeros((pad2, ROW_WORDS), dtype=np.uint32)
            rows2[:n2] = arr.view(np.uint32).reshape(len(arr), ROW_WORDS)[idx]
            ts2 = np.zeros(pad2, dtype=np.uint64)
            base = timestamp - n + 1  # first event's ts (host int: no sync)
            ts2[:n2] = np.uint64(base) + idx.astype(np.uint64)
            self.state, r_res = self.kernels.commit_transfers_residue(
                self.state,
                {"rows": jnp.asarray(rows2), "ts": jnp.asarray(ts2)},
                jnp.int32(n2),
            )
            idx_pad = np.full(pad2, n_pad, dtype=np.int32)  # OOB -> dropped
            idx_pad[:n2] = idx
            results = self.kernels.merge_results(
                results, r_res, jnp.asarray(idx_pad)
            )
        return results

    # Fixed fused-group capacities: a lax.scan over K slots traces the
    # commit kernel ONCE regardless of K (an unrolled K multiplies the
    # graph and has broken the remote compiler); smaller runs pad with
    # zero-count slots. Two capacities bound the padded-upload waste.
    GROUP_KS = (16, 4)

    def _group_staging_slot(self, k: int, n_pad: int) -> dict:
        """One of TWO alternating preallocated host staging buffers per
        (k, n_pad): group N+1 packs into buffer B while buffer A's kernel
        (group N) still runs — upload staging double-buffers against
        device execution, and the per-group 16 MiB zeros+alloc (a measured
        host-side tax on the core the event loop shares) disappears.
        `used` tracks per-slot row counts so only stale tails are zeroed;
        `fence` is the flat results of the last group dispatched from the
        buffer (see the reuse fence at the call site)."""
        pool = getattr(self, "_group_staging", None)
        if pool is None:
            pool = self._group_staging = {}
        key = (k, n_pad)
        entry = pool.get(key)
        if entry is None:
            entry = pool[key] = {"i": 0, "slots": [None, None]}
        i = entry["i"]
        entry["i"] = 1 - i
        slot = entry["slots"][i]
        if slot is None:
            slot = entry["slots"][i] = {
                "rows": np.zeros((k, n_pad, ROW_WORDS), dtype=np.uint32),
                "used": np.zeros(k, dtype=np.int64),
                "fence": None,
            }
        return slot

    def _group_stepper(self, k: int, n_pad: int):
        """Jitted fused commit of k fast-tier batch slots in ONE launch
        (group commit: the replica coalesces its pipeline the way the
        flagship benchmark K-fuses device-generated batches). Returns
        (state', flat results [k * n_pad + 1]; last word = fault)."""
        cache = getattr(self.kernels, "_group_cache", None)
        if cache is None:
            cache = self.kernels._group_cache = {}
        fn = cache.get((k, n_pad))
        if fn is None:
            kernels = self.kernels

            def step(state, rows, ns, tss):
                def body(st, x):
                    r, n, t = x
                    st, res = kernels._commit_transfers(
                        st, {"rows": r}, n, t, mode="fast"
                    )
                    res = res.astype(jnp.uint32)
                    lane = jnp.arange(res.shape[0], dtype=jnp.int32)
                    cnt = jnp.sum(
                        ((res != 0) & (lane < n)).astype(jnp.uint32)
                    )
                    return st, (res, cnt)

                state, (results, cnts) = jax.lax.scan(
                    body, state, (rows, ns, tss)
                )
                fault = state["fault"].reshape(1).astype(jnp.uint32)
                flat = jnp.concatenate([results.reshape(-1), fault])
                # summary = per-slot failure counts + fault: the only words
                # the all-success drain ever transfers
                return state, flat, jnp.concatenate([cnts, fault])

            fn = cache[(k, n_pad)] = sentinel_jit(
                f"group_stepper_{k}x{n_pad}", step, donate_argnums=(0,)
            )
        return fn

    def try_execute_group_async(self, items) -> list[PendingBatch] | None:
        """Fuse `items` = [(timestamp, transfers ndarray), ...] into one
        device dispatch, or return None when fusion is unsound — spill
        store active (reloads mutate state between batches), forced mode,
        or any batch not proven fast-tier. The caller falls back to
        per-batch execute_async."""
        if self.mode != "auto" or self.spill is not None or len(items) < 2:
            return None
        if getattr(self, "_group_disabled", False):
            return None
        # never truncate silently: callers zip the returned pendings with
        # their items — a shorter list would drop batches without a trace
        assert len(items) <= self.GROUP_KS[0], (len(items), self.GROUP_KS)
        total = sum(len(arr) for _, arr in items)
        if self._xfer_used + total > self._xfer_limit:
            return None  # per-batch path raises the descriptive guard
        # Probe tier decisions with rollback: plan() advances the
        # monotone amount_sum overflow bound (and plan_stats), and a
        # rejected fusion falls back to per-batch execute_async which
        # calls plan() AGAIN — without rollback every mixed-tier window
        # double-counts toward the 2^127 serial cutoff.
        sum_before = self.hazards.amount_sum
        stats_before = dict(self.hazards.plan_stats)
        decisions = [self.hazards.plan(arr) for _, arr in items]
        if any(d != "fast" for d, _plan in decisions):
            self.hazards.amount_sum = sum_before
            self.hazards.plan_stats = stats_before
            return None
        k = next(g for g in reversed(self.GROUP_KS) if g >= len(items))
        n_pad = self._pad_for(max(len(arr) for _, arr in items))
        slot = self._group_staging_slot(k, n_pad)
        if slot["fence"] is not None:
            # Double-buffer fence: this buffer last fed the group dispatched
            # TWO groups ago — wait for that kernel before mutating it (on
            # backends where device_put aliases host memory, e.g. CPU,
            # reuse mid-flight would corrupt the in-flight rows). In steady
            # state the fence is long retired and this is free; when the
            # device is more than two groups behind, it is exactly the
            # backpressure we want.
            with self.tracer.span("ledger.staging_wait"), \
                    self.metrics.histogram("ledger.staging_wait_us").time():
                jax.block_until_ready(slot["fence"])
            slot["fence"] = None
        rows = slot["rows"]
        used = slot["used"]
        ns = np.zeros(k, dtype=np.int32)  # padding slots: n=0 -> no-ops
        tss = np.zeros(k, dtype=np.uint64)
        for i, (ts, arr) in enumerate(items):
            na = len(arr)
            rows[i, :na] = arr.view(np.uint32).reshape(na, ROW_WORDS)
            if used[i] > na:
                rows[i, na : used[i]] = 0  # zero only the stale tail
            used[i] = na
            ns[i] = na
            tss[i] = ts
        for i in range(len(items), k):
            if used[i]:
                rows[i, : used[i]] = 0
                used[i] = 0
        dev_rows = jax.device_put(rows)
        # upload-issued boundary for the device anatomy's h2d_stage
        # sub-leg (device_put returns once the transfer is initiated; on
        # aliasing backends it is the staging copy itself)
        self.last_h2d_done_ns = perf_counter_ns()
        self._c_h2d.add(rows.nbytes)
        try:
            state, flat, summary = self._group_stepper(k, n_pad)(
                self.state, dev_rows, jnp.asarray(ns),
                jnp.asarray(tss),
            )
        except Exception:
            # A broken/flaky (remote) compile must not take the server
            # down: fall back to per-batch dispatch. But the stepper
            # donates self.state — a RUNTIME failure after donation leaves
            # deleted buffers, and no fallback is sound; re-raise then.
            for buf in self.state.values():
                if getattr(buf, "is_deleted", lambda: False)():
                    raise
            self._group_disabled = True
            return None
        slot["fence"] = flat  # this buffer is consumed once `flat` resolves
        self.state = state
        for _ts, arr in items:
            self.hazards.note_pending(arr)
        if self.prefetch_results:
            try:
                summary.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
        self._xfer_used += total
        group = PendingGroup(flat, n_pad, k, summary=summary)
        return [
            PendingBatch(
                Operation.create_transfers, len(arr), flat,
                flags=arr["flags"].copy(), epoch=self._occupancy_epoch,
                group=group, group_idx=i,
            )
            for i, (_ts, arr) in enumerate(items)
        ]

    def fingerprint_lazy(self) -> dict:
        """state_fingerprint as DEVICE scalars (dispatch only, no d2h):
        the dual applier's commitment probe stashes these at each
        checkpoint boundary and materializes them once, at finalize."""
        fn = getattr(self, "_fingerprint_cache", None)
        if fn is None:
            fn = self._fingerprint_cache = sentinel_jit("fingerprint", state_fingerprint)
        return fn(self.state)

    def fingerprint(self) -> dict:
        """Materialized state_fingerprint (ONE scalar-only d2h — the dual
        server calls this once, after its clock stops)."""
        return {k: int(np.asarray(v)) for k, v in self.fingerprint_lazy().items()}

    def check_fault(self) -> None:
        """Raise if the device hit the fault protocol (see module docstring).
        Synchronizes with the device — amortize on the hot path."""
        raise_on_fault(int(np.asarray(self.state["fault"])), "device ledger")

    # ------------------------------------------------------------------
    # snapshot row install (the dual follower's restore path)
    # ------------------------------------------------------------------

    INSTALL_CHUNK = 8192  # rows per install upload (one compile per table)

    def reset_state(self) -> None:
        """Drop every table back to fresh (the install path's
        precondition): a state-sync jump installs a snapshot onto a
        device that already holds applied rows — claim_slots would give
        each already-present key a SECOND slot and the occupancy
        trackers would double-count. In-flight kernels keep their
        references to the old arrays (functional updates), so this is
        safe to run between dispatches."""
        self.state = init_state(self.process)
        self._acct_used = 0
        self._xfer_used = 0
        self.hazards = HazardTracker()

    def _install_fn(self, table: str):
        """Jitted chunk installer for one table: claim slots for `n` wire
        rows and scatter them in (h2d upload + insert kernels ONLY — no
        device->host read; install failures set the sticky fault word and
        surface at the caller's next check_fault). `ful` carries the
        per-row posted/voided resolution for transfers (ignored for
        accounts — the column is scattered into the dump slot)."""
        cache = getattr(self.kernels, "_install_cache", None)
        if cache is None:
            cache = self.kernels._install_cache = {}
        fn = cache.get(table)
        if fn is None:
            log2 = self.kernels.a_log2 if table == "acct" else self.kernels.t_log2
            dump = jnp.int32(1 << log2)
            rows_key = f"{table}_rows"
            claim_key = f"{table}_claim"
            count_key = "acct_count" if table == "acct" else "xfer_count"
            used_key = (
                "acct_used_slots" if table == "acct" else "xfer_used_slots"
            )
            is_xfer = table == "xfer"

            def f(state, rows_b, ful, n):
                active = jnp.arange(rows_b.shape[0], dtype=jnp.int32) < n
                slots, claim, resolved = ht.claim_slots(
                    rows_b[:, :4], active, state[rows_key],
                    state[claim_key], log2,
                )
                ok = active & resolved
                w = jnp.where(ok, slots, dump)
                out = dict(state)
                out[rows_key] = state[rows_key].at[w].set(rows_b)
                out[claim_key] = claim
                if is_xfer:
                    out["fulfill"] = state["fulfill"].at[w].set(ful)
                nn = jnp.sum(ok.astype(jnp.uint64))
                out[count_key] = state[count_key] + nn
                out[used_key] = state[used_key] + nn
                # an unresolved active lane (probe-window overflow) is an
                # unrecoverable install: sticky fault, checked at finalize
                out["fault"] = state["fault"] | jnp.where(
                    jnp.any(active & ~resolved), jnp.uint32(1 << 30),
                    jnp.uint32(0),
                )
                return out

            fn = cache[table] = sentinel_jit(
                f"install_{table}", f, donate_argnums=(0,)
            )
        return fn

    def install_snapshot_rows(
        self,
        accounts: np.ndarray,
        transfers: np.ndarray,
        fulfill: np.ndarray,
        commit_timestamp: int,
    ) -> None:
        """Rebuild the device tables from host-side 128-byte wire row
        images (the native engine's snapshot format parses to exactly
        these) — the row-level upload path the dual follower uses to
        re-seed the device after a checkpoint restore or state-sync jump.
        Precondition: fresh (empty) device state. `fulfill` is the
        per-transfer posted/voided column (0 = unresolved), aligned with
        `transfers`. H2d staging and insert kernels only: no d2h."""
        assert len(fulfill) == len(transfers)
        ch = self.INSTALL_CHUNK
        for table, arr, ful in (
            ("acct", accounts, None),
            ("xfer", transfers, fulfill),
        ):
            fn = self._install_fn(table)
            for i in range(0, len(arr), ch):
                part = arr[i : i + ch]
                n = len(part)
                rows_b = jnp.asarray(_to_rows_np(part, ch))
                fv = np.zeros(ch, dtype=np.uint32)
                if ful is not None:
                    fv[:n] = ful[i : i + n]
                self.state = fn(
                    self.state, rows_b, jnp.asarray(fv), jnp.int32(n)
                )
        # device-side commit clock + host-side occupancy/hazard rebuild
        self.state["commit_ts"] = jnp.uint64(commit_timestamp)
        self._acct_used += len(accounts)
        self._xfer_used += len(transfers)
        self.hazards.note_limit_accounts(accounts)
        if len(transfers):
            # conservative superset of live pendings (extra entries only
            # degrade later post/void batches to the serial tier)
            pen = (transfers["flags"] & np.uint16(F_PENDING)) != 0
            for idl, idh, dl, cl in zip(
                transfers["id_lo"][pen], transfers["id_hi"][pen],
                transfers["debit_account_id_lo"][pen],
                transfers["credit_account_id_lo"][pen],
            ):
                self.hazards.pending_accounts[
                    int(idl) | (int(idh) << 64)
                ] = (int(dl), int(cl))
        # amount_sum is the proof bound "no balance can exceed this": the
        # sum of every restored posted+pending balance is an upper bound
        # on any restored balance, and future batches keep adding theirs
        for col in (
            "debits_posted", "credits_posted",
            "debits_pending", "credits_pending",
        ):
            if len(accounts):
                lo = accounts[col + "_lo"]
                hi = accounts[col + "_hi"]
                self.hazards.amount_sum += (
                    int(np.sum(lo & np.uint64(0xFFFFFFFF), dtype=np.uint64))
                    + (int(np.sum(lo >> np.uint64(32), dtype=np.uint64)) << 32)
                    + ((int(np.sum(hi & np.uint64(0xFFFFFFFF), dtype=np.uint64))
                        + (int(np.sum(hi >> np.uint64(32), dtype=np.uint64)) << 32)) << 64)
                )

    def drain(self, pending: PendingBatch) -> list[int]:
        """Materialize a pending batch's dense result codes; reconciles the
        conservative occupancy charge to the exact ever-applied insert count
        (rolled-back inserts leave tombstones, which still occupy probe
        slots — see applied_insert_mask). Idempotent: a second drain returns
        the cached codes without double-reconciling.

        Fast path: the device-side summary (failure count + fault word —
        a few words, prefetched at dispatch) proves the batch all-success,
        in which case every event applied (applied == n, reconcile is a
        no-op) and the dense codes are all zeros — no codes transfer, no
        per-event host loop."""
        if pending.dense is not None:
            return pending.dense
        if pending.group is not None:
            g = pending.group
            if g.summary is not None:
                s = g.fetch_summary()  # [k counts..., fault]: a few words
                fault = int(s[-1])
                if int(s[pending.group_idx]) == 0:
                    return self._drain_all_ok(pending, fault)
            arr = g.fetch()  # one transfer for the whole group (cached)
            off = pending.group_idx * g.n_pad
            codes = arr[off : off + pending.n]
            return self._drain_from_host(pending, codes, int(arr[-1]))
        if pending.summary is not None:
            s = np.asarray(pending.summary)  # [count, fault]
            if int(s[0]) == 0:
                return self._drain_all_ok(pending, int(s[1]))
        arr = np.asarray(pending.results)  # one transfer: results + fault
        return self._drain_from_host(pending, arr[: pending.n], int(arr[-1]))

    def _drain_all_ok(self, pending: PendingBatch, fault: int) -> list[int]:
        raise_on_fault(fault, "device ledger")
        pending.failures = 0
        pending.dense = [0] * pending.n
        return pending.dense

    def drain_reply(self, pending: PendingBatch, operation) -> bytes:
        """The reply body bytes (sparse non-ok result structs, reference:
        src/tigerbeetle.zig:231-249) without any per-event Python loop:
        all-success replies are empty by construction, and the failure path
        encodes via vectorized nonzero."""
        self.drain(pending)
        if not pending.failures:
            return b""
        from tigerbeetle_tpu.state_machine import encode_sparse_results

        return encode_sparse_results(pending.codes_np, operation)

    def drain_many(self, pendings) -> None:
        """Materialize a window of pending batches. Each batch's
        device->host copy was started AT DISPATCH (it pipelines right
        behind the commit kernel), so draining the window costs one
        wait for the oldest in-flight copy and the rest read landed
        buffers — NOT one transport round trip per batch. (A device-side
        concat would be worse: a fresh launch + fetch that ignores the
        prefetched copies.)"""
        for p in pendings:
            if p is not None:
                self.drain(p)

    def _drain_from_host(self, pending: PendingBatch, codes,
                         fault: int) -> list[int]:
        raise_on_fault(fault, "device ledger")
        pending.codes_np = np.asarray(codes, dtype=np.uint32)
        pending.failures = int(np.count_nonzero(pending.codes_np))
        dense = [int(x) for x in codes]
        applied = int(applied_insert_mask(dense, pending.flags).sum())
        if pending.operation == Operation.create_transfers:
            # A spill cycle after dispatch rebuilt the table and recounted
            # occupancy exactly — this batch's effect is already measured;
            # reconciling again would double-count the correction.
            if pending.epoch == self._occupancy_epoch:
                self._xfer_used += applied - pending.n
        else:
            self._acct_used += applied - pending.n
        # Cache only AFTER the fault check and reconcile: a drain retried
        # after a fault exception must re-raise, not return unsound codes.
        pending.dense = dense
        return dense

    def execute_dense(self, operation, timestamp: int, events) -> list[int]:
        return self.drain(self.execute_async(operation, timestamp, events))

    # -- lookups (spill-aware: HBM miss falls back to the LSM store) --

    def lookup_rows(self, operation: Operation, ids: list[int]) -> bytes:
        if self.spill is None or operation == Operation.lookup_accounts:
            return super().lookup_rows(operation, ids)
        found, rows = self._lookup(self.kernels.lookup_transfers, ids)
        return self.spill.merge_lookup_rows(ids, found, rows)

    def lookup_transfers(self, ids: list[int]) -> list[types.Transfer]:
        if self.spill is None:
            return super().lookup_transfers(ids)
        body = self.lookup_rows(Operation.lookup_transfers, ids)
        arr = np.frombuffer(body, dtype=types.TRANSFER_DTYPE)
        return [types.Transfer.from_np(arr[i]) for i in range(len(arr))]

    # -- secondary-index equality queries (device filter scan + LSM tail) --

    def _query_scan(self, table: str, field: str, value: int) -> np.ndarray:
        words = _ACCOUNT_QUERY_WORDS if table == "acct" else _TRANSFER_QUERY_WORDS
        _, nwords, halfword = words[field]
        width_bits = 16 if halfword else nwords * 32
        if not 0 <= value < (1 << width_bits):
            raise ValueError(f"{field} value out of range: {value}")
        vw = np.frombuffer(value.to_bytes(16, "little"), dtype=np.uint32).copy()
        rows_key = "acct_rows" if table == "acct" else "xfer_rows"
        rows_d, total_d = self.kernels.filter_scan(table, field)(
            self.state[rows_key], jnp.asarray(vw)
        )
        total = int(np.asarray(total_d))
        if total > QUERY_LIMIT:
            raise RuntimeError(
                f"query matches {total} rows > QUERY_LIMIT {QUERY_LIMIT}"
            )
        return np.asarray(rows_d)[:total]

    def query_accounts(self, field: str, value: int) -> list[types.Account]:
        """Accounts whose `field` equals `value`, ascending timestamp (the
        analog of a reference index-tree range query; accounts never spill,
        so the device scan is the whole store)."""
        rows = self._query_scan("acct", field, value)
        arr = np.frombuffer(rows.tobytes(), dtype=types.ACCOUNT_DTYPE)
        out = [types.Account.from_np(arr[i]) for i in range(len(arr))]
        return sorted(out, key=lambda a: a.timestamp)

    def query_transfers(self, field: str, value: int) -> list[types.Transfer]:
        """Transfers whose `field` equals `value`, ascending timestamp:
        device filter scan over HBM merged with the LSM index trees over the
        spilled tail (lsm/groove.py query)."""
        rows = self._query_scan("xfer", field, value)
        arr = np.frombuffer(rows.tobytes(), dtype=types.TRANSFER_DTYPE)
        by_ts = {
            int(arr[i]["timestamp"]): types.Transfer.from_np(arr[i])
            for i in range(len(arr))
        }
        if self.spill is not None and self.spill.spilled:
            self.spill.io_drain()  # queued inserts must land before scans
            g = self.spill.forest.transfers
            for ts in g.query(field, value):
                if ts in by_ts:
                    continue  # HBM wins (stale LSM rows of reloaded ids)
                row = g.get_by_timestamp(ts)
                t = types.Transfer.from_np(
                    np.frombuffer(row, dtype=types.TRANSFER_DTYPE)[0]
                )
                if t.id in self.spill.spilled:
                    by_ts[ts] = t
            if len(by_ts) > QUERY_LIMIT:
                raise RuntimeError(
                    f"query matches {len(by_ts)} rows > QUERY_LIMIT"
                )
        return [by_ts[ts] for ts in sorted(by_ts)]

    # -- parity extraction --

    def extract(self):
        """Pull the full device state to host dicts (accounts, transfers,
        posted) for bit-exact comparison against the oracle."""
        acct_rows = np.asarray(self.state["acct_rows"])[:-1]
        xfer_rows = np.asarray(self.state["xfer_rows"])[:-1]
        fulfill = np.asarray(self.state["fulfill"])[:-1]

        accounts: dict[int, types.Account] = {}
        transfers: dict[int, types.Transfer] = {}
        posted: dict[int, int] = {}

        occ = _occupied_rows(acct_rows)
        arr = np.frombuffer(acct_rows[occ].tobytes(), dtype=types.ACCOUNT_DTYPE)
        for i in range(len(arr)):
            a = types.Account.from_np(arr[i])
            accounts[a.id] = a
        occ = _occupied_rows(xfer_rows)
        arr = np.frombuffer(xfer_rows[occ].tobytes(), dtype=types.TRANSFER_DTYPE)
        ful = fulfill[occ]
        for i in range(len(arr)):
            t = types.Transfer.from_np(arr[i])
            transfers[t.id] = t
            if ful[i]:
                posted[t.timestamp] = int(ful[i])
        if self.spill is not None:
            self.spill.extract_into(transfers, posted)
        return accounts, transfers, posted

    @property
    def commit_timestamp(self) -> int:
        return int(self.state["commit_ts"])


def _occupied_rows(rows: np.ndarray) -> np.ndarray:
    k4 = rows[:, :4]
    empty = (k4 == 0).all(axis=1)
    tomb = (k4 == 0xFFFFFFFF).all(axis=1)
    return ~empty & ~tomb
