"""DualLedger: native C++ engine serves replies, the TPU applies the same
prepares asynchronously — the dual-commit durable modes.

The problem this solves (round-4 verdict): on this environment's tunneled
TPU, ANY device->host fetch permanently degrades the dispatch path
(models/native_ledger.py), so a reply-serving server cannot run its hot
loop through the device — but that blocks *reply-from-device*, not
*commit-on-device*. Here the native engine (native/ledger.cc) computes
reply codes at host speed, while a background device thread applies the
SAME prepares, same timestamps, same order, to the JAX DeviceLedger —
host->device uploads and kernel launches only, nothing ever read back
until shutdown. Device state is REAL state: maintained batch-by-batch by
the same commit kernels the flagship benchmark measures.

Two modes:

- **shadow** (``--backend native+device``): the ledger auto-enqueues every
  create batch at execute time; the device is a passive mirror verified at
  shutdown. No op numbers, no replica integration.
- **follower** (``--backend dual``): the REPLICA drives the apply queue —
  each committed op is enqueued at commit FINALIZE (reply built, WAL
  durable) via apply_commit(op, ...), so the device follows the committed
  op stream with an explicit watermark. This buys: a rolling per-op
  hash-log ring on BOTH sides (first divergent op is named exactly, not
  just "the digests differ"), bounded-lag admission backpressure
  (`apply_lag_excess` feeds Replica.ingress_occupancy and the PR-6
  credit regulator), checkpoint/state-sync drains, and restart recovery —
  restore_bytes re-seeds the device from the native snapshot's row images
  through DeviceLedger.install_snapshot_rows (h2d only).

Verification (hash_log semantics, testing/hash_log.py):
- every batch's dense reply codes are folded into a chained u64 digest on
  BOTH sides — on device (fold_reply_codes, no d2h) and on host over the
  native engine's codes (same stream order);
- in follower mode each op's post-fold chain value is also written into a
  rolling ring (host-side numpy ring + device-side ring updated inside the
  fold kernel), so the end-of-run check can walk the rings and fail AT the
  first divergent op — the reference's -Dhash-log-mode check applied
  across heterogeneous engines (src/testing/hash_log.zig);
- at shutdown, finalize() drains the apply queue and does the process's
  FIRST device->host reads: the fold scalars must match, the rings must
  match entry for entry, and state_fingerprint — an order-independent
  digest over every live account/transfer row's 128-byte wire image,
  implemented identically in C++ (tb_ledger_fingerprint) and JAX
  (models/ledger.py state_fingerprint) — must match row-set for row-set.

Reference seam: src/state_machine.zig:508-540 — commit determinism is the
consensus invariant; the dual mode extends it across heterogeneous engines
(the reference's simulator cross-checks replicas the same way,
src/testing/cluster/state_checker.zig).
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.latency import (
    DLEG_BUSY,
    DLEG_COALESCE,
    DLEG_DISPATCH,
    DLEG_H2D,
    NULL_DEVICE_ANATOMY,
    DeviceAnatomy,
)
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.models.native_ledger import NativeLedger
from tigerbeetle_tpu.testing.hash_log import HashLogDivergence
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.types import Operation

_STOP = object()
_INSTALL = "__install__"  # control item: re-seed the device from a snapshot
_PROBE = "__probe__"  # control item: checkpoint-commitment fingerprint probe

# Rolling per-op digest ring (follower mode): one chained-fold value per
# committed create op, op % RING. 4096 ops cover well over a full WAL ring
# of divergence localization without unbounded memory on either side.
APPLY_RING = 1 << 12

_FOLD_GROUP_CACHE: dict = {}
_FOLD_RING_CACHE: dict = {}


def _fold_group_fn(k: int, n_pad: int):
    """Jitted chained fold over a fused group's flat results: one dispatch
    folds up to k batches' code streams (active-masked — padding slots
    must NOT advance the chain, the native side folds only real batches).
    Digest-identical to k sequential fold_reply_codes calls: the per-batch
    mix only sums lanes < n, so the trailing fault word / slot layout
    never contributes."""
    fn = _FOLD_GROUP_CACHE.get((k, n_pad))
    if fn is None:
        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu.models.ledger import fold_reply_codes, sentinel_jit

        def f(chk, flat, ns, active):
            flat2 = flat[: k * n_pad].reshape(k, n_pad)

            def body(c, x):
                res, n, a = x
                return jnp.where(
                    a, fold_reply_codes(c, res, n), c
                ), None

            c2, _ = jax.lax.scan(body, chk, (flat2, ns, active))
            return c2

        fn = _FOLD_GROUP_CACHE[(k, n_pad)] = sentinel_jit(
            f"fold_group_{k}x{n_pad}", f
        )
    return fn


def _fold_group_ring_fn(k: int, n_pad: int):
    """Follower variant of _fold_group_fn: the scan also EMITS each
    batch's post-fold chain value, and the per-op values are scattered
    into the rolling device ring at their ops' slots. The ring carries a
    DUMP slot at index APPLY_RING and inactive lanes are routed there by
    the caller — scattering a stale read-back at a real slot instead
    would race an active lane that maps to the same slot (duplicate-index
    .at[].set is order-undefined) and fabricate a divergence. Chain-
    identical to _fold_group_fn — the ring write rides the same dispatch,
    so the apply loop stays one launch per fused group with no d2h."""
    fn = _FOLD_RING_CACHE.get(("group", k, n_pad))
    if fn is None:
        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu.models.ledger import fold_reply_codes, sentinel_jit

        def f(chk, ring, idxs, flat, ns, active):
            flat2 = flat[: k * n_pad].reshape(k, n_pad)

            def body(c, x):
                res, n, a = x
                c2 = jnp.where(a, fold_reply_codes(c, res, n), c)
                return c2, c2

            c2, chain = jax.lax.scan(body, chk, (flat2, ns, active))
            return c2, ring.at[idxs].set(chain)

        fn = _FOLD_RING_CACHE[("group", k, n_pad)] = sentinel_jit(
            f"fold_group_ring_{k}x{n_pad}", f, donate_argnums=(1,)
        )
    return fn


def _fold_ring_fn():
    """Solo-batch follower fold: chain + one ring write, one dispatch."""
    fn = _FOLD_RING_CACHE.get("solo")
    if fn is None:
        import jax

        from tigerbeetle_tpu.models.ledger import fold_reply_codes, sentinel_jit

        def f(chk, ring, idx, results, n):
            c2 = fold_reply_codes(chk, results, n)
            return c2, ring.at[idx].set(c2)

        fn = _FOLD_RING_CACHE["solo"] = sentinel_jit(
            "fold_ring_solo", f, donate_argnums=(1,)
        )
    return fn


def raise_on_parity_divergence(report: dict) -> None:
    """Hash-log check mode over a finalize() report: a failed run raises
    HashLogDivergence AT the first divergent op when the rings localized
    one (testing/hash_log.py semantics), else a plain AssertionError."""
    if report.get("verified") is not False:
        return
    hl = report.get("hash_log") or {}
    op = hl.get("first_divergent_op")
    if op is not None:
        raise HashLogDivergence(
            op, "device-apply", hl.get("want", 0), hl.get("got", 0)
        )
    raise AssertionError(f"dual-commit parity failed: {report}")


class DualLedger:
    """Replica backend: NativeLedger semantics + an asynchronous device
    apply loop. All reply-serving calls delegate to the native engine; the
    device never blocks (or touches) the reply path."""

    zero_copy_events = True  # both consumers only read the event rows

    SHADOW_KEYS = (
        "batches", "groups", "solo", "stage_s", "idle_s", "overlapped",
    )

    def instrument(self, metrics, tracer) -> None:
        """Re-bind onto a shared registry/tracer (the replica's).
        Accumulated values carry over; the apply loop reads
        self.shadow_stats/self.tracer per use. A loop update racing
        the carry-over/rebind window lands in the discarded old group
        and is DROPPED from the new registry — at most one update, and
        instrument() runs at setup before commits flow, so nothing of
        record is lost."""
        for key in self.SHADOW_KEYS:
            metrics.counter(f"shadow.{key}").add(self.shadow_stats[key])
        # rebound on the event loop while the apply thread reads per
        # use — a GIL-atomic reference swap, never a torn value; see the
        # docstring for the (setup-time-only) dropped-update window
        self.metrics = metrics  # vet: handoff
        self.tracer = tracer  # vet: handoff
        # registry-backed StatGroup; Counter.add serializes internally
        self.shadow_stats = metrics.group(  # vet: handoff
            "shadow", self.SHADOW_KEYS
        )
        if self.follower:
            # gauges bound ONCE (a registry lookup per committed op would
            # tax the hot paths the counters observe — the PR-6 bus
            # lesson); the APPLY thread is the only writer
            self._lag_gauge = metrics.gauge(  # vet: handoff
                "shadow.device_lag_ops"
            )
            self._overlap_gauge = metrics.gauge(  # vet: handoff
                "shadow.device_apply_overlap"
            )
            # device-apply lag lane (latency.py parallel-lane contract):
            # bound once; observed from the APPLY thread only (the
            # Histogram serializes internally)
            self._h_apply_lag = metrics.histogram(  # vet: handoff
                "latency.device_apply_lag_us"
            )
            # device anatomy: opened/stamped/finished on the APPLY thread
            # only (the enqueue stamp arrives by value in the apply
            # tuple); rebinding swaps the whole object — a GIL-atomic
            # reference swap read per run
            self.device_anatomy = DeviceAnatomy(metrics)  # vet: handoff
        # applier throughput surfaces (flight-recorder device columns);
        # written by the apply thread only
        self._g_qdepth = metrics.gauge("device.queue_depth")  # vet: handoff
        self._c_dispatch = metrics.counter("device.dispatches")  # vet: handoff
        # the device ledger's own instrumentation (group staging
        # fence waits + h2d byte counting) reports into the same store
        self.device.instrument(metrics, tracer)

    def __init__(
        self,
        acct_slots_log2: int = 16,
        xfer_slots_log2: int = 20,
        queue_max: int = 256,
        warm_kernels: bool = False,
        follower: bool = False,
        lag_window: int = 128,
    ):
        self.native = NativeLedger(acct_slots_log2, xfer_slots_log2)
        # follower (the `dual` backend plan): the replica enqueues ops at
        # commit finalize via apply_commit; execute paths do NOT
        # auto-enqueue. Replica detects the plan via this attribute.
        self.follower = self.dual_follower = follower
        # Bounded-lag admission window (ops): apply lag beyond it feeds
        # Replica.ingress_occupancy, so the PR-6 credit regulator (and
        # the bare _on_request cap) throttles ADMISSION instead of the
        # bounded queue's put() eventually stalling the event loop.
        self.lag_window = lag_window
        from tigerbeetle_tpu.models.ledger import DeviceLedger

        process = ConfigProcess(
            account_slots_log2=acct_slots_log2,
            transfer_slots_log2=xfer_slots_log2,
        )
        # Warm the device kernels BEFORE serving (the server path sets
        # warm_kernels): an in-window compile would stall the apply loop
        # until the bounded queue fills and then block the reply path
        # (measured: a 2M-transfer run collapsed from ~960k to ~108k TPS
        # exactly this way). Warming runs BEFORE the real ledger is
        # allocated so the scratch tables never double device memory; with
        # the persistent compilation cache (package __init__) only the
        # first-ever server pays real compiles here — later boots load
        # from disk in seconds.
        if warm_kernels:
            self._warm_device_kernels(process)
        self.device = DeviceLedger(process=process, mode="auto")
        self.device.prefetch_results = False  # NO d2h until finalize()
        self.process = None  # replica duck-typing (native backend shape)
        self.spill = None
        self.hazards = self.device.hazards  # [stats] observability
        # chained digests of the dense reply-code stream (hash_log pair);
        # shadow mode folds on the native engine's done-callbacks, read at
        # finalize (follower mode folds on the apply thread instead)
        self._chk_native = 0  # vet: guarded-by=_chk_lock
        self._chk_lock = threading.Lock()
        # written only by the apply thread; finalize() joins the thread
        # before reading either (join-before-read)
        self._shadow_error: Exception | None = None  # vet: handoff
        self._shadow_batches = 0  # vet: handoff
        # follower watermarks: _enqueued_op/_enq_ops written by the event
        # loop at apply_commit, read by the apply thread for the lag
        # gauge; _applied_op/_done_ops/_consumed_seq written by the apply
        # thread, read by the event loop (lag/backpressure/drain). All
        # GIL-atomic int flips whose one-iteration staleness only skews a
        # gauge reading. Lag counts ITEMS (one item == one committed
        # create op), not op-number distance — committed non-create ops
        # (lookups, registers) and the op-number jump after a restart
        # never enter the queue and must not read as phantom lag.
        self._enqueued_op = 0  # vet: handoff
        self._applied_op = 0  # vet: handoff
        self._enq_ops = 0  # vet: handoff
        self._done_ops = 0  # vet: handoff
        self._put_seq = 0  # event-loop-only (apply_commit/restore_bytes)
        self._consumed_seq = 0  # vet: handoff
        self._apply_cond = threading.Condition()
        # follower hash-log rings (APPLY_RING entries): the host ring
        # holds (op, prepare_checksum, native chain value) per applied
        # op; the device ring is its on-device twin, fetched ONCE at
        # finalize. Written only by the apply thread; finalize joins
        # before reading (join-before-read).
        self._op_ring: list = [None] * APPLY_RING  # vet: handoff
        self._dev_ring_out = None  # vet: handoff
        self._chk_native_thread = 0  # vet: handoff
        # test hooks (seeded fault injection for the hash-log check-mode
        # tests): set before traffic flows, read by the apply thread
        self._test_corrupt_apply_op: int | None = None  # vet: handoff
        self._test_apply_delay_s = 0.0  # vet: handoff
        # commitment probes (federation/commitment.py): (op, host
        # fingerprint, LAZY device fingerprint) per checkpoint boundary,
        # appended by the apply thread, materialized + compared at
        # finalize (join-before-read)
        self._probe_out: list = []  # vet: handoff
        # loop cost accounting (the h2d/staging tax shares the core
        # with the reply-serving event loop): stage_s = host time spent
        # staging + dispatching apply work; idle_s = blocked on an empty
        # queue; overlapped = groups whose staging/dispatch completed
        # while the PREVIOUS group's kernel was still executing (the
        # double-buffer pipeline working as intended). BENCH reports
        # overlapped/groups as shadow_upload_overlap. Registry-backed
        # (metrics.py StatGroup under `shadow.`): instrument() re-binds
        # onto the replica's shared registry so the [stats] line and the
        # bench read the same store.
        self.metrics = Metrics()
        self.tracer = NULL_TRACER
        self.shadow_stats = self.metrics.group("shadow", self.SHADOW_KEYS)
        self.device_anatomy = NULL_DEVICE_ANATOMY
        self._g_qdepth = self.metrics.gauge("device.queue_depth")
        self._c_dispatch = self.metrics.counter("device.dispatches")
        if follower:
            self._lag_gauge = self.metrics.gauge("shadow.device_lag_ops")
            self._overlap_gauge = self.metrics.gauge(
                "shadow.device_apply_overlap"
            )
            self._h_apply_lag = self.metrics.histogram(
                "latency.device_apply_lag_us"
            )
            self.device_anatomy = DeviceAnatomy(self.metrics)
        # --device-trace: a bounded jax.profiler window started/stopped
        # by the APPLY thread (so it brackets real apply work); armed by
        # the event loop — a GIL-atomic flag flip polled once per run
        self._trace_armed = False  # vet: handoff
        self._trace_dir = ""  # vet: handoff
        self._trace_window_s = 3.0  # vet: handoff
        # device cannot follow a snapshot restore without an install path
        # (shadow mode, or a follower whose snapshot exceeds the device
        # geometry). Set on the event loop, polled by the apply loop: a
        # GIL-atomic bool flip whose one-iteration staleness only delays
        # the stand-down by a batch
        self._restored = False  # vet: handoff
        # the queue IS the cross-thread handoff (bounded, blocking put)
        self._q: queue.Queue = queue.Queue(maxsize=queue_max)  # vet: handoff
        self._thread = threading.Thread(
            target=self._apply_loop,
            name="device-applier" if follower else "device-shadow",
            daemon=True,
        )
        self._thread.start()

    def _warm_device_kernels(self, process: ConfigProcess) -> None:
        """Compile the kernel set the apply loop will hit, against a
        SCRATCH ledger of the same geometry (kernels are shared per
        ConfigProcess — models.ledger.get_kernels — so the real ledger
        reuses every compile; scratch state is freed before the real
        tables allocate). Covers: accounts commit, transfers fast tier,
        fast_pv (posts), group steppers (both fused capacities), the
        results summarizer, and the fold kernels (ring variants too in
        follower mode), all at the wire batch pad. Rare tiers (serial
        residue at odd pads) compile on demand — the 256-slot queue
        absorbs those stalls."""
        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu import types
        from tigerbeetle_tpu.constants import BATCH_PAD, BENCH_BATCH
        from tigerbeetle_tpu.models.ledger import (
            DeviceLedger,
            fold_reply_codes,
        )

        scratch = DeviceLedger(process=process, mode="auto")
        scratch.prefetch_results = False
        # ~10n transfer rows + n accounts land in the scratch tables; the
        # warm batch shrinks for small-table configs (then it warms a
        # smaller pad — still useful, and the guard never trips)
        n = min(
            BENCH_BATCH,
            scratch._xfer_limit // 12,
            scratch._acct_limit // 2,
        )
        if n < 2:
            return  # simple() needs two distinct accounts (mod n-1)
        # full wire batches pad to BATCH_PAD (the driver's steady state);
        # odd tail sizes compile on demand behind the queue
        if n == BENCH_BATCH:
            scratch.pad_to = BATCH_PAD  # the wire-batch pad the real
            # ledger resolves to for full 8190-event batches
        pad = scratch._pad_for(n)
        ts = 1 << 40

        acct = np.zeros(n, dtype=types.ACCOUNT_DTYPE)
        acct["id_lo"] = np.arange(1, n + 1, dtype=np.uint64)
        acct["ledger"] = 1
        acct["code"] = 1
        ts += n
        scratch.execute_async(Operation.create_accounts, ts, acct)

        def simple(base):
            x = np.zeros(n, dtype=types.TRANSFER_DTYPE)
            x["id_lo"] = np.arange(base, base + n, dtype=np.uint64)
            x["debit_account_id_lo"] = 1 + np.arange(n) % (n - 1)
            x["credit_account_id_lo"] = 1 + (np.arange(n) + 1) % (n - 1)
            x["amount_lo"] = 1
            x["ledger"] = 1
            x["code"] = 1
            return x

        # fast tier + summarizer
        ts += n
        scratch.execute_async(
            Operation.create_transfers, ts, simple(1_000_000)
        )
        # pending batch, then a full post batch -> the fast_pv tier
        pend = simple(2_000_000)
        pend["flags"] = 2
        ts += n
        scratch.execute_async(Operation.create_transfers, ts, pend)
        post = np.zeros(n, dtype=types.TRANSFER_DTYPE)
        post["id_lo"] = np.arange(3_000_000, 3_000_000 + n, dtype=np.uint64)
        post["pending_id_lo"] = pend["id_lo"]
        post["flags"] = 4
        ts += n
        scratch.execute_async(Operation.create_transfers, ts, post)
        # conflict-wave scheduler: a same-batch pend->post batch compiles
        # the scanned 2-wave stepper (the smallest _WAVE_BUCKETS shape) so
        # a dependent-transfer burst doesn't stall the apply loop on a
        # compile; deeper buckets compile on demand behind the queue
        half = n // 2
        if half >= 2:
            wav = simple(5_000_000)
            wav["flags"][:half] = 2  # pendings
            wav["pending_id_lo"][half : 2 * half] = wav["id_lo"][:half]
            wav["debit_account_id_lo"][half : 2 * half] = 0
            wav["credit_account_id_lo"][half : 2 * half] = 0
            wav["amount_lo"][half : 2 * half] = 0
            wav["flags"][half : 2 * half] = 4  # posts of same-batch pendings
            ts += n
            scratch.execute_async(Operation.create_transfers, ts, wav)
        # both fused group capacities (the replica's group commit) + the
        # fused group-fold kernel over each (ring variant in follower
        # mode — the production apply path dispatches that one)
        scratch_ring = jnp.zeros(APPLY_RING + 1, dtype=jnp.uint64)
        for k in (5, 2):  # 5 -> the 16-slot stepper, 2 -> the 4-slot
            items = []
            for j in range(k):
                ts += n
                items.append((ts, simple(4_000_000 + j * n)))
            pendings = scratch.try_execute_group_async(items)
            if pendings is not None:
                g = pendings[0].group
                ns = np.zeros(g.k, dtype=np.int32)
                ns[:k] = [len(a) for _, a in items]
                active = np.zeros(g.k, dtype=bool)
                active[:k] = True
                if self.follower:
                    idxs = np.arange(g.k, dtype=np.int32)
                    _, scratch_ring = _fold_group_ring_fn(g.k, g.n_pad)(
                        jnp.uint64(0), scratch_ring, jnp.asarray(idxs),
                        g.results, jnp.asarray(ns), jnp.asarray(active),
                    )
                else:
                    _fold_group_fn(g.k, g.n_pad)(
                        jnp.uint64(0), g.results, jnp.asarray(ns),
                        jnp.asarray(active),
                    )
        # the solo fold kernel
        if self.follower:
            chk, scratch_ring = _fold_ring_fn()(
                jnp.uint64(0), scratch_ring, jnp.int32(0),
                jnp.zeros(pad + 1, dtype=jnp.uint32), jnp.int32(1),
            )
        else:
            chk = jax.jit(fold_reply_codes)(
                jnp.uint64(0),
                jnp.zeros(pad + 1, dtype=jnp.uint32),
                jnp.int32(1),
            )
        # block WITHOUT fetching: any device->host read here would
        # permanently degrade this process's tunnel transport before the
        # server ever serves (the whole reason the dual mode exists)
        jax.block_until_ready(chk)
        # compiles past this point are hot-path events (rare tiers and
        # odd pads compile on demand behind the queue — exactly the
        # stalls the sentinel exists to name)
        from tigerbeetle_tpu.models.ledger import COMPILE_SENTINEL

        COMPILE_SENTINEL.mark_warm()

    # -- the device apply loop --------------------------------------------

    def _apply_loop(self) -> None:
        """One loop serves both modes (the generalized shadow loop): items
        are (op, operation, ts, arr, codes, prepare_checksum, trace) —
        shadow mode enqueues op=None/codes=None/trace=0 (digests fold via
        the engine done-callbacks instead), follower mode carries the
        committed op number, the native dense codes, the prepare checksum
        and the op's cluster-causal trace id (tags the shadow.upload
        span). Control items (first element a str) re-seed/reset the
        device between runs."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu.models.ledger import (
            DeviceLedger,
            fold_reply_codes,
            fold_reply_codes_np,
        )

        fold = jax.jit(fold_reply_codes)
        chk = jnp.uint64(0)
        chk_nat = 0
        # +1: the DUMP slot inactive group lanes scatter into (see
        # _fold_group_ring_fn); real ops land in [0, APPLY_RING)
        dev_ring = (
            jnp.zeros(APPLY_RING + 1, dtype=jnp.uint64)
            if self.follower else None
        )
        group_max = DeviceLedger.GROUP_KS[0]
        prev_flat = None  # previous fused group's results (overlap probe)
        stop = False

        def note_applied(op: int | None, n_items: int) -> None:
            if op is not None:
                self._applied_op = op
                self._done_ops += n_items
                self._lag_gauge.set(max(0, self._enq_ops - self._done_ops))

        def fold_native_run(items) -> None:
            """Chain the native codes + ring entries for a run, in op
            order (follower mode; runs are consumed in queue order so the
            chain matches the commit stream)."""
            nonlocal chk_nat
            for op2, _o, _t, _a, codes, prep, *_rest in items:
                chk_nat = fold_reply_codes_np(chk_nat, codes)
                self._op_ring[op2 % APPLY_RING] = (op2, prep, chk_nat)

        trace_until = 0.0  # active --device-trace window deadline
        while not stop:
            t_wait = _time.perf_counter()
            run = [self._q.get()]
            self.shadow_stats.add("idle_s", _time.perf_counter() - t_wait)
            if run[0] is _STOP:
                break
            if self._trace_armed:
                self._trace_armed = False
                trace_until = self._start_trace_window()
            if isinstance(run[0][0], str):  # control item
                kind = run[0][0]
                if kind == _INSTALL:
                    try:
                        chk, chk_nat, dev_ring = self._apply_install(
                            run[0][1], dev_ring
                        )
                    except Exception as e:
                        self._shadow_error = e
                elif kind == _PROBE:
                    try:
                        self._apply_probe(run[0][1], run[0][2])
                    except Exception as e:
                        self._shadow_error = e
                self._consumed_seq += 1
                with self._apply_cond:
                    self._apply_cond.notify_all()
                continue
            # device anatomy: open a record per SAMPLED item (slot 8, the
            # commit path's enqueue stamp) as it leaves the queue — the
            # open closes queue_wait at this item's true dequeue time.
            # Keyed by the cluster trace id when one flows (slot 7), else
            # the op number — trace-id sampling is its own knob, and a
            # live server with tracing off must still decompose (open
            # rejects tid 0). Unsampled items cost one truthiness test.
            anat = self.device_anatomy
            toks = [
                anat.open(run[0][6] or run[0][0], run[0][7])
                if run[0][7] else 0
            ]
            self._g_qdepth.set(self._q.qsize())
            # drain a run of queued create_transfers batches: one fused
            # group dispatch covers up to GROUP_KS[0] of them — per-batch
            # host work (hazard analysis, upload, launch) is the loop's
            # dominant cost on a single-core host, and it shares that core
            # with the reply-serving event loop
            deferred_control = None
            while (
                len(run) < group_max
                and run[-1][1] == Operation.create_transfers
            ):
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                if isinstance(nxt[0], str):
                    # a control item partitions the run: apply the run
                    # first, then handle it below — queue order preserved
                    deferred_control = nxt
                    break
                run.append(nxt)
                toks.append(
                    anat.open(nxt[6] or nxt[0], nxt[7]) if nxt[7] else 0
                )
            if self._test_apply_delay_s:
                _time.sleep(self._test_apply_delay_s)
            if self._shadow_error is not None or self._restored:
                for t in toks:
                    anat.discard(t)
                self._consumed_seq += len(run) + (
                    1 if deferred_control is not None else 0
                )
                note_applied(run[-1][0], len(run))
                with self._apply_cond:
                    self._apply_cond.notify_all()
                continue  # drain without applying; finalize reports why
            any_tok = any(toks)
            try:
                if self._test_corrupt_apply_op is not None:
                    # seeded divergence injection (hash-log check tests):
                    # corrupt the DEVICE applier's view of one op's rows
                    run = [
                        (
                            item
                            if item[0] != self._test_corrupt_apply_op
                            else self._corrupt_item(item)
                        )
                        for item in run
                    ]
                i = 0
                while i < len(run):
                    # longest create_transfers stretch from i
                    j = i
                    while (
                        j < len(run)
                        and run[j][1] == Operation.create_transfers
                    ):
                        j += 1
                    # coalesce_hold closes here for this stretch's sampled
                    # items: the run is assembled and staging begins (a
                    # refused fusion's hazard re-probe counts into the
                    # following dispatch sub-leg)
                    stretch_toks = ()
                    if any_tok:
                        stretch_toks = [
                            t for t in toks[i:j if j > i else i + 1] if t
                        ]
                        if stretch_toks:
                            t_co = _time.perf_counter_ns()
                            for t in stretch_toks:
                                anat.stamp(t, DLEG_COALESCE, t_co)
                    pendings = None
                    if j - i >= 2:
                        t_stage = _time.perf_counter()
                        with self.tracer.span("shadow.upload",
                                              batches=j - i,
                                              trace=run[i][6]):
                            pendings = self.device.try_execute_group_async(
                                [(t, a) for _, _, t, a, *_ in run[i:j]]
                            )
                    if pendings is not None:
                        g = pendings[0].group
                        m = j - i
                        ns = np.zeros(g.k, dtype=np.int32)
                        ns[:m] = [len(a) for _, _, _, a, *_ in run[i:j]]
                        active = np.zeros(g.k, dtype=bool)
                        active[:m] = True
                        if self.follower:
                            idxs = np.full(
                                g.k, APPLY_RING, dtype=np.int32
                            )  # inactive lanes -> the dump slot
                            idxs[:m] = [
                                it[0] % APPLY_RING for it in run[i:j]
                            ]
                            # two ACTIVE ops in one run congruent mod
                            # APPLY_RING (>4096 non-create ops between
                            # them): duplicate-index scatter is order-
                            # undefined, so route all but the LAST to
                            # the dump slot — the host ring keeps the
                            # last op per slot too (dict overwrite)
                            seen_slots: dict[int, int] = {}
                            for lane in range(m):
                                s_prev = seen_slots.get(int(idxs[lane]))
                                if s_prev is not None:
                                    idxs[s_prev] = APPLY_RING
                                seen_slots[int(idxs[lane])] = lane
                            chk, dev_ring = _fold_group_ring_fn(
                                g.k, g.n_pad
                            )(
                                chk, dev_ring, jnp.asarray(idxs),
                                g.results, jnp.asarray(ns),
                                jnp.asarray(active),
                            )
                            fold_native_run(run[i:j])
                        else:
                            chk = _fold_group_fn(g.k, g.n_pad)(
                                chk, g.results, jnp.asarray(ns),
                                jnp.asarray(active),
                            )
                        self._shadow_batches += m
                        self._c_dispatch.add()
                        stats = self.shadow_stats
                        stats.add("batches", m)
                        stats.add("groups")
                        stats.add("stage_s", _time.perf_counter() - t_stage)
                        if prev_flat is not None and not prev_flat.is_ready():
                            # this group's staging + dispatch finished
                            # while the previous kernel was still running:
                            # the upload pipeline overlapped execution
                            stats.add("overlapped")
                        if self.follower and stats["groups"]:
                            self._overlap_gauge.set(round(
                                stats["overlapped"] / stats["groups"], 4
                            ))
                        prev_flat = g.results
                        if stretch_toks:
                            # h2d_stage closes at the ledger's upload-
                            # issued seam; device_busy is fenced on the
                            # fold kernel's chain scalar — blocking is
                            # allowed (no fetch), but it serializes this
                            # sampled run against the device, so the
                            # overlap probe reads ready for ~1/16 of
                            # groups (the sampling tax)
                            h2d_ns = self.device.last_h2d_done_ns
                            t_disp = _time.perf_counter_ns()
                            for t in stretch_toks:
                                if h2d_ns:
                                    anat.stamp(t, DLEG_H2D, h2d_ns)
                                anat.stamp(t, DLEG_DISPATCH, t_disp)
                            jax.block_until_ready(chk)
                            t_busy = _time.perf_counter_ns()
                            for t in stretch_toks:
                                anat.stamp(t, DLEG_BUSY, t_busy)
                    else:
                        # fusion refused (a batch failed the fast-tier
                        # proof) or too short: run the stretch per-batch —
                        # re-probing fusion at every offset would redo the
                        # vectorized hazard analysis O(k^2) times on the
                        # core the event loop needs. j == i means run[i]
                        # is not create_transfers (accounts): one batch.
                        end = j if j > i else i + 1
                        t_stage = _time.perf_counter()
                        with self.tracer.span("shadow.upload",
                                              batches=end - i, solo=True,
                                              trace=run[i][6]):
                            for op2, opn2, ts2, arr2, *_rest in run[i:end]:
                                pending = self.device.execute_async(
                                    opn2, ts2, arr2
                                )
                                self.device._c_h2d.add(arr2.nbytes)
                                if self.follower:
                                    chk, dev_ring = _fold_ring_fn()(
                                        chk, dev_ring,
                                        jnp.int32(op2 % APPLY_RING),
                                        pending.results,
                                        jnp.int32(len(arr2)),
                                    )
                                else:
                                    chk = fold(
                                        chk, pending.results,
                                        jnp.int32(len(arr2)),
                                    )
                                self._shadow_batches += 1
                                self.shadow_stats.add("batches")
                                self.shadow_stats.add("solo")
                        if self.follower:
                            fold_native_run(run[i:end])
                        self.shadow_stats.add(
                            "stage_s", _time.perf_counter() - t_stage)
                        self._c_dispatch.add(end - i)
                        if stretch_toks:
                            # no h2d seam on the per-batch path (the
                            # upload rides the dispatch): h2d_stage folds
                            # as uncrossed, dispatch absorbs it
                            t_disp = _time.perf_counter_ns()
                            for t in stretch_toks:
                                anat.stamp(t, DLEG_DISPATCH, t_disp)
                            jax.block_until_ready(chk)
                            t_busy = _time.perf_counter_ns()
                            for t in stretch_toks:
                                anat.stamp(t, DLEG_BUSY, t_busy)
                        j = end
                    i = j
            except Exception as e:  # divergence surfaces at finalize
                self._shadow_error = e
            if self.follower:
                # latency anatomy's device-apply LANE: enqueue (commit
                # finalize, event loop) -> dispatched to the device (all
                # of this run's uploads issued). Sampled ops only (slot 8
                # is 0 otherwise); same perf_counter_ns domain both sides.
                t_done = _time.perf_counter_ns()
                for item in run:
                    if item[7]:
                        self._h_apply_lag.observe(
                            (t_done - item[7]) / 1000.0
                        )
            self._consumed_seq += len(run)
            note_applied(run[-1][0], len(run))
            if any_tok:
                # finalize_visible: watermarks/lag gauge updated — the
                # applied op is observable to the event loop
                t_fin = _time.perf_counter_ns()
                for t in toks:
                    if t:
                        anat.finish(t, t_fin)
            if deferred_control is not None:
                if deferred_control[0] == _INSTALL:
                    try:
                        chk, chk_nat, dev_ring = self._apply_install(
                            deferred_control[1], dev_ring
                        )
                    except Exception as e:
                        self._shadow_error = e
                elif deferred_control[0] == _PROBE:
                    try:
                        self._apply_probe(
                            deferred_control[1], deferred_control[2]
                        )
                    except Exception as e:
                        self._shadow_error = e
                self._consumed_seq += 1
            with self._apply_cond:
                self._apply_cond.notify_all()
            if trace_until and _time.monotonic() >= trace_until:
                trace_until = 0.0
                self._stop_trace_window()
        if trace_until:
            self._stop_trace_window()
        # written once at apply-loop exit; finalize() joins before reading
        self._chk_device_scalar = chk  # vet: handoff
        self._chk_native_thread = chk_nat
        self._dev_ring_out = dev_ring

    @staticmethod
    def _corrupt_item(item):
        """Test hook payload: reroute EVERY lane's debit account (or
        ledger) to a nonexistent/invalid value so any valid lane's DEVICE
        reply code diverges from the native engine's (the exact failure
        the hash-log ring must localize). Whole-batch corruption — a
        single-lane flip could land on an event that was already invalid
        and change nothing."""
        op2, opn2, ts2, arr2, codes, prep, tr, lat = item
        bad = arr2.copy()
        if opn2 == Operation.create_transfers:
            bad["debit_account_id_lo"][:] = 0xDEAD_BEEF_DEAD_BEEF
            bad["debit_account_id_hi"][:] = 0xDEAD_BEEF_DEAD_BEEF
        else:
            bad["ledger"][:] = 0  # ledger_must_not_be_zero on valid lanes
        return (op2, opn2, ts2, bad, codes, prep, tr, lat)

    def _apply_install(self, raw: bytes, dev_ring):
        """Handle an _INSTALL control item ON the apply thread: re-seed
        the device tables from a native snapshot's row images
        (DeviceLedger.install_snapshot_rows — h2d only) and reset both
        digest chains/rings: the chains cover the op stream SINCE this
        state, exactly like the native side's restored tables."""
        import jax.numpy as jnp

        # install items are only ever enqueued in follower mode
        # (restore_bytes); both exits restart the chains/rings from the
        # installed state
        fresh_chains = (
            jnp.uint64(0), 0, jnp.zeros(APPLY_RING + 1, dtype=jnp.uint64),
        )
        accounts, transfers, fulfill, commit_ts = _parse_native_snapshot(raw)
        if (
            len(accounts) > self.device._acct_limit
            or len(transfers) > self.device._xfer_limit
        ):
            # snapshot exceeds the device geometry: stand down (finalize
            # reports skipped) rather than overflow the probe windows
            self._restored = True
            return fresh_chains
        # a mid-run state-sync jump installs onto a device that already
        # holds applied rows: reset to fresh first (claim_slots would
        # otherwise give every already-present key a SECOND slot and the
        # occupancy trackers would double-count)
        self.device.reset_state()
        self.hazards = self.device.hazards  # vet: handoff
        self.device.install_snapshot_rows(
            accounts, transfers, fulfill, commit_ts
        )
        for i in range(APPLY_RING):
            self._op_ring[i] = None
        return fresh_chains

    def _apply_probe(self, op: int, fp_host: dict) -> None:
        """Handle a _PROBE control item ON the apply thread: stash the
        DEVICE state fingerprint at a checkpoint-commitment boundary.
        The probe item was enqueued at the boundary op's commit finalize
        — finalizes run in op order, so every create <= op is already in
        the queue ahead of it and none after it — which makes the lazy
        fingerprint exact for the boundary. Dispatch-only (no d2h): the
        scalars materialize at finalize() alongside the digest rings."""
        if self._restored:
            return
        self._probe_out.append((op, fp_host, self.device.fingerprint_lazy()))

    def _commitment_probe_check(self) -> dict:
        """Materialize the probed device fingerprints (finalize-time d2h,
        a handful of scalars per checkpoint) and compare each against the
        host engine's fingerprint recorded in the commitment chain —
        names the FIRST checkpoint where the device twin's state diverged
        from the committed history."""
        from tigerbeetle_tpu.federation.commitment import FP_FIELDS

        first = None
        detail = {}
        for op, fp_host, fp_dev_lazy in self._probe_out:
            fp_dev = {k: int(np.asarray(v)) for k, v in fp_dev_lazy.items()}
            for k in FP_FIELDS:
                if int(fp_host[k]) != int(fp_dev[k]):
                    if first is None:
                        first = op
                        detail = {
                            "field": k,
                            "host": int(fp_host[k]),
                            "device": int(fp_dev[k]),
                        }
                    break
        return {
            "checked": len(self._probe_out),
            "ok": first is None,
            "first_divergent_op": first,
            **detail,
        }

    # -- follower apply seam (driven by the replica at commit finalize) ----

    def apply_commit(
        self,
        op: int,
        operation: Operation,
        timestamp: int,
        arr: np.ndarray,
        codes: np.ndarray,
        prepare_checksum: int = 0,
        trace: int = 0,
        lat_ns: int = 0,
    ) -> None:
        """Enqueue one COMMITTED op for the device applier (follower
        mode): called by the replica at commit finalize, in op order,
        with the event rows (a read-only view over the prepare body) and
        the native engine's dense reply codes. The bounded queue
        backpressures the event loop only as a last resort — admission
        throttling via apply_lag_excess() engages first. `trace` is the
        op's cluster-causal trace id (vsr/header.py): the apply loop tags
        its shadow.upload span with the run's first id, so the device
        hop joins the op's Perfetto flow. `lat_ns` is the latency
        anatomy's enqueue stamp for SAMPLED ops (perf_counter_ns on the
        event loop): the apply loop observes enqueue->device-dispatch
        into latency.device_apply_lag_us — the dual mode's parallel
        lane, never part of the reply's critical-path legs."""
        assert self.follower
        self._enqueued_op = op
        self._enq_ops += 1
        self._put_seq += 1
        self._q.put(
            (op, operation, timestamp, arr, codes, prepare_checksum,
             trace, lat_ns)
        )

    def commitment_probe(self, op: int, fp_host: dict) -> None:
        """Enqueue a checkpoint-commitment fingerprint probe (follower
        mode): called by the replica at the boundary op's commit
        finalize with the HOST engine's fingerprint from the commitment
        chain. The apply thread stashes the device twin's lazy
        fingerprint at the matching point in its queue; finalize()
        compares them per checkpoint."""
        assert self.follower
        self._put_seq += 1
        self._q.put((_PROBE, op, fp_host))

    # -- XLA trace bridge (--device-trace) ---------------------------------

    def start_device_trace(self, out_dir: str, window_s: float = 3.0) -> None:
        """Arm a bounded jax.profiler window: the APPLY thread starts the
        capture at its next dequeue (so the window brackets real apply
        work, not idle), runs it for ~window_s, and stops it after the
        run that crosses the deadline. The profile lands under
        `out_dir/plugins/profile/<ts>/` (gzipped Chrome trace) next to a
        `device_trace_meta.json` clock anchor — scripts/stitch_trace.py
        merges it into the stitched Perfetto file with that anchor."""
        self._trace_dir = out_dir
        self._trace_window_s = float(window_s)
        self._trace_armed = True

    def _start_trace_window(self) -> float:
        """APPLY thread: begin the capture + write the clock anchor.
        Returns the monotonic deadline (0.0 on failure)."""
        import json
        import os
        import time as _time

        import jax

        try:
            os.makedirs(self._trace_dir, exist_ok=True)
            jax.profiler.start_trace(self._trace_dir)
            anchor_ns = _time.perf_counter_ns()
            meta = {
                # perf_counter_ns at profiler start: our spans' clock at
                # the device timeline's t~0 (alignment is ~ms-accurate —
                # good enough to line kernels up under their spans)
                "anchor_perf_ns": anchor_ns,
                "anchor_unix_s": round(_time.time(), 6),
                "window_s": self._trace_window_s,
            }
            with open(
                os.path.join(self._trace_dir, "device_trace_meta.json"), "w"
            ) as f:
                json.dump(meta, f, indent=1)
            self.metrics.counter("device.trace_windows").add()
            return _time.monotonic() + self._trace_window_s
        except Exception as e:  # profiling must never take the applier down
            self._trace_dir = f"<failed: {e}>"
            return 0.0

    def _stop_trace_window(self) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass

    def apply_lag_ops(self) -> int:
        """Committed-but-not-yet-device-applied CREATE ops (enqueued
        items minus consumed items — one item per committed create op;
        op-number distance would misread interleaved lookups/registers
        and the post-restart op jump as phantom lag). Applied means
        dispatched to the device: the kernels execute in stream order
        behind it, and nothing on the host ever waits on them."""
        return max(0, self._enq_ops - self._done_ops)

    def apply_lag_excess(self) -> int:
        """Lag beyond the admission window — the saturation signal
        Replica.ingress_occupancy adds to its pipeline occupancy so the
        credit regulator sheds before the apply queue's put() blocks."""
        return max(0, self.apply_lag_ops() - self.lag_window)

    def drain_applier(self, timeout: float = 600.0) -> bool:
        """Block until every enqueued item (ops and control items) has
        been consumed by the apply loop — the checkpoint/state-sync
        barrier. Returns False on timeout or a dead apply thread."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._apply_cond:
            while self._consumed_seq < self._put_seq:
                if not self._thread.is_alive():
                    return False
                left = deadline - _time.monotonic()
                if left <= 0 or not self._apply_cond.wait(timeout=min(left, 1.0)):
                    if _time.monotonic() >= deadline:
                        return False
        return True

    def _enqueue_shadow(self, operation, timestamp: int, arr) -> None:
        # the queue bounds host-memory growth; a full queue briefly
        # backpressures the event loop rather than dropping shadow batches
        # (a dropped batch would be an unverifiable run, not a fast one)
        self._q.put((None, operation, timestamp, arr, None, 0, 0, 0))

    def _fold_native(self, pending) -> None:
        """Chain the native codes into the host-side digest when the engine
        worker completes the batch (FIFO worker => stream order matches the
        shadow queue's). Shadow mode only — the follower folds on the
        apply thread with op numbers instead."""
        from tigerbeetle_tpu.models.ledger import fold_reply_codes_np

        def _cb(_fut, codes=pending.codes):
            with self._chk_lock:
                self._chk_native = fold_reply_codes_np(self._chk_native, codes)

        pending.fut.add_done_callback(_cb)

    # -- backend protocol (reply path: native) ----------------------------

    @property
    def prepare_timestamp(self) -> int:
        return self.native.prepare_timestamp

    @prepare_timestamp.setter
    def prepare_timestamp(self, value: int) -> None:
        self.native.prepare_timestamp = value

    def prepare(self, operation: Operation, event_count: int) -> None:
        self.native.prepare(operation, event_count)

    def execute_async(self, operation, timestamp: int, events):
        arr = events if isinstance(events, np.ndarray) else None
        pending = self.native.execute_async(operation, timestamp, events)
        if self.follower:
            return pending  # the replica enqueues at commit finalize
        if operation in (Operation.create_accounts, Operation.create_transfers):
            if arr is None:
                # list-of-objects path (REPL/tests): reuse the bytes the
                # native wrapper built
                from tigerbeetle_tpu import types as _t

                arr = (
                    _t.accounts_to_np(events)
                    if operation == Operation.create_accounts
                    else _t.transfers_to_np(events)
                )
            self._fold_native(pending)
            self._enqueue_shadow(operation, timestamp, arr)
        return pending

    def try_execute_group_async(self, items):
        pendings = self.native.try_execute_group_async(items)
        if pendings is None:
            return None
        if not self.follower:
            for (ts, arr), p in zip(items, pendings):
                self._fold_native(p)
                self._enqueue_shadow(Operation.create_transfers, ts, arr)
        return pendings

    def drain(self, pending):
        return self.native.drain(pending)

    def drain_many(self, pendings) -> None:
        self.native.drain_many(pendings)

    def drain_reply(self, pending, operation) -> bytes:
        return self.native.drain_reply(pending, operation)

    def execute_dense(self, operation, timestamp: int, events):
        return self.drain(self.execute_async(operation, timestamp, events))

    def execute(self, operation, timestamp: int, events):
        dense = self.execute_dense(operation, timestamp, events)
        return [(i, c) for i, c in enumerate(dense) if c]

    def lookup_rows(self, operation: Operation, ids) -> bytes:
        return self.native.lookup_rows(operation, ids)

    def lookup_accounts(self, ids):
        return self.native.lookup_accounts(ids)

    def lookup_transfers(self, ids):
        return self.native.lookup_transfers(ids)

    def counts(self) -> dict:
        return self.native.counts()

    @property
    def commit_timestamp(self) -> int:
        return self.native.commit_timestamp

    def fingerprint(self) -> dict:
        """The host engine's state digest (commitment chain input). The
        device twin's fingerprint is compared per checkpoint at
        finalize() via the commitment_probe seam."""
        return self.native.fingerprint()

    def snapshot_bytes(self) -> bytes:
        return self.native.snapshot_bytes()

    def restore_bytes(self, raw: bytes) -> None:
        self.native.restore_bytes(raw)
        if self.follower:
            # Re-seed the device from the SAME snapshot's row images (the
            # row-level upload path: h2d staging + insert kernels, no
            # d2h) — queued as a control item so it serializes with any
            # in-flight applies; the replica drains the applier before
            # any state-replacing restore (checkpoint/state-sync
            # contract). Digest chains/rings reset with the state.
            if len(raw) <= 64:
                return  # fresh/empty snapshot: nothing to install
            self._put_seq += 1
            self._q.put((_INSTALL, raw))
            return
        # Shadow mode: the device table cannot be rebuilt from a
        # mid-history snapshot (no op-tagged apply seam); the shadow
        # stands down and finalize() reports it (bench/format-fresh runs
        # never hit this).
        if len(raw) > 64 and self.native.counts()["accounts"] > 0:
            self._restored = True

    # -- shutdown verification --------------------------------------------

    def _shadow_report(self) -> dict:
        """Apply-loop cost/overlap summary for the [stats] line. The
        upload_overlap ratio is the fraction of fused groups whose staging
        + dispatch completed while the previous group's kernel was still
        executing — 1.0 means the h2d path never waited on the device."""
        s = dict(self.shadow_stats)
        s["stage_s"] = round(s["stage_s"], 3)
        s["idle_s"] = round(s["idle_s"], 3)
        s["upload_overlap"] = (
            round(s["overlapped"] / s["groups"], 4) if s["groups"] else None
        )
        if self.follower:
            s["applied_op"] = self._applied_op
            s["lag_ops"] = self.apply_lag_ops()
            # worst sampled apply items with their sub-leg breakdowns
            # (the commit_wait decomposition, latency.py DeviceAnatomy)
            ds = self.device_anatomy.slowest(4)
            if ds:
                s["device_slowest"] = ds
        return s

    def _hash_ring_check(self) -> dict:
        """Walk the host/device per-op digest rings (one ring fetch — the
        finalize-time d2h) and name the FIRST divergent op, the
        -Dhash-log-mode check across engines. Only meaningful in follower
        mode (shadow mode has no op numbers)."""
        dev = np.asarray(self._dev_ring_out)
        entries = sorted(
            (e for e in self._op_ring if e is not None), key=lambda e: e[0]
        )
        first = None
        want = got = prep = 0
        for op, prep_chk, nat_chk in entries:
            dv = int(dev[op % APPLY_RING])
            if dv != nat_chk:
                first, want, got, prep = op, nat_chk, dv, prep_chk
                break
        return {
            "ops": len(entries),
            "ok": first is None,
            "first_divergent_op": first,
            **(
                # the op's PREPARE checksum ties the divergence back to
                # the consensus stream (hash_log's prepare half): grep it
                # in a --hash-log recording / the WAL to find the exact
                # batch both engines executed
                {"want": want, "got": got, "prepare": f"{prep:#x}"}
                if first is not None else {}
            ),
        }

    def finalize(self, timeout: float = 600.0) -> dict:
        """Drain the apply queue, then do the process's FIRST d2h reads:
        compare the two reply-code digests, the per-op digest rings
        (follower mode), and the two state fingerprints. Returns the
        verification report the server prints on its [stats] line."""
        self._q.put(_STOP)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            return {"verified": False, "error": "shadow drain timed out",
                    "shadow": self._shadow_report()}
        if self._restored:
            return {
                "verified": None,
                "skipped": "snapshot restore: shadow stood down",
            }
        if self._shadow_error is not None:
            return {
                "verified": False,
                "error": f"{type(self._shadow_error).__name__}: "
                f"{self._shadow_error}",
            }
        try:
            self.device.check_fault()  # deferred fault word: report, not
        except Exception as e:         # crash — the [stats] line must land
            return {
                "verified": False,
                "error": f"{type(e).__name__}: {e}",
            }
        chk_dev = int(np.asarray(self._chk_device_scalar))
        if self.follower:
            chk_nat = self._chk_native_thread
        else:
            # Barrier through the engine's FIFO worker: a job submitted
            # now starts only after every prior execute's future has
            # resolved AND run its inline done-callbacks (the fold chain)
            # on the worker thread — Future.result() alone wakes waiters
            # BEFORE callbacks, so without this the last batch's fold
            # could be missing.
            self.native._submit(lambda: 0).result()
            with self._chk_lock:
                chk_nat = self._chk_native
        fp_nat = self.native.fingerprint()
        fp_dev = self.device.fingerprint()
        ok = (
            chk_nat == chk_dev
            and fp_nat["accounts_fp"] == fp_dev["accounts_fp"]
            and fp_nat["transfers_fp"] == fp_dev["transfers_fp"]
            and fp_nat["accounts"] == fp_dev["accounts"]
            and fp_nat["transfers"] == fp_dev["transfers"]
            and fp_nat["commit_timestamp"] == fp_dev["commit_timestamp"]
        )
        report = {
            "verified": bool(ok),
            "shadow_batches": self._shadow_batches,
            "shadow": self._shadow_report(),
            "code_stream_digest": {"native": chk_nat, "device": chk_dev},
            "fingerprint_native": fp_nat,
            "fingerprint_device": fp_dev,
        }
        if self.follower and self._dev_ring_out is not None:
            report["hash_log"] = self._hash_ring_check()
            if not report["hash_log"]["ok"]:
                report["verified"] = False
        if self._probe_out:
            report["commitments"] = self._commitment_probe_check()
            if not report["commitments"]["ok"]:
                report["verified"] = False
        return report


def _parse_native_snapshot(raw: bytes):
    """Decode the native engine's snapshot blob (native/ledger.cc
    tb_ledger_snapshot layout: 64-byte header, live account rows, live
    transfer rows, posted {ts, val} pairs) into the wire-row arrays +
    per-transfer fulfill column DeviceLedger.install_snapshot_rows
    ingests. Host-side numpy only."""
    from tigerbeetle_tpu import types

    head = np.frombuffer(raw[:64], dtype=np.uint64)
    n_a, n_t, n_p = int(head[0]), int(head[1]), int(head[2])
    commit_ts = int(head[3])
    off = 64
    accounts = np.frombuffer(
        raw[off : off + n_a * 128], dtype=types.ACCOUNT_DTYPE
    )
    off += n_a * 128
    transfers = np.frombuffer(
        raw[off : off + n_t * 128], dtype=types.TRANSFER_DTYPE
    )
    off += n_t * 128
    posted = np.frombuffer(
        raw[off : off + n_p * 16], dtype=np.uint64
    ).reshape(n_p, 2)
    # posted pairs key the PENDING transfer by its timestamp; the device
    # keeps the same fact in the fulfill column 1:1 with transfer rows
    fulfill = np.zeros(n_t, dtype=np.uint32)
    if n_p and n_t:
        order = np.argsort(posted[:, 0])
        pts = posted[order, 0]
        pvals = posted[order, 1]
        idx = np.searchsorted(pts, transfers["timestamp"])
        idxc = np.minimum(idx, len(pts) - 1)
        match = pts[idxc] == transfers["timestamp"]
        fulfill = np.where(match, pvals[idxc], 0).astype(np.uint32)
    return accounts, transfers, fulfill, commit_ts
