"""DualLedger: native C++ engine serves replies, the TPU shadows every
prepare — the `--backend native+device` durable mode.

The problem this solves (round-4 verdict): on this environment's tunneled
TPU, ANY device->host fetch permanently degrades the dispatch path
(models/native_ledger.py), so a reply-serving server cannot run its hot
loop through the device — but that blocks *reply-from-device*, not
*commit-on-device*. Here the native engine (native/ledger.cc) computes
reply codes at host speed, while a background shadow thread applies the
SAME prepares, same timestamps, same order, to the JAX DeviceLedger —
host->device uploads and kernel launches only, nothing ever read back
until shutdown. Device state is REAL state: maintained batch-by-batch by
the same commit kernels the flagship benchmark measures.

Verification (hash_log semantics, testing/hash_log.py):
- every batch's dense reply codes are folded into a chained u64 digest on
  BOTH sides — on device (fold_reply_codes, no d2h) and on host over the
  native engine's codes (fold_reply_codes_np, chained off the engine
  worker's completion callbacks, same FIFO order);
- at shutdown, finalize() drains the shadow queue and does the process's
  FIRST device->host reads: the two fold scalars must match (the full
  reply-code stream was bit-identical), and state_fingerprint — an
  order-independent digest over every live account/transfer row's 128-byte
  wire image, implemented identically in C++ (tb_ledger_fingerprint) and
  JAX (models/ledger.py state_fingerprint) — must match row-set for
  row-set.

Reference seam: src/state_machine.zig:508-540 — commit determinism is the
consensus invariant; the dual mode extends it across heterogeneous engines
(the reference's simulator cross-checks replicas the same way,
src/testing/cluster/state_checker.zig).
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from tigerbeetle_tpu.constants import ConfigProcess
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.models.native_ledger import NativeLedger
from tigerbeetle_tpu.tracer import NULL_TRACER
from tigerbeetle_tpu.types import Operation

_STOP = object()

_FOLD_GROUP_CACHE: dict = {}


def _fold_group_fn(k: int, n_pad: int):
    """Jitted chained fold over a fused group's flat results: one dispatch
    folds up to k batches' code streams (active-masked — padding slots
    must NOT advance the chain, the native side folds only real batches).
    Digest-identical to k sequential fold_reply_codes calls: the per-batch
    mix only sums lanes < n, so the trailing fault word / slot layout
    never contributes."""
    fn = _FOLD_GROUP_CACHE.get((k, n_pad))
    if fn is None:
        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu.models.ledger import fold_reply_codes

        def f(chk, flat, ns, active):
            flat2 = flat[: k * n_pad].reshape(k, n_pad)

            def body(c, x):
                res, n, a = x
                return jnp.where(
                    a, fold_reply_codes(c, res, n), c
                ), None

            c2, _ = jax.lax.scan(body, chk, (flat2, ns, active))
            return c2

        fn = _FOLD_GROUP_CACHE[(k, n_pad)] = jax.jit(f)
    return fn


class DualLedger:
    """Replica backend: NativeLedger semantics + an asynchronous device
    shadow. All reply-serving calls delegate to the native engine; the
    device never blocks (or touches) the reply path."""

    zero_copy_events = True  # both consumers only read the event rows

    SHADOW_KEYS = (
        "batches", "groups", "solo", "stage_s", "idle_s", "overlapped",
    )

    def instrument(self, metrics, tracer) -> None:
        """Re-bind onto a shared registry/tracer (the replica's).
        Accumulated values carry over; the shadow loop reads
        self.shadow_stats/self.tracer per use. A shadow update racing
        the carry-over/rebind window lands in the discarded old group
        and is DROPPED from the new registry — at most one update, and
        instrument() runs at setup before commits flow, so nothing of
        record is lost."""
        for key in self.SHADOW_KEYS:
            metrics.counter(f"shadow.{key}").add(self.shadow_stats[key])
        self.metrics = metrics
        # rebound on the event loop while the shadow thread reads per
        # use — a GIL-atomic reference swap, never a torn value; see the
        # docstring for the (setup-time-only) dropped-update window
        self.tracer = tracer  # vet: handoff
        # registry-backed StatGroup; Counter.add serializes internally
        self.shadow_stats = metrics.group(  # vet: handoff
            "shadow", self.SHADOW_KEYS
        )
        # the shadow DeviceLedger's own instrumentation (group staging
        # fence waits) reports into the same store
        self.device.instrument(metrics, tracer)

    def __init__(
        self,
        acct_slots_log2: int = 16,
        xfer_slots_log2: int = 20,
        queue_max: int = 256,
        warm_kernels: bool = False,
    ):
        self.native = NativeLedger(acct_slots_log2, xfer_slots_log2)
        from tigerbeetle_tpu.models.ledger import DeviceLedger

        process = ConfigProcess(
            account_slots_log2=acct_slots_log2,
            transfer_slots_log2=xfer_slots_log2,
        )
        # Warm the device kernels BEFORE serving (the server path sets
        # warm_kernels): an in-window compile would stall the shadow until
        # the bounded queue fills and then block the reply path (measured:
        # a 2M-transfer run collapsed from ~960k to ~108k TPS exactly this
        # way). Warming runs BEFORE the real ledger is allocated so the
        # scratch tables never double device memory; with the persistent
        # compilation cache (package __init__) only the first-ever server
        # pays real compiles here — later boots load from disk in seconds.
        if warm_kernels:
            self._warm_device_kernels(process)
        self.device = DeviceLedger(process=process, mode="auto")
        self.device.prefetch_results = False  # NO d2h until finalize()
        self.process = None  # replica duck-typing (native backend shape)
        self.spill = None
        self.hazards = self.device.hazards  # [stats] observability
        # chained digests of the dense reply-code stream (hash_log pair);
        # folded on the native engine's done-callbacks, read at finalize
        self._chk_native = 0  # vet: guarded-by=_chk_lock
        self._chk_lock = threading.Lock()
        # written only by the shadow thread; finalize() joins the thread
        # before reading either (join-before-read)
        self._shadow_error: Exception | None = None  # vet: handoff
        self._shadow_batches = 0  # vet: handoff
        # shadow-loop cost accounting (the h2d/staging tax shares the core
        # with the reply-serving event loop): stage_s = host time spent
        # staging + dispatching shadow work; idle_s = blocked on an empty
        # queue; overlapped = groups whose staging/dispatch completed
        # while the PREVIOUS group's kernel was still executing (the
        # double-buffer pipeline working as intended). BENCH reports
        # overlapped/groups as shadow_upload_overlap. Registry-backed
        # (metrics.py StatGroup under `shadow.`): instrument() re-binds
        # onto the replica's shared registry so the [stats] line and the
        # bench read the same store.
        self.metrics = Metrics()
        self.tracer = NULL_TRACER
        self.shadow_stats = self.metrics.group("shadow", self.SHADOW_KEYS)
        # device cannot follow a snapshot restore. Set on the event loop,
        # polled by the shadow loop: a GIL-atomic bool flip whose one-
        # iteration staleness only delays the stand-down by a batch
        self._restored = False  # vet: handoff
        # the queue IS the cross-thread handoff (bounded, blocking put)
        self._q: queue.Queue = queue.Queue(maxsize=queue_max)  # vet: handoff
        self._thread = threading.Thread(
            target=self._shadow_loop, name="device-shadow", daemon=True
        )
        self._thread.start()

    def _warm_device_kernels(self, process: ConfigProcess) -> None:
        """Compile the kernel set the shadow will hit, against a SCRATCH
        ledger of the same geometry (kernels are shared per ConfigProcess
        — models.ledger.get_kernels — so the real ledger reuses every
        compile; scratch state is freed before the real tables allocate).
        Covers: accounts commit, transfers fast tier, fast_pv (posts),
        group steppers (both fused capacities), the results summarizer,
        and the fold kernels, all at the wire batch pad. Rare tiers
        (serial residue at odd pads) compile on demand — the 256-slot
        queue absorbs those stalls."""
        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu import types
        from tigerbeetle_tpu.constants import BATCH_PAD, BENCH_BATCH
        from tigerbeetle_tpu.models.ledger import (
            DeviceLedger,
            fold_reply_codes,
        )

        scratch = DeviceLedger(process=process, mode="auto")
        scratch.prefetch_results = False
        # ~10n transfer rows + n accounts land in the scratch tables; the
        # warm batch shrinks for small-table configs (then it warms a
        # smaller pad — still useful, and the guard never trips)
        n = min(
            BENCH_BATCH,
            scratch._xfer_limit // 12,
            scratch._acct_limit // 2,
        )
        if n < 2:
            return  # simple() needs two distinct accounts (mod n-1)
        # full wire batches pad to BATCH_PAD (the driver's steady state);
        # odd tail sizes compile on demand behind the queue
        if n == BENCH_BATCH:
            scratch.pad_to = BATCH_PAD  # the wire-batch pad the real
            # ledger resolves to for full 8190-event batches
        pad = scratch._pad_for(n)
        ts = 1 << 40

        acct = np.zeros(n, dtype=types.ACCOUNT_DTYPE)
        acct["id_lo"] = np.arange(1, n + 1, dtype=np.uint64)
        acct["ledger"] = 1
        acct["code"] = 1
        ts += n
        scratch.execute_async(Operation.create_accounts, ts, acct)

        def simple(base):
            x = np.zeros(n, dtype=types.TRANSFER_DTYPE)
            x["id_lo"] = np.arange(base, base + n, dtype=np.uint64)
            x["debit_account_id_lo"] = 1 + np.arange(n) % (n - 1)
            x["credit_account_id_lo"] = 1 + (np.arange(n) + 1) % (n - 1)
            x["amount_lo"] = 1
            x["ledger"] = 1
            x["code"] = 1
            return x

        # fast tier + summarizer
        ts += n
        scratch.execute_async(
            Operation.create_transfers, ts, simple(1_000_000)
        )
        # pending batch, then a full post batch -> the fast_pv tier
        pend = simple(2_000_000)
        pend["flags"] = 2
        ts += n
        scratch.execute_async(Operation.create_transfers, ts, pend)
        post = np.zeros(n, dtype=types.TRANSFER_DTYPE)
        post["id_lo"] = np.arange(3_000_000, 3_000_000 + n, dtype=np.uint64)
        post["pending_id_lo"] = pend["id_lo"]
        post["flags"] = 4
        ts += n
        scratch.execute_async(Operation.create_transfers, ts, post)
        # both fused group capacities (the replica's group commit) + the
        # shadow's fused group-fold kernel over each
        for k in (5, 2):  # 5 -> the 16-slot stepper, 2 -> the 4-slot
            items = []
            for j in range(k):
                ts += n
                items.append((ts, simple(4_000_000 + j * n)))
            pendings = scratch.try_execute_group_async(items)
            if pendings is not None:
                g = pendings[0].group
                ns = np.zeros(g.k, dtype=np.int32)
                ns[:k] = [len(a) for _, a in items]
                active = np.zeros(g.k, dtype=bool)
                active[:k] = True
                _fold_group_fn(g.k, g.n_pad)(
                    jnp.uint64(0), g.results, jnp.asarray(ns),
                    jnp.asarray(active),
                )
        # the shadow's fold kernel
        chk = jax.jit(fold_reply_codes)(
            jnp.uint64(0),
            jnp.zeros(pad + 1, dtype=jnp.uint32),
            jnp.int32(1),
        )
        # block WITHOUT fetching: any device->host read here would
        # permanently degrade this process's tunnel transport before the
        # server ever serves (the whole reason the dual mode exists)
        jax.block_until_ready(chk)

    # -- the device shadow ------------------------------------------------

    def _shadow_loop(self) -> None:
        import time as _time

        import jax
        import jax.numpy as jnp

        from tigerbeetle_tpu.models.ledger import DeviceLedger, fold_reply_codes

        fold = jax.jit(fold_reply_codes)
        chk = jnp.uint64(0)
        group_max = DeviceLedger.GROUP_KS[0]
        prev_flat = None  # previous fused group's results (overlap probe)
        stop = False
        while not stop:
            t_wait = _time.perf_counter()
            run = [self._q.get()]
            self.shadow_stats.add("idle_s", _time.perf_counter() - t_wait)
            if run[0] is _STOP:
                break
            # drain a run of queued create_transfers batches: one fused
            # group dispatch covers up to GROUP_KS[0] of them — per-batch
            # host work (hazard analysis, upload, launch) is the shadow's
            # dominant cost on a single-core host, and it shares that core
            # with the reply-serving event loop
            while (
                len(run) < group_max
                and run[-1][0] == Operation.create_transfers
            ):
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                run.append(nxt)
            if self._shadow_error is not None or self._restored:
                continue  # drain without applying; finalize reports why
            try:
                i = 0
                while i < len(run):
                    # longest create_transfers stretch from i
                    j = i
                    while (
                        j < len(run)
                        and run[j][0] == Operation.create_transfers
                    ):
                        j += 1
                    pendings = None
                    if j - i >= 2:
                        t_stage = _time.perf_counter()
                        with self.tracer.span("shadow.upload",
                                              batches=j - i):
                            pendings = self.device.try_execute_group_async(
                                [(t, a) for _, t, a in run[i:j]]
                            )
                    if pendings is not None:
                        g = pendings[0].group
                        m = j - i
                        ns = np.zeros(g.k, dtype=np.int32)
                        ns[:m] = [len(a) for _, _, a in run[i:j]]
                        active = np.zeros(g.k, dtype=bool)
                        active[:m] = True
                        chk = _fold_group_fn(g.k, g.n_pad)(
                            chk, g.results, jnp.asarray(ns),
                            jnp.asarray(active),
                        )
                        self._shadow_batches += m
                        stats = self.shadow_stats
                        stats.add("batches", m)
                        stats.add("groups")
                        stats.add("stage_s", _time.perf_counter() - t_stage)
                        if prev_flat is not None and not prev_flat.is_ready():
                            # this group's staging + dispatch finished
                            # while the previous kernel was still running:
                            # the upload pipeline overlapped execution
                            stats.add("overlapped")
                        prev_flat = g.results
                    else:
                        # fusion refused (a batch failed the fast-tier
                        # proof) or too short: run the stretch per-batch —
                        # re-probing fusion at every offset would redo the
                        # vectorized hazard analysis O(k^2) times on the
                        # core the event loop needs. j == i means run[i]
                        # is not create_transfers (accounts): one batch.
                        end = j if j > i else i + 1
                        t_stage = _time.perf_counter()
                        with self.tracer.span("shadow.upload",
                                              batches=end - i, solo=True):
                            for op2, ts2, arr2 in run[i:end]:
                                pending = self.device.execute_async(
                                    op2, ts2, arr2
                                )
                                chk = fold(
                                    chk, pending.results,
                                    jnp.int32(len(arr2)),
                                )
                                self._shadow_batches += 1
                                self.shadow_stats.add("batches")
                                self.shadow_stats.add("solo")
                        self.shadow_stats.add(
                            "stage_s", _time.perf_counter() - t_stage)
                        j = end
                    i = j
            except Exception as e:  # divergence surfaces at finalize
                self._shadow_error = e
        # written once at shadow-loop exit; finalize() joins before reading
        self._chk_device_scalar = chk  # vet: handoff

    def _enqueue_shadow(self, operation, timestamp: int, arr) -> None:
        # the queue bounds host-memory growth; a full queue briefly
        # backpressures the event loop rather than dropping shadow batches
        # (a dropped batch would be an unverifiable run, not a fast one)
        self._q.put((operation, timestamp, arr))

    def _fold_native(self, pending) -> None:
        """Chain the native codes into the host-side digest when the engine
        worker completes the batch (FIFO worker => stream order matches the
        shadow queue's)."""
        from tigerbeetle_tpu.models.ledger import fold_reply_codes_np

        def _cb(_fut, codes=pending.codes):
            with self._chk_lock:
                self._chk_native = fold_reply_codes_np(self._chk_native, codes)

        pending.fut.add_done_callback(_cb)

    # -- backend protocol (reply path: native) ----------------------------

    @property
    def prepare_timestamp(self) -> int:
        return self.native.prepare_timestamp

    @prepare_timestamp.setter
    def prepare_timestamp(self, value: int) -> None:
        self.native.prepare_timestamp = value

    def prepare(self, operation: Operation, event_count: int) -> None:
        self.native.prepare(operation, event_count)

    def execute_async(self, operation, timestamp: int, events):
        arr = events if isinstance(events, np.ndarray) else None
        pending = self.native.execute_async(operation, timestamp, events)
        if operation in (Operation.create_accounts, Operation.create_transfers):
            if arr is None:
                # list-of-objects path (REPL/tests): reuse the bytes the
                # native wrapper built
                from tigerbeetle_tpu import types as _t

                arr = (
                    _t.accounts_to_np(events)
                    if operation == Operation.create_accounts
                    else _t.transfers_to_np(events)
                )
            self._fold_native(pending)
            self._enqueue_shadow(operation, timestamp, arr)
        return pending

    def try_execute_group_async(self, items):
        pendings = self.native.try_execute_group_async(items)
        if pendings is None:
            return None
        for (ts, arr), p in zip(items, pendings):
            self._fold_native(p)
            self._enqueue_shadow(Operation.create_transfers, ts, arr)
        return pendings

    def drain(self, pending):
        return self.native.drain(pending)

    def drain_many(self, pendings) -> None:
        self.native.drain_many(pendings)

    def drain_reply(self, pending, operation) -> bytes:
        return self.native.drain_reply(pending, operation)

    def execute_dense(self, operation, timestamp: int, events):
        return self.drain(self.execute_async(operation, timestamp, events))

    def execute(self, operation, timestamp: int, events):
        dense = self.execute_dense(operation, timestamp, events)
        return [(i, c) for i, c in enumerate(dense) if c]

    def lookup_rows(self, operation: Operation, ids) -> bytes:
        return self.native.lookup_rows(operation, ids)

    def lookup_accounts(self, ids):
        return self.native.lookup_accounts(ids)

    def lookup_transfers(self, ids):
        return self.native.lookup_transfers(ids)

    def counts(self) -> dict:
        return self.native.counts()

    @property
    def commit_timestamp(self) -> int:
        return self.native.commit_timestamp

    def snapshot_bytes(self) -> bytes:
        return self.native.snapshot_bytes()

    def restore_bytes(self, raw: bytes) -> None:
        self.native.restore_bytes(raw)
        # The device table cannot be rebuilt from a mid-history snapshot
        # without a row-level upload path; the shadow stands down and
        # finalize() reports it (bench/format-fresh runs never hit this).
        if len(raw) > 64 and self.native.counts()["accounts"] > 0:
            self._restored = True

    # -- shutdown verification --------------------------------------------

    def _shadow_report(self) -> dict:
        """Shadow-loop cost/overlap summary for the [stats] line. The
        upload_overlap ratio is the fraction of fused groups whose staging
        + dispatch completed while the previous group's kernel was still
        executing — 1.0 means the h2d path never waited on the device."""
        s = dict(self.shadow_stats)
        s["stage_s"] = round(s["stage_s"], 3)
        s["idle_s"] = round(s["idle_s"], 3)
        s["upload_overlap"] = (
            round(s["overlapped"] / s["groups"], 4) if s["groups"] else None
        )
        return s

    def finalize(self, timeout: float = 600.0) -> dict:
        """Drain the shadow, then do the process's FIRST d2h reads: compare
        the two reply-code digests and the two state fingerprints. Returns
        the verification report the server prints on its [stats] line."""
        self._q.put(_STOP)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            return {"verified": False, "error": "shadow drain timed out",
                    "shadow": self._shadow_report()}
        if self._restored:
            return {
                "verified": None,
                "skipped": "snapshot restore: shadow stood down",
            }
        if self._shadow_error is not None:
            return {
                "verified": False,
                "error": f"{type(self._shadow_error).__name__}: "
                f"{self._shadow_error}",
            }
        try:
            self.device.check_fault()  # deferred fault word: report, not
        except Exception as e:         # crash — the [stats] line must land
            return {
                "verified": False,
                "error": f"{type(e).__name__}: {e}",
            }
        chk_dev = int(np.asarray(self._chk_device_scalar))
        # Barrier through the engine's FIFO worker: a job submitted now
        # starts only after every prior execute's future has resolved AND
        # run its inline done-callbacks (the fold chain) on the worker
        # thread — Future.result() alone wakes waiters BEFORE callbacks,
        # so without this the last batch's fold could be missing.
        self.native._submit(lambda: 0).result()
        with self._chk_lock:
            chk_nat = self._chk_native
        fp_nat = self.native.fingerprint()
        fp_dev = self.device.fingerprint()
        ok = (
            chk_nat == chk_dev
            and fp_nat["accounts_fp"] == fp_dev["accounts_fp"]
            and fp_nat["transfers_fp"] == fp_dev["transfers_fp"]
            and fp_nat["accounts"] == fp_dev["accounts"]
            and fp_nat["transfers"] == fp_dev["transfers"]
            and fp_nat["commit_timestamp"] == fp_dev["commit_timestamp"]
        )
        return {
            "verified": bool(ok),
            "shadow_batches": self._shadow_batches,
            "shadow": self._shadow_report(),
            "code_stream_digest": {"native": chk_nat, "device": chk_dev},
            "fingerprint_native": fp_nat,
            "fingerprint_device": fp_dev,
        }
