"""NativeLedger: ctypes wrapper over the C++ host ledger engine
(native/ledger.cc).

The durable replicated server's commit backend: the reference's state
machine is a CPU engine (reference: src/state_machine.zig:612-1077), and
on this environment's tunneled TPU any device->host fetch permanently
degrades the dispatch path (measured in ops/hashtable.py; h2d collapses to
~14 MiB/s), so a reply-serving server cannot run its hot loop through the
device. The native engine computes reply codes at host speed with EXACT
result-code parity against the Python oracle and the JAX DeviceLedger
(tests/test_native_ledger.py), while the DeviceLedger remains the TPU
compute path (flagship throughput, sharded mesh, HBM-resident analytics).

Implements the same backend protocol the Replica/StateMachine drive:
prepare / execute_async / drain / drain_reply / lookup_rows /
snapshot_bytes / restore_bytes.
"""

from __future__ import annotations

import ctypes
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from tigerbeetle_tpu import native, types
from tigerbeetle_tpu.types import Operation


class _NativePending:
    """Pending handle for a commit running on the engine worker thread
    (ctypes releases the GIL during tb_ledger_execute, so the event loop
    keeps receiving/journaling batch N+1 while batch N executes — the
    replica's commit-stage overlap, reference: src/vsr/replica.zig:52-70).
    Commits stay serial: ONE worker, FIFO."""

    __slots__ = ("operation", "n", "codes", "failures", "results", "group",
                 "summary", "dense", "fut", "arr")

    def __init__(self, operation, n, codes, fut, arr):
        self.operation = operation
        self.n = n
        self.codes = codes  # np.uint32 dense result codes (filled by fut)
        self.fut: Future = fut  # resolves to the failure count
        self.arr = arr  # keeps the zero-copy event rows alive until done
        self.failures = None
        self.results = None
        self.group = None
        self.summary = None
        self.dense = None

    def is_ready(self) -> bool:
        return self.fut.done()

    def wait(self) -> None:
        if self.failures is None:
            self.failures = int(self.fut.result())
            self.arr = None
            assert self.failures >= 0, "tb_ledger_execute: invalid arguments"


class NativeLedger:
    process = None  # no device table geometry (Replica backend duck-typing)
    zero_copy_events = True  # engine only reads event rows (no defensive copy)

    def __init__(self, acct_slots_log2: int = 16, xfer_slots_log2: int = 20):
        self._lib = native.lib()
        self._h = self._lib.tb_ledger_new(acct_slots_log2, xfer_slots_log2)
        assert self._h
        self.prepare_timestamp = 0
        # ONE worker = serial commits in submission order; lookups ride the
        # same queue so reads see every prior commit (linearizable at the
        # engine seam).
        self._executor: ThreadPoolExecutor | None = None

    def _submit(self, fn, *args) -> Future:
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="native-ledger"
            )
        return self._executor.submit(fn, *args)

    def __del__(self):
        try:
            ex = getattr(self, "_executor", None)
            if ex is not None:
                ex.shutdown(wait=True)
            h = getattr(self, "_h", None)
            if h:
                self._lib.tb_ledger_free(h)
                self._h = None
        except Exception:
            pass  # interpreter teardown: modules may already be gone

    # -- lifecycle (oracle-compatible) --

    def prepare(self, operation: Operation, event_count: int) -> None:
        if operation in (Operation.create_accounts, Operation.create_transfers):
            self.prepare_timestamp += event_count

    # -- execution --

    def _events_bytes(self, operation, events) -> tuple[bytes, int]:
        # ndarray inputs never reach here: execute_async takes the
        # zero-copy pointer path for them
        if events and not isinstance(events[0], (bytes, bytearray)):
            arr = (
                types.accounts_to_np(events)
                if operation == Operation.create_accounts
                else types.transfers_to_np(events)
            )
            return arr.tobytes(), len(arr)
        raw = b"".join(events) if events else b""
        return raw, len(raw) // 128

    def execute_async(self, operation, timestamp: int, events) -> _NativePending:
        arr = None
        if isinstance(events, np.ndarray):
            arr = np.ascontiguousarray(events)  # zero-copy pass-through
            n = len(arr)
            raw = arr.ctypes.data_as(ctypes.c_char_p)
        else:
            raw, n = self._events_bytes(operation, events)
        codes = np.empty(n, dtype=np.uint32)
        fut = self._submit(
            self._lib.tb_ledger_execute,
            self._h, int(operation), raw, n, timestamp,
            codes.ctypes.data_as(ctypes.c_void_p),
        )
        return _NativePending(operation, n, codes, fut, arr if arr is not None else raw)

    GROUP_MAX = 16  # fused prepares per worker call (mirrors Replica.GROUP_MAX)

    def try_execute_group_async(self, items) -> list[_NativePending] | None:
        """Fused commit: a run of quorum-ready create_transfers prepares
        executed by ONE worker-queue call (one GIL release + one FIFO hop
        instead of k), preserving exact per-batch semantics — each batch
        keeps its own timestamp and dense codes. `items` =
        [(timestamp, transfer_rows_ndarray), ...]. The group seam the
        device backend exposes for kernel fusion serves here to amortize
        the per-submit overhead of the host engine (reference pipelining:
        src/vsr/replica.zig:3263-3315)."""
        k = len(items)
        if k < 2:
            return None
        # never truncate silently: callers zip the returned pendings with
        # their items — a shorter list would drop batches without a trace
        assert k <= self.GROUP_MAX, (k, self.GROUP_MAX)
        arrs = [np.ascontiguousarray(a) for _, a in items]
        codes = [np.empty(len(a), dtype=np.uint32) for a in arrs]
        fails = np.full(k, -1, dtype=np.int64)
        ns = (ctypes.c_uint32 * k)(*[len(a) for a in arrs])
        tss = (ctypes.c_uint64 * k)(*[int(ts) for ts, _ in items])
        ptrs = (ctypes.c_void_p * k)(*[a.ctypes.data for a in arrs])
        outs = (ctypes.c_void_p * k)(*[c.ctypes.data for c in codes])
        keepalive = (arrs, codes, fails, ns, tss, ptrs, outs)

        def _run():
            rc = self._lib.tb_ledger_execute_group(
                self._h, int(Operation.create_transfers), ptrs, ns, tss, k,
                outs, fails.ctypes.data_as(ctypes.c_void_p),
            )
            assert rc == 0, "tb_ledger_execute_group: invalid arguments"
            return keepalive

        gfut = self._submit(_run)
        pendings = []
        for j in range(k):
            f: Future = Future()

            def _chain(gf, j=j, f=f):
                if gf.exception() is not None:
                    f.set_exception(gf.exception())
                else:
                    f.set_result(int(fails[j]))

            gfut.add_done_callback(_chain)
            pendings.append(_NativePending(
                Operation.create_transfers, len(arrs[j]), codes[j], f, arrs[j]
            ))
        return pendings

    def fingerprint(self) -> dict:
        """Order-independent digest of the live table contents (rides the
        worker queue: sees every prior commit). Matches the DeviceLedger's
        state_fingerprint iff the logical row sets are bit-identical — the
        dual-commit verification seam."""
        out = np.zeros(8, dtype=np.uint64)
        self._submit(
            self._lib.tb_ledger_fingerprint,
            self._h, out.ctypes.data_as(ctypes.c_void_p),
        ).result()
        return {
            "accounts_fp": int(out[0]),
            "transfers_fp": int(out[1]),
            "accounts": int(out[2]),
            "transfers": int(out[3]),
            "posted": int(out[4]),
            "commit_timestamp": int(out[5]),
        }

    def drain(self, pending: _NativePending) -> list[int]:
        pending.wait()
        if pending.dense is None:
            pending.dense = [int(x) for x in pending.codes]
        return pending.dense

    def drain_many(self, pendings) -> None:
        for p in pendings:
            if p is not None:
                p.wait()

    def drain_reply(self, pending: _NativePending, operation) -> bytes:
        pending.wait()
        if not pending.failures:
            return b""
        from tigerbeetle_tpu.state_machine import encode_sparse_results

        return encode_sparse_results(pending.codes, operation)

    def execute_dense(self, operation, timestamp: int, events) -> list[int]:
        return self.drain(self.execute_async(operation, timestamp, events))

    def execute(self, operation, timestamp: int, events) -> list[tuple[int, int]]:
        dense = self.execute_dense(operation, timestamp, events)
        return [(i, c) for i, c in enumerate(dense) if c]

    # -- lookups --

    def lookup_rows(self, operation: Operation, ids: list[int]) -> bytes:
        n = len(ids)
        raw = np.zeros(2 * n, dtype=np.uint64)
        for i, x in enumerate(ids):
            raw[2 * i] = x & 0xFFFFFFFFFFFFFFFF
            raw[2 * i + 1] = x >> 64
        out = np.empty(n * 128, dtype=np.uint8)
        # ride the engine worker queue: the read sees every prior commit
        found = self._submit(
            self._lib.tb_ledger_lookup,
            self._h, int(operation), raw.tobytes(), n,
            out.ctypes.data_as(ctypes.c_void_p),
        ).result()
        return out[: found * 128].tobytes()

    def lookup_accounts(self, ids) -> list[types.Account]:
        body = self.lookup_rows(Operation.lookup_accounts, list(ids))
        arr = np.frombuffer(body, dtype=types.ACCOUNT_DTYPE)
        return [types.Account.from_np(arr[i]) for i in range(len(arr))]

    def lookup_transfers(self, ids) -> list[types.Transfer]:
        body = self.lookup_rows(Operation.lookup_transfers, list(ids))
        arr = np.frombuffer(body, dtype=types.TRANSFER_DTYPE)
        return [types.Transfer.from_np(arr[i]) for i in range(len(arr))]

    # -- counters --

    @property
    def commit_timestamp(self) -> int:
        return self.counts()["commit_timestamp"]

    def counts(self) -> dict:
        out = np.zeros(4, dtype=np.uint64)
        self._submit(
            self._lib.tb_ledger_counts,
            self._h, out.ctypes.data_as(ctypes.c_void_p),
        ).result()
        return {
            "accounts": int(out[0]),
            "transfers": int(out[1]),
            "posted": int(out[2]),
            "commit_timestamp": int(out[3]),
        }

    # -- checkpoint blobs (the replica's oracle-backend snapshot path) --

    def snapshot_bytes(self) -> bytes:
        def _snap():
            size = self._lib.tb_ledger_snapshot_size(self._h)
            buf = ctypes.create_string_buffer(size)
            self._lib.tb_ledger_snapshot(self._h, buf)
            return buf.raw

        return self._submit(_snap).result()

    def restore_bytes(self, raw: bytes) -> None:
        rc = self._submit(
            self._lib.tb_ledger_restore, self._h, raw, len(raw)
        ).result()
        assert rc == 0, "tb_ledger_restore: truncated snapshot"
