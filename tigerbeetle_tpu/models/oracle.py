"""Scalar reference state machine — the parity oracle.

An exact, line-faithful reimplementation of the reference ledger semantics
(reference: src/state_machine.zig:612-1077) over in-memory dict stores, using
Python arbitrary-precision ints with explicit u64/u128 overflow semantics.

This is NOT the production path — it is the oracle every device kernel is
tested against for bit-exact result-code and state parity (SURVEY.md §7
build-plan stage 2), and the model behind the simulator's auditor.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

import numpy as np

from tigerbeetle_tpu.constants import NS_PER_S, U64_MAX, U128_MAX
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    Transfer,
    TransferFlags,
)

POSTED = 1
VOIDED = 2


def sum_overflows_u128(a: int, b: int) -> bool:
    return a + b > U128_MAX


def sum_overflows_u64(a: int, b: int) -> bool:
    return a + b > U64_MAX


@dataclasses.dataclass
class _Scope:
    """Rollback scope for linked chains (reference: src/lsm/groove.zig:990-1010).

    Records prior values of mutated keys; discard restores them in reverse.
    """

    accounts: list[tuple[int, Account | None]] = dataclasses.field(default_factory=list)
    transfers: list[tuple[int, Transfer | None]] = dataclasses.field(default_factory=list)
    posted: list[tuple[int, int | None]] = dataclasses.field(default_factory=list)


class OracleStateMachine:
    """Exact semantics of reference src/state_machine.zig over dict stores."""

    process = None  # no device table geometry (Replica backend duck-typing)

    def __init__(self) -> None:
        self.accounts: dict[int, Account] = {}
        self.transfers: dict[int, Transfer] = {}
        # posted groove: pending transfer timestamp -> POSTED | VOIDED
        # (reference: src/state_machine.zig:185-198 PostedGrooveValue).
        self.posted: dict[int, int] = {}
        self.commit_timestamp: int = 0
        self.prepare_timestamp: int = 0
        self._scope: _Scope | None = None

    # --- store accessors with scope recording ---

    def _put_account(self, a: Account) -> None:
        if self._scope is not None:
            prev = self.accounts.get(a.id)
            self._scope.accounts.append(
                (a.id, dataclasses.replace(prev) if prev is not None else None)
            )
        self.accounts[a.id] = a

    def _put_transfer(self, t: Transfer) -> None:
        if self._scope is not None:
            prev = self.transfers.get(t.id)
            self._scope.transfers.append(
                (t.id, dataclasses.replace(prev) if prev is not None else None)
            )
        self.transfers[t.id] = t

    def _put_posted(self, pending_timestamp: int, fulfillment: int) -> None:
        if self._scope is not None:
            self._scope.posted.append(
                (pending_timestamp, self.posted.get(pending_timestamp))
            )
        self.posted[pending_timestamp] = fulfillment

    def _scope_open(self) -> None:
        assert self._scope is None
        self._scope = _Scope()

    def _scope_close(self, persist: bool) -> None:
        scope = self._scope
        assert scope is not None
        self._scope = None
        if persist:
            return
        for key, prev in reversed(scope.posted):
            if prev is None:
                del self.posted[key]
            else:
                self.posted[key] = prev
        for key, prev in reversed(scope.transfers):
            if prev is None:
                del self.transfers[key]
            else:
                self.transfers[key] = prev
        for key, prev in reversed(scope.accounts):
            if prev is None:
                del self.accounts[key]
            else:
                self.accounts[key] = prev

    # --- lifecycle (reference: src/state_machine.zig:336-343) ---

    def prepare(self, operation: Operation, event_count: int) -> None:
        if operation in (Operation.create_accounts, Operation.create_transfers):
            self.prepare_timestamp += event_count

    # --- batch executor (reference: src/state_machine.zig:612-698) ---

    def execute(
        self, operation: Operation, timestamp: int, events: list
    ) -> list[tuple[int, int]]:
        """Returns the sparse (index, result) list, exactly as the reference
        emits it (only non-ok results; chain rollbacks appended in FIFO order).
        """
        if isinstance(events, np.ndarray):  # wire rows -> record classes
            cls = Account if operation == Operation.create_accounts else Transfer
            events = [cls.from_np(events[i]) for i in range(len(events))]

        results: list[tuple[int, int]] = []
        chain: int | None = None
        chain_broken = False

        for index, event_in in enumerate(events):
            event = dataclasses.replace(event_in)
            result = None

            if event.flags & 0x1:  # linked
                if chain is None:
                    chain = index
                    assert not chain_broken
                    self._scope_open()
                if index == len(events) - 1:
                    result = 2  # linked_event_chain_open

            if result is None and chain_broken:
                result = 1  # linked_event_failed
            if result is None and event.timestamp != 0:
                result = 3  # timestamp_must_be_zero

            if result is None:
                event.timestamp = timestamp - len(events) + index + 1
                if operation == Operation.create_accounts:
                    result = int(self.create_account(event))
                elif operation == Operation.create_transfers:
                    result = int(self.create_transfer(event))
                else:
                    raise AssertionError(operation)

            if result != 0:
                if chain is not None:
                    if not chain_broken:
                        chain_broken = True
                        self._scope_close(persist=False)
                        for chain_index in range(chain, index):
                            results.append((chain_index, 1))  # linked_event_failed
                    else:
                        assert result in (1, 2)
                results.append((index, result))

            if chain is not None and (not (event.flags & 0x1) or result == 2):
                if not chain_broken:
                    self._scope_close(persist=True)
                chain = None
                chain_broken = False

        assert chain is None
        assert not chain_broken
        from tigerbeetle_tpu import constants

        if constants.VERIFY:
            self._audit_count = getattr(self, "_audit_count", 0) + 1
            if self._audit_count % 8 == 0:
                self.verify_conservation()
        return results

    def verify_conservation(self) -> None:
        """Intensive-tier audit (constants.VERIFY; reference
        src/constants.zig:592): per ledger, total debits_posted ==
        total credits_posted and total debits_pending ==
        total credits_pending — money never appears or vanishes.
        O(accounts) per audit, run on a commit cadence."""
        per_ledger: dict[int, list[int]] = {}
        for a in self.accounts.values():
            t = per_ledger.setdefault(a.ledger, [0, 0, 0, 0])
            t[0] += a.debits_posted
            t[1] += a.credits_posted
            t[2] += a.debits_pending
            t[3] += a.credits_pending
        for ledger, (dp, cp, dpe, cpe) in per_ledger.items():
            assert dp == cp, (
                f"VERIFY: ledger {ledger} posted conservation broken: "
                f"debits {dp} != credits {cp}"
            )
            assert dpe == cpe, (
                f"VERIFY: ledger {ledger} pending conservation broken: "
                f"debits {dpe} != credits {cpe}"
            )

    def execute_dense(
        self, operation: Operation, timestamp: int, events: list
    ) -> list[int]:
        """Dense per-event result codes (ok = 0), the device kernels' output
        format. Sparse wire results = [(i, c) for i, c in enumerate(dense) if c]."""
        sparse = self.execute(operation, timestamp, events)
        dense = [0] * len(events)
        for index, result in sparse:
            dense[index] = result
        return dense

    # -- parity extraction + snapshot (so the oracle can stand in for the
    # device ledger behind the Replica in logic-level simulations) --

    def extract(self):
        return (
            {k: dataclasses.replace(v) for k, v in self.accounts.items()},
            {k: dataclasses.replace(v) for k, v in self.transfers.items()},
            dict(self.posted),
        )

    def assert_parity(self, backend) -> None:
        """Diff another backend's extract() surface against this oracle
        and FAIL NAMING the first divergent object (id + both values) —
        the wave-scheduler parity tests run adversarial thousand-event
        batches, where a whole-dict assert's diff is unreadable."""
        accounts, transfers, posted = backend.extract()
        for name, got, want in (
            ("account", accounts, self.accounts),
            ("transfer", transfers, self.transfers),
            ("posted", posted, self.posted),
        ):
            assert set(got) == set(want), (
                f"{name} id sets differ: only-device="
                f"{sorted(set(got) - set(want))[:4]} only-oracle="
                f"{sorted(set(want) - set(got))[:4]}"
            )
            for k in sorted(want):
                assert got[k] == want[k], (
                    f"{name} {k}: device={got[k]} oracle={want[k]}"
                )
        assert backend.commit_timestamp == self.commit_timestamp, (
            backend.commit_timestamp, self.commit_timestamp,
        )

    def fingerprint(self) -> dict:
        """Order-independent state digest matching DeviceLedger /
        NativeLedger fingerprint() bit-exactly (models/ledger.py
        fp_rows_np): the commutative per-row sum makes the dict-ordered
        wire images hash identically to the device's slot layout. This
        is what lets StreamVerifier recompute a region's checkpoint
        commitments from its CDC stream alone."""
        from tigerbeetle_tpu.models.ledger import fp_rows_np
        from tigerbeetle_tpu.types import accounts_to_np, transfers_to_np

        afp, alive = fp_rows_np(accounts_to_np(list(self.accounts.values())))
        tfp, tlive = fp_rows_np(transfers_to_np(list(self.transfers.values())))
        assert alive == len(self.accounts) and tlive == len(self.transfers)
        return {
            "accounts_fp": afp,
            "transfers_fp": tfp,
            "accounts": alive,
            "transfers": tlive,
            "commit_timestamp": self.commit_timestamp,
        }

    def snapshot_bytes(self) -> bytes:
        import json

        from tigerbeetle_tpu.types import accounts_to_np, transfers_to_np

        acc = accounts_to_np([self.accounts[k] for k in sorted(self.accounts)])
        xfr = transfers_to_np([self.transfers[k] for k in sorted(self.transfers)])
        posted = json.dumps(
            [[str(k), v] for k, v in sorted(self.posted.items())]
        ).encode()
        head = (
            len(acc).to_bytes(8, "little")
            + len(xfr).to_bytes(8, "little")
            + len(posted).to_bytes(8, "little")
            + self.commit_timestamp.to_bytes(8, "little")
        )
        return head + acc.tobytes() + xfr.tobytes() + posted

    def restore_bytes(self, raw: bytes) -> None:
        import json

        import numpy as np

        from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE

        n_acc = int.from_bytes(raw[0:8], "little")
        n_xfr = int.from_bytes(raw[8:16], "little")
        n_posted = int.from_bytes(raw[16:24], "little")
        self.commit_timestamp = int.from_bytes(raw[24:32], "little")
        off = 32
        acc = np.frombuffer(raw[off : off + 128 * n_acc], dtype=ACCOUNT_DTYPE)
        off += 128 * n_acc
        xfr = np.frombuffer(raw[off : off + 128 * n_xfr], dtype=TRANSFER_DTYPE)
        off += 128 * n_xfr
        posted = json.loads(raw[off : off + n_posted].decode())
        self.accounts = {}
        for i in range(n_acc):
            a = Account.from_np(acc[i])
            self.accounts[a.id] = a
        self.transfers = {}
        for i in range(n_xfr):
            t = Transfer.from_np(xfr[i])
            self.transfers[t.id] = t
        self.posted = {int(k): v for k, v in posted}

    def lookup_accounts(self, ids: Iterable[int]) -> list[Account]:
        # reference: src/state_machine.zig:701-717
        return [
            dataclasses.replace(self.accounts[i]) for i in ids if i in self.accounts
        ]

    def lookup_transfers(self, ids: Iterable[int]) -> list[Transfer]:
        # reference: src/state_machine.zig:720-736
        return [
            dataclasses.replace(self.transfers[i]) for i in ids if i in self.transfers
        ]

    # --- create_account (reference: src/state_machine.zig:738-777) ---

    def create_account(self, a: Account) -> CreateAccountResult:
        R = CreateAccountResult
        if a.reserved != 0:
            return R.reserved_field
        if a.flags & AccountFlags.padding_mask():
            return R.reserved_flag
        if a.id == 0:
            return R.id_must_not_be_zero
        if a.id == U128_MAX:
            return R.id_must_not_be_int_max
        if (a.flags & AccountFlags.debits_must_not_exceed_credits) and (
            a.flags & AccountFlags.credits_must_not_exceed_debits
        ):
            return R.flags_are_mutually_exclusive
        if a.debits_pending != 0:
            return R.debits_pending_must_be_zero
        if a.debits_posted != 0:
            return R.debits_posted_must_be_zero
        if a.credits_pending != 0:
            return R.credits_pending_must_be_zero
        if a.credits_posted != 0:
            return R.credits_posted_must_be_zero
        if a.ledger == 0:
            return R.ledger_must_not_be_zero
        if a.code == 0:
            return R.code_must_not_be_zero

        e = self.accounts.get(a.id)
        if e is not None:
            return self._create_account_exists(a, e)

        self._put_account(dataclasses.replace(a))
        self.commit_timestamp = a.timestamp
        return R.ok

    @staticmethod
    def _create_account_exists(a: Account, e: Account) -> CreateAccountResult:
        # reference: src/state_machine.zig:767-777
        R = CreateAccountResult
        assert a.id == e.id
        if a.flags != e.flags:
            return R.exists_with_different_flags
        if a.user_data_128 != e.user_data_128:
            return R.exists_with_different_user_data_128
        if a.user_data_64 != e.user_data_64:
            return R.exists_with_different_user_data_64
        if a.user_data_32 != e.user_data_32:
            return R.exists_with_different_user_data_32
        assert a.reserved == 0 and e.reserved == 0
        if a.ledger != e.ledger:
            return R.exists_with_different_ledger
        if a.code != e.code:
            return R.exists_with_different_code
        return R.exists

    # --- create_transfer (reference: src/state_machine.zig:779-884) ---

    def create_transfer(self, t: Transfer) -> CreateTransferResult:
        R = CreateTransferResult
        F = TransferFlags

        if t.flags & TransferFlags.padding_mask():
            return R.reserved_flag
        if t.id == 0:
            return R.id_must_not_be_zero
        if t.id == U128_MAX:
            return R.id_must_not_be_int_max

        if t.flags & (F.post_pending_transfer | F.void_pending_transfer):
            return self._post_or_void_pending_transfer(t)

        if t.debit_account_id == 0:
            return R.debit_account_id_must_not_be_zero
        if t.debit_account_id == U128_MAX:
            return R.debit_account_id_must_not_be_int_max
        if t.credit_account_id == 0:
            return R.credit_account_id_must_not_be_zero
        if t.credit_account_id == U128_MAX:
            return R.credit_account_id_must_not_be_int_max
        if t.credit_account_id == t.debit_account_id:
            return R.accounts_must_be_different

        if t.pending_id != 0:
            return R.pending_id_must_be_zero
        if not (t.flags & F.pending):
            if t.timeout != 0:
                return R.timeout_reserved_for_pending_transfer
        if not (t.flags & (F.balancing_debit | F.balancing_credit)):
            if t.amount == 0:
                return R.amount_must_not_be_zero

        if t.ledger == 0:
            return R.ledger_must_not_be_zero
        if t.code == 0:
            return R.code_must_not_be_zero

        dr_account = self.accounts.get(t.debit_account_id)
        if dr_account is None:
            return R.debit_account_not_found
        cr_account = self.accounts.get(t.credit_account_id)
        if cr_account is None:
            return R.credit_account_not_found
        assert t.timestamp > dr_account.timestamp
        assert t.timestamp > cr_account.timestamp

        if dr_account.ledger != cr_account.ledger:
            return R.accounts_must_have_the_same_ledger
        if t.ledger != dr_account.ledger:
            return R.transfer_must_have_the_same_ledger_as_accounts

        # If the transfer already exists, it must not influence the overflow
        # or limit checks (reference: src/state_machine.zig:823-824).
        e = self.transfers.get(t.id)
        if e is not None:
            return self._create_transfer_exists(t, e)

        amount = t.amount
        if t.flags & (F.balancing_debit | F.balancing_credit):
            if amount == 0:
                amount = U64_MAX  # note: u64 max (reference: :829)
        else:
            assert amount != 0
        if t.flags & F.balancing_debit:
            dr_balance = dr_account.debits_posted + dr_account.debits_pending
            amount = min(amount, max(0, dr_account.credits_posted - dr_balance))
            if amount == 0:
                return R.exceeds_credits
        if t.flags & F.balancing_credit:
            cr_balance = cr_account.credits_posted + cr_account.credits_pending
            amount = min(amount, max(0, cr_account.debits_posted - cr_balance))
            if amount == 0:
                return R.exceeds_debits

        if t.flags & F.pending:
            if sum_overflows_u128(amount, dr_account.debits_pending):
                return R.overflows_debits_pending
            if sum_overflows_u128(amount, cr_account.credits_pending):
                return R.overflows_credits_pending
        if sum_overflows_u128(amount, dr_account.debits_posted):
            return R.overflows_debits_posted
        if sum_overflows_u128(amount, cr_account.credits_posted):
            return R.overflows_credits_posted
        if sum_overflows_u128(amount, dr_account.debits_pending + dr_account.debits_posted):
            return R.overflows_debits
        if sum_overflows_u128(amount, cr_account.credits_pending + cr_account.credits_posted):
            return R.overflows_credits

        if sum_overflows_u64(t.timestamp, t.timeout * NS_PER_S):
            return R.overflows_timeout
        if dr_account.debits_exceed_credits(amount):
            return R.exceeds_credits
        if cr_account.credits_exceed_debits(amount):
            return R.exceeds_debits

        t2 = dataclasses.replace(t, amount=amount)
        self._put_transfer(t2)

        dr_new = dataclasses.replace(dr_account)
        cr_new = dataclasses.replace(cr_account)
        if t.flags & F.pending:
            dr_new.debits_pending += amount
            cr_new.credits_pending += amount
        else:
            dr_new.debits_posted += amount
            cr_new.credits_posted += amount
        self._put_account(dr_new)
        self._put_account(cr_new)

        self.commit_timestamp = t.timestamp
        return R.ok

    @staticmethod
    def _create_transfer_exists(t: Transfer, e: Transfer) -> CreateTransferResult:
        # reference: src/state_machine.zig:886-905
        R = CreateTransferResult
        assert t.id == e.id
        if t.flags != e.flags:
            return R.exists_with_different_flags
        if t.debit_account_id != e.debit_account_id:
            return R.exists_with_different_debit_account_id
        if t.credit_account_id != e.credit_account_id:
            return R.exists_with_different_credit_account_id
        if t.amount != e.amount:
            return R.exists_with_different_amount
        assert t.pending_id == 0 and e.pending_id == 0
        if t.user_data_128 != e.user_data_128:
            return R.exists_with_different_user_data_128
        if t.user_data_64 != e.user_data_64:
            return R.exists_with_different_user_data_64
        if t.user_data_32 != e.user_data_32:
            return R.exists_with_different_user_data_32
        if t.timeout != e.timeout:
            return R.exists_with_different_timeout
        assert t.ledger == e.ledger
        if t.code != e.code:
            return R.exists_with_different_code
        return R.exists

    # --- post/void (reference: src/state_machine.zig:907-1014) ---

    def _post_or_void_pending_transfer(self, t: Transfer) -> CreateTransferResult:
        R = CreateTransferResult
        F = TransferFlags
        assert t.id != 0
        assert t.flags & (F.post_pending_transfer | F.void_pending_transfer)

        if (t.flags & F.post_pending_transfer) and (t.flags & F.void_pending_transfer):
            return R.flags_are_mutually_exclusive
        if t.flags & F.pending:
            return R.flags_are_mutually_exclusive
        if t.flags & F.balancing_debit:
            return R.flags_are_mutually_exclusive
        if t.flags & F.balancing_credit:
            return R.flags_are_mutually_exclusive

        if t.pending_id == 0:
            return R.pending_id_must_not_be_zero
        if t.pending_id == U128_MAX:
            return R.pending_id_must_not_be_int_max
        if t.pending_id == t.id:
            return R.pending_id_must_be_different
        if t.timeout != 0:
            return R.timeout_reserved_for_pending_transfer

        p = self.transfers.get(t.pending_id)
        if p is None:
            return R.pending_transfer_not_found
        assert p.id == t.pending_id
        if not (p.flags & F.pending):
            return R.pending_transfer_not_pending

        dr_account = self.accounts[p.debit_account_id]
        cr_account = self.accounts[p.credit_account_id]
        assert p.timestamp > dr_account.timestamp
        assert p.timestamp > cr_account.timestamp
        assert p.amount > 0

        if t.debit_account_id > 0 and t.debit_account_id != p.debit_account_id:
            return R.pending_transfer_has_different_debit_account_id
        if t.credit_account_id > 0 and t.credit_account_id != p.credit_account_id:
            return R.pending_transfer_has_different_credit_account_id
        # user_data is allowed to differ across pending and post/void transfers.
        if t.ledger > 0 and t.ledger != p.ledger:
            return R.pending_transfer_has_different_ledger
        if t.code > 0 and t.code != p.code:
            return R.pending_transfer_has_different_code

        amount = t.amount if t.amount > 0 else p.amount
        if amount > p.amount:
            return R.exceeds_pending_transfer_amount
        if (t.flags & F.void_pending_transfer) and amount < p.amount:
            return R.pending_transfer_has_different_amount

        e = self.transfers.get(t.id)
        if e is not None:
            return self._post_or_void_pending_transfer_exists(t, e, p)

        fulfillment = self.posted.get(p.timestamp)
        if fulfillment is not None:
            if fulfillment == POSTED:
                return R.pending_transfer_already_posted
            return R.pending_transfer_already_voided

        assert p.timestamp < t.timestamp
        if p.timeout > 0:
            timeout_ns = p.timeout * NS_PER_S
            if t.timestamp >= p.timestamp + timeout_ns:
                return R.pending_transfer_expired

        t2 = Transfer(
            id=t.id,
            debit_account_id=p.debit_account_id,
            credit_account_id=p.credit_account_id,
            user_data_128=t.user_data_128 if t.user_data_128 > 0 else p.user_data_128,
            user_data_64=t.user_data_64 if t.user_data_64 > 0 else p.user_data_64,
            user_data_32=t.user_data_32 if t.user_data_32 > 0 else p.user_data_32,
            ledger=p.ledger,
            code=p.code,
            pending_id=t.pending_id,
            timeout=0,
            timestamp=t.timestamp,
            flags=t.flags,
            amount=amount,
        )
        self._put_transfer(t2)

        self._put_posted(
            p.timestamp, POSTED if t.flags & F.post_pending_transfer else VOIDED
        )

        dr_new = dataclasses.replace(dr_account)
        cr_new = dataclasses.replace(cr_account)
        dr_new.debits_pending -= p.amount
        cr_new.credits_pending -= p.amount
        if t.flags & F.post_pending_transfer:
            assert amount > 0
            assert amount <= p.amount
            dr_new.debits_posted += amount
            cr_new.credits_posted += amount
        self._put_account(dr_new)
        self._put_account(cr_new)

        self.commit_timestamp = t.timestamp
        return R.ok

    @staticmethod
    def _post_or_void_pending_transfer_exists(
        t: Transfer, e: Transfer, p: Transfer
    ) -> CreateTransferResult:
        # reference: src/state_machine.zig:1016-1077
        R = CreateTransferResult
        assert t.id == e.id
        assert t.id != p.id
        assert t.pending_id == p.id

        if t.flags != e.flags:
            return R.exists_with_different_flags

        if t.amount == 0:
            if e.amount != p.amount:
                return R.exists_with_different_amount
        else:
            if t.amount != e.amount:
                return R.exists_with_different_amount

        if t.pending_id != e.pending_id:
            return R.exists_with_different_pending_id

        if t.user_data_128 == 0:
            if e.user_data_128 != p.user_data_128:
                return R.exists_with_different_user_data_128
        else:
            if t.user_data_128 != e.user_data_128:
                return R.exists_with_different_user_data_128

        if t.user_data_64 == 0:
            if e.user_data_64 != p.user_data_64:
                return R.exists_with_different_user_data_64
        else:
            if t.user_data_64 != e.user_data_64:
                return R.exists_with_different_user_data_64

        if t.user_data_32 == 0:
            if e.user_data_32 != p.user_data_32:
                return R.exists_with_different_user_data_32
        else:
            if t.user_data_32 != e.user_data_32:
                return R.exists_with_different_user_data_32

        return R.exists
