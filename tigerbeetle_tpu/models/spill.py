"""HBM↔LSM spill scheduler: the bounded-memory story.

The device ledger's transfer table is a capacity-bounded HBM hash table
(models/ledger.py); the reference's store is an unbounded LSM forest with a
residency-guaranteed in-memory cache (reference: src/lsm/groove.zig:602-760
prefetch contract; src/lsm/cache_map.zig:10-25 CacheMap residency). This
module closes that gap the TPU-native way:

- HBM is the CacheMap: every row a batch can touch is resident BEFORE the
  kernel runs, so the kernels stay pure, synchronous, and data-parallel.
- The LSM forest (lsm/groove.py over the grid) is the backing store: when
  HBM occupancy reaches the spill trigger, the OLDEST transfers spill to
  the forest (timestamp order — the reference's object trees are
  timestamp-keyed for exactly this access pattern) and the HBM table is
  rebuilt with only the hot tail. Rebuilding also sheds rollback
  tombstones, so a cycle resets probe-chain density to the live load.
- Before every commit, the host checks the batch's id and pending_id
  references against the spilled-id set (sorted-limb prefilter + exact
  set — the host analog of the reference's per-table bloom filters,
  src/lsm/bloom_filter.zig) and RELOADS referenced spilled rows into HBM.
  This is the prefetch contract: after admit(), the kernels' HBM lookups
  are equivalent to lookups against the full store.

The OVERLAPPED SPILL PIPELINE (the reference saturates IO depth while the
previous op commits, src/lsm/groove.zig:710-760; all storage IO rides one
async loop, src/io/linux.zig:17-42):

- prefetch/commit overlap: a driver that knows batch N+1 while batch N's
  commit kernel runs calls ``prefetch_async(arr)`` — the referenced-
  spilled id scan happens inline (cheap numpy), and the LSM point reads +
  row staging run on the IO executor into a double-buffered host slot.
  The admit() that later commits the batch finds the rows staged and pays
  only the device reload launch; ``stats`` accounts how much of the gather
  time was hidden (``t_prefetch_worker`` vs ``t_prefetch_wait``).
- vectorized multi-lookup: cold-row fetches resolve through ONE batched
  LSM multi-point-read per tree (lsm/tree.py Tree.get_many) — memtable and
  each level walked once per id set, bloom probes vectorized, index blocks
  parsed once per table per call — instead of a full per-id cascade.
- the reload staging buffers double-buffer against device execution the
  same way the group-commit upload slots do (models/ledger.py
  _group_staging_slot): two alternating preallocated host buffers, each
  fenced on the last reload dispatched from it.

Accounts do not spill: account rows are the working set of every batch
(dr/cr balance updates), and the reference's workload shape is a bounded
account population with unbounded transfer history — the transfer table is
the wall that matters (BASELINE.md: 10k accounts, 10M+ transfers). The
account-table guard stays hard.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.models.ledger import (
    FAULT_CAPACITY,
    FAULT_CLAIM,
    FAULT_PROBE,
    raise_on_fault,
)
from tigerbeetle_tpu.models.validate import F_POST, F_VOID
from tigerbeetle_tpu.ops import hashtable as ht
from tigerbeetle_tpu.tracer import NULL_TRACER

U64 = jnp.uint64
U32 = jnp.uint32
ROW_WORDS = 32

CHUNK = 8192  # static shape of gather/reload kernels (= BATCH_PAD)


# ----------------------------------------------------------------------
# the IO executor seam (reference: ALL storage IO rides one event loop off
# the replica's hot path, src/io/linux.zig:17-42). Two implementations:
#
# - ThreadedSpillIO (production): ONE worker thread, FIFO — the insert
#   order is deterministic, and LSM insertion/compaction truly overlaps
#   the caller's commits in wall time.
# - DeferredSpillIO (deterministic harnesses — the VSR replica, cluster
#   tests, the simulator): jobs queue and run inline at pump()/drain() on
#   the caller's thread, so seeded runs never depend on thread timing,
#   while the commit dispatch path still never executes LSM insertion —
#   jobs run at the event loop's tick boundary (Replica.tick pumps).
#   Grid-block ALLOCATION order stays identical to the threaded executor's
#   (same FIFO job order), which is what cross-replica repair-by-address
#   depends on.
# ----------------------------------------------------------------------


class ThreadedSpillIO:
    """Single-worker FIFO executor: real async IO for wall-clock overlap."""

    settle_in_worker = True  # jobs may settle trees (raises surface at drain)

    def __init__(self):
        self._ex = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="spill-io"
        )
        self._jobs: list[Future] = []

    def submit(self, fn, *args) -> Future:
        f = self._ex.submit(fn, *args)
        self._jobs.append(f)
        return f

    def drain(self) -> None:
        """Barrier: wait for EVERY queued job even when an earlier one
        raised — dropping the tail would let a healed-and-retried caller
        read trees the worker is still mutating. The first exception
        surfaces after the whole queue has settled."""
        jobs, self._jobs = self._jobs, []
        err = None
        for f in jobs:
            try:
                f.result()
            except BaseException as e:
                if err is None:
                    err = e
        if err is not None:
            raise err

    def pump(self) -> None:
        """Reap finished jobs (surfacing their exceptions) without
        blocking on the ones still running. Finished jobs are evicted
        BEFORE any exception propagates — a failed job must raise once,
        not on every subsequent pump."""
        keep, finished = [], []
        for f in self._jobs:
            (keep if not f.done() else finished).append(f)
        self._jobs = keep
        err = None
        for f in finished:
            try:
                f.result()
            except BaseException as e:
                if err is None:
                    err = e
        if err is not None:
            raise err

    def wait(self, fut: Future):
        return fut.result()

    def pending(self) -> int:
        return len(self._jobs)


class DeferredSpillIO:
    """Deterministic executor: jobs queue and run inline at pump()/drain()
    — off the commit dispatch path, with zero thread timing. Jobs here
    must be pure pending-appends (settle_in_worker=False): a
    GridBlockCorrupt raised from a tick-boundary pump would have no
    heal-and-retry context, so settles stay in admit's _settle_forest,
    where the replica's repair path catches them."""

    settle_in_worker = False

    def __init__(self):
        self._q: deque = deque()

    def submit(self, fn, *args) -> Future:
        f: Future = Future()
        self._q.append((f, fn, args))
        return f

    def _run_one(self) -> None:
        f, fn, args = self._q.popleft()
        try:
            r = fn(*args)
        except BaseException as e:
            f.set_exception(e)
            raise
        f.set_result(r)

    def pump(self) -> None:
        while self._q:
            self._run_one()

    drain = pump

    def wait(self, fut: Future):
        while self._q and not fut.done():
            self._run_one()
        return fut.result()

    def pending(self) -> int:
        return len(self._q)


def _make_io(async_io: bool, io):
    if os.environ.get("TB_SPILL_SYNC") == "1":
        return None  # forced inline IO (debugging)
    if io == "threaded":
        return ThreadedSpillIO()
    if io == "deferred":
        return DeferredSpillIO()
    if io is not None:
        return io  # caller-provided executor instance
    return ThreadedSpillIO() if async_io else None


_SPILL_KERNELS_CACHE: dict = {}


def get_spill_kernels(process) -> "SpillKernels":
    """One SpillKernels per table geometry (stateless; same contract as
    models.ledger.get_kernels — fresh managers reuse the jit cache)."""
    k = _SPILL_KERNELS_CACHE.get(process)
    if k is None:
        k = _SPILL_KERNELS_CACHE[process] = SpillKernels(process)
    return k


class SpillKernels:
    """Jitted device ops for the spill cycle, closed over table geometry."""

    def __init__(self, process):
        self.t_log2 = process.transfer_slots_log2
        self.t_dump = 1 << self.t_log2
        self.ts_occ = jax.jit(self._ts_occ)
        self.cycle_head = jax.jit(self._cycle_head)
        self.split_idx = jax.jit(self._split_idx)
        self.gather = jax.jit(self._gather)
        self.reload = jax.jit(self._reload, donate_argnums=(0, 1, 2))

    def _ts_occ(self, xfer_rows):
        """Per-slot (timestamp u64, occupied bool) — the cycle's scan."""
        occ = ht.occupied_mask(xfer_rows).at[self.t_dump].set(False)
        ts = xfer_rows[:, 30].astype(U64) | (
            xfer_rows[:, 31].astype(U64) << jnp.uint64(32)
        )
        return ts, occ

    def _cycle_head(self, xfer_rows, fault):
        """[live count, fault]: the ONLY words the cycle fetches before
        deciding the split — the old path shipped the full per-slot
        (ts, occ) arrays device->host and sorted on host, a whole-table
        d2h + sync per cycle on the degraded-transport rig."""
        _, occ = self._ts_occ(xfer_rows)
        live = jnp.sum(occ.astype(U32))
        return jnp.stack([live, fault.astype(U32)])

    def _split_idx(self, xfer_rows, n_cold):
        """Device-side cold/hot partition: sort the live timestamps, take
        the watermark at n_cold (timestamps are unique by construction, so
        the split is exact), and emit padded index arrays the gather
        kernels consume DIRECTLY — no host round trip. Padding lanes hold
        t_dump (the gather sentinel row); the arrays are oversized by one
        CHUNK so every CHUNK-window slice is full-width (one gather
        compile)."""
        ts, occ = self._ts_occ(xfer_rows)
        inf = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        ts_m = jnp.where(occ, ts, inf)
        watermark = jnp.sort(ts_m)[n_cold]
        cold = occ & (ts_m < watermark)
        hot = occ & ~(ts_m < watermark)
        size = self.t_dump + CHUNK
        cold_idx = jnp.nonzero(cold, size=size, fill_value=self.t_dump)[0]
        hot_idx = jnp.nonzero(hot, size=size, fill_value=self.t_dump)[0]
        return cold_idx.astype(jnp.int32), hot_idx.astype(jnp.int32)

    def _gather(self, xfer_rows, fulfill, idx):
        return xfer_rows[idx], fulfill[idx]

    def _reload(self, xfer_rows, fulfill, claim, used_slots, fault,
                rows_b, ful_b, active):
        """Insert absent rows (verbatim stored content, fulfill included)
        into the transfer table. Lanes whose key is already resident are
        skipped — reload is idempotent. Every write gates on the sticky
        fault word (models/ledger.py fault protocol)."""
        key4 = rows_b[:, :4]
        _, found, res = ht.lookup(key4, xfer_rows, self.t_log2)
        need = active & ~found
        slots, claim, ins_res = ht.claim_slots(
            key4, need, xfer_rows, claim, self.t_log2
        )
        n_new = jnp.sum(need).astype(U64)
        cap_bad = used_slots + n_new > np.uint64(self.t_dump // 2)
        fault = (
            fault
            | jnp.where(jnp.any(active & ~res), jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(jnp.any(~ins_res), jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0
        w = jnp.where(proceed & need, slots, self.t_dump)
        xfer_rows = xfer_rows.at[w].set(rows_b)
        fulfill = fulfill.at[w].set(ful_b)
        used_slots = used_slots + jnp.where(proceed, n_new, jnp.uint64(0))
        # probe: a dedicated output NOTHING else consumes — the staging
        # double-buffer fences on it (state outputs get donated by later
        # kernels, so their buffers may be deleted before the fence fires;
        # the xor keeps it a distinct graph node so XLA cannot alias it
        # onto a state output's buffer)
        probe = used_slots.astype(U32) ^ fault.astype(U32)
        return xfer_rows, fulfill, claim, used_slots, fault, probe


class SpillManager:
    """Owns the spilled-id set, the LSM backing store, and the cycle.

    Attached to a DeviceLedger via ``DeviceLedger(forest=...)``; the ledger
    calls ``admit(arr, n)`` before every create_transfers commit and merges
    spilled rows into lookups/extract.
    """

    STAT_KEYS = (
        "cycles", "spilled", "reloaded",
        "t_scan", "t_gather_d2h", "t_stage",
        "t_rebuild", "t_reload", "t_lsm_worker",
        "prefetches", "prefetched",
        "t_prefetch_worker", "t_prefetch_wait",
        "lookup_batches", "lookup_ids",
    )

    def instrument(self, metrics, tracer) -> None:
        """Re-bind onto a shared registry/tracer (the replica's, or the
        bench driver's). Accumulated values carry over; the forest's trees
        and grid report into the same registry. A worker-side stat update
        racing the carry-over/rebind window lands in the discarded old
        group and is dropped from the new registry — at most one update,
        and instrument() runs at setup before IO jobs flow."""
        for key in self.STAT_KEYS:
            metrics.counter(f"spill.{key}").add(self.stats[key])
        self.metrics = metrics
        # rebound on the event loop while IO-worker jobs read per use —
        # a GIL-atomic reference swap (worst case one span lands in the
        # old tracer); registry counters serialize internally
        self.tracer = tracer  # vet: handoff
        self.stats = metrics.group("spill", self.STAT_KEYS)  # vet: handoff
        for tree in self.forest._trees():
            tree.metrics = metrics
            tree.tracer = tracer
        self.forest.grid.metrics = metrics

    def __init__(self, ledger, forest, keep_frac: float = 0.25,
                 async_io: bool = True, io=None):
        assert 0.0 < keep_frac < 1.0
        self.ledger = ledger
        self.forest = forest
        self.keep_frac = keep_frac
        self.kernels = get_spill_kernels(ledger.process)
        # ids present ONLY in the LSM store (reloading removes the id; the
        # stale LSM row is overwritten on the next spill of that id).
        self.spilled: set[int] = set()
        # Sorted lo-limb prefilter over `spilled` (may carry stale entries
        # between cycles; exactness comes from the set).
        self._lo = np.empty(0, dtype=np.uint64)
        # Grid block chain holding the checkpointed spilled-id set (the
        # set can exceed the superblock's copy size; only the addresses
        # ride the superblock meta — the trailer pattern, reference:
        # src/vsr/superblock.zig:31-34).
        self._id_chain: list[int] = []
        # t_* keys: cumulative seconds per cycle stage (the spill bench's
        # isolating artifact — which part of the cycle carries the bill).
        # Overlap accounting: t_prefetch_worker = executor seconds spent
        # gathering prefetched rows; t_prefetch_wait = seconds admit
        # BLOCKED on an unfinished prefetch (0 wait = the gather fully hid
        # behind the previous batch's commit). lookup_ids/lookup_batches =
        # multi-lookup amortization (mean ids per batched LSM read).
        # `stats` is a registry-backed Mapping (tigerbeetle_tpu/metrics.py
        # StatGroup under the `spill.` prefix): dict reads everywhere stay
        # valid, and instrument() re-binds the storage onto the replica's /
        # bench's shared registry so overlap_report and the [stats] line
        # read the same numbers.
        self.metrics = Metrics()
        self.tracer = NULL_TRACER
        self.stats = self.metrics.group("spill", self.STAT_KEYS)
        # the IO executor seam (see module docstring / ThreadedSpillIO vs
        # DeferredSpillIO); None = fully inline synchronous IO
        self._io = _make_io(async_io, io)
        # rows in flight to the LSM sit in _staged (id -> (row, ful));
        # fetches check _staged first and barrier on the executor before
        # any direct forest read
        self._staged: dict[int, tuple[np.ndarray, int]] = {}  # vet: guarded-by=_staged_lock
        self._staged_lock = threading.Lock()
        # one outstanding prefetch (consumed by the next reload) + its two
        # alternating host staging slots
        self._prefetch: dict | None = None
        self._pf_slots = {"i": 0, "slots": [None, None]}
        # double-buffered reload staging (pad -> two fenced slots)
        self._reload_slots: dict[int, dict] = {}

    # ------------------------------------------------------------------
    # the IO executor seam
    # ------------------------------------------------------------------

    def _io_submit(self, fn, *args) -> None:
        if self._io is None:
            fn(*args)
            return
        self._io.submit(fn, *args)

    def io_drain(self) -> None:
        """Barrier: every queued LSM job has run (and surfaced its
        exception, if any). After this the forest is safe to read inline —
        only the commit thread submits jobs, so none can appear while the
        caller holds the drained state."""
        if self._io is not None:
            self._io.drain()

    def io_pump(self) -> None:
        """Non-blocking housekeeping: run deferred jobs (DeferredSpillIO)
        or reap finished worker jobs (ThreadedSpillIO). The replica calls
        this at its tick boundary — LSM insertion then never runs inside
        the commit dispatch path."""
        if self._io is not None:
            self._io.pump()

    def io_pending(self) -> int:
        """Queued-but-undrained job count (the replica's scrub pass skips
        a turn while inserts are in flight rather than reading blocks the
        worker may be mid-writing)."""
        return 0 if self._io is None else self._io.pending()

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def _prefilter(self, lo: np.ndarray) -> np.ndarray:
        """Lanes whose id lo-limb appears in the sorted prefilter."""
        if len(self._lo) == 0:
            return np.zeros(len(lo), dtype=bool)
        pos = np.searchsorted(self._lo, lo)
        pos_c = np.minimum(pos, len(self._lo) - 1)
        return self._lo[pos_c] == lo

    def referenced_spilled(self, arr: np.ndarray) -> list[int]:
        """Distinct spilled ids this batch references: its own ids (the
        exists/idempotency checks, reference: src/state_machine.zig:767-777,
        886-905) and post/void pending_id references (reference: :907-1014).
        """
        out: set[int] = set()
        if not self.spilled:
            return []
        cand = self._prefilter(arr["id_lo"])
        for i in np.nonzero(cand)[0]:
            key = int(arr["id_lo"][i]) | (int(arr["id_hi"][i]) << 64)
            if key in self.spilled:
                out.add(key)
        pv = (arr["flags"] & np.uint16(F_POST | F_VOID)) != 0
        if pv.any():
            cand = self._prefilter(arr["pending_id_lo"]) & pv
            for i in np.nonzero(cand)[0]:
                key = int(arr["pending_id_lo"][i]) | (
                    int(arr["pending_id_hi"][i]) << 64
                )
                if key in self.spilled:
                    out.add(key)
        return sorted(out)

    # ------------------------------------------------------------------
    # prefetch/commit overlap
    # ------------------------------------------------------------------

    @property
    def prefetch_enabled(self) -> bool:
        """True when prefetch_async can actually overlap (threaded
        executor) — callers gate side work (e.g. the backup's WAL peek)
        on this."""
        return self._io is not None and getattr(
            self._io, "settle_in_worker", False
        )

    def _pf_slot(self, k: int) -> dict:
        """One of two alternating prefetch staging slots, grown to cover
        k rows. Only one prefetch is ever outstanding and its rows are
        copied out synchronously at consume time, so alternation alone
        keeps a lingering job from racing a fresh submission."""
        pool = self._pf_slots
        i = pool["i"]
        pool["i"] = 1 - i
        slot = pool["slots"][i]
        cap = _next_pow2(k)
        if slot is None or slot["cap"] < cap:
            slot = pool["slots"][i] = {
                "rows": np.zeros((cap, ROW_WORDS), dtype=np.uint32),
                "ful": np.zeros(cap, dtype=np.uint32),
                "cap": cap,
            }
        return slot

    def prefetch_async(self, arr: np.ndarray) -> None:
        """Start gathering the referenced-spilled rows of an UPCOMING
        batch on the IO executor: the id scan runs inline (cheap numpy —
        and `spilled` mutates only on the commit thread, so the scan must
        not move to the worker), the LSM point reads + row staging run as
        one FIFO job behind every queued insert (so no drain barrier is
        needed). The admit() that commits the batch consumes the staged
        rows; content is stable meanwhile because an id's LSM row can only
        change after a reload removes it from `spilled`, and reloads
        happen only in admit on this same thread.

        Threaded executors only: on DeferredSpillIO the job would run
        inline on this same thread (no overlap to win), and its
        read-triggered settle could raise GridBlockCorrupt at the tick
        pump — outside the admit context where the replica's
        heal-and-retry contract lives."""
        if not self.prefetch_enabled or not self.spilled:
            return
        pf = self._prefetch
        if pf is not None and not pf["fut"].done():
            return  # one outstanding prefetch; don't pile up slot reuse
        ids = self.referenced_spilled(arr)
        if not ids:
            return
        slot = self._pf_slot(len(ids))
        fut = self._io.submit(self._prefetch_job, ids, slot)
        self._prefetch = {
            "fut": fut,
            "rows": slot["rows"],
            "ful": slot["ful"],
            "by_id": {id_: j for j, id_ in enumerate(ids)},
        }
        self.stats.add("prefetches")

    def _prefetch_job(self, ids: list[int], slot: dict) -> None:
        import time as _time

        t0 = _time.perf_counter()
        tok = self.tracer.start("spill.prefetch_worker", ids=len(ids))
        try:
            rows, ful = slot["rows"], slot["ful"]
            missing: list[tuple[int, int]] = []
            with self._staged_lock:
                for j, id_ in enumerate(ids):
                    hit = self._staged.get(id_)
                    if hit is not None:
                        rows[j] = hit[0]
                        ful[j] = hit[1]
                    else:
                        missing.append((j, id_))
            if missing:
                # FIFO position: every earlier insert already landed
                self._fetch_forest(missing, rows, ful)
            self.stats.add("t_prefetch_worker", _time.perf_counter() - t0)
        finally:
            self.tracer.stop(tok)

    def _consume_prefetch(self, ids, rows: np.ndarray,
                          ful: np.ndarray) -> list[tuple[int, int]]:
        """Fill rows/ful lanes served by the outstanding prefetch; returns
        the (lane, id) pairs it did not cover. Consumed once on any hit;
        a COMPLETE miss keeps it armed for a later batch (a driver may
        prefetch op N+1 before op N's own reload runs) — sound because a
        kept entry's id is still in `spilled` (only a reload that served
        it would have removed it), and an id's backing content is stable
        while spilled (see prefetch_async)."""
        import time as _time

        pf = self._prefetch
        if pf is None:
            return list(enumerate(ids))
        by_id = pf["by_id"]
        if not any(id_ in by_id for id_ in ids):
            return list(enumerate(ids))  # foreign batch: keep it armed
        self._prefetch = None
        t0 = _time.perf_counter()
        with self.tracer.span("spill.prefetch_wait"):
            # pump-aware (DeferredSpillIO runs inline)
            self._io.wait(pf["fut"])
        self.stats.add("t_prefetch_wait", _time.perf_counter() - t0)
        prows, pful = pf["rows"], pf["ful"]
        remaining: list[tuple[int, int]] = []
        for i, id_ in enumerate(ids):
            j = by_id.get(id_)
            if j is None:
                remaining.append((i, id_))
            else:
                rows[i] = prows[j]
                ful[i] = pful[j]
                self.stats.add("prefetched")
        return remaining

    # ------------------------------------------------------------------
    # admission: called before every create_transfers commit
    # ------------------------------------------------------------------

    def admit(self, arr: np.ndarray, n: int) -> None:
        with self.tracer.span("spill.admit", n=n), \
                self.metrics.histogram("spill.admit_us").time():
            self._admit(arr, n)

    def _admit(self, arr: np.ndarray, n: int) -> None:
        led = self.ledger
        # Capacity to free: the CONSERVATIVE occupancy transient, not the
        # true row growth. True growth is <= n + n_pv (an event's own id
        # yields a fresh insert OR a reload-then-exists, never both), but
        # the ledger charges +n at dispatch and only reconciles at drain —
        # so between reload and drain the counter can read
        # reloads (<= n + n_pv) + n. `need` must cover that transient or
        # the hard load guard would raise on a batch that actually fits.
        n_pv = int(((arr["flags"] & np.uint16(F_POST | F_VOID)) != 0).sum())
        reload_ids = self.referenced_spilled(arr)
        if led._xfer_used + n + len(reload_ids) > led._xfer_limit:
            self.cycle(need=2 * n + n_pv)
            # the cycle may have spilled rows this batch references
            reload_ids = self.referenced_spilled(arr)
        if reload_ids:
            self._reload_rows(reload_ids)
        if self._io is None or not self._io.settle_in_worker:
            # sync/deferred mode: discharge the deferred settles /
            # compaction debt HERE, after the cycle has committed (HBM
            # rebuilt, counters updated) — a GridBlockCorrupt raise from a
            # settle leaves the cycle done, so the replica's heal-and-retry
            # re-enters this admit with nothing to re-cycle and the settle
            # RESUMES
            self._settle_forest()

    def _settle_forest(self) -> None:
        """Discharge compaction debt and settle trees whose pending
        buffers crossed the size threshold, in the forest's fixed tree
        order (deterministic across replicas). Thresholded, not eager:
        settling every admit would write many tiny tables and churn the
        grid; below-threshold pendings settle lazily at reads/flush."""
        for tree in self.forest._trees():
            if (
                tree._compact_debt
                or tree._pending_rows >= tree.settle_max
            ):
                tree._settle()

    def _fetch(self, id_: int) -> tuple[bytes, int]:
        """One spilled row + fulfill byte: the in-flight staging area
        first (no barrier), then the LSM store (barrier: the queued
        inserts must land before a direct forest read)."""
        with self._staged_lock:
            hit = self._staged.get(id_)
        if hit is not None:
            return hit[0].tobytes(), hit[1]
        self.io_drain()
        g = self.forest.transfers
        ts_key = g.ids.get(g._id_key(id_))
        assert ts_key is not None, f"spilled id {id_} missing from LSM"
        row = g.objects.get(ts_key)
        assert row is not None
        ful = self.forest.posted.get(ts_key)
        return row, (ful[0] if ful else 0)

    def _fetch_forest(self, missing: list[tuple[int, int]],
                      rows: np.ndarray, ful: np.ndarray) -> None:
        """Resolve (lane, id) pairs against the forest with ONE vectorized
        multi-point-read per tree (IdTree -> ObjectTree -> posted) — the
        bloom/index amortization lives in Tree.get_many. Caller guarantees
        the forest is current (drained, or running ON the FIFO worker)."""
        g = self.forest.transfers
        ids_list = [id_ for _, id_ in missing]
        row_list, ts_keys = g.get_many_rows(ids_list)
        fuls = self.forest.posted.get_many(
            [t if t is not None else b"\x00" * 8 for t in ts_keys]
        )
        for (i, id_), row, tsk, f in zip(missing, row_list, ts_keys, fuls):
            assert tsk is not None and row is not None, (
                f"spilled id {id_} missing from LSM"
            )
            rows[i] = np.frombuffer(row, dtype=np.uint32)
            ful[i] = f[0] if f else 0
        self.stats.add("lookup_batches")
        self.stats.add("lookup_ids", len(missing))

    def _fetch_many(self, ids: list[int], rows: np.ndarray,
                    ful: np.ndarray) -> None:
        """Fill rows[:k]/ful[:k] for `ids`: prefetched rows first (no IO),
        then staged hits (no barrier), then ONE batched forest read after
        ONE io_drain."""
        remaining = self._consume_prefetch(ids, rows, ful)
        if not remaining:
            return
        missing: list[tuple[int, int]] = []
        with self._staged_lock:
            for i, id_ in remaining:
                hit = self._staged.get(id_)
                if hit is not None:
                    rows[i] = hit[0]
                    ful[i] = hit[1]
                else:
                    missing.append((i, id_))
        if not missing:
            return
        self.io_drain()
        self._fetch_forest(missing, rows, ful)

    def _reload_slot(self, pad: int) -> dict:
        """One of TWO alternating preallocated reload staging buffers per
        pad (the PR-1 _group_staging_slot pattern): batch N+1's rows stage
        into buffer B while buffer A's reload kernel (batch N) may still
        run. `fence` is the device result of the last reload dispatched
        from the buffer — on backends where jnp.asarray aliases host
        memory, mutating the buffer before that kernel retires would
        corrupt the in-flight rows. `used` bounds the stale-tail zeroing."""
        pool = self._reload_slots
        entry = pool.get(pad)
        if entry is None:
            entry = pool[pad] = {"i": 0, "slots": [None, None]}
        i = entry["i"]
        entry["i"] = 1 - i
        slot = entry["slots"][i]
        if slot is None:
            slot = entry["slots"][i] = {
                "rows": np.zeros((pad, ROW_WORDS), dtype=np.uint32),
                "ful": np.zeros(pad, dtype=np.uint32),
                "used": 0,
                "fence": None,
            }
        if slot["fence"] is not None:
            with self.tracer.span("spill.staging_wait"), \
                    self.metrics.histogram("spill.staging_wait_us").time():
                jax.block_until_ready(slot["fence"])
            slot["fence"] = None
        return slot

    def _reload_rows(self, ids: list[int]) -> None:
        import time as _time

        t0 = _time.perf_counter()
        led = self.ledger
        st = led.state
        for start in range(0, len(ids), CHUNK):
            chunk = ids[start : start + CHUNK]
            k = len(chunk)
            pad = CHUNK if len(ids) > CHUNK else _next_pow2(k)
            slot = self._reload_slot(pad)
            rows, ful = slot["rows"], slot["ful"]
            if slot["used"] > k:  # zero only the stale tail
                rows[k : slot["used"]] = 0
                ful[k : slot["used"]] = 0
            slot["used"] = k
            self._fetch_many(chunk, rows, ful)
            active = np.zeros(pad, dtype=bool)
            active[:k] = True
            (
                st["xfer_rows"], st["fulfill"], st["xfer_claim"],
                st["xfer_used_slots"], st["fault"], probe,
            ) = self.kernels.reload(
                st["xfer_rows"], st["fulfill"], st["xfer_claim"],
                st["xfer_used_slots"], st["fault"],
                jnp.asarray(rows), jnp.asarray(ful), jnp.asarray(active),
            )
            slot["fence"] = probe
            for id_ in chunk:
                self.spilled.discard(id_)
            led._xfer_used += k
            self.stats.add("reloaded", k)
        self.stats.add("t_reload", _time.perf_counter() - t0)

    def _stage_and_submit(self, rows: np.ndarray, ful: np.ndarray,
                          ids_lo: np.ndarray, ids_hi: np.ndarray,
                          ts_np: np.ndarray) -> None:
        """Stage one gathered cold chunk (rows visible to _fetch at once)
        and queue its LSM insertion on the IO worker. The job unstages
        only entries it staged itself (identity check): a later cycle may
        re-spill an id and overwrite the staged tuple before this job
        lands — its newer insert is FIFO-behind ours, so the LSM ends
        newest-wins either way."""
        k = len(rows)
        entries: dict[int, tuple] = {}
        with self._staged_lock:
            for i in range(k):
                key = int(ids_lo[i]) | (int(ids_hi[i]) << 64)
                tup = (rows[i], int(ful[i]))
                self._staged[key] = tup
                entries[key] = tup

        def job():
            import time as _time

            t0 = _time.perf_counter()
            # APPEND-THEN-SETTLE, always: the appends (settle=False) are
            # pure pending-appends that CANNOT raise, so every row and
            # fulfillment lands — and unstages — exactly once even when
            # the settle below trips GridBlockCorrupt. A raise then only
            # interrupts settling/compaction, which is resume-safe by the
            # _pending/_compact_debt contract (the next settle — a later
            # job, admit's _settle_forest, or the checkpoint flush —
            # resumes it); the old settle-inside-append ordering lost the
            # chunk's posted flags + unstage when a threaded worker raised
            # mid-insert and the tick pump routed the error to repair.
            g = self.forest.transfers
            g.insert_bulk(rows.view(np.uint8).reshape(k, 128), ts_np,
                          settle=False)
            nz = np.nonzero(ful)[0]
            if len(nz):
                self.forest.posted.put_array(
                    np.ascontiguousarray(
                        ts_np[nz].astype(">u8")
                    ).view(np.uint8).reshape(len(nz), 8),
                    ful[nz].astype(np.uint8).reshape(len(nz), 1),
                    settle=False,
                )
            with self._staged_lock:
                for key, tup in entries.items():
                    if self._staged.get(key) is tup:
                        del self._staged[key]
            # worker-thread seconds (accumulated under the stats lock's
            # coarse protection — a float add race would only smear stats)
            self.stats.add("t_lsm_worker", _time.perf_counter() - t0)
            if self._io is not None and self._io.settle_in_worker:
                # threaded mode settles on the worker; sync/deferred mode
                # leaves it to admit's _settle_forest (heal-retry context)
                self._settle_forest()

        self._io_submit(job)

    # ------------------------------------------------------------------
    # the spill cycle
    # ------------------------------------------------------------------

    def cycle(self, need: int) -> None:
        """Spill the cold majority to the LSM forest and rebuild the HBM
        table with the hot tail, guaranteeing room for `need` new rows.
        A host-paced maintenance op (the analog of the reference's paced
        compaction beats trading throughput for bounded memory). The scan
        and cold/hot split run ON DEVICE (SpillKernels.cycle_head /
        split_idx): the host fetches two words, not the whole table."""
        with self.tracer.span("spill.cycle", need=need):
            self._cycle(need)

    def _cycle(self, need: int) -> None:
        import time as _time

        led = self.ledger
        st = led.state
        t0 = _time.perf_counter()
        head = np.asarray(self.kernels.cycle_head(st["xfer_rows"], st["fault"]))
        live, fault = int(head[0]), int(head[1])
        if fault:
            raise_on_fault(fault, "spill cycle")
        if led._xfer_limit - need < 0:
            raise RuntimeError(
                f"batch needs {need} transfer slots but the table limit is "
                f"{led._xfer_limit}: grow ConfigProcess.transfer_slots_log2"
            )
        keep = min(int(live * self.keep_frac), led._xfer_limit - need)
        n_cold = live - keep
        if n_cold <= 0:
            return  # nothing live to spill
        cold_idx, hot_idx = self.kernels.split_idx(
            st["xfer_rows"], jnp.int32(n_cold)
        )
        n_hot = live - n_cold
        self.stats.add("t_scan", _time.perf_counter() - t0)
        t0 = _time.perf_counter()

        # 1. Cold rows -> host. The d2h gather is synchronous (the spilled
        # set must be exact before the next admit()), pipelined across
        # chunks; LSM insertion is NOT — rows stage in _staged and the IO
        # worker drains them into the forest while commits continue
        # (reference keeps all storage IO off the replica's hot path,
        # src/io/linux.zig:17-42).
        gathered = []
        for start in range(0, n_cold, CHUNK):
            k = min(CHUNK, n_cold - start)
            rows_d, ful_d = self.kernels.gather(
                st["xfer_rows"], st["fulfill"],
                cold_idx[start : start + CHUNK],
            )
            for buf in (rows_d, ful_d):
                try:
                    buf.copy_to_host_async()
                except (AttributeError, RuntimeError):
                    pass
            gathered.append((k, rows_d, ful_d))
        for k, rows_d, ful_d in gathered:
            # ascontiguousarray: some backends (axon) hand back arrays the
            # later .view(uint8) reinterpretation rejects
            rows = np.ascontiguousarray(np.asarray(rows_d)[:k])
            ful = np.ascontiguousarray(np.asarray(ful_d)[:k])
            self.stats.add("t_gather_d2h", _time.perf_counter() - t0)
            t0 = _time.perf_counter()
            ids_lo = rows[:, 0].astype(np.uint64) | (
                rows[:, 1].astype(np.uint64) << np.uint64(32)
            )
            ids_hi = rows[:, 2].astype(np.uint64) | (
                rows[:, 3].astype(np.uint64) << np.uint64(32)
            )
            ts_np = rows[:, 30].astype(np.uint64) | (
                rows[:, 31].astype(np.uint64) << np.uint64(32)
            )
            self._stage_and_submit(rows, ful, ids_lo, ids_hi, ts_np)
            self.spilled.update(
                (int(lo) | (int(hi) << 64))
                for lo, hi in zip(ids_lo, ids_hi)
            )
            self.stats.add("spilled", k)
            self.stats.add("t_stage", _time.perf_counter() - t0)
            t0 = _time.perf_counter()

        # 2. Rebuild: fresh table, reinsert the hot tail (device-to-device;
        #    hot rows never visit the host).
        cap1 = self.kernels.t_dump + 1
        new_rows = jnp.zeros((cap1, ROW_WORDS), dtype=U32)
        new_ful = jnp.zeros(cap1, dtype=U32)
        new_claim = jnp.full(cap1, ht.CLAIM_FREE, dtype=U32)
        new_used = jnp.uint64(0)
        new_fault = jnp.uint32(0)
        for start in range(0, n_hot, CHUNK):
            k = min(CHUNK, n_hot - start)
            rows_d, ful_d = self.kernels.gather(
                st["xfer_rows"], st["fulfill"],
                hot_idx[start : start + CHUNK],
            )
            active = np.zeros(CHUNK, dtype=bool)
            active[:k] = True
            new_rows, new_ful, new_claim, new_used, new_fault, _ = (
                self.kernels.reload(
                    new_rows, new_ful, new_claim, new_used, new_fault,
                    rows_d, ful_d, jnp.asarray(active),
                )
            )
        new_fault_host = int(np.asarray(new_fault))
        if new_fault_host:
            raise_on_fault(new_fault_host, "spill rebuild")
        st["xfer_rows"] = new_rows
        st["fulfill"] = new_ful
        st["xfer_claim"] = new_claim
        st["xfer_used_slots"] = new_used
        led._xfer_used = n_hot
        led._occupancy_epoch += 1
        self._lo = np.sort(
            np.array([x & ((1 << 64) - 1) for x in self.spilled], dtype=np.uint64)
        )
        self.stats.add("t_rebuild", _time.perf_counter() - t0)
        self.stats.add("cycles")

    # ------------------------------------------------------------------
    # lookup / extract merging
    # ------------------------------------------------------------------

    def merge_lookup_rows(self, ids: list[int], found: np.ndarray,
                          rows: np.ndarray) -> bytes:
        """Reply body: wire rows in request order, HBM hits from the device
        lookup, spilled hits from the LSM store, misses skipped (_fetch
        barriers internally when it must read the forest)."""
        out = []
        for i, id_ in enumerate(ids):
            if found[i]:
                out.append(rows[i].tobytes())
            elif id_ in self.spilled:
                out.append(self._fetch(id_)[0])
        return b"".join(out)

    def extract_into(self, transfers: dict, posted: dict) -> None:
        """Merge spilled rows into extract() results (parity surface).
        Sorted: dict insertion order is part of the extract surface
        (parity dumps serialize it), and set order is not stable."""
        self.io_drain()
        for id_ in sorted(self.spilled):
            row, ful = self._fetch(id_)
            t = types.Transfer.from_np(
                np.frombuffer(row, dtype=types.TRANSFER_DTYPE)[0]
            )
            transfers[t.id] = t
            if ful:
                posted[t.timestamp] = ful

    # ------------------------------------------------------------------
    # durability
    # ------------------------------------------------------------------

    def checkpoint_meta(self) -> dict:
        """Persist the spill store: the spilled-id set goes into a grid
        block chain (it can exceed the superblock copy size; the forest's
        IdTree holds a superset — this exact set exists to exclude
        reloaded-and-stale LSM entries), then the forest checkpoint flushes
        trees, writes the manifest log, and encodes the free set LAST (so
        the id blocks created here are covered, and the previous chain's
        staged releases apply)."""
        from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX

        self.io_drain()  # queued inserts are part of this checkpoint
        g = self.forest.grid
        for address in self._id_chain:
            g.release(address)  # staged until the encode below
        payload = b"".join(
            x.to_bytes(16, "little") for x in sorted(self.spilled)
        )
        per_block = BLOCK_PAYLOAD_MAX // 16 * 16
        self._id_chain = [
            g.create_block(payload[i : i + per_block])
            for i in range(0, len(payload), per_block)
        ]
        manifest = self.forest.checkpoint()
        return {
            "manifest": manifest,
            "spilled_blocks": list(self._id_chain),
            "spilled_count": len(self.spilled),
        }

    def restore(self, meta: dict) -> None:
        self.io_drain()
        with self._staged_lock:
            self._staged.clear()
        self._prefetch = None  # gathered against the pre-restore store
        self.forest.restore(meta["manifest"])
        self._id_chain = list(meta["spilled_blocks"])
        self.spilled = set()
        for address in self._id_chain:
            raw = self.forest.grid.read_block(address)
            for i in range(0, len(raw), 16):
                self.spilled.add(int.from_bytes(raw[i : i + 16], "little"))
        assert len(self.spilled) == int(meta["spilled_count"])
        self._lo = np.sort(
            np.array([x & ((1 << 64) - 1) for x in self.spilled], dtype=np.uint64)
        )

    def overlap_report(self) -> dict:
        """The bench's overlap-accounting artifact (analogous to PR 1's
        shadow_upload_overlap): spill_overlap = fraction of prefetch-
        gather seconds hidden behind commits (1.0 = admit never waited);
        spill_lookup_batch = mean ids per batched LSM multi-lookup."""
        s = self.stats
        worker = s["t_prefetch_worker"]
        overlap = (
            round(max(0.0, 1.0 - s["t_prefetch_wait"] / worker), 4)
            if worker > 0 else None
        )
        batch = (
            round(s["lookup_ids"] / s["lookup_batches"], 1)
            if s["lookup_batches"] else None
        )
        return {"spill_overlap": overlap, "spill_lookup_batch": batch}


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p
