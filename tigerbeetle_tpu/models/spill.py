"""HBM↔LSM spill scheduler: the bounded-memory story.

The device ledger's transfer table is a capacity-bounded HBM hash table
(models/ledger.py); the reference's store is an unbounded LSM forest with a
residency-guaranteed in-memory cache (reference: src/lsm/groove.zig:602-760
prefetch contract; src/lsm/cache_map.zig:10-25 CacheMap residency). This
module closes that gap the TPU-native way:

- HBM is the CacheMap: every row a batch can touch is resident BEFORE the
  kernel runs, so the kernels stay pure, synchronous, and data-parallel.
- The LSM forest (lsm/groove.py over the grid) is the backing store: when
  HBM occupancy reaches the spill trigger, the OLDEST transfers spill to
  the forest (timestamp order — the reference's object trees are
  timestamp-keyed for exactly this access pattern) and the HBM table is
  rebuilt with only the hot tail. Rebuilding also sheds rollback
  tombstones, so a cycle resets probe-chain density to the live load.
- Before every commit, the host checks the batch's id and pending_id
  references against the spilled-id set (sorted-limb prefilter + exact
  set — the host analog of the reference's per-table bloom filters,
  src/lsm/bloom_filter.zig) and RELOADS referenced spilled rows into HBM.
  This is the prefetch contract: after admit(), the kernels' HBM lookups
  are equivalent to lookups against the full store.

Accounts do not spill: account rows are the working set of every batch
(dr/cr balance updates), and the reference's workload shape is a bounded
account population with unbounded transfer history — the transfer table is
the wall that matters (BASELINE.md: 10k accounts, 10M+ transfers). The
account-table guard stays hard.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.models.ledger import (
    FAULT_CAPACITY,
    FAULT_CLAIM,
    FAULT_PROBE,
    raise_on_fault,
)
from tigerbeetle_tpu.models.validate import F_POST, F_VOID
from tigerbeetle_tpu.ops import hashtable as ht

U64 = jnp.uint64
U32 = jnp.uint32
ROW_WORDS = 32

CHUNK = 8192  # static shape of gather/reload kernels (= BATCH_PAD)


_SPILL_KERNELS_CACHE: dict = {}


def get_spill_kernels(process) -> "SpillKernels":
    """One SpillKernels per table geometry (stateless; same contract as
    models.ledger.get_kernels — fresh managers reuse the jit cache)."""
    k = _SPILL_KERNELS_CACHE.get(process)
    if k is None:
        k = _SPILL_KERNELS_CACHE[process] = SpillKernels(process)
    return k


class SpillKernels:
    """Jitted device ops for the spill cycle, closed over table geometry."""

    def __init__(self, process):
        self.t_log2 = process.transfer_slots_log2
        self.t_dump = 1 << self.t_log2
        self.ts_occ = jax.jit(self._ts_occ)
        self.gather = jax.jit(self._gather)
        self.reload = jax.jit(self._reload, donate_argnums=(0, 1, 2))

    def _ts_occ(self, xfer_rows):
        """Per-slot (timestamp u64, occupied bool) — the cycle's scan."""
        occ = ht.occupied_mask(xfer_rows).at[self.t_dump].set(False)
        ts = xfer_rows[:, 30].astype(U64) | (
            xfer_rows[:, 31].astype(U64) << jnp.uint64(32)
        )
        return ts, occ

    def _gather(self, xfer_rows, fulfill, idx):
        return xfer_rows[idx], fulfill[idx]

    def _reload(self, xfer_rows, fulfill, claim, used_slots, fault,
                rows_b, ful_b, active):
        """Insert absent rows (verbatim stored content, fulfill included)
        into the transfer table. Lanes whose key is already resident are
        skipped — reload is idempotent. Every write gates on the sticky
        fault word (models/ledger.py fault protocol)."""
        key4 = rows_b[:, :4]
        _, found, res = ht.lookup(key4, xfer_rows, self.t_log2)
        need = active & ~found
        slots, claim, ins_res = ht.claim_slots(
            key4, need, xfer_rows, claim, self.t_log2
        )
        n_new = jnp.sum(need).astype(U64)
        cap_bad = used_slots + n_new > np.uint64(self.t_dump // 2)
        fault = (
            fault
            | jnp.where(jnp.any(active & ~res), jnp.uint32(FAULT_PROBE), jnp.uint32(0))
            | jnp.where(jnp.any(~ins_res), jnp.uint32(FAULT_CLAIM), jnp.uint32(0))
            | jnp.where(cap_bad, jnp.uint32(FAULT_CAPACITY), jnp.uint32(0))
        )
        proceed = fault == 0
        w = jnp.where(proceed & need, slots, self.t_dump)
        xfer_rows = xfer_rows.at[w].set(rows_b)
        fulfill = fulfill.at[w].set(ful_b)
        used_slots = used_slots + jnp.where(proceed, n_new, jnp.uint64(0))
        return xfer_rows, fulfill, claim, used_slots, fault


class SpillManager:
    """Owns the spilled-id set, the LSM backing store, and the cycle.

    Attached to a DeviceLedger via ``DeviceLedger(forest=...)``; the ledger
    calls ``admit(arr, n)`` before every create_transfers commit and merges
    spilled rows into lookups/extract.
    """

    def __init__(self, ledger, forest, keep_frac: float = 0.25,
                 async_io: bool = True):
        assert 0.0 < keep_frac < 1.0
        self.ledger = ledger
        self.forest = forest
        self.keep_frac = keep_frac
        self.kernels = get_spill_kernels(ledger.process)
        # ids present ONLY in the LSM store (reloading removes the id; the
        # stale LSM row is overwritten on the next spill of that id).
        self.spilled: set[int] = set()
        # Sorted lo-limb prefilter over `spilled` (may carry stale entries
        # between cycles; exactness comes from the set).
        self._lo = np.empty(0, dtype=np.uint64)
        # Grid block chain holding the checkpointed spilled-id set (the
        # set can exceed the superblock's copy size; only the addresses
        # ride the superblock meta — the trailer pattern, reference:
        # src/vsr/superblock.zig:31-34).
        self._id_chain: list[int] = []
        # t_* keys: cumulative seconds per cycle stage (the spill bench's
        # isolating artifact — which part of the cycle carries the bill)
        self.stats = {
            "cycles": 0, "spilled": 0, "reloaded": 0,
            "t_scan": 0.0, "t_gather_d2h": 0.0, "t_stage": 0.0,
            "t_rebuild": 0.0, "t_reload": 0.0, "t_lsm_worker": 0.0,
        }
        # Async IO executor (reference: ALL storage IO rides one event
        # loop off the replica's hot path, src/io/linux.zig:17-42): the
        # spill cycle hands LSM insertion to ONE worker (FIFO = the insert
        # order is deterministic) and commit continues as soon as the d2h
        # gather lands. Rows in flight sit in _staged (id -> (row, ful));
        # _fetch checks _staged first and barriers on the queue before any
        # direct forest read. TB_SPILL_SYNC=1 forces inline IO (debugging).
        self._io: ThreadPoolExecutor | None = (
            None
            if not async_io or os.environ.get("TB_SPILL_SYNC") == "1"
            else ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="spill-io"
            )
        )
        self._io_jobs: list[Future] = []
        self._staged: dict[int, tuple[np.ndarray, int]] = {}
        self._staged_lock = threading.Lock()

    # ------------------------------------------------------------------
    # the IO executor seam
    # ------------------------------------------------------------------

    def _io_submit(self, fn, *args) -> None:
        if self._io is None:
            fn(*args)
            return
        self._io_jobs.append(self._io.submit(fn, *args))

    def io_drain(self) -> None:
        """Barrier: every queued LSM job has run (and surfaced its
        exception, if any). After this the forest is safe to read inline —
        only the commit thread submits jobs, so none can appear while the
        caller holds the drained state."""
        jobs, self._io_jobs = self._io_jobs, []
        for f in jobs:
            f.result()

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def _prefilter(self, lo: np.ndarray) -> np.ndarray:
        """Lanes whose id lo-limb appears in the sorted prefilter."""
        if len(self._lo) == 0:
            return np.zeros(len(lo), dtype=bool)
        pos = np.searchsorted(self._lo, lo)
        pos_c = np.minimum(pos, len(self._lo) - 1)
        return self._lo[pos_c] == lo

    def referenced_spilled(self, arr: np.ndarray) -> list[int]:
        """Distinct spilled ids this batch references: its own ids (the
        exists/idempotency checks, reference: src/state_machine.zig:767-777,
        886-905) and post/void pending_id references (reference: :907-1014).
        """
        out: set[int] = set()
        if not self.spilled:
            return []
        cand = self._prefilter(arr["id_lo"])
        for i in np.nonzero(cand)[0]:
            key = int(arr["id_lo"][i]) | (int(arr["id_hi"][i]) << 64)
            if key in self.spilled:
                out.add(key)
        pv = (arr["flags"] & np.uint16(F_POST | F_VOID)) != 0
        if pv.any():
            cand = self._prefilter(arr["pending_id_lo"]) & pv
            for i in np.nonzero(cand)[0]:
                key = int(arr["pending_id_lo"][i]) | (
                    int(arr["pending_id_hi"][i]) << 64
                )
                if key in self.spilled:
                    out.add(key)
        return sorted(out)

    # ------------------------------------------------------------------
    # admission: called before every create_transfers commit
    # ------------------------------------------------------------------

    def admit(self, arr: np.ndarray, n: int) -> None:
        led = self.ledger
        # Capacity to free: the CONSERVATIVE occupancy transient, not the
        # true row growth. True growth is <= n + n_pv (an event's own id
        # yields a fresh insert OR a reload-then-exists, never both), but
        # the ledger charges +n at dispatch and only reconciles at drain —
        # so between reload and drain the counter can read
        # reloads (<= n + n_pv) + n. `need` must cover that transient or
        # the hard load guard would raise on a batch that actually fits.
        n_pv = int(((arr["flags"] & np.uint16(F_POST | F_VOID)) != 0).sum())
        reload_ids = self.referenced_spilled(arr)
        if led._xfer_used + n + len(reload_ids) > led._xfer_limit:
            self.cycle(need=2 * n + n_pv)
            # the cycle may have spilled rows this batch references
            reload_ids = self.referenced_spilled(arr)
        if reload_ids:
            self._reload_rows(reload_ids)
        if self._io is None:
            # sync mode: discharge the deferred settles / compaction debt
            # HERE, after the cycle has committed (HBM rebuilt, counters
            # updated) — a GridBlockCorrupt raise from a settle leaves the
            # cycle done, so the replica's heal-and-retry re-enters this
            # admit with nothing to re-cycle and the settle RESUMES
            self._settle_forest()

    def _settle_forest(self) -> None:
        """Discharge compaction debt and settle trees whose pending
        buffers crossed the size threshold, in the forest's fixed tree
        order (deterministic across replicas). Thresholded, not eager:
        settling every admit would write many tiny tables and churn the
        grid; below-threshold pendings settle lazily at reads/flush."""
        for tree in self.forest._trees():
            if (
                tree._compact_debt
                or tree._pending_rows >= tree.settle_max
            ):
                tree._settle()

    def _fetch(self, id_: int) -> tuple[bytes, int]:
        """One spilled row + fulfill byte: the in-flight staging area
        first (no barrier), then the LSM store (barrier: the queued
        inserts must land before a direct forest read)."""
        with self._staged_lock:
            hit = self._staged.get(id_)
        if hit is not None:
            return hit[0].tobytes(), hit[1]
        self.io_drain()
        g = self.forest.transfers
        ts_key = g.ids.get(g._id_key(id_))
        assert ts_key is not None, f"spilled id {id_} missing from LSM"
        row = g.objects.get(ts_key)
        assert row is not None
        ful = self.forest.posted.get(ts_key)
        return row, (ful[0] if ful else 0)

    def _fetch_many(self, ids: list[int], rows: np.ndarray,
                    ful: np.ndarray) -> None:
        """Fill rows[:k]/ful[:k] for `ids`: staged hits copied without a
        barrier, the rest read from the forest after ONE io_drain."""
        missing: list[tuple[int, int]] = []
        with self._staged_lock:
            for i, id_ in enumerate(ids):
                hit = self._staged.get(id_)
                if hit is not None:
                    rows[i] = hit[0]
                    ful[i] = hit[1]
                else:
                    missing.append((i, id_))
        if not missing:
            return
        self.io_drain()
        g = self.forest.transfers
        for i, id_ in missing:
            ts_key = g.ids.get(g._id_key(id_))
            assert ts_key is not None, f"spilled id {id_} missing from LSM"
            row = g.objects.get(ts_key)
            assert row is not None
            rows[i] = np.frombuffer(row, dtype=np.uint32)
            f = self.forest.posted.get(ts_key)
            ful[i] = f[0] if f else 0

    def _reload_rows(self, ids: list[int]) -> None:
        import time as _time

        t0 = _time.perf_counter()
        led = self.ledger
        st = led.state
        for start in range(0, len(ids), CHUNK):
            chunk = ids[start : start + CHUNK]
            k = len(chunk)
            pad = CHUNK if len(ids) > CHUNK else _next_pow2(k)
            rows = np.zeros((pad, ROW_WORDS), dtype=np.uint32)
            ful = np.zeros(pad, dtype=np.uint32)
            self._fetch_many(chunk, rows, ful)
            active = np.zeros(pad, dtype=bool)
            active[:k] = True
            (
                st["xfer_rows"], st["fulfill"], st["xfer_claim"],
                st["xfer_used_slots"], st["fault"],
            ) = self.kernels.reload(
                st["xfer_rows"], st["fulfill"], st["xfer_claim"],
                st["xfer_used_slots"], st["fault"],
                jnp.asarray(rows), jnp.asarray(ful), jnp.asarray(active),
            )
            for id_ in chunk:
                self.spilled.discard(id_)
            led._xfer_used += k
            self.stats["reloaded"] += k
        self.stats["t_reload"] += _time.perf_counter() - t0

    def _stage_and_submit(self, rows: np.ndarray, ful: np.ndarray,
                          ids_lo: np.ndarray, ids_hi: np.ndarray,
                          ts_np: np.ndarray) -> None:
        """Stage one gathered cold chunk (rows visible to _fetch at once)
        and queue its LSM insertion on the IO worker. The job unstages
        only entries it staged itself (identity check): a later cycle may
        re-spill an id and overwrite the staged tuple before this job
        lands — its newer insert is FIFO-behind ours, so the LSM ends
        newest-wins either way."""
        k = len(rows)
        entries: dict[int, tuple] = {}
        with self._staged_lock:
            for i in range(k):
                key = int(ids_lo[i]) | (int(ids_hi[i]) << 64)
                tup = (rows[i], int(ful[i]))
                self._staged[key] = tup
                entries[key] = tup

        def job():
            import time as _time

            t0 = _time.perf_counter()
            # sync (replica-attached) mode: settle=False — the job is a
            # pure pending-append that CANNOT raise, so it runs exactly
            # once even when a later settle trips GridBlockCorrupt and
            # the replica retries the commit (admit re-drives the settle
            # via _settle_forest, resume-safe). Async mode settles on the
            # worker thread as usual.
            settle = self._io is not None
            g = self.forest.transfers
            g.insert_bulk(rows.view(np.uint8).reshape(k, 128), ts_np,
                          settle=settle)
            nz = np.nonzero(ful)[0]
            if len(nz):
                self.forest.posted.put_array(
                    np.ascontiguousarray(
                        ts_np[nz].astype(">u8")
                    ).view(np.uint8).reshape(len(nz), 8),
                    ful[nz].astype(np.uint8).reshape(len(nz), 1),
                    settle=settle,
                )
            with self._staged_lock:
                for key, tup in entries.items():
                    if self._staged.get(key) is tup:
                        del self._staged[key]
            # worker-thread seconds (accumulated under the stats lock's
            # coarse protection — a float add race would only smear stats)
            self.stats["t_lsm_worker"] += _time.perf_counter() - t0

        self._io_submit(job)

    # ------------------------------------------------------------------
    # the spill cycle
    # ------------------------------------------------------------------

    def cycle(self, need: int) -> None:
        """Spill the cold majority to the LSM forest and rebuild the HBM
        table with the hot tail, guaranteeing room for `need` new rows.
        A host-paced maintenance op (the analog of the reference's paced
        compaction beats trading throughput for bounded memory)."""
        import time as _time

        led = self.ledger
        st = led.state
        t0 = _time.perf_counter()
        fault = int(np.asarray(st["fault"]))
        if fault:
            raise_on_fault(fault, "spill cycle")
        ts, occ = self.kernels.ts_occ(st["xfer_rows"])
        ts = np.asarray(ts)
        occ = np.asarray(occ)
        live = int(occ.sum())
        if led._xfer_limit - need < 0:
            raise RuntimeError(
                f"batch needs {need} transfer slots but the table limit is "
                f"{led._xfer_limit}: grow ConfigProcess.transfer_slots_log2"
            )
        keep = min(int(live * self.keep_frac), led._xfer_limit - need)
        ts_live = np.sort(ts[occ])  # timestamps are unique by construction
        n_cold = live - keep
        if n_cold <= 0:
            return  # nothing live to spill
        # first KEPT timestamp (keep == 0: spill everything)
        watermark = (
            int(ts_live[n_cold]) if n_cold < live else int(ts_live[-1]) + 1
        )
        cold = occ & (ts < watermark)
        hot = occ & (ts >= watermark)
        cold_idx = np.nonzero(cold)[0].astype(np.int32)
        hot_idx = np.nonzero(hot)[0].astype(np.int32)
        self.stats["t_scan"] += _time.perf_counter() - t0
        t0 = _time.perf_counter()

        # 1. Cold rows -> host. The d2h gather is synchronous (the spilled
        # set must be exact before the next admit()), pipelined across
        # chunks; LSM insertion is NOT — rows stage in _staged and the IO
        # worker drains them into the forest while commits continue
        # (reference keeps all storage IO off the replica's hot path,
        # src/io/linux.zig:17-42).
        gathered = []
        for start in range(0, len(cold_idx), CHUNK):
            idx = cold_idx[start : start + CHUNK]
            idx_pad = np.full(CHUNK, self.kernels.t_dump, dtype=np.int32)
            idx_pad[: len(idx)] = idx
            rows_d, ful_d = self.kernels.gather(
                st["xfer_rows"], st["fulfill"], jnp.asarray(idx_pad)
            )
            for buf in (rows_d, ful_d):
                try:
                    buf.copy_to_host_async()
                except (AttributeError, RuntimeError):
                    pass
            gathered.append((idx, rows_d, ful_d))
        for idx, rows_d, ful_d in gathered:
            # ascontiguousarray: some backends (axon) hand back arrays the
            # later .view(uint8) reinterpretation rejects
            rows = np.ascontiguousarray(np.asarray(rows_d)[: len(idx)])
            ful = np.ascontiguousarray(np.asarray(ful_d)[: len(idx)])
            self.stats["t_gather_d2h"] += _time.perf_counter() - t0
            t0 = _time.perf_counter()
            ids_lo = rows[:, 0].astype(np.uint64) | (
                rows[:, 1].astype(np.uint64) << np.uint64(32)
            )
            ids_hi = rows[:, 2].astype(np.uint64) | (
                rows[:, 3].astype(np.uint64) << np.uint64(32)
            )
            ts_np = rows[:, 30].astype(np.uint64) | (
                rows[:, 31].astype(np.uint64) << np.uint64(32)
            )
            self._stage_and_submit(rows, ful, ids_lo, ids_hi, ts_np)
            self.spilled.update(
                (int(lo) | (int(hi) << 64))
                for lo, hi in zip(ids_lo, ids_hi)
            )
            self.stats["spilled"] += len(idx)
            self.stats["t_stage"] += _time.perf_counter() - t0
            t0 = _time.perf_counter()

        # 2. Rebuild: fresh table, reinsert the hot tail (device-to-device;
        #    hot rows never visit the host).
        cap1 = self.kernels.t_dump + 1
        new_rows = jnp.zeros((cap1, ROW_WORDS), dtype=U32)
        new_ful = jnp.zeros(cap1, dtype=U32)
        new_claim = jnp.full(cap1, ht.CLAIM_FREE, dtype=U32)
        new_used = jnp.uint64(0)
        new_fault = jnp.uint32(0)
        for start in range(0, len(hot_idx), CHUNK):
            idx = hot_idx[start : start + CHUNK]
            idx_pad = np.full(CHUNK, self.kernels.t_dump, dtype=np.int32)
            idx_pad[: len(idx)] = idx
            rows_d, ful_d = self.kernels.gather(
                st["xfer_rows"], st["fulfill"], jnp.asarray(idx_pad)
            )
            active = np.zeros(CHUNK, dtype=bool)
            active[: len(idx)] = True
            new_rows, new_ful, new_claim, new_used, new_fault = (
                self.kernels.reload(
                    new_rows, new_ful, new_claim, new_used, new_fault,
                    rows_d, ful_d, jnp.asarray(active),
                )
            )
        new_fault_host = int(np.asarray(new_fault))
        if new_fault_host:
            raise_on_fault(new_fault_host, "spill rebuild")
        st["xfer_rows"] = new_rows
        st["fulfill"] = new_ful
        st["xfer_claim"] = new_claim
        st["xfer_used_slots"] = new_used
        led._xfer_used = len(hot_idx)
        led._occupancy_epoch += 1
        self._lo = np.sort(
            np.array([x & ((1 << 64) - 1) for x in self.spilled], dtype=np.uint64)
        )
        self.stats["t_rebuild"] += _time.perf_counter() - t0
        self.stats["cycles"] += 1

    # ------------------------------------------------------------------
    # lookup / extract merging
    # ------------------------------------------------------------------

    def merge_lookup_rows(self, ids: list[int], found: np.ndarray,
                          rows: np.ndarray) -> bytes:
        """Reply body: wire rows in request order, HBM hits from the device
        lookup, spilled hits from the LSM store, misses skipped (_fetch
        barriers internally when it must read the forest)."""
        out = []
        for i, id_ in enumerate(ids):
            if found[i]:
                out.append(rows[i].tobytes())
            elif id_ in self.spilled:
                out.append(self._fetch(id_)[0])
        return b"".join(out)

    def extract_into(self, transfers: dict, posted: dict) -> None:
        """Merge spilled rows into extract() results (parity surface)."""
        self.io_drain()
        for id_ in self.spilled:
            row, ful = self._fetch(id_)
            t = types.Transfer.from_np(
                np.frombuffer(row, dtype=types.TRANSFER_DTYPE)[0]
            )
            transfers[t.id] = t
            if ful:
                posted[t.timestamp] = ful

    # ------------------------------------------------------------------
    # durability
    # ------------------------------------------------------------------

    def checkpoint_meta(self) -> dict:
        """Persist the spill store: the spilled-id set goes into a grid
        block chain (it can exceed the superblock copy size; the forest's
        IdTree holds a superset — this exact set exists to exclude
        reloaded-and-stale LSM entries), then the forest checkpoint flushes
        trees, writes the manifest log, and encodes the free set LAST (so
        the id blocks created here are covered, and the previous chain's
        staged releases apply)."""
        from tigerbeetle_tpu.lsm.grid import BLOCK_PAYLOAD_MAX

        self.io_drain()  # queued inserts are part of this checkpoint
        g = self.forest.grid
        for address in self._id_chain:
            g.release(address)  # staged until the encode below
        payload = b"".join(
            x.to_bytes(16, "little") for x in sorted(self.spilled)
        )
        per_block = BLOCK_PAYLOAD_MAX // 16 * 16
        self._id_chain = [
            g.create_block(payload[i : i + per_block])
            for i in range(0, len(payload), per_block)
        ]
        manifest = self.forest.checkpoint()
        return {
            "manifest": manifest,
            "spilled_blocks": list(self._id_chain),
            "spilled_count": len(self.spilled),
        }

    def restore(self, meta: dict) -> None:
        self.io_drain()
        with self._staged_lock:
            self._staged.clear()
        self.forest.restore(meta["manifest"])
        self._id_chain = list(meta["spilled_blocks"])
        self.spilled = set()
        for address in self._id_chain:
            raw = self.forest.grid.read_block(address)
            for i in range(0, len(raw), 16):
                self.spilled.add(int.from_bytes(raw[i : i + 16], "little"))
        assert len(self.spilled) == int(meta["spilled_count"])
        self._lo = np.sort(
            np.array([x & ((1 << 64) - 1) for x in self.spilled], dtype=np.uint64)
        )


def _next_pow2(n: int, floor: int = 8) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p
