"""Pure, shape-polymorphic validation ladders for the ledger state machine.

These functions encode the exact result-code precedence of the reference
(reference: src/state_machine.zig:738-1077 create_account / create_transfer /
post_or_void_pending_transfer and the exists-check helpers). They are shared
verbatim between the vectorized fast path and the exact serial scan kernel in
models/ledger.py, so both execution tiers agree with the oracle by
construction.

Inputs are dicts of per-lane arrays (a scalar lane in the serial kernel, a
full batch in the vectorized path):
- `ev`: the event being validated (transfer or account wire fields).
- `dr`/`cr`/`ex`/`p`/`pdr`/`pcr`: gathered store rows (garbage when the
  corresponding *_found flag is False — every use is gated).
All u128 quantities are (lo, hi) u64 limb pairs — see ops/u128.py.
"""

from __future__ import annotations

import jax.numpy as jnp

from tigerbeetle_tpu.constants import NS_PER_S
from tigerbeetle_tpu.ops import u128

U32 = jnp.uint32
U64 = jnp.uint64

# Transfer flag bits (reference: src/tigerbeetle.zig:91-104).
F_LINKED = 1
F_PENDING = 2
F_POST = 4
F_VOID = 8
F_BAL_DR = 16
F_BAL_CR = 32
TRANSFER_FLAGS_PADDING = 0xFFFF & ~0b111111

# Account flag bits (reference: src/tigerbeetle.zig:42-62).
A_LINKED = 1
A_DR_LIMIT = 2  # debits_must_not_exceed_credits
A_CR_LIMIT = 4  # credits_must_not_exceed_debits
ACCOUNT_FLAGS_PADDING = 0xFFFF & ~0b111


class Ladder:
    """First-match-wins result-code accumulator."""

    def __init__(self, r0):
        self.r = r0

    def set(self, cond, code: int):
        self.r = jnp.where((self.r == 0) & cond, jnp.uint32(code), self.r)

    def merge(self, other_r):
        self.r = jnp.where(self.r == 0, other_r, self.r)


def transfer_common(ev, r0):
    """Checks shared by the simple and post/void branches
    (reference: src/state_machine.zig:779-787)."""
    lad = Ladder(r0)
    lad.set((ev["flags"] & jnp.uint32(TRANSFER_FLAGS_PADDING)) != 0, 4)  # reserved_flag
    lad.set(u128.is_zero(ev["id_lo"], ev["id_hi"]), 5)  # id_must_not_be_zero
    lad.set(u128.is_max(ev["id_lo"], ev["id_hi"]), 6)  # id_must_not_be_int_max
    return lad.r


def transfer_exists_code(ev, ex):
    """reference: src/state_machine.zig:886-905 (simple-transfer exists)."""
    lad = Ladder(jnp.zeros_like(ev["flags"]))
    lad.set(ev["flags"] != ex["flags"], 36)
    lad.set(~u128.eq(ev["dr_lo"], ev["dr_hi"], ex["dr_lo"], ex["dr_hi"]), 37)
    lad.set(~u128.eq(ev["cr_lo"], ev["cr_hi"], ex["cr_lo"], ex["cr_hi"]), 38)
    lad.set(~u128.eq(ev["amt_lo"], ev["amt_hi"], ex["amt_lo"], ex["amt_hi"]), 39)
    lad.set(~u128.eq(ev["ud128_lo"], ev["ud128_hi"], ex["ud128_lo"], ex["ud128_hi"]), 41)
    lad.set(ev["ud64"] != ex["ud64"], 42)
    lad.set(ev["ud32"] != ex["ud32"], 43)
    lad.set(ev["timeout"] != ex["timeout"], 44)
    lad.set(ev["code"] != ex["code"], 45)
    lad.set(jnp.ones_like(ev["flags"], dtype=bool), 46)  # exists
    return lad.r


def validate_simple_transfer(r0, ev, dr, cr, dr_found, cr_found, ex, ex_found):
    """The non-post/void create_transfer path
    (reference: src/state_machine.zig:789-884). Returns (result, amt_lo, amt_hi)
    where amt is the clamped amount to apply when result == 0."""
    flags = ev["flags"]
    pending = (flags & jnp.uint32(F_PENDING)) != 0
    bal_dr = (flags & jnp.uint32(F_BAL_DR)) != 0
    bal_cr = (flags & jnp.uint32(F_BAL_CR)) != 0

    lad = Ladder(r0)
    lad.set(u128.is_zero(ev["dr_lo"], ev["dr_hi"]), 8)
    lad.set(u128.is_max(ev["dr_lo"], ev["dr_hi"]), 9)
    lad.set(u128.is_zero(ev["cr_lo"], ev["cr_hi"]), 10)
    lad.set(u128.is_max(ev["cr_lo"], ev["cr_hi"]), 11)
    lad.set(u128.eq(ev["cr_lo"], ev["cr_hi"], ev["dr_lo"], ev["dr_hi"]), 12)
    lad.set(~u128.is_zero(ev["pid_lo"], ev["pid_hi"]), 13)  # pending_id_must_be_zero
    lad.set(~pending & (ev["timeout"] != 0), 17)
    lad.set(
        ~bal_dr & ~bal_cr & u128.is_zero(ev["amt_lo"], ev["amt_hi"]), 18
    )  # amount_must_not_be_zero
    lad.set(ev["ledger"] == 0, 19)
    lad.set(ev["code"] == 0, 20)
    lad.set(~dr_found, 21)
    lad.set(~cr_found, 22)
    lad.set(dr_found & cr_found & (dr["ledger"] != cr["ledger"]), 23)
    lad.set(dr_found & (ev["ledger"] != dr["ledger"]), 24)
    lad.merge(jnp.where(ex_found, transfer_exists_code(ev, ex), jnp.uint32(0)))

    # Balancing clamp (reference: src/state_machine.zig:826-846). amount==0 with
    # a balancing flag means "as much as possible", sentinel u64 max (:829).
    amt_lo, amt_hi = ev["amt_lo"], ev["amt_hi"]
    use_sentinel = (bal_dr | bal_cr) & u128.is_zero(amt_lo, amt_hi)
    amt_lo = jnp.where(use_sentinel, jnp.uint64(0xFFFFFFFFFFFFFFFF), amt_lo)
    amt_hi = jnp.where(use_sentinel, jnp.uint64(0), amt_hi)

    # dr_balance = dr.debits_pending + dr.debits_posted (never overflows by the
    # overflows_debits invariant enforced at every prior commit).
    dr_bal_lo, dr_bal_hi, _ = u128.add(dr["dp_lo"], dr["dp_hi"], dr["dpo_lo"], dr["dpo_hi"])
    dr_avail_lo, dr_avail_hi = u128.sat_sub(dr["cpo_lo"], dr["cpo_hi"], dr_bal_lo, dr_bal_hi)
    c_lo, c_hi = u128.min_(amt_lo, amt_hi, dr_avail_lo, dr_avail_hi)
    amt_lo, amt_hi = u128.select(bal_dr, c_lo, c_hi, amt_lo, amt_hi)
    lad.set(bal_dr & u128.is_zero(amt_lo, amt_hi), 54)  # exceeds_credits

    cr_bal_lo, cr_bal_hi, _ = u128.add(cr["cp_lo"], cr["cp_hi"], cr["cpo_lo"], cr["cpo_hi"])
    cr_avail_lo, cr_avail_hi = u128.sat_sub(cr["dpo_lo"], cr["dpo_hi"], cr_bal_lo, cr_bal_hi)
    c_lo, c_hi = u128.min_(amt_lo, amt_hi, cr_avail_lo, cr_avail_hi)
    amt_lo, amt_hi = u128.select(bal_cr, c_lo, c_hi, amt_lo, amt_hi)
    lad.set(bal_cr & u128.is_zero(amt_lo, amt_hi), 55)  # exceeds_debits

    # Overflow checks (reference: src/state_machine.zig:848-862).
    lad.set(pending & u128.sum_overflows(amt_lo, amt_hi, dr["dp_lo"], dr["dp_hi"]), 47)
    lad.set(pending & u128.sum_overflows(amt_lo, amt_hi, cr["cp_lo"], cr["cp_hi"]), 48)
    lad.set(u128.sum_overflows(amt_lo, amt_hi, dr["dpo_lo"], dr["dpo_hi"]), 49)
    lad.set(u128.sum_overflows(amt_lo, amt_hi, cr["cpo_lo"], cr["cpo_hi"]), 50)
    lad.set(u128.sum_overflows(amt_lo, amt_hi, dr_bal_lo, dr_bal_hi), 51)
    lad.set(u128.sum_overflows(amt_lo, amt_hi, cr_bal_lo, cr_bal_hi), 52)
    lad.set(
        u128.sum_overflows_u64(ev["ts"], ev["timeout"].astype(U64) * jnp.uint64(NS_PER_S)),
        53,
    )

    # Balance-limit invariants (reference: src/tigerbeetle.zig:31-39; checked
    # after the overflow codes, so the sums below cannot wrap when reached).
    dr_tot_lo, dr_tot_hi, _ = u128.add(dr_bal_lo, dr_bal_hi, amt_lo, amt_hi)
    dr_limited = (dr["flags"] & jnp.uint32(A_DR_LIMIT)) != 0
    lad.set(
        dr_limited & u128.gt(dr_tot_lo, dr_tot_hi, dr["cpo_lo"], dr["cpo_hi"]), 54
    )  # exceeds_credits
    cr_tot_lo, cr_tot_hi, _ = u128.add(cr_bal_lo, cr_bal_hi, amt_lo, amt_hi)
    cr_limited = (cr["flags"] & jnp.uint32(A_CR_LIMIT)) != 0
    lad.set(
        cr_limited & u128.gt(cr_tot_lo, cr_tot_hi, cr["dpo_lo"], cr["dpo_hi"]), 55
    )  # exceeds_debits

    return lad.r, amt_lo, amt_hi


def post_void_exists_code(ev, ex, p):
    """reference: src/state_machine.zig:1016-1077."""
    lad = Ladder(jnp.zeros_like(ev["flags"]))
    lad.set(ev["flags"] != ex["flags"], 36)
    t_amt_zero = u128.is_zero(ev["amt_lo"], ev["amt_hi"])
    amt_ref_lo = jnp.where(t_amt_zero, p["amt_lo"], ev["amt_lo"])
    amt_ref_hi = jnp.where(t_amt_zero, p["amt_hi"], ev["amt_hi"])
    lad.set(~u128.eq(amt_ref_lo, amt_ref_hi, ex["amt_lo"], ex["amt_hi"]), 39)
    lad.set(~u128.eq(ev["pid_lo"], ev["pid_hi"], ex["pid_lo"], ex["pid_hi"]), 40)
    ud128_zero = u128.is_zero(ev["ud128_lo"], ev["ud128_hi"])
    ud128_ref_lo = jnp.where(ud128_zero, p["ud128_lo"], ev["ud128_lo"])
    ud128_ref_hi = jnp.where(ud128_zero, p["ud128_hi"], ev["ud128_hi"])
    lad.set(~u128.eq(ud128_ref_lo, ud128_ref_hi, ex["ud128_lo"], ex["ud128_hi"]), 41)
    ud64_ref = jnp.where(ev["ud64"] == 0, p["ud64"], ev["ud64"])
    lad.set(ud64_ref != ex["ud64"], 42)
    ud32_ref = jnp.where(ev["ud32"] == 0, p["ud32"], ev["ud32"])
    lad.set(ud32_ref != ex["ud32"], 43)
    lad.set(jnp.ones_like(ev["flags"], dtype=bool), 46)
    return lad.r


def validate_post_void(r0, ev, p, p_found, ex, ex_found):
    """The post/void_pending_transfer path
    (reference: src/state_machine.zig:907-1014). `p` is the pending transfer's
    row (including its device-side `fulfill` column, which replaces the
    reference's posted groove). The pending transfer's accounts are not
    validated — only mutated on apply, exactly as the reference.
    Returns (result, amt_lo, amt_hi) — the posted amount."""
    flags = ev["flags"]
    is_post = (flags & jnp.uint32(F_POST)) != 0
    is_void = (flags & jnp.uint32(F_VOID)) != 0

    lad = Ladder(r0)
    lad.set(is_post & is_void, 7)  # flags_are_mutually_exclusive
    lad.set((flags & jnp.uint32(F_PENDING)) != 0, 7)
    lad.set((flags & jnp.uint32(F_BAL_DR)) != 0, 7)
    lad.set((flags & jnp.uint32(F_BAL_CR)) != 0, 7)
    lad.set(u128.is_zero(ev["pid_lo"], ev["pid_hi"]), 14)
    lad.set(u128.is_max(ev["pid_lo"], ev["pid_hi"]), 15)
    lad.set(u128.eq(ev["pid_lo"], ev["pid_hi"], ev["id_lo"], ev["id_hi"]), 16)
    lad.set(ev["timeout"] != 0, 17)
    lad.set(~p_found, 25)  # pending_transfer_not_found
    lad.set((p["flags"] & jnp.uint32(F_PENDING)) == 0, 26)
    lad.set(
        ~u128.is_zero(ev["dr_lo"], ev["dr_hi"])
        & ~u128.eq(ev["dr_lo"], ev["dr_hi"], p["dr_lo"], p["dr_hi"]),
        27,
    )
    lad.set(
        ~u128.is_zero(ev["cr_lo"], ev["cr_hi"])
        & ~u128.eq(ev["cr_lo"], ev["cr_hi"], p["cr_lo"], p["cr_hi"]),
        28,
    )
    lad.set((ev["ledger"] != 0) & (ev["ledger"] != p["ledger"]), 29)
    lad.set((ev["code"] != 0) & (ev["code"] != p["code"]), 30)

    t_amt_zero = u128.is_zero(ev["amt_lo"], ev["amt_hi"])
    amt_lo = jnp.where(t_amt_zero, p["amt_lo"], ev["amt_lo"])
    amt_hi = jnp.where(t_amt_zero, p["amt_hi"], ev["amt_hi"])
    lad.set(u128.gt(amt_lo, amt_hi, p["amt_lo"], p["amt_hi"]), 31)  # exceeds_pending
    lad.set(is_void & u128.lt(amt_lo, amt_hi, p["amt_lo"], p["amt_hi"]), 32)

    lad.merge(jnp.where(ex_found, post_void_exists_code(ev, ex, p), jnp.uint32(0)))

    lad.set(p["fulfill"] == 1, 33)  # pending_transfer_already_posted
    lad.set(p["fulfill"] == 2, 34)  # pending_transfer_already_voided

    timeout_ns = p["timeout"].astype(U64) * jnp.uint64(NS_PER_S)
    lad.set((p["timeout"] != 0) & (ev["ts"] >= p["ts"] + timeout_ns), 35)  # expired

    return lad.r, amt_lo, amt_hi


def account_exists_code(ev, ex):
    """reference: src/state_machine.zig:767-777."""
    lad = Ladder(jnp.zeros_like(ev["flags"]))
    lad.set(ev["flags"] != ex["flags"], 15)
    lad.set(~u128.eq(ev["ud128_lo"], ev["ud128_hi"], ex["ud128_lo"], ex["ud128_hi"]), 16)
    lad.set(ev["ud64"] != ex["ud64"], 17)
    lad.set(ev["ud32"] != ex["ud32"], 18)
    lad.set(ev["ledger"] != ex["ledger"], 19)
    lad.set(ev["code"] != ex["code"], 20)
    lad.set(jnp.ones_like(ev["flags"], dtype=bool), 21)  # exists
    return lad.r


def validate_create_account(r0, ev, ex, ex_found):
    """reference: src/state_machine.zig:738-765."""
    lad = Ladder(r0)
    lad.set(ev["reserved"] != 0, 4)  # reserved_field
    lad.set((ev["flags"] & jnp.uint32(ACCOUNT_FLAGS_PADDING)) != 0, 5)  # reserved_flag
    lad.set(u128.is_zero(ev["id_lo"], ev["id_hi"]), 6)
    lad.set(u128.is_max(ev["id_lo"], ev["id_hi"]), 7)
    both_limits = ((ev["flags"] & jnp.uint32(A_DR_LIMIT)) != 0) & (
        (ev["flags"] & jnp.uint32(A_CR_LIMIT)) != 0
    )
    lad.set(both_limits, 8)
    lad.set(~u128.is_zero(ev["dp_lo"], ev["dp_hi"]), 9)
    lad.set(~u128.is_zero(ev["dpo_lo"], ev["dpo_hi"]), 10)
    lad.set(~u128.is_zero(ev["cp_lo"], ev["cp_hi"]), 11)
    lad.set(~u128.is_zero(ev["cpo_lo"], ev["cpo_hi"]), 12)
    lad.set(ev["ledger"] == 0, 13)
    lad.set(ev["code"] == 0, 14)
    lad.merge(jnp.where(ex_found, account_exists_code(ev, ex), jnp.uint32(0)))
    return lad.r
