"""StateMachine: the wire-facing execution interface the VSR layer drives.

This is the TPU build's analog of the reference's StateMachine lifecycle
(reference: src/state_machine.zig:336-540 prepare/commit and :208-214 the
operation enum): one entry point accepts an operation (128-131) plus the
prepare's body bytes, and returns the reply body bytes in the reference's
wire encoding:

- create_accounts / create_transfers: sparse ``{index: u32, result: u32}``
  result structs, only non-ok entries, chain rollbacks in FIFO order
  (reference: src/tigerbeetle.zig:231-249, src/state_machine.zig:612-698).
- lookup_accounts / lookup_transfers: the found objects' 128-byte wire rows,
  in request order, missing ids skipped (reference:
  src/state_machine.zig:701-736).

The backend is anything with the ledger driver API (execute_dense /
prepare / lookup_* — device backends also expose lookup_rows, the
zero-copy reply path): the single-chip DeviceLedger, the multi-chip
ShardedLedger, or the scalar OracleStateMachine — so VSR, the REPL, and the
client server all run unchanged on any of them, and wire-level parity tests
can diff backends byte-for-byte.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.constants import ConfigCluster, DEFAULT_CLUSTER
from tigerbeetle_tpu.types import (
    ACCOUNT_DTYPE,
    CREATE_ACCOUNTS_RESULT_DTYPE,
    CREATE_TRANSFERS_RESULT_DTYPE,
    TRANSFER_DTYPE,
    Operation,
)

ID_SIZE = 16  # lookup request: packed little-endian u128 ids
EVENT_SIZE = 128
RESULT_SIZE = 8

_EVENT_DTYPES = {
    Operation.create_accounts: ACCOUNT_DTYPE,
    Operation.create_transfers: TRANSFER_DTYPE,
}
_RESULT_DTYPES = {
    Operation.create_accounts: CREATE_ACCOUNTS_RESULT_DTYPE,
    Operation.create_transfers: CREATE_TRANSFERS_RESULT_DTYPE,
}


def encode_results(sparse: list[tuple[int, int]], operation: Operation) -> bytes:
    """Sparse (index, result) pairs -> reply body bytes (reference:
    src/tigerbeetle.zig:231-249)."""
    out = np.zeros(len(sparse), dtype=_RESULT_DTYPES[operation])
    for i, (index, result) in enumerate(sparse):
        out[i]["index"] = index
        out[i]["result"] = result
    return out.tobytes()


def encode_sparse_results(codes: np.ndarray, operation: Operation) -> bytes:
    """Dense u32 codes -> sparse non-ok reply body, vectorized (reference:
    src/tigerbeetle.zig:231-249). Shared by the device and native
    backends' drain_reply."""
    idx = np.nonzero(codes)[0]
    out = np.zeros(len(idx), dtype=_RESULT_DTYPES[operation])
    out["index"] = idx.astype(np.uint32)
    out["result"] = codes[idx]
    return out.tobytes()


def decode_results(body: bytes, operation: Operation) -> list[tuple[int, int]]:
    assert len(body) % RESULT_SIZE == 0, len(body)
    arr = np.frombuffer(body, dtype=_RESULT_DTYPES[operation])
    return [(int(r["index"]), int(r["result"])) for r in arr]


def encode_ids(ids: list[int]) -> bytes:
    out = np.zeros(2 * len(ids), dtype=np.uint64)
    for i, x in enumerate(ids):
        lo, hi = types.split_u128(x)
        out[2 * i] = lo
        out[2 * i + 1] = hi
    return out.tobytes()


def decode_ids(body: bytes) -> list[int]:
    assert len(body) % ID_SIZE == 0, len(body)
    arr = np.frombuffer(body, dtype=np.uint64)
    return [types.join_u128(arr[2 * i], arr[2 * i + 1]) for i in range(len(arr) // 2)]


def decode_accounts(body: bytes) -> np.ndarray:
    assert len(body) % EVENT_SIZE == 0, len(body)
    return np.frombuffer(body, dtype=ACCOUNT_DTYPE).copy()


def decode_transfers(body: bytes) -> np.ndarray:
    assert len(body) % EVENT_SIZE == 0, len(body)
    return np.frombuffer(body, dtype=TRANSFER_DTYPE).copy()


class StateMachine:
    """Drives a ledger backend with wire-format bodies.

    Lifecycle mirrors the reference (src/state_machine.zig:336-540):
      count = sm.input_count(op, body)   # body validation / batch sizing
      sm.prepare(op, count)              # advances prepare_timestamp
      reply = sm.commit(op, timestamp, body)
    """

    def __init__(self, backend, cluster: ConfigCluster = DEFAULT_CLUSTER):
        self.backend = backend
        self.cluster = cluster

    # -- body validation & batch sizing --

    def batch_max(self, operation: Operation) -> int:
        """Per-op batch max = body_size_max / max(event_size, result_size)
        (reference: src/state_machine.zig:59-64 operation_batch_max) — the
        REPLY must fit in one message too, which is what bounds lookups
        (16-byte id events but 128-byte object results)."""
        body_max = self.cluster.message_size_max - 128  # header
        event = EVENT_SIZE if operation in _EVENT_DTYPES else ID_SIZE
        result = RESULT_SIZE if operation in _EVENT_DTYPES else EVENT_SIZE
        return body_max // max(event, result)

    def input_valid(self, operation: Operation, body: bytes) -> bool:
        if operation in _EVENT_DTYPES:
            event_size = EVENT_SIZE
        elif operation in (Operation.lookup_accounts, Operation.lookup_transfers):
            event_size = ID_SIZE
        else:
            return False
        if len(body) == 0 or len(body) % event_size != 0:
            return False
        return len(body) // event_size <= self.batch_max(operation)

    def input_count(self, operation: Operation, body: bytes) -> int:
        assert self.input_valid(operation, body)
        size = (
            EVENT_SIZE
            if operation in _EVENT_DTYPES
            else ID_SIZE
        )
        return len(body) // size

    def prepare(self, operation: Operation, body: bytes) -> None:
        self.backend.prepare(operation, self.input_count(operation, body))

    @property
    def prepare_timestamp(self) -> int:
        return self.backend.prepare_timestamp

    @prepare_timestamp.setter
    def prepare_timestamp(self, value: int) -> None:
        self.backend.prepare_timestamp = value

    # -- commit: wire body in, wire reply out --

    def commit_async(self, operation: Operation, timestamp: int, body: bytes):
        """Dispatch a commit WITHOUT materializing results (the device
        launch is queued; results stay on device). Returns a handle for
        commit_finish. Only create ops are truly asynchronous; lookups are
        reads and compute their reply inline (the handle is the bytes).
        This is the replica's commit-stage overlap seam (reference:
        src/vsr/replica.zig:3045-3103 commit_dispatch stages)."""
        if operation not in _EVENT_DTYPES or not hasattr(
            self.backend, "execute_async"
        ):
            return self.commit(operation, timestamp, body)  # reads / oracle
        if getattr(self.backend, "zero_copy_events", False):
            # backend only reads the rows: skip the 1 MiB defensive copy
            events = np.frombuffer(body, dtype=_EVENT_DTYPES[operation])
        else:
            events = (
                decode_accounts(body)
                if operation == Operation.create_accounts
                else decode_transfers(body)
            )
        return (operation, self.backend.execute_async(operation, timestamp, events))

    @staticmethod
    def handle_plan(handle):
        """The backend's wave-planner decision for a commit_async handle:
        (decision, wave_count) — e.g. ("waves", 3) — or None when the
        backend has no planner (oracle/native) or the op wasn't a create.
        The replica surfaces this as commit.group.wave_* without reaching
        into backend-specific pending types."""
        if isinstance(handle, bytes):
            return None
        return getattr(handle[1], "plan", None)

    def commit_group_async(self, operation: Operation, batches):
        """Fuse consecutive create_transfers commits into one device
        dispatch (group commit). `batches` = [(timestamp, body), ...].
        Returns a list of commit_async-compatible handles, or None when
        fusion is unavailable/unsound — callers fall back per batch."""
        if operation != Operation.create_transfers or len(batches) < 2:
            return None
        if not hasattr(self.backend, "try_execute_group_async"):
            return None
        # read-only views (no 1 MiB copy per batch): the group path only
        # reads the rows into the staging buffer
        items = [
            (ts, np.frombuffer(body, dtype=TRANSFER_DTYPE))
            for ts, body in batches
        ]
        pendings = self.backend.try_execute_group_async(items)
        if pendings is None:
            return None
        return [(operation, p) for p in pendings]

    def commit_finish_many(self, handles) -> None:
        """Pre-materialize several commit_async handles with one
        device->host transfer (see DeviceLedger.drain_many); the
        subsequent per-handle commit_finish calls hit the cache."""
        pendings = [h[1] for h in handles if not isinstance(h, bytes)]
        if pendings and hasattr(self.backend, "drain_many"):
            self.backend.drain_many(pendings)

    def commit_finish(self, handle) -> bytes:
        """Materialize a commit_async handle into the reply body bytes."""
        if isinstance(handle, bytes):
            return handle
        operation, pending = handle
        if hasattr(self.backend, "drain_reply"):
            # vectorized sparse encoding; empty for all-success without
            # materializing dense codes at all
            return self.backend.drain_reply(pending, operation)
        dense = self.backend.drain(pending)
        return encode_results(
            [(i, c) for i, c in enumerate(dense) if c], operation
        )

    def commit(self, operation: Operation, timestamp: int, body: bytes) -> bytes:
        if operation == Operation.create_accounts:
            events = decode_accounts(body)
            dense = self.backend.execute_dense(operation, timestamp, events)
            return encode_results(
                [(i, c) for i, c in enumerate(dense) if c], operation
            )
        if operation == Operation.create_transfers:
            events = decode_transfers(body)
            dense = self.backend.execute_dense(operation, timestamp, events)
            return encode_results(
                [(i, c) for i, c in enumerate(dense) if c], operation
            )
        if operation in (Operation.lookup_accounts, Operation.lookup_transfers):
            ids = decode_ids(body)
            if hasattr(self.backend, "lookup_rows"):  # device backends:
                return self.backend.lookup_rows(operation, ids)  # raw wire rows
            found = (
                self.backend.lookup_accounts(ids)
                if operation == Operation.lookup_accounts
                else self.backend.lookup_transfers(ids)
            )
            if operation == Operation.lookup_accounts:
                return types.accounts_to_np(found).tobytes()
            return types.transfers_to_np(found).tobytes()
        raise AssertionError(operation)
