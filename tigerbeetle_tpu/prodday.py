"""Production-day harness: scripted scenario timeline, phase-aligned
SLO scorecard, and the recovery probe both drivers share.

A benchmark measures one regime; a production day strings regimes
together — diurnal ramp, steady state, a flash crowd onto the hot
accounts, a primary kill mid-spike, a gray (wedged, not dead) replica, a
connection-reset storm, a WAL disk fault surfacing on restart, a slow
CDC consumer — and asks one question per phase: did the cluster hold its
SLOs *through* the story, not just on average? (The reference's VOPR
plays the same trick in miniature: a scripted fault swarm plus a
liveness checker that must see progress after the swarm ends.)

This module is the harness's pure core, and it is deliberately
clock-free (callers pass timestamps) with seeded randomness only, so it
sits inside the determinism closure and the simulator twin can replay a
timeline byte-identically:

- the **timeline DSL**: `Phase` (offered-load curve + per-phase SLO
  budgets) and `Event` (faults at offsets) compose into a `Timeline`;
  `offered_rate()` turns a phase's curve into events/s at any instant.
  Each phase carries BOTH its live shape (duration_s, load curve) and
  its sim shape (sim_ticks, sim_duty) so one declaration drives the
  live cluster and the deterministic twin.
- the **scorer**: `slice_history()` splits flight-recorder entries by
  the `phase` stamp the `mark` wire command wrote (vsr/replica.py
  `_on_mark`), and `score()` grades every declared SLO against its
  slice — measured value, budget, pass/fail, and for any violated
  phase the dominant critical-path leg (latency.py windowed totals)
  plus the dominant device sub-leg, so a red row names its bottleneck.
- the **recovery probe**: armed at fault time, resolved by the first
  reply that PROVES post-fault service (newer view, or a reply to a
  request issued after the fault) — `testing/chaos.py` delegates to it,
  so the bench failover number and the prodday recovery SLO are one
  code path.
- the **sim twin**: `run_sim_twin()` maps the same timeline onto the
  simulator's fault axes (kills -> `kill_primary`, the storm ->
  `storm_tick`, the disk flip -> `wal_fault_probability`, the slow
  consumer -> the throttled fan-out store) and records a flight ring on
  virtual ticks; same seed => byte-identical histories AND scorecards.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field

from tigerbeetle_tpu.latency import DEVICE_LEGS, LEGS, dominant_in_entries

# Event kinds a timeline may schedule. Live semantics (scripts/
# prodday.py) vs sim mapping (run_sim_twin):
#   kill_primary            SIGKILL the current primary        | Simulator.kill_primary()
#   gray_primary            SIGSTOP (wedged-not-dead) primary  | kill_primary() — a stopped
#                           for `arg` seconds, then SIGCONT    | process needs a live OS; the
#                                                              | sim's nearest axis is a crash
#   reset_conns             RST every client bus, sessions     | connect storm: `arg` new
#                           reconnect + `arg` new sessions     | sessions at the event tick
#   disk_fault_on_restart   arm: next restart boots from a     | wal_fault_probability=1.0
#                           WAL with an injected fault         | from the event tick on
#   slow_consumer           wrap the last named CDC sink in    | throttled fan-out store
#                           CountThrottleSink(accept_every=arg)| (cdc_fanout_throttle=arg)
EVENT_KINDS = (
    "kill_primary",
    "gray_primary",
    "reset_conns",
    "disk_fault_on_restart",
    "slow_consumer",
)

# Load-curve shapes (`Phase.load[0]`): how offered_rate() interpolates
# across the phase. All rates are events/s (a batch of k transfers is k
# events, matching benchmark.py's open-loop accounting).
LOAD_SHAPES = ("ramp", "steady", "spike")


@dataclass(frozen=True)
class Phase:
    """One chapter of the day: a load curve plus the SLOs it must hold.

    `load` is (shape, *rates): ("ramp", lo, hi) interpolates linearly,
    ("steady", r) holds r, ("spike", base, peak) holds base with peak
    through the middle third — the flash crowd. `slo` maps budget keys
    to bounds: p99_ms (phase p99 latency budget), availability (min
    acked/offered fraction, typed sheds and timeouts count against),
    shed_rate (max typed-shed fraction), cdc_lag_ops (max CDC lag gauge
    observed in the phase). `hot_accounts` >0 points the spike's
    transfers at a zipfian-hot subset (live driver knob)."""

    name: str
    duration_s: float
    load: tuple
    sim_ticks: int
    sim_duty: float = 0.5  # SimClient issue probability per idle draw
    slo: dict = field(default_factory=dict)
    hot_accounts: int = 0

    def validate(self) -> None:
        if self.load[0] not in LOAD_SHAPES:
            raise ValueError(f"phase {self.name}: unknown load shape "
                             f"{self.load[0]!r} (want {LOAD_SHAPES})")
        want = {"ramp": 3, "steady": 2, "spike": 3}[self.load[0]]
        if len(self.load) != want:
            raise ValueError(f"phase {self.name}: load {self.load!r} "
                             f"needs {want} elements")
        if self.duration_s <= 0 or self.sim_ticks <= 0:
            raise ValueError(f"phase {self.name}: empty duration")
        if not 0.0 < self.sim_duty <= 1.0:
            raise ValueError(f"phase {self.name}: sim_duty out of (0,1]")


@dataclass(frozen=True)
class Event:
    """A scheduled fault: `kind` (EVENT_KINDS) fired `at_s` seconds into
    the timeline (live) / at the proportional tick (sim). `arg` is the
    kind-specific dial (gray hold seconds, storm session count, slow
    consumer accept_every)."""

    at_s: float
    kind: str
    arg: int = 0

    def validate(self, total_s: float) -> None:
        if self.kind not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {self.kind!r}")
        if not 0.0 <= self.at_s < total_s:
            raise ValueError(f"event {self.kind} at {self.at_s}s is "
                             f"outside the {total_s}s timeline")


@dataclass(frozen=True)
class Timeline:
    """The whole day: ordered phases, scheduled events, and the
    timeline-level SLOs that don't belong to one phase — recovery_ms
    (every armed fault must prove post-fault service within budget),
    cdc_lag_ops (day-wide lag bound), zero_lost (wire conservation +
    hash-log parity + CDC dedup must all hold)."""

    name: str
    phases: tuple
    events: tuple = ()
    slo: dict = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    @property
    def total_sim_ticks(self) -> int:
        return sum(p.sim_ticks for p in self.phases)

    def validate(self) -> "Timeline":
        if not self.phases:
            raise ValueError("timeline has no phases")
        names = [p.name for p in self.phases]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate phase names: {names}")
        for p in self.phases:
            p.validate()
        for e in self.events:
            e.validate(self.duration_s)
        return self

    def phase_at(self, t_s: float):
        """(phase, seconds-into-phase) at timeline offset t_s."""
        acc = 0.0
        for p in self.phases:
            if t_s < acc + p.duration_s:
                return p, t_s - acc
            acc += p.duration_s
        return self.phases[-1], self.phases[-1].duration_s

    def phase_starts_s(self) -> list:
        """[(start_s, phase), ...] in declaration order."""
        out, acc = [], 0.0
        for p in self.phases:
            out.append((acc, p))
            acc += p.duration_s
        return out

    def phase_starts_ticks(self) -> list:
        """[(start_tick, phase), ...] — the sim twin's boundaries."""
        out, acc = [], 0
        for p in self.phases:
            out.append((acc, p))
            acc += p.sim_ticks
        return out

    def event_tick(self, e: Event) -> int:
        """Map a live offset to a sim tick, proportionally per phase (a
        kill 30s into a 60s phase lands halfway through its ticks)."""
        p, into = self.phase_at(e.at_s)
        start = dict((ph.name, t) for t, ph in self.phase_starts_ticks())
        return start[p.name] + int(into / p.duration_s * p.sim_ticks)


def offered_rate(phase: Phase, frac: float) -> float:
    """events/s at `frac` in [0,1) through the phase."""
    shape = phase.load[0]
    if shape == "steady":
        return float(phase.load[1])
    if shape == "ramp":
        lo, hi = phase.load[1], phase.load[2]
        return lo + (hi - lo) * frac
    base, peak = phase.load[1], phase.load[2]  # spike
    return float(peak if 1 / 3 <= frac < 2 / 3 else base)


def scale_timeline(tl: Timeline, time: float = 1.0,
                   rate: float = 1.0) -> Timeline:
    """The sandbox dial: shrink/stretch a timeline's wall durations
    (`time`) and offered rates (`rate`) without touching its SHAPE —
    phase SLOs, event ordering and the sim mapping stay identical, so a
    20%-length rehearsal still tells the same story."""
    from dataclasses import replace

    phases = tuple(
        replace(
            p,
            duration_s=p.duration_s * time,
            load=(p.load[0],) + tuple(r * rate for r in p.load[1:]),
        )
        for p in tl.phases
    )
    events = tuple(replace(e, at_s=e.at_s * time) for e in tl.events)
    return Timeline(tl.name, phases, events, dict(tl.slo)).validate()


# -- recovery probe ----------------------------------------------------


class RecoveryProbe:
    """Time-to-first-commit-after-fault, by PROOF of post-fault service.

    Armed with the pre-fault view and issue sequence; resolved by the
    first reply carrying a view newer than the fault-time view (a new
    primary served or resent it) or answering a request ISSUED after the
    fault. A bare "next reply" would under-read the metric: bytes the
    dead primary wrote to a socket just before the SIGKILL are still
    delivered by TCP and would resolve the probe in ~1ms.

    Overlapping faults arm INDEPENDENT probes: a second fault landing
    before the first resolves must not drop the first's measurement
    (a gray-primary stall followed by a connection-reset storm is one
    compound outage, but each fault's recovery window is its own — a
    reply proving post-reset service usually proves post-gray service
    too and resolves both, each measured from its OWN arm time).

    Clock-free (callers pass `now`); `testing/chaos.py` feeds it
    wall-clock monotonic seconds, so the bench failover segment and the
    prodday recovery SLO read the same arithmetic."""

    def __init__(self, histogram=None):
        self.histogram = histogram  # optional: chaos.recovery_ms
        self.recoveries_ms: list = []
        self._pending: list = []  # [(armed_at, view, issue_seq), ...]

    @property
    def armed(self) -> bool:
        return bool(self._pending)

    def arm(self, now: float, view: int, issue_seq: int) -> None:
        self._pending.append((now, view, issue_seq))

    def observe_reply(self, now: float, view: int, issue_seq: int):
        """Feed one harvested reply; resolves EVERY pending arm this
        reply proves post-fault service for (in arm order, each from
        its own arm time). Returns the newest resolved window in ms,
        else None."""
        if not self._pending:
            return None
        resolved_ms = None
        keep = []
        for at, v, s in self._pending:
            if view > v or issue_seq > s:
                ms = (now - at) * 1e3
                self.recoveries_ms.append(ms)
                if self.histogram is not None:
                    self.histogram.observe(ms)
                resolved_ms = ms
            else:
                keep.append((at, v, s))
        self._pending = keep
        return resolved_ms


# -- phase-aligned scoring ---------------------------------------------


def slice_history(entries: list) -> dict:
    """Partition flight-recorder entries by their `phase` stamp, in
    ring order. Entries recorded before the first mark land under
    None."""
    out: dict = {}
    for e in entries:
        out.setdefault(e.get("phase"), []).append(e)
    return out


def _slice_p99_ms(entries: list):
    """Worst per-interval windowed e2e p99 in the slice, in ms — the
    recorder-derived latency measurement (the live driver overrides it
    with its own due-time p99 when it has one)."""
    worst = None
    for e in entries:
        h = e.get("histograms", {}).get("latency.e2e_us")
        if h and h.get("p99") is not None:
            v = h["p99"] / 1e3
            worst = v if worst is None or v > worst else worst
    return round(worst, 3) if worst is not None else None


def _slice_cdc_lag(entries: list):
    """Worst CDC lag gauge in the slice (single-pump `cdc.lag_ops` or
    the fan-out hub's `ingress.fanout_lag_ops`)."""
    worst = None
    for e in entries:
        g = e.get("gauges", {})
        for k in ("cdc.lag_ops", "ingress.fanout_lag_ops"):
            if k in g:
                v = g[k]
                worst = v if worst is None or v > worst else worst
    return worst


def _dominant(entries: list) -> dict:
    """Name the bottleneck for a violated row: the dominant critical-
    path leg across the slice's windowed histograms, plus the dominant
    device sub-leg when commit_wait dominates (PR 18's device
    anatomy)."""
    leg, share = dominant_in_entries(entries, legs=LEGS, prefix="latency")
    out: dict = {"dominant_leg": leg, "dominant_leg_share": share}
    if leg == "commit_wait":
        sub, sub_share = dominant_in_entries(
            entries, legs=DEVICE_LEGS, prefix="device"
        )
        out["dominant_device_subleg"] = sub
        out["dominant_device_subleg_share"] = sub_share
    return out


def _row(phase, slo: str, budget, measured, ok, entries: list) -> dict:
    row = {
        "phase": phase,
        "slo": slo,
        "budget": budget,
        "measured": measured,
        "pass": ok,
    }
    if ok is False and entries:
        row.update(_dominant(entries))
    return row


def score(timeline: Timeline, slices: dict, *, measures: dict = None,
          recoveries_ms: list = None, faults_armed: int = 0,
          checks: dict = None) -> dict:
    """Grade every declared SLO. `slices` is slice_history() output;
    `measures` optionally maps phase name -> {availability, shed_rate,
    p99_ms, offered, acked, shed, timeouts} from the driver's own
    bookkeeping (the recorder can't see offered load that was never
    admitted). Rows come out in declaration order with SLO keys sorted,
    so two runs that measure identically serialize identically.

    A row with measured=None scores pass=None ("no data"): visible,
    never silently green. The overall verdict fails only on an explicit
    False row."""

    measures = measures or {}
    rows = []
    for p in timeline.phases:
        entries = slices.get(p.name, [])
        m = measures.get(p.name, {})
        for key in sorted(p.slo):
            budget = p.slo[key]
            if key == "p99_ms":
                v = m.get("p99_ms")
                if v is None:
                    v = _slice_p99_ms(entries)
                ok = None if v is None else v <= budget
            elif key == "availability":
                v = m.get("availability")
                ok = None if v is None else v >= budget
            elif key == "shed_rate":
                v = m.get("shed_rate")
                ok = None if v is None else v <= budget
            elif key == "cdc_lag_ops":
                v = _slice_cdc_lag(entries)
                if v is None:
                    v = m.get("cdc_lag_ops")
                ok = None if v is None else v <= budget
            else:
                raise ValueError(f"phase {p.name}: unknown SLO {key!r}")
            rows.append(_row(p.name, key, budget, v, ok, entries))

    all_entries = [e for p in timeline.phases
                   for e in slices.get(p.name, [])]
    for key in sorted(timeline.slo):
        budget = timeline.slo[key]
        if key == "recovery_ms":
            if recoveries_ms is None:
                v, ok = None, None  # live-only probe (the sim's virtual
                # clock makes wall recovery time meaningless)
            elif faults_armed and len(recoveries_ms) < faults_armed:
                v, ok = None, False  # an armed fault never proved
                # post-fault service: that IS the violation
            elif recoveries_ms:
                v = round(max(recoveries_ms), 3)
                ok = v <= budget
            else:
                v, ok = None, None
        elif key == "cdc_lag_ops":
            v = _slice_cdc_lag(all_entries)
            ok = None if v is None else v <= budget
        elif key == "zero_lost":
            v = checks if checks else None
            ok = None if v is None else all(checks.values())
        else:
            raise ValueError(f"timeline: unknown SLO {key!r}")
        rows.append(_row("*", key, budget, v, ok, all_entries))

    return {
        "timeline": timeline.name,
        "rows": rows,
        "violations": sum(1 for r in rows if r["pass"] is False),
        "no_data": sum(1 for r in rows if r["pass"] is None),
        "pass": all(r["pass"] is not False for r in rows),
    }


def scorecard_json(card: dict) -> str:
    """Canonical serialization — the byte string two same-seed sim-twin
    runs must reproduce exactly."""
    return json.dumps(card, sort_keys=True, separators=(",", ":"))


# -- deterministic history digest --------------------------------------


def history_digest(histories: list) -> str:
    """sha256 over a stable serialization of every replica's committed
    (op -> checksum, operation, timestamp, body) history — the byte-
    identity witness for same-seed twin runs."""
    h = hashlib.sha256()
    for i, hist in enumerate(histories):
        h.update(f"replica {i}:{len(hist)};".encode())
        for op in sorted(hist):
            checksum, operation, timestamp, body = hist[op]
            h.update(f"{op},{checksum},{operation},{timestamp},".encode())
            h.update(hashlib.sha256(body).digest())
    return h.hexdigest()


# -- the simulator twin ------------------------------------------------


def run_sim_twin(timeline: Timeline, seed: int, *, n_clients: int = 2,
                 record_every: int = 50, replica_count: int = 3,
                 crash_probability: float = 0.0,
                 sim_kwargs: dict = None) -> dict:
    """Replay the timeline in the deterministic simulator: phases set
    the clients' duty cycle, events fire at proportional ticks through
    the sim's own fault axes, and a FlightRecorder on replica 0's
    registry records every `record_every` ticks at virtual seconds
    (tick * 10ms), phase-stamped at each boundary — so the scorer runs
    on exactly the history shape the live harness produces.

    Background randomness defaults OFF (crash_probability=0): the
    timeline's scripted events are the only faults, which keeps a smoke
    twin's story legible. Same (timeline, seed) => byte-identical
    committed histories and byte-identical scorecard JSON."""

    from tigerbeetle_tpu.metrics import FlightRecorder
    from tigerbeetle_tpu.testing.simulator import Simulator

    timeline.validate()
    ticks = timeline.total_sim_ticks
    kills = sorted(
        timeline.event_tick(e) for e in timeline.events
        if e.kind in ("kill_primary", "gray_primary")
    )
    storms = {
        timeline.event_tick(e): (e.arg or 4) for e in timeline.events
        if e.kind == "reset_conns"
    }
    disk_flip_at = min(
        (timeline.event_tick(e) for e in timeline.events
         if e.kind == "disk_fault_on_restart"),
        default=None,
    )
    slow = [e for e in timeline.events if e.kind == "slow_consumer"]

    kwargs = dict(
        seed=seed,
        replica_count=replica_count,
        n_clients=n_clients,
        ticks=ticks,
        crash_probability=crash_probability,
        # scripted timelines own their faults; restart-time WAL faults
        # only happen when the timeline flips the disk
        wal_fault_probability=0.0,
        latency_sample_every=1,
    )
    if storms:
        kwargs["storm_clients"] = max(storms.values())
    if slow:
        kwargs["cdc_fanout"] = 3
        kwargs["cdc_fanout_throttle"] = slow[0].arg or 4
    kwargs.update(sim_kwargs or {})
    sim = Simulator(**kwargs)
    if storms:
        # the constructor draws a seed-random storm tick; pin it to the
        # timeline's reset_conns offset instead (still deterministic)
        sim.storm_tick = min(storms)

    def primary_metrics(s):
        """The registry worth recording: the current primary's (e2e
        latency is only observed where replies egress). View-derived,
        so the choice — and the recorded history — is deterministic;
        the recorder's swap clamps absorb each re-attach."""
        views = [
            s.replicas[i].view for i in range(s.replica_count)
            if i not in s.down and s.replicas[i].status == "normal"
        ]
        p = (max(views) % s.replica_count) if views else 0
        if p in s.down:
            p = next(
                (i for i in range(s.replica_count) if i not in s.down), 0
            )
        return s.replicas[p].metrics

    recorder = FlightRecorder(
        sim.replicas[0].metrics, capacity=max(64, ticks // record_every + 8)
    )
    boundaries = {t: p for t, p in timeline.phase_starts_ticks()}
    kill_at = list(kills)
    state = {"kills": 0}

    def hook(s: Simulator, now: int) -> None:
        if now in boundaries:
            recorder.set_phase(boundaries[now].name, now_s=now * 0.01)
            for c in s.clients:
                c.duty = boundaries[now].sim_duty
        if kill_at and now >= kill_at[0]:
            kill_at.pop(0)
            if s.kill_primary(now):
                state["kills"] += 1
        if disk_flip_at is not None and now >= disk_flip_at:
            s.wal_fault_probability = 1.0
        if now % record_every == 0:
            # restarts/failovers move the interesting registry: follow
            # the primary (the recorder clamps the deltas a swap skews)
            recorder.metrics = primary_metrics(s)
            recorder.record(now * 0.01)

    sim.tick_hook = hook
    stats = sim.run()  # raises if any invariant checker trips
    recorder.metrics = primary_metrics(sim)
    recorder.record(ticks * 0.01)

    slices = slice_history(recorder.history())
    checks = {"histories_converged": True, "conservation_ok": True}
    if slow:
        checks["cdc_fanout_complete"] = True  # SimCdcFanout._check ran
    card = score(timeline, slices, checks=checks)
    return {
        "stats": stats,
        "scripted_kills": state["kills"],
        "history_digest": history_digest(sim.histories),
        "phase_log": list(recorder.phase_log),
        "flight_history": recorder.history(),
        "scorecard": card,
        "scorecard_json": scorecard_json(card),
    }


# -- canonical timelines -----------------------------------------------


def production_day(scale: float = 1.0) -> Timeline:
    """The canonical day: morning ramp, steady business, a flash crowd
    onto zipfian-hot accounts with a primary kill mid-spike, a gray
    primary and connection-reset storm in the afternoon, a disk fault
    surfacing on the kill's restart, a slow CDC consumer from mid-day,
    and an evening drain. `scale` multiplies offered rates (live runs
    tune it to the sandbox's frontier)."""

    def r(x: float) -> float:
        return round(x * scale, 3)

    phases = (
        Phase("ramp", 60.0, ("ramp", r(100), r(400)), sim_ticks=900,
              sim_duty=0.3,
              slo={"p99_ms": 80.0, "availability": 0.99}),
        Phase("steady", 90.0, ("steady", r(400)), sim_ticks=1400,
              sim_duty=0.5,
              slo={"p99_ms": 60.0, "availability": 0.995,
                   "shed_rate": 0.01, "cdc_lag_ops": 512}),
        Phase("flash_crowd", 60.0, ("spike", r(400), r(1200)),
              sim_ticks=1200, sim_duty=0.9, hot_accounts=16,
              slo={"p99_ms": 250.0, "availability": 0.97,
                   "shed_rate": 0.15}),
        Phase("afternoon", 90.0, ("steady", r(350)), sim_ticks=1400,
              sim_duty=0.5,
              slo={"p99_ms": 80.0, "availability": 0.99,
                   "cdc_lag_ops": 768}),
        Phase("drain", 30.0, ("ramp", r(300), r(50)), sim_ticks=600,
              sim_duty=0.2,
              slo={"p99_ms": 60.0, "availability": 0.995}),
    )
    events = (
        Event(120.0, "slow_consumer", arg=4),
        Event(175.0, "kill_primary"),
        Event(176.0, "disk_fault_on_restart"),
        Event(250.0, "gray_primary", arg=8),
        Event(280.0, "reset_conns", arg=4),
    )
    return Timeline(
        "production_day", phases, events,
        slo={"recovery_ms": 10_000.0, "cdc_lag_ops": 4096,
             "zero_lost": True},
    ).validate()


def smoke_timeline(p99_budget_ms: float = 500.0) -> Timeline:
    """Tier-1 twin: three short phases, one scripted primary kill in the
    middle one. `p99_budget_ms` is the warm-up/steady budget — pass a
    tiny value (e.g. 0.001) to intentionally blow it and watch the
    scorer fail the row with a named dominant leg."""
    phases = (
        Phase("warm", 10.0, ("ramp", 50, 200), sim_ticks=300,
              sim_duty=0.4, slo={"p99_ms": p99_budget_ms}),
        Phase("storm", 15.0, ("spike", 200, 600), sim_ticks=500,
              sim_duty=0.8,
              slo={"p99_ms": max(p99_budget_ms, 4 * p99_budget_ms)}),
        Phase("cool", 10.0, ("steady", 100), sim_ticks=300,
              sim_duty=0.3, slo={"p99_ms": p99_budget_ms}),
    )
    events = (Event(17.0, "kill_primary"),)
    return Timeline(
        "smoke", phases, events, slo={"zero_lost": True},
    ).validate()
