"""Change-data-capture: durable change streams over the committed op log.

The reference ships a CDC runner (`tigerbeetle amqp`, src/cdc/runner.zig)
that tails the committed double-entry history and publishes change events
downstream without ever stalling the state machine. This package is that
subsystem for the TPU build:

- `record`: the change-record encoder — per-transfer/per-account records
  with exact result codes and balance deltas, derived from the committed
  prepare plus the reply buffer the replica already materialized (no new
  device->host transfer anywhere in the pipeline);
- `cursor`: the durable per-consumer cursor — an atomic write-rename file
  (superblock-style: checksummed payload, torn writes read as absent)
  storing `(op, checksum)` so redelivery is dedupable: the at-least-once
  contract;
- `sink`: pluggable delivery — JSONL file, in-memory (tests/simulator),
  UDP datagrams (reusing the statsd MTU batching), and a non-blocking
  throttle wrapper that models a deliberately slow consumer;
- `pump`: `CdcPump` — tails live commits via the replica's `cdc_hook`
  with a bounded in-flight window, degrades to WAL-ring reads when the
  window overflows, and cold-starts/resumes by replaying the AOF through
  the scalar oracle (parity-locked with the device engines, so replayed
  result codes are exact). Backpressure pauses the PUMP, never the
  replica: a refusing sink simply stops stream progress and `cdc.lag_ops`
  grows.

Delivery semantics: at-least-once, in op order, gap-free up to the WAL
ring (beyond it the AOF is the backfill source; a state-synced replica
declares the ops it never executed as an explicit `gap` record instead of
skipping them silently).
"""

from tigerbeetle_tpu.cdc.cursor import FileCursor, MemoryCursor
from tigerbeetle_tpu.cdc.pump import AofReplaySource, CdcPump
from tigerbeetle_tpu.cdc.record import encode_batch, gap_record, record_line
from tigerbeetle_tpu.cdc.sink import (
    CountThrottleSink,
    JsonlFileSink,
    MemorySink,
    StdoutSink,
    ThrottleSink,
    UdpSink,
)

__all__ = [
    "AofReplaySource",
    "CdcPump",
    "CountThrottleSink",
    "FileCursor",
    "JsonlFileSink",
    "MemoryCursor",
    "MemorySink",
    "StdoutSink",
    "ThrottleSink",
    "UdpSink",
    "encode_batch",
    "gap_record",
    "record_line",
]
