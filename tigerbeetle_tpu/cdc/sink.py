"""CDC sinks: where change-record lines go.

The sink contract is NON-BLOCKING: `emit_lines(lines)` either accepts the
whole batch (True) or refuses it (False) — it must never block the caller,
because the pump runs on the server's event loop. A refusal is
backpressure: the pump pauses and retries the same op later (the WAL
ring / AOF hold the history, so nothing is lost by waiting). `lines` is
always one committed op's records, emitted atomically — op-granular
delivery is what keeps redelivery dedupable by the cursor's op.
"""

from __future__ import annotations

import sys
import time


class MemorySink:
    """In-memory sink (tests, the simulator's downstream store). An
    optional capacity bound turns it into a backpressuring consumer:
    emit_lines refuses once `capacity` lines are buffered, until drain()
    frees room — the deliberately-slow-consumer model."""

    def __init__(self, capacity: int | None = None):
        self.lines: list[str] = []
        self.capacity = capacity
        self.flushes = 0

    def emit_lines(self, lines: list[str]) -> bool:
        if (
            self.capacity is not None
            and len(self.lines) + len(lines) > self.capacity
        ):
            return False
        self.lines.extend(lines)
        return True

    def drain(self, n: int | None = None) -> list[str]:
        n = len(self.lines) if n is None else n
        out, self.lines = self.lines[:n], self.lines[n:]
        return out

    def flush(self) -> None:
        self.flushes += 1

    def close(self) -> None:
        pass


class JsonlFileSink:
    """Append-only JSONL file. O_APPEND like the AOF: concurrent writers
    would interleave whole lines, and a crash mid-write leaves a torn tail
    line a reader skips (newline-framed)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", buffering=1 << 16)

    def emit_lines(self, lines: list[str]) -> bool:
        self._f.write("\n".join(lines) + "\n")
        return True

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class StdoutSink:
    """The `tigerbeetle cdc` subcommand's default: the stream on stdout,
    one record per line (pipe it wherever)."""

    def emit_lines(self, lines: list[str]) -> bool:
        sys.stdout.write("\n".join(lines) + "\n")
        return True

    def flush(self) -> None:
        sys.stdout.flush()

    def close(self) -> None:
        pass


class UdpSink:
    """Fire-and-forget UDP delivery reusing the statsd MTU batching
    (statsd.StatsD.send_batch packs newline-separated lines into <=1400 B
    datagrams — the same packing the metrics emitter uses). Lossy by
    nature; the durable cursor/AOF replay is what makes the stream
    recoverable, the datagrams are just the live feed."""

    def __init__(self, host: str, port: int):
        from tigerbeetle_tpu.statsd import StatsD

        self._statsd = StatsD(host, port)
        self.datagrams = 0

    def emit_lines(self, lines: list[str]) -> bool:
        self.datagrams += self._statsd.send_batch(lines)
        return True

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._statsd.close()


class ThrottleSink:
    """Non-blocking slow-consumer wrapper: accepts at most one emission
    per `interval_us`, REFUSING (not sleeping) in between. This is how the
    bench models a deliberately slow sink without ever blocking the event
    loop — the pump sees backpressure and pauses while the replica keeps
    committing at full speed."""

    def __init__(self, inner, interval_us: int):
        self.inner = inner
        self.interval_s = interval_us / 1e6
        self._not_before = 0.0

    def emit_lines(self, lines: list[str]) -> bool:
        now = time.monotonic()
        if now < self._not_before:
            return False
        if not self.inner.emit_lines(lines):
            return False
        self._not_before = now + self.interval_s
        return True

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()


class CountThrottleSink:
    """Count-based slow-consumer wrapper: accepts only every
    `accept_every`-th emission attempt, refusing the rest. The live
    analog of the simulator's `_FanoutStore(throttle_every=k)` — no
    clock involved, so the refusal pattern is deterministic in attempt
    order and the prodday timeline's "slow CDC consumer" event means the
    same thing in both harnesses. Behind the fan-out hub the laggard
    pauses only itself; the WAL/AOF reads cover what the live window
    released past it."""

    def __init__(self, inner, accept_every: int):
        assert accept_every >= 1
        self.inner = inner
        self.accept_every = accept_every
        self.attempts = 0
        self.refusals = 0

    def emit_lines(self, lines: list[str]) -> bool:
        self.attempts += 1
        if self.attempts % self.accept_every:
            self.refusals += 1
            return False
        return self.inner.emit_lines(lines)

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()
