"""Change-record encoder: committed prepare + reply buffer -> records.

One record per event of a committed `create_accounts` / `create_transfers`
prepare (lookups and registers change nothing and encode to no records —
their ops still advance the stream cursor, keeping op coverage contiguous).
The inputs are exactly what the replica already holds at commit finalize:
the prepare body (the event rows) and the reply body (the sparse non-ok
result structs) — result codes come from the buffer that was materialized
for the client reply anyway, so the encoder adds no device->host transfer.

Balance deltas are attached where they are derivable from the row alone:

- plain transfers (no two-phase/balancing flags): debit.debits_posted and
  credit.credits_posted each move by `amount` — exact, `resolved: true`;
- pending transfers: the pending columns move by `amount` — exact;
- post/void/balancing: the moved amount resolves against the PENDING
  transfer's state at execution time (reference:
  src/state_machine.zig:907-1014), which only the execution engine sees —
  the record carries the event verbatim with `resolved: false` and no
  deltas, and a consumer that needs those balances materializes them from
  its own pending store (it has every pending transfer earlier in the
  stream).

Records serialize as canonical JSON lines (sorted keys, fixed separators):
the same committed history always produces byte-identical stream dumps,
which is what the simulator's same-seed determinism check diffs.
"""

from __future__ import annotations

import json

import numpy as np

from tigerbeetle_tpu.types import (
    ACCOUNT_DTYPE,
    CREATE_TRANSFERS_RESULT_DTYPE,
    TRANSFER_DTYPE,
    Operation,
    TransferFlags,
    join_u128,
)

# Transfer flags whose amount/accounts resolve against prior state at
# execution time (two-phase second legs + balancing caps).
_INDIRECT_FLAGS = int(
    TransferFlags.post_pending_transfer
    | TransferFlags.void_pending_transfer
    | TransferFlags.balancing_debit
    | TransferFlags.balancing_credit
)

_CREATE_OPS = (int(Operation.create_accounts), int(Operation.create_transfers))


def _result_codes(n: int, reply_body: bytes | None) -> np.ndarray | None:
    """Sparse non-ok reply structs -> dense per-event u32 codes.
    None means the reply buffer is unavailable (results unknown)."""
    if reply_body is None:
        return None
    codes = np.zeros(n, dtype=np.uint32)
    if reply_body:
        sparse = np.frombuffer(reply_body, dtype=CREATE_TRANSFERS_RESULT_DTYPE)
        codes[sparse["index"]] = sparse["result"]
    return codes


def encode_batch(header, body: bytes, reply_body: bytes | None) -> list[dict]:
    """Change records for one committed prepare. `header` is the prepare's
    VSR header; `reply_body` the reply wire body (sparse result structs)
    or None when unknown (records then carry `result: null`)."""
    operation = int(header.operation)
    if operation not in _CREATE_OPS:
        return []
    rows = np.frombuffer(
        body,
        dtype=(
            ACCOUNT_DTYPE
            if operation == int(Operation.create_accounts)
            else TRANSFER_DTYPE
        ),
    )
    n = len(rows)
    codes = _result_codes(n, reply_body)
    # per-event timestamp rule: the kernel assigns ts - n + i + 1
    ts0 = int(header.timestamp) - n + 1
    out: list[dict] = []
    if operation == int(Operation.create_accounts):
        for i in range(n):
            r = rows[i]
            code = None if codes is None else int(codes[i])
            rec = {
                "kind": "account",
                "op": int(header.op),
                "ix": i,
                "ts": ts0 + i,
                "result": code,
                "id": join_u128(r["id_lo"], r["id_hi"]),
                "ledger": int(r["ledger"]),
                "code": int(r["code"]),
                "flags": int(r["flags"]),
                "user_data_128": join_u128(
                    r["user_data_128_lo"], r["user_data_128_hi"]
                ),
                "user_data_64": int(r["user_data_64"]),
                "user_data_32": int(r["user_data_32"]),
                "resolved": code is not None,
            }
            # event balance fields: zero on every VALID create, but the
            # validation family (debits_posted_must_be_zero & friends)
            # rejects on them — a stream replayer can only reproduce
            # those result codes if the record carries the fields
            for field in (
                "debits_pending", "debits_posted",
                "credits_pending", "credits_posted",
            ):
                rec[field] = join_u128(r[field + "_lo"], r[field + "_hi"])
            rec["reserved"] = int(r["reserved"])
            out.append(rec)
        return out
    for i in range(n):
        r = rows[i]
        code = None if codes is None else int(codes[i])
        flags = int(r["flags"])
        amount = join_u128(r["amount_lo"], r["amount_hi"])
        debit = join_u128(r["debit_account_id_lo"], r["debit_account_id_hi"])
        credit = join_u128(r["credit_account_id_lo"], r["credit_account_id_hi"])
        rec = {
            "kind": "transfer",
            "op": int(header.op),
            "ix": i,
            "ts": ts0 + i,
            "result": code,
            "id": join_u128(r["id_lo"], r["id_hi"]),
            "debit_account_id": debit,
            "credit_account_id": credit,
            "amount": amount,
            "pending_id": join_u128(r["pending_id_lo"], r["pending_id_hi"]),
            "timeout": int(r["timeout"]),
            "ledger": int(r["ledger"]),
            "code": int(r["code"]),
            "flags": flags,
            "user_data_128": join_u128(
                r["user_data_128_lo"], r["user_data_128_hi"]
            ),
            "user_data_64": int(r["user_data_64"]),
            "user_data_32": int(r["user_data_32"]),
        }
        if code is None:
            rec["resolved"] = False
        elif code != 0:
            rec["resolved"] = True  # failed: exactly zero effect
        elif flags & _INDIRECT_FLAGS:
            rec["resolved"] = False  # amount resolves against pending state
        else:
            rec["resolved"] = True
            if flags & int(TransferFlags.pending):
                rec["deltas"] = [
                    [debit, "debits_pending", amount],
                    [credit, "credits_pending", amount],
                ]
            else:
                rec["deltas"] = [
                    [debit, "debits_posted", amount],
                    [credit, "credits_posted", amount],
                ]
        out.append(rec)
    return out


def commitment_record(op: int, commitment: int, prev: int) -> dict:
    """Checkpoint state-commitment record (federation/commitment.py):
    the chained digest of the ledger's state fingerprint at boundary
    `op`. A consumer replaying the stream through its own state machine
    recomputes the chain and rejects a tampered stream/state naming this
    exact checkpoint. Defined here (not in federation/) so the encoder
    module owns every stream record kind without importing upward."""
    return {
        "kind": "commitment",
        "op": op,
        "commitment": commitment,
        "prev": prev,
    }


def gap_record(from_op: int, to_op: int) -> dict:
    """Declared hole in the stream: ops this replica never executed
    (state-sync install jumped over them) or whose bytes are no longer
    reachable (WAL ring wrapped, no AOF). Explicit so a consumer can halt
    or re-point rather than silently missing history."""
    return {"kind": "gap", "from": from_op, "to": to_op}


def record_line(rec: dict) -> str:
    """Canonical JSON line (sorted keys, fixed separators): the same
    record always encodes to the same bytes."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":"))
