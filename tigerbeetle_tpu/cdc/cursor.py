"""Durable per-consumer cursor: the at-least-once bookmark.

A consumer's cursor records the highest op whose records the sink has
accepted, plus that prepare's checksum. On resume the pump restarts at
`op + 1`; anything delivered after the last ack is REDELIVERED, and the
`(op, checksum)` pair is what makes redelivery dedupable (apply only ops
above the cursor; the checksum detects a timeline that forked under the
consumer, which committed history never does — so a mismatch is loud).

Durability is superblock-style (reference: src/vsr/superblock.zig's
checksummed, atomically-replaced state): the payload is canonical JSON
with an embedded AEGIS checksum, written to a temp file, fsynced, then
`os.replace`d over the cursor path, then the directory fsynced. A crash
at any point leaves either the old cursor or the new one — a torn or
corrupt file fails its checksum and reads as absent (op 0: replay from
the start, which at-least-once permits).
"""

from __future__ import annotations

import json
import os
import sys

from tigerbeetle_tpu import native


def _encode(op: int, checksum: int) -> bytes:
    payload = {"op": op, "checksum": f"{checksum:032x}"}
    body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    crc = native.checksum(body.encode())
    return json.dumps(
        {"body": payload, "crc": f"{crc:032x}"},
        sort_keys=True, separators=(",", ":"),
    ).encode() + b"\n"


def _decode(raw: bytes) -> tuple[int, int] | None:
    try:
        outer = json.loads(raw)
        body = outer["body"]
        canon = json.dumps(body, sort_keys=True, separators=(",", ":"))
        if f"{native.checksum(canon.encode()):032x}" != outer["crc"]:
            return None
        return int(body["op"]), int(body["checksum"], 16)
    except (ValueError, KeyError, TypeError):
        return None


class FileCursor:
    """Atomic write-rename cursor file."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> tuple[int, int]:
        """(op, checksum); (0, 0) when absent or corrupt (corruption
        warns: replaying from scratch is safe but worth an operator's
        attention)."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return (0, 0)
        got = _decode(raw)
        if got is None:
            sys.stderr.write(
                f"cdc: cursor {self.path} corrupt; restarting stream "
                "from op 0 (at-least-once: consumers dedup by op)\n"
            )
            return (0, 0)
        return got

    def ack(self, op: int, checksum: int) -> None:
        tmp = self.path + ".tmp"
        data = _encode(op, checksum)
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.write(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, self.path)
        dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)  # the rename itself must be durable
        finally:
            os.close(dfd)


class MemoryCursor:
    """Same interface, process-local: the simulator's "durable" consumer
    state (survives consumer crash/restart inside one simulated run) and
    unit tests."""

    def __init__(self):
        self.op = 0
        self.checksum = 0

    def load(self) -> tuple[int, int]:
        return (self.op, self.checksum)

    def ack(self, op: int, checksum: int) -> None:
        self.op = op
        self.checksum = checksum
