"""Benchmark driver: the reference's scripts/benchmark.sh protocol on TPU.

Reference protocol (reference: src/benchmark.zig:23-73, scripts/benchmark.sh):
10_000 accounts, 10_000_000 transfers submitted in batches of 8190
(id_order=reversed, two uniform-random distinct accounts per transfer,
amount=1), measure transfers/s and batch-latency percentiles
p00/p25/p50/p75/p100 (reference: src/benchmark.zig main loop printout).

Measured paths:

- **Durable (the BASELINE protocol)**: a REAL replica process (WAL +
  consensus + TCP session clients at batch=8190), conservation verified
  over the wire. The commit engine is the native C++ host ledger
  (native/ledger.cc) — on this environment's tunneled TPU, ANY
  device->host fetch permanently degrades the transport (dispatch ~30us ->
  ~12ms, h2d 140+ MiB/s -> ~14 MiB/s; measured, see ops/hashtable.py and
  models/native_ledger.py), so a reply-serving server cannot run its hot
  loop through the device. A short device-backend durable run is reported
  separately (durable_device_tps) as the honest through-stack TPU number,
  plus a two-phase-heavy durable run (durable_two_phase_tps).
- **Flagship (device-generated ingest)**: the protocol workload is generated
  ON DEVICE from a seeded PRNG (same distribution: reversed sequential ids,
  uniform random distinct account pairs, amount=1) and committed batch by
  batch, K batches fused per dispatch — the TPU commit kernel's throughput,
  the way the reference's loopback benchmark measures its state machine.
  Median of 5 timed segments with the per-run values reported.
- **Ingest-limited (host-upload)**: batches built on host and uploaded
  per-batch (1 MiB each), pipelined, no d2h until the clock stops. Reported
  as `ingest_tps`.
- **Tracked configs**: lookups, two-phase, linked chains, balancing, mixed
  split, and the spill-active steady state (which INCLUDES posts of
  spilled pendings so the pre-commit reload path is measured; its ceiling
  is set by the degraded-transport artifact above — the first cold row
  shipped to the host LSM degrades every later 1 MiB batch upload).

No device->host transfer happens in the flagship/ingest phases until their
clocks stop. Verification (result-code maxes, fault word, conservation
sums) runs after, reduced on device to scalars.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": "transfers/s", "vs_baseline": N, ...}
vs_baseline is value / 10_000_000 — BASELINE.json's target (>= 10M
transfers/s on one v5e chip). The stage-time table goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.tracer import NULL_TRACER, JsonTracer

# The bench's shared observability pair (tigerbeetle_tpu/metrics.py):
# every phase reports into METRICS (stage spans, batch-latency histogram,
# the instrumented spill pipeline), and `--trace <path>` swaps TRACER for
# a JsonTracer whose dump — merged with the e2e server's span dump — is
# one Perfetto-loadable file covering driver AND server.
METRICS = Metrics()
TRACER = NULL_TRACER

def _jax_cache_bytes() -> int:
    """Size of the repo's persistent XLA compilation cache (.jax_cache),
    recorded at driver start and end so the summary carries compile-cache
    provenance — growth here IS the recompiles the sentinel counted."""
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache")
    total = 0
    for root, _dirs, files in os.walk(cache):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


_JAX_CACHE_BYTES_START = _jax_cache_bytes()


def _sentinel_summary() -> dict | None:
    """Compile-sentinel totals for THIS driver process (the in-process
    device phases; subprocess servers report theirs via SIGQUIT/stats).
    None when the device stack never got imported (host-only runs)."""
    mod = sys.modules.get("tigerbeetle_tpu.models.ledger")
    if mod is None:
        return None
    snap = mod.COMPILE_SENTINEL.snapshot()
    return {
        "total": snap["total"],
        "post_warmup": snap["post_warmup"],
        "per_fn": snap["per_fn"],
    }


BASELINE_TPS = 10_000_000.0  # BASELINE.json north-star target
N_ACCOUNTS = 10_000
BATCH = 8190  # (1 MiB - 128 B) / 128 B, reference: src/constants.zig:167-168
N_TRANSFERS = int(os.environ.get("BENCH_TRANSFERS", 10_000_000))
N_INGEST = int(os.environ.get("BENCH_INGEST_TRANSFERS", 1_000_000))
N_LATENCY = 30  # synced batches for the latency percentiles
K_FUSE = 8  # batches committed per device dispatch in the flagship phase


def build_accounts(start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import ACCOUNT_DTYPE

    arr = np.zeros(count, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + count, dtype=np.uint64)
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def build_transfers(rng, start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import TRANSFER_DTYPE

    arr = np.zeros(count, dtype=TRANSFER_DTYPE)
    # id_order=reversed (reference: src/benchmark.zig:66-73 default).
    arr["id_lo"] = np.arange(start_id + count - 1, start_id - 1, -1, dtype=np.uint64)
    dr = rng.integers(1, N_ACCOUNTS + 1, size=count, dtype=np.uint64)
    off = rng.integers(1, N_ACCOUNTS, size=count, dtype=np.uint64)
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = (dr - 1 + off) % N_ACCOUNTS + 1  # distinct
    arr["amount_lo"] = 1
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def make_device_stepper(kernels, n_pad: int, k_fuse: int):
    """Jitted: generate k_fuse protocol batches on device (seeded PRNG, same
    distribution as build_transfers) and run the fast-tier commit kernel on
    each, sequentially, in ONE dispatch. Returns (state', code_max')."""
    import jax
    import jax.numpy as jnp

    B = n_pad
    n_acc = np.uint64(N_ACCOUNTS)  # np constants embed as XLA literals
    mask32 = np.uint64(0xFFFFFFFF)

    def gen_rows(key, start_id):
        lane = jnp.arange(B, dtype=jnp.uint64)
        id_lo = start_id + jnp.uint64(BATCH - 1) - lane  # reversed ids
        k1, k2 = jax.random.split(key)
        dr = jax.random.randint(
            k1, (B,), 1, N_ACCOUNTS + 1, dtype=jnp.uint32
        ).astype(jnp.uint64)
        off = jax.random.randint(
            k2, (B,), 1, N_ACCOUNTS, dtype=jnp.uint32
        ).astype(jnp.uint64)
        cr = (dr - jnp.uint64(1) + off) % n_acc + jnp.uint64(1)
        u32 = jnp.uint32
        z = jnp.zeros(B, dtype=u32)
        one = jnp.ones(B, dtype=u32)
        words = [z] * 32
        words[0] = (id_lo & mask32).astype(u32)
        words[1] = (id_lo >> jnp.uint64(32)).astype(u32)
        words[4] = dr.astype(u32)  # account ids < 2^32
        words[8] = cr.astype(u32)
        words[12] = one  # amount = 1
        words[28] = one  # ledger = 1
        words[29] = one  # code = 1, flags = 0
        return jnp.stack(words, axis=1)

    def step(state, code_max, key, start_id, ts_end):
        # Batch j of this dispatch: ids [start_id + j*BATCH, ...), final
        # timestamp ts_end - (k_fuse-1-j)*BATCH (per-event ts assigned by the
        # kernel as timestamp - n + i + 1).
        for j in range(k_fuse):
            kj = jax.random.fold_in(key, j)
            rows = gen_rows(kj, start_id + jnp.uint64(j * BATCH))
            ts_j = ts_end - jnp.uint64((k_fuse - 1 - j) * BATCH)
            state, r = kernels._commit_transfers(
                state, {"rows": rows}, jnp.int32(BATCH), ts_j, mode="fast"
            )
            code_max = jnp.maximum(code_max, jnp.max(r))
        return state, code_max

    return jax.jit(step, donate_argnums=(0,))


def bench_tracked_configs(stage) -> dict:
    """BASELINE.json's five tracked configs beyond the flagship: the read
    path, pure two-phase, linked chains, balancing (exact serial tier), and
    a realistic mixed batch exercising the conflict-partitioned middle
    tier. Synced per batch (these are serial/residue-dominated, so dispatch
    overlap is irrelevant); a warmup batch per config absorbs compiles."""
    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
    from tigerbeetle_tpu.models.ledger import DeviceLedger, ids_to_batch
    from tigerbeetle_tpu.types import TRANSFER_DTYPE, Operation

    out = {}
    n_runs = int(os.environ.get("BENCH_CFG_RUNS", 3))
    # Events per tracked-config batch. Default = the protocol BATCH (the
    # rig artifact); smaller values exist for CPU-sandbox artifacts — the
    # serial tier is a lax.scan of one step per EVENT, so a full 8190-
    # event chains/balancing config costs hours on one CPU core. The
    # chosen value rides out in the artifact (`cfg_batch` field below)
    # and all config-vs-config ratios stay batch-size-consistent.
    cbatch = int(os.environ.get("BENCH_CFG_BATCH", BATCH))
    cpad = BATCH_PAD if cbatch >= BATCH else max(
        8, 1 << (cbatch - 1).bit_length()
    )
    # Transfer-table size scales with cbatch at the protocol's load factor
    # (2^22 slots for 5 full batches): the serial tier's lax.scan carries
    # the whole table as loop state, and XLA-CPU materializes it per step
    # — table SIZE, not event count, drives serial cost off the rig
    # (measured: 256-event chains batch, 2^22 table 50 s vs 2^18 1.8 s;
    # on the rig donation aliases the update in place and this is free).
    xfer_log2 = 22
    while xfer_log2 > 16 and (1 << (xfer_log2 - 1)) * BATCH >= (1 << 22) * cbatch:
        xfer_log2 -= 1
    out["cfg_batch"] = cbatch

    def fresh(n_accounts=N_ACCOUNTS):
        process = ConfigProcess(
            account_slots_log2=16, transfer_slots_log2=xfer_log2
        )
        ledger = DeviceLedger(process=process, mode="auto")
        ledger.pad_to = cpad
        ts = 1 << 40
        next_id = 1
        while next_id <= n_accounts:
            k = min(cbatch, n_accounts - next_id + 1)
            ts += k
            ledger.execute_async(
                Operation.create_accounts, ts, build_accounts(next_id, k)
            )
            next_id += k
        return ledger, ts

    def run_batches(ledger, ts, batches, events_per_batch=None,
                    warmup=1) -> float:
        """`warmup` batches absorb jit compiles and must exercise every tier
        the timed batches hit (two-phase passes 2: pending=fast,
        post=fast_pv). Returns the timed TPS."""
        if events_per_batch is None:
            events_per_batch = cbatch
        pends = []
        for b in batches[:warmup]:
            ts += events_per_batch
            pends.append(ledger.execute_async(Operation.create_transfers, ts, b))
        jax.block_until_ready(pends[-1].results)
        t0 = time.perf_counter()
        n = 0
        for b in batches[warmup:]:
            ts += events_per_batch
            p = ledger.execute_async(Operation.create_transfers, ts, b)
            jax.block_until_ready(p.results)
            n += events_per_batch
        return n / (time.perf_counter() - t0)

    def median_config(name, one_run) -> None:
        """Each tracked config runs N times over FRESH ledgers (kernels
        are process-cached, so only run 1 pays compiles — its warmup
        batches absorb them) and reports median + per-run values + spread
        (round-4 verdict: single samples swung 2x between bench runs)."""
        t0 = time.perf_counter()
        vals = [one_run(np.random.default_rng(77 + 13 * i))
                for i in range(n_runs)]
        med = float(np.median(vals))
        out[name] = round(med, 1)
        out[name + "_runs"] = [round(v, 1) for v in vals]
        out[name + "_spread"] = (
            round((max(vals) - min(vals)) / med, 4) if med else None
        )
        # progress attribution: the configs are the bench's longest silent
        # stretch — without this line a stall cannot be pinned to a config
        print(
            f"[cfg] {name}: {out[name]:.1f} spread="
            f"{out[name + '_spread']} ({time.perf_counter() - t0:.1f}s)",
            file=sys.stderr,
        )

    # 1. read path: lookup_accounts over full id batches
    def cfg_lookup(rng):
        ledger, ts = fresh()
        ids = ids_to_batch(
            [int(x) for x in rng.integers(1, N_ACCOUNTS + 1, size=cbatch)],
            cpad,
        )
        k = ledger.kernels.lookup_accounts
        jax.block_until_ready(k(ledger.state, ids)[0])  # compile
        t0 = time.perf_counter()
        for _ in range(20):
            found, rows, res = k(ledger.state, ids)
        jax.block_until_ready(found)
        return 20 * cbatch / (time.perf_counter() - t0)

    with stage("cfg_lookup"):
        median_config("lookup_accounts_per_s", cfg_lookup)

    # 2. two-phase: full pending batches (fast tier) then full post batches
    # (the VECTORIZED fast_pv tier — distinct prior-batch pendings)
    def cfg_two_phase(rng):
        ledger, ts = fresh()
        batches = []
        for g in range(4):
            base = 1 + g * 2 * cbatch
            pend = build_transfers(rng, base, cbatch)
            pend["flags"] = 2  # pending
            post = np.zeros(cbatch, dtype=TRANSFER_DTYPE)
            post["id_lo"] = np.arange(base + cbatch, base + 2 * cbatch, dtype=np.uint64)
            post["pending_id_lo"] = pend["id_lo"]
            post["flags"] = 4  # post_pending_transfer
            batches += [pend, post]
        return run_batches(ledger, ts, batches, warmup=2)

    with stage("cfg_two_phase"):
        median_config("two_phase_tps", cfg_two_phase)

    # 3. linked chains: every batch is chains of 4 (exact serial tier)
    def cfg_chains(rng):
        ledger, ts = fresh()
        batches = []
        for g in range(3):
            b = build_transfers(rng, 1 + g * cbatch, cbatch)
            b["flags"] = 1  # linked
            b["flags"][3::4] = 0  # chain terminators every 4th event
            b["flags"][-1] = 0
            batches.append(b)
        return run_batches(ledger, ts, batches)

    with stage("cfg_chains"):
        median_config("linked_chains_tps", cfg_chains)

    # 4. balancing: balancing_debit over funded accounts (exact serial tier)
    def cfg_balancing(rng):
        ledger, ts = fresh()
        seed_batch = build_transfers(rng, 1, cbatch)  # fund accounts first
        ts += cbatch
        ledger.execute_async(Operation.create_transfers, ts, seed_batch)
        batches = []
        for g in range(3):
            b = build_transfers(rng, 1 + (g + 1) * cbatch, cbatch)
            b["flags"] = 16  # balancing_debit
            batches.append(b)
        return run_batches(ledger, ts, batches)

    with stage("cfg_balancing"):
        median_config("balancing_tps", cfg_balancing)

    # 5. mixed: ~88% simple transfers + ~6% posts (fast_pv lanes) + ~6%
    # linked-chain pairs on their own accounts -> the conflict-WAVE
    # scheduler with a serial residue (the chains; everything else rides
    # one fast_pv wave)
    def cfg_mixed(rng):
        ledger, ts = fresh()
        pend0 = build_transfers(rng, 1, cbatch)
        pend0["flags"] = 2
        # keep pending accounts in a reserved low range, disjoint from the
        # fast majority below
        # pending accounts 1..599: disjoint from the chain range (600..900)
        # AND the fast majority (>1000), so the fixpoint cannot cascade
        pend0["debit_account_id_lo"] = 1 + (np.arange(cbatch) % 300)
        pend0["credit_account_id_lo"] = 301 + (np.arange(cbatch) % 299)
        ts += cbatch
        ledger.execute_async(Operation.create_transfers, ts, pend0)
        batches = []
        n_res = cbatch // 16  # residue events (~512 at the protocol BATCH)
        for g in range(4):
            b = build_transfers(rng, 1 + (g + 1) * cbatch, cbatch)
            # fast majority over accounts > 1000
            dr = rng.integers(1001, N_ACCOUNTS + 1, size=cbatch, dtype=np.uint64)
            off = rng.integers(1, N_ACCOUNTS - 1001, size=cbatch, dtype=np.uint64)
            b["debit_account_id_lo"] = dr
            b["credit_account_id_lo"] = (dr - 1001 + off) % (N_ACCOUNTS - 1000) + 1001
            # residue: posts of the pending batch, scattered through the lanes
            # chains: the first 2*k lanes form linked pairs CLOSED over a
            # reserved account range (so the disjointness fixpoint cannot
            # cascade into the fast majority) — the serial residue that
            # forces the SPLIT executor
            k = n_res // 2
            heads = np.arange(0, 2 * k, 2)
            pair = np.arange(0, 2 * k)
            b["flags"][heads] = 1  # linked; the adjacent lane terminates
            b["debit_account_id_lo"][pair] = 600 + (pair % 150)
            b["credit_account_id_lo"][pair] = 751 + (pair % 150)
            # posts of prior-batch pendings (fast_pv lanes) in the remainder
            post_lanes = rng.choice(
                np.arange(2 * k, cbatch), size=n_res, replace=False
            )
            b["pending_id_lo"][post_lanes] = pend0["id_lo"][g * n_res:(g + 1) * n_res]
            b["debit_account_id_lo"][post_lanes] = 0
            b["credit_account_id_lo"][post_lanes] = 0
            b["amount_lo"][post_lanes] = 0
            b["flags"][post_lanes] = 4
            batches.append(b)
        tps = run_batches(ledger, ts, batches)
        # plan_stats carries the wave-planner keys AND the deprecated
        # split/split_pv compat keys (same dict) — dashboards reading
        # split_stats keep working, new readers take the wave keys
        ps = ledger.hazards.plan_stats
        out["split_stats"] = dict(ledger.hazards.split_stats)
        out["wave_plan_stats"] = dict(ps)
        assert ps.get("waves", 0) >= 3, (
            "mixed config must exercise the conflict-wave scheduler"
        )
        assert ps.get("residue_events", 0) > 0, (
            "mixed config's linked chains must fall to the serial residue"
        )
        return tps

    with stage("cfg_mixed"):
        median_config("mixed_split_tps", cfg_mixed)

    # 5b. hot-account waves (ROADMAP item 2's workload): a few viral hot
    # accounts absorb most traffic AND every batch carries same-batch
    # pend->post dependency pairs. The retired all-or-nothing analysis
    # serialized such batches whole; the wave planner runs them as ~2
    # dependency-ordered waves (each post one wave after its creator),
    # with NO serial residue.
    def cfg_mixed_hot(rng):
        ledger, ts = fresh()
        batches = []
        n_dep = cbatch // 8  # same-batch pend->post pairs per batch
        for g in range(4):
            b = build_transfers(rng, 1 + g * cbatch, cbatch)
            # zipf-flavored mix: ~25% of debits hit ONE hot account, the
            # rest spread power-law across the id space
            u = rng.random(cbatch)
            dr = (1 + (N_ACCOUNTS - 1) * u**3).astype(np.uint64)
            dr[rng.random(cbatch) < 0.25] = 1
            off = rng.integers(1, N_ACCOUNTS, size=cbatch, dtype=np.uint64)
            b["debit_account_id_lo"] = dr
            b["credit_account_id_lo"] = (dr - 1 + off) % N_ACCOUNTS + 1
            b["flags"][:n_dep] = 2  # pendings...
            post_lanes = rng.choice(  # ...posted later IN THE SAME BATCH
                np.arange(n_dep, cbatch), size=n_dep, replace=False
            )
            b["pending_id_lo"][post_lanes] = b["id_lo"][:n_dep]
            b["debit_account_id_lo"][post_lanes] = 0
            b["credit_account_id_lo"][post_lanes] = 0
            b["amount_lo"][post_lanes] = 0
            b["flags"][post_lanes] = 4
            batches.append(b)
        tps = run_batches(ledger, ts, batches)
        ps = ledger.hazards.plan_stats
        out["mixed_hot_plan_stats"] = dict(ps)
        assert ps.get("waves", 0) >= 3, (
            "hot config must run the conflict-wave scheduler"
        )
        assert ps.get("residue_events", 0) == 0, (
            "hot config has no chains/balancing: nothing may fall serial"
        )
        return tps

    with stage("cfg_mixed_hot"):
        median_config("mixed_hot_tps", cfg_mixed_hot)

    # dependent-transfer segments vs the fast path, measured under the
    # SAME synced per-batch protocol (two_phase_tps is the pure
    # fast/fast_pv configuration) — ROADMAP item 2 targets >= 0.5x
    if out.get("two_phase_tps"):
        out["mixed_vs_fast_ratio"] = round(
            out["mixed_split_tps"] / out["two_phase_tps"], 4
        )
        out["mixed_hot_vs_fast_ratio"] = round(
            out["mixed_hot_tps"] / out["two_phase_tps"], 4
        )

    # 6. spill-active steady state: the transfer table's HBM budget is a
    # fraction of the workload, so the cold tail spills to the LSM forest
    # every few batches and the pre-commit reload path stays hot — the
    # bounded-memory cliff, measured rather than assumed.
    try:
        _bench_spill_config(stage, out, np.random.default_rng(77))
    except Exception as e:  # never sink the whole benchmark
        out["spill_active_tps"] = 0.0
        out["spill_error"] = f"{type(e).__name__}: {e}"
        print(f"[spill config] FAILED: {e}", file=sys.stderr)

    return out


def _bench_spill_config(stage, out, rng) -> None:
    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.constants import BATCH_PAD, TEST_CLUSTER, ConfigProcess
    from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
    from tigerbeetle_tpu.lsm.grid import Grid
    from tigerbeetle_tpu.lsm.groove import Forest
    from tigerbeetle_tpu.models.ledger import DeviceLedger
    from tigerbeetle_tpu.types import Operation

    # A/B transport probe (round-4 verdict: the "degraded transport" claim
    # needs its isolating artifact, like the flagship's dispatch probe).
    # This config is the bench's only phase that DRAINS every batch — and
    # the first drain is this process-section's first device->host fetch,
    # the cliff that permanently degrades the tunnel. Probing launch
    # latency before ANY drain, after the first drain, and after the first
    # spill cycle separates "any reply-serving d2h degrades the transport"
    # from "the spill machinery is slow".
    _pz = jnp.zeros(1, dtype=jnp.uint32)
    _pf = jax.jit(lambda a, b: jnp.maximum(a, jnp.max(b)))
    jax.block_until_ready(_pf(jnp.uint32(0), _pz))  # absorb the compile

    def probe_dispatch(n=40):
        x = jnp.uint32(0)
        t0 = time.perf_counter()
        for _ in range(n):
            x = _pf(x, _pz)
        jax.block_until_ready(x)
        return round((time.perf_counter() - t0) / n * 1e6, 1)  # us/launch

    probe = {"dispatch_us_fresh": probe_dispatch()}  # pre-ANY-d2h

    with stage("cfg_spill"):
        layout = ZoneLayout(TEST_CLUSTER, grid_size=768 * 1024 * 1024)
        forest = Forest(Grid(
            MemoryStorage(layout), offset=0, block_count=5760,
            cache_blocks=128,
        ), memtable_max=8192)  # spill-heavy: bigger tables, less churn
        process = ConfigProcess(account_slots_log2=16,
                                transfer_slots_log2=16)  # 32k-row budget
        ledger = DeviceLedger(process=process, mode="auto", forest=forest)
        # shared registry: spill_overlap / spill_lookup_batch below are
        # read back out of METRICS (overlap_report reads the registry-
        # backed StatGroup), and --trace records the prefetch/admit spans
        ledger.instrument(METRICS, TRACER)
        ledger.pad_to = BATCH_PAD
        ts2 = 1 << 41
        next_id = 1
        while next_id <= N_ACCOUNTS:
            k = min(BATCH, N_ACCOUNTS - next_id + 1)
            ts2 += k
            ledger.execute_async(
                Operation.create_accounts, ts2, build_accounts(next_id, k)
            )
            next_id += k
        n_sp = 0
        nbatches = int(os.environ.get("BENCH_SPILL_BATCHES", 24))
        n_pend = max(2, nbatches // 6)  # oldest batches: spilled first
        n_post = n_pend // 2  # posts of (by then) SPILLED pendings
        # Warm until a spill CYCLE and a RELOAD have both run: the cycle's
        # kernels (ts/occ scan, gather, reload, post tier) otherwise
        # compile inside the timed loop — tens of seconds of remote
        # compiles booked against the steady-state number.
        warm_pend = build_transfers(rng, 4_000_000, BATCH)
        warm_pend["flags"] = 2
        ts2 += BATCH
        ledger.drain(ledger.execute_async(
            Operation.create_transfers, ts2, warm_pend
        ))
        # the drain above was the first d2h: THE transport cliff
        probe["dispatch_us_post_first_drain"] = probe_dispatch()
        wg = 0
        pre_spill_batch_s = []
        while ledger.spill.stats["cycles"] < 1 and wg < 8:
            warm = build_transfers(rng, 4_500_000 + wg * BATCH, BATCH)
            ts2 += BATCH
            tb = time.perf_counter()
            ledger.drain(ledger.execute_async(
                Operation.create_transfers, ts2, warm
            ))
            if ledger.spill.stats["cycles"] == 0:  # pure commit, no cycle
                pre_spill_batch_s.append(time.perf_counter() - tb)
            wg += 1
        # after the first spill cycle's own gathers: unchanged from the
        # post-drain value when the cycle adds no further transport damage
        probe["dispatch_us_post_first_cycle"] = probe_dispatch()
        if pre_spill_batch_s:
            probe["commit_ms_best_pre_spill"] = round(
                min(pre_spill_batch_s) * 1e3, 1
            )
        warm_post = np.zeros(BATCH, dtype=warm_pend.dtype)
        warm_post["id_lo"] = np.arange(
            4_900_000, 4_900_000 + BATCH, dtype=np.uint64
        )
        warm_post["pending_id_lo"] = warm_pend["id_lo"]
        warm_post["flags"] = 4  # posts of spilled pendings: reload + tier
        ts2 += BATCH
        ledger.drain(ledger.execute_async(
            Operation.create_transfers, ts2, warm_post
        ))
        # Build the whole workload BEFORE the clock (the flagship generates
        # on device for the same reason: batch construction is workload
        # generation, not the system under test).
        pend_bodies = []
        batches = []
        for g in range(nbatches):
            if g < n_pend:
                # two-phase pendings on a reserved account range; their
                # rows age out to the LSM store before the posts arrive
                b = build_transfers(rng, 6_000_000 + g * BATCH, BATCH)
                b["flags"] = 2  # pending
                pend_bodies.append(b.copy())
            elif g >= nbatches - n_post and pend_bodies:
                # posts referencing SPILLED pendings: the pre-commit
                # reload path (the prefetch contract) under measurement
                p = pend_bodies.pop(0)
                b = np.zeros(BATCH, dtype=p.dtype)
                b["id_lo"] = np.arange(
                    8_000_000 + g * BATCH, 8_000_000 + (g + 1) * BATCH,
                    dtype=np.uint64,
                )
                b["pending_id_lo"] = p["id_lo"]
                b["flags"] = 4  # post_pending_transfer
            else:
                b = build_transfers(rng, 6_000_000 + g * BATCH, BATCH)
            batches.append(b)

        # The OVERLAPPED spill pipeline under measurement (models/spill.py
        # module docstring): a window of W batches stays in flight (drain
        # lags dispatch, so the per-batch d2h never serializes the degraded
        # transport), and batch g+1's referenced-spilled rows prefetch on
        # the spill IO worker while batch g's commit kernel runs — admit()
        # then finds them staged. spill_overlap (reported below) accounts
        # the hidden fraction of the gather, the analog of PR 1's
        # shadow_upload_overlap.
        W = int(os.environ.get("BENCH_SPILL_WINDOW", 4))
        window = []
        dispatch_s = []
        t0 = time.perf_counter()
        for g, b in enumerate(batches):
            ts2 += BATCH
            tb = time.perf_counter()
            window.append(ledger.execute_async(
                Operation.create_transfers, ts2, b
            ))
            if g + 1 < len(batches):
                ledger.spill.prefetch_async(batches[g + 1])
            while len(window) > W:
                ledger.drain(window.pop(0))
            dispatch_s.append(time.perf_counter() - tb)
            n_sp += BATCH
            # the checkpoint-cadence free-set apply: staged releases from
            # compaction churn become reusable, as the durable system's
            # checkpoint chain would do (grid.py contract). io_drain first:
            # the spill-IO worker mutates the same lock-free grid/free-set
            # (the SpillManager.checkpoint_meta pattern); every 4th batch,
            # a real checkpoint cadence, so the drain barrier doesn't
            # serialize every batch against the worker
            if g % 4 == 3:
                ledger.spill.io_drain()
                forest.grid.encode_free_set()
        for p in window:
            ledger.drain(p)
        out["spill_active_tps"] = round(n_sp / (time.perf_counter() - t0), 1)
        # best dispatch+lagged-drain turn = a cycle-free post-d2h commit:
        # against commit_ms_best_pre_spill it splits the bill between "the
        # tunnel degraded every dispatch" and "cycles/reloads cost time"
        probe["commit_ms_best_spill_active"] = round(
            min(dispatch_s) * 1e3, 1
        )
        out["spill_transport_probe"] = probe
        out["spill_window"] = W
        out["spill_stats"] = {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in ledger.spill.stats.items()
        }
        # overlap accounting: spill_overlap = fraction of prefetch-gather
        # seconds hidden behind commits; spill_lookup_batch = mean ids per
        # batched LSM multi-point-read
        out.update(ledger.spill.overlap_report())
        assert ledger.spill.stats["cycles"] >= 2, "spill never engaged"
        assert ledger.spill.stats["reloaded"] > 0, (
            "spill bench never exercised the reload path"
        )
        assert ledger.spill.stats["prefetches"] >= 1, (
            "spill bench never exercised the prefetch overlap path"
        )


def _median_e2e(stage, name: str, n_runs: int, log, trace: bool = False,
                **kw) -> dict:
    """run_e2e N times (fresh server each), report the median with per-run
    values + spread (round-4 verdict: single samples hid a 30%+ swing).
    Dual-mode runs must ALL verify their device shadow. With trace=True
    the FIRST run's server dumps its commit-pipeline spans; they ride out
    as `trace_events` for the driver to merge into the --trace file."""
    from tigerbeetle_tpu.benchmark import run_e2e

    backend = kw.get("backend", "native")
    dual = "+" in backend or backend == "dual"
    runs, shadows, hash_logs, hits, last = [], [], [], [], None
    trace_events = None
    for i in range(n_runs):
        kw_i = dict(kw, trace="server") if (trace and i == 0) else kw
        with stage(f"{name}_{i}"):
            last = run_e2e(log=log, **kw_i)
        if trace and i == 0:
            trace_events = last.pop("trace_events", None)
        runs.append(last["durable_tps"])
        hits.append(last.get("group_commit_hit_rate"))
        if dual:
            # a run whose server died before printing [stats] has no
            # device_shadow at all — that is an UNVERIFIED run, not a
            # skippable one
            shadows.append(
                last.get("device_shadow", {}).get("verified")
            )
            hash_logs.append(last.get("device_hash_log_ok"))
    med = float(np.median(runs))
    out = dict(last)
    out["durable_tps"] = round(med, 1)
    out["durable_runs"] = [round(x, 1) for x in runs]
    out["durable_spread"] = (
        round((max(runs) - min(runs)) / med, 4) if med else None
    )
    # per-run fuse hit rates (the fuse-window regression's artifact:
    # a single aggregated rate hid which segment/run had the bad window)
    out["group_commit_hit_rate_runs"] = hits
    if dual:
        out["shadow_verified_all"] = all(v is True for v in shadows)
        if backend == "dual":
            # follower runs MUST carry the per-op ring check: a missing
            # report (server died before [stats], finalize timed out) is
            # an UNVERIFIED run, not a skippable one — same rule as
            # shadow_verified_all. Shadow-mode segments have no ring and
            # no flag at all.
            out["hash_log_ok_all"] = all(v is True for v in hash_logs)
    if trace_events is not None:
        out["trace_events"] = trace_events
    return out


def bench_e2e(stage, trace: bool = False) -> dict:
    """The durable, through-consensus numbers: format a data file, start a
    REAL replica process (WAL on), drive create_transfers through TCP
    session clients at batch=8190 and verify conservation over the wire —
    the reference's actual measurement protocol (reference:
    scripts/benchmark.sh:34-78, src/benchmark.zig:23-73). Three workloads,
    each median-of-N over fresh server processes:

    - DUAL backend (native+device), simple transfers: the headline
      durable_tps. The C++ engine serves replies while the TPU applies the
      same prepares asynchronously (h2d only, models/dual_ledger.py);
      shutdown verifies device state bit-exact (reply-code digests +
      state fingerprints) — the TPU holds real durable state without a
      d2h in the timed path.
    - DUAL backend, two-phase-heavy (pend->post pairs);
    - dual-commit durable mode (`--backend dual`, the e2e_device
      segment): the device applier FOLLOWS the committed op stream off
      the reply path (h2d only) — durable_device_tps is the
      through-stack TPU number with the device holding real, verified
      state (per-op hash-log ring + fingerprints), replacing the old
      reply-through-the-device configuration that paid a device round
      trip per commit (15x under native in r05).

    MUST run before this process touches JAX: the server subprocesses own
    the TPU chip."""
    log = lambda *a: print("[e2e]", *a, file=sys.stderr)  # noqa: E731
    n = int(os.environ.get("BENCH_E2E_TRANSFERS", 2_000_000))
    n_runs = int(os.environ.get("BENCH_E2E_RUNS", 3))
    clients = int(os.environ.get("BENCH_E2E_CLIENTS", 10))
    # ONE client process drives the whole protocol through the async packet
    # ABI (native/tb_client.cc session pool) — BENCH_E2E_DRIVER=python
    # falls back to the per-session Python driver
    driver = os.environ.get("BENCH_E2E_DRIVER", "async")
    try:
        out = _median_e2e(
            stage, "e2e_durable", n_runs, log, trace=trace,
            n_accounts=N_ACCOUNTS, n_transfers=n, clients=clients,
            backend="native+device", driver=driver,
        )
    except Exception as e:  # never sink the kernel benchmark
        print(f"[e2e] FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        return {"durable_tps": 0.0, "error": f"{type(e).__name__}: {e}"}
    try:
        tp = _median_e2e(
            stage, "e2e_two_phase", n_runs, log,
            n_accounts=N_ACCOUNTS,
            n_transfers=int(os.environ.get("BENCH_E2E_TP", 1_000_000)),
            clients=clients, workload="two_phase", backend="native+device",
            driver=driver,
        )
        out["two_phase"] = tp
        out["durable_two_phase_tps"] = tp["durable_tps"]
        out["durable_two_phase_runs"] = tp["durable_runs"]
        out["durable_two_phase_spread"] = tp["durable_spread"]
        # the headline verified flag covers EVERY dual run, both workloads
        out["shadow_verified_all"] = bool(
            out.get("shadow_verified_all")
        ) and bool(tp.get("shadow_verified_all"))
    except Exception as e:
        out["two_phase"] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[e2e two-phase] FAILED: {e}", file=sys.stderr)
    try:
        # The e2e_device segment now MEASURES dual-commit durable mode
        # (`--backend dual`): the native engine serves replies on the
        # critical path while the device applier follows the committed op
        # stream asynchronously (h2d only) — so durable_device_tps is the
        # honest through-stack number for a server whose device state is
        # real, verified state, instead of the reply-through-the-device
        # configuration that paid a device round trip per commit (47.2k
        # in r05, 15x under the native path). Parity is proven per run:
        # state fingerprints + code-stream digests + the per-op hash-log
        # ring check, all after the clock stops.
        dv = _median_e2e(
            stage, "e2e_device", n_runs, log,
            n_accounts=N_ACCOUNTS,
            n_transfers=int(os.environ.get("BENCH_E2E_DEV", 1_000_000)),
            clients=clients, backend="dual", driver=driver,
        )
        out["device_backend"] = dv
        out["durable_device_tps"] = dv["durable_tps"]
        out["durable_device_runs"] = dv["durable_runs"]
        out["durable_device_spread"] = dv["durable_spread"]
        out["device_shadow_verified_all"] = dv.get("shadow_verified_all")
        out["device_hash_log_ok"] = dv.get("hash_log_ok_all")
        out["device_lag_ops"] = dv.get("device_lag_ops")
        out["device_apply_overlap"] = dv.get("device_apply_overlap")
    except Exception as e:
        out["device_backend"] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[e2e device] FAILED: {e}", file=sys.stderr)
    try:
        # CDC A/B: same backend/driver/batch protocol as the headline
        # durable run, with a live change-stream pump attached to a
        # DELIBERATELY slow (refusing, never blocking) sink. The contract
        # under measurement: durable_cdc_tps within noise of durable_tps
        # — backpressure pauses the pump (cdc_backpressure_pauses), the
        # stream lags (cdc_lag_ops), the commit path never waits.
        with stage("e2e_cdc"):
            from tigerbeetle_tpu.benchmark import run_e2e

            cdc = run_e2e(
                n_accounts=N_ACCOUNTS,
                n_transfers=int(os.environ.get("BENCH_E2E_CDC", 1_000_000)),
                clients=clients, backend="native+device", driver=driver,
                # ~50 ops/s sink ceiling — well below the durable commit
                # rate, so the sink genuinely saturates and the lag/pause
                # counters prove the pump (not the replica) absorbed it
                cdc_slow_us=20_000, log=log,
            )
        out["cdc"] = cdc
        out["durable_cdc_tps"] = cdc["durable_tps"]
        out["cdc_lag_ops"] = cdc.get("cdc_lag_ops")
        out["cdc_backpressure_pauses"] = cdc.get("cdc_backpressure_pauses")
        out["cdc_ops_streamed"] = cdc.get("cdc_ops_streamed")
    except Exception as e:
        out["cdc"] = {"error": f"{type(e).__name__}: {e}"}
        print(f"[e2e cdc] FAILED: {e}", file=sys.stderr)
    # Fuse-window regression artifact: the hit rate (and the window the
    # autotune ended at) PER SEGMENT — r05's single 0.4562 aggregate could
    # not say which workload/window pairing produced it.
    segs = {
        "e2e_durable": out,
        "e2e_two_phase": out.get("two_phase", {}),
        "e2e_device": out.get("device_backend", {}),
        "e2e_cdc": out.get("cdc", {}),
    }
    out["group_hit_rate_by_segment"] = {
        k: {
            "hit_rate": v.get("group_commit_hit_rate"),
            "hit_rate_runs": v.get("group_commit_hit_rate_runs"),
            "fuse_window_us": v.get("fuse_window_us"),
            "fuse_holds": v.get("group_fuse_holds"),
            "fuse_expired": v.get("group_fuse_expired"),
        }
        for k, v in segs.items()
    }
    return out


def bench_ingress(stage) -> dict:
    """The ingress_sessions segment: 10k live multiplexed sessions
    through the gateway (tigerbeetle_tpu/ingress) against one replica —
    p99 vs the 10-session baseline, plus a deliberately saturating phase
    whose sheds must not collapse throughput. Host-only (numpy +
    sockets): runs in the pre-JAX section like the e2e phases."""
    log = lambda *a: print("[ingress]", *a, file=sys.stderr)  # noqa: E731
    n = int(os.environ.get("BENCH_INGRESS_SESSIONS", 10_000))
    try:
        with stage("ingress_sessions"):
            from tigerbeetle_tpu.benchmark import run_ingress_sessions

            return run_ingress_sessions(
                n_sessions=n,
                conns=int(os.environ.get("BENCH_INGRESS_CONNS", 16)),
                log=log,
            )
    except Exception as e:  # never sink the kernel benchmark
        print(f"[ingress] FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"}


def bench_failover(stage) -> dict:
    """The failover segment (live chaos harness, testing/chaos.py): a
    real 3-replica cluster under a multiplexed fleet, the primary
    SIGKILLed mid-run — reports failover_recovery_ms (kill to first
    client reply) and the post-failover throughput ratio, with zero
    lost/duplicated transfers verified (conservation + CDC). Host-only
    like the other live segments: the servers own the accelerator.

    RETRY-ONCE: the segment drives real processes under real signals, so
    a single scheduler flake (r06's 1-core chaos timeout) used to null
    the artifact's failover fields for the whole round — one retry with
    a fresh cluster keeps one flake from erasing the measurement. Both
    attempts failing is reported as the error it is."""
    log = lambda *a: print("[failover]", *a, file=sys.stderr)  # noqa: E731
    last: dict = {}
    for attempt in (1, 2):
        try:
            with stage("failover" if attempt == 1 else "failover_retry"):
                from tigerbeetle_tpu.testing.chaos import run_failover

                out = run_failover(
                    n_sessions=int(
                        os.environ.get("BENCH_FAILOVER_SESSIONS", 128)
                    ),
                    conns=8,
                    events_per_batch=int(
                        os.environ.get("BENCH_FAILOVER_EVENTS", 64)
                    ),
                    batches_per_session=int(
                        os.environ.get("BENCH_FAILOVER_BATCHES", 10)
                    ),
                    backend=os.environ.get(
                        "BENCH_FAILOVER_BACKEND", "native"
                    ),
                    jax_platform=None,  # servers inherit the rig platform
                    # measurement mode: a CDC stream-audit failure is
                    # REPORTED (cdc_ok/verification_error) instead of
                    # nulling the recovery numbers — wire conservation
                    # (zero lost/dup ledger effects) is still asserted
                    strict_stream=False,
                    log=log,
                )
            out["failover_attempts"] = attempt
            if out.get("failover_recovery_ms") is not None:
                return out
            last = out  # completed but measured nothing: retry once
            print("[failover] recovery_ms null — retrying once",
                  file=sys.stderr)
        except Exception as e:  # never sink the kernel benchmark
            print(
                f"[failover] attempt {attempt} FAILED: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            last = {"error": f"{type(e).__name__}: {e}",
                    "failover_attempts": attempt}
    return last


def bench_frontier(stage) -> dict:
    """The load/latency frontier segment (benchmark.run_frontier): an
    offered-load ladder against one live gateway-fronted durable server
    (default `--backend dual`) — per step, offered vs achieved tps,
    client p50/p95/p99, the typed-shed rate, and the dominant critical-
    path leg from the server's per-request latency anatomy. The
    ROADMAP-item-4 artifact: it names the leg to attack first and the
    load where the knee is. Host-only (numpy + sockets) like the other
    live segments."""
    log = lambda *a: print("[frontier]", *a, file=sys.stderr)  # noqa: E731
    steps = tuple(
        int(x) for x in os.environ.get(
            "BENCH_FRONTIER_STEPS", "25000,50000,100000,200000,400000"
        ).split(",") if x
    )
    try:
        with stage("frontier"):
            from tigerbeetle_tpu.benchmark import run_frontier

            return run_frontier(
                steps=steps,
                step_s=float(os.environ.get("BENCH_FRONTIER_STEP_S", 6.0)),
                batch=int(os.environ.get("BENCH_FRONTIER_BATCH", 2048)),
                sessions=int(
                    os.environ.get("BENCH_FRONTIER_SESSIONS", 32)
                ),
                backend=os.environ.get("BENCH_FRONTIER_BACKEND", "dual"),
                jax_platform=None,  # the server inherits the platform
                log=log,
            )
    except Exception as e:  # never sink the kernel benchmark
        print(f"[frontier] FAILED: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"}


def bench_cross_ledger(stage) -> dict:
    """The cross_ledger_tps segment (federation/live.py): two real
    regions — each a live replica cluster with commitment chains and an
    AOF-backed CDC tail — with the settlement agent posting mirror/
    resolve legs between them through the client runtime. Measurement
    mode runs WITHOUT the region kill (that path is the chaos harness
    and its tier-1 test); the number is settled origin pendings per
    wall second of the drive (each one costs a pending + a remote
    mirror + a resolve, all consensus ops), with the settlement lag
    bound (ops) and the counterparty commitment-stream audit attached.
    Host-only (numpy + sockets) like the other live segments."""
    log = lambda *a: print("[cross_ledger]", *a, file=sys.stderr)  # noqa: E731
    try:
        with stage("cross_ledger"):
            from tigerbeetle_tpu.federation.live import run_federation_chaos

            out = run_federation_chaos(
                payments=int(os.environ.get("BENCH_CROSS_PAYMENTS", 96)),
                batch=8,
                kill_cluster=False,
                backend=os.environ.get("BENCH_CROSS_BACKEND", "native"),
                jax_platform=None,  # servers inherit the rig platform
                log=log,
            )
        out["cross_ledger_tps"] = round(
            out["issued"] / out["drive_wall_s"], 1
        )
        out["commitment_verify_ok"] = all(
            v["checked"] > 0 for v in out["stream_verify"].values()
        )
        return out
    except Exception as e:  # never sink the kernel benchmark
        print(f"[cross_ledger] FAILED: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {"error": f"{type(e).__name__}: {e}"}


def _parse_trace_arg(argv) -> str | None:
    """`--trace <path>` / `--trace=<path>`: dump a merged Chrome
    trace-event JSON (driver spans + the first e2e server's spans) there."""
    it = iter(argv)
    trace = None
    for a in it:
        if a == "--trace":
            trace = next(it, None)
        elif a.startswith("--trace="):
            trace = a.split("=", 1)[1]
    return trace


def main() -> None:
    global TRACER
    trace_path = _parse_trace_arg(sys.argv[1:])
    if trace_path:
        TRACER = JsonTracer(metrics=METRICS)
    stages: dict[str, float] = {}

    def stage(name):
        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                self.tok = TRACER.start(f"bench.{name}")

            def __exit__(self, *a):
                TRACER.stop(self.tok)
                stages[name] = time.perf_counter() - self.t0

        return _T()

    # E2E first: host-only in this process (subprocess server owns the TPU)
    e2e = bench_e2e(stage, trace=bool(trace_path))
    ingress = bench_ingress(stage)
    failover = bench_failover(stage)
    frontier = bench_frontier(stage)
    cross_ledger = bench_cross_ledger(stage)

    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
    from tigerbeetle_tpu.models.ledger import DeviceLedger, ids_to_batch
    from tigerbeetle_tpu.types import Operation

    # Transfers at load factor <= 1/2: flagship (10M) + ingest (1M) need 2^25
    # transfer slots (4 GiB of HBM rows); 10k accounts sit in 2^16.
    slots_log2 = 25
    while (N_TRANSFERS + N_INGEST) > (1 << slots_log2) // 2:
        slots_log2 += 1
    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=slots_log2)
    ledger = DeviceLedger(process=process, mode="auto")
    ledger.pad_to = BATCH_PAD

    rng = np.random.default_rng(42)
    ts = 1 << 40

    fold_max = jax.jit(lambda acc, r: jnp.maximum(acc, jnp.max(r)))
    code_max = jnp.uint32(0)

    # --- phase 0: load accounts (async; verified after the timed runs) ---
    with stage("accounts"):
        next_id = 1
        while next_id <= N_ACCOUNTS:
            n = min(BATCH, N_ACCOUNTS - next_id + 1)
            ts += n
            pending = ledger.execute_async(
                Operation.create_accounts, ts, build_accounts(next_id, n)
            )
            code_max = fold_max(code_max, pending.results)
            next_id += n
        jax.block_until_ready(code_max)

    # =========== FLAGSHIP: device-generated protocol workload ===========
    n_flag_batches = N_TRANSFERS // BATCH  # whole batches only
    n_flag = n_flag_batches * BATCH
    stepper = make_device_stepper(ledger.kernels, BATCH_PAD, K_FUSE)
    stepper1 = make_device_stepper(ledger.kernels, BATCH_PAD, 1)
    key = jax.random.PRNGKey(42)
    next_id = 1_000_000_000  # flagship id namespace (disjoint from ingest)
    state = ledger.state

    # warmup/compile both steppers
    with stage("compile"):
        for s, k in ((stepper, K_FUSE), (stepper1, 1)):
            ts += k * BATCH
            state, code_max = s(
                state, code_max, jax.random.fold_in(key, 0),
                jnp.uint64(next_id), jnp.uint64(ts),
            )
            next_id += k * BATCH
            jax.block_until_ready(code_max)
        done = K_FUSE + 1

    # latency: synced single-batch dispatches (shrunk if the transfer budget
    # is smaller than the compile+latency overheads)
    n_latency = min(N_LATENCY, max(0, n_flag_batches - done))
    lat_ms = []
    with stage("latency"):
        for i in range(n_latency):
            ts += BATCH
            t0 = time.perf_counter()
            state, code_max = stepper1(
                state, code_max, jax.random.fold_in(key, done + i),
                jnp.uint64(next_id), jnp.uint64(ts),
            )
            jax.block_until_ready(code_max)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            next_id += BATCH
        done += n_latency

    # Dispatch-health probe: the flagship's inter-segment spread tracks
    # the REMOTE dispatch path's launch latency (the axon tunnel), not the
    # kernels — measure it directly so the spread has its artifact.
    _probe_z = jnp.zeros(1, dtype=jnp.uint32)  # outside the timed loop
    jax.block_until_ready(fold_max(code_max, _probe_z))  # absorb the compile

    def probe_dispatch(n=40):
        t0 = time.perf_counter()
        x = code_max
        for _ in range(n):
            x = fold_max(x, _probe_z)
        jax.block_until_ready(x)
        return (time.perf_counter() - t0) / n * 1e6  # us/launch

    dispatch_us_before = round(probe_dispatch(), 1)

    # throughput: K-fused dispatches in equal segments, each blocked at
    # its end. The headline is the median over segments SELECTED by a
    # printed dispatch-health rule: the inter-segment spread tracks the
    # REMOTE launch path's latency, not the kernels (round-5 verdict: a
    # 0.49 spread whose outlier segment coincided with a degraded probe).
    # Round-8 tightening (r05 still printed 0.49 vs the <= 0.15 target):
    # (1) WARMUP DISCIPLINE — a few untimed steady-state groups run
    # before segment 0 (the compile/latency phases exercised stepper1,
    # so the first timed segment used to pay sustained-run establishment
    # inside its clock); (2) each segment is probed BEFORE AND AFTER
    # (a mid-segment transport degradation lands in the post-probe that
    # the pre-probe missed); (3) the health factor drops 2.0 -> 1.5.
    # Every decision input (both probe arrays, the floor, the factor)
    # rides out in the bench JSON so the artifact shows whether the rule
    # held, not just its verdict.
    SEG_PLAN, SEG_SPARE, SEG_PROBE_FACTOR = 5, 2, 1.5
    n_groups = max(0, (n_flag_batches - done) // K_FUSE)
    n_total = SEG_PLAN + SEG_SPARE
    # small-budget runs (BENCH_TRANSFERS shrunk) still get the SEG_PLAN
    # multi-segment median — only the spares are dropped; a single
    # segment would hide exactly the variance segmentation measures
    if n_groups >= 4 * n_total:
        n_segs = n_total
    elif n_groups >= SEG_PLAN:
        n_segs = SEG_PLAN
    else:
        n_segs = 1 if n_groups else 0
    warm_groups = 2 if n_groups >= 4 * n_total else 0
    n_seg_groups = n_groups - warm_groups
    seg_size = n_seg_groups // n_segs if n_segs else 0
    seg_runs_all: list[float] = []
    seg_probes: list[float] = []
    seg_probes_after: list[float] = []
    g = 0
    t_all = time.perf_counter()
    for _ in range(warm_groups):
        # untimed steady-state establishment (counts toward conservation)
        ts += K_FUSE * BATCH
        state, code_max = stepper(
            state, code_max, jax.random.fold_in(key, 10_000 + g),
            jnp.uint64(next_id), jnp.uint64(ts),
        )
        next_id += K_FUSE * BATCH
        g += 1
    if warm_groups:
        jax.block_until_ready(code_max)
    for seg in range(n_segs):
        seg_probes.append(round(probe_dispatch(20), 1))
        take = (
            seg_size if seg < n_segs - 1
            else n_seg_groups - seg_size * (n_segs - 1)
        )
        t0 = time.perf_counter()
        for _ in range(take):
            ts += K_FUSE * BATCH
            state, code_max = stepper(
                state, code_max, jax.random.fold_in(key, 10_000 + g),
                jnp.uint64(next_id), jnp.uint64(ts),
            )
            next_id += K_FUSE * BATCH
            g += 1
        jax.block_until_ready(code_max)
        dt = time.perf_counter() - t0
        seg_probes_after.append(round(probe_dispatch(20), 1))
        if take:
            seg_runs_all.append(take * K_FUSE * BATCH / dt)
    stages["flagship"] = time.perf_counter() - t_all
    dispatch_us_after = round(probe_dispatch(), 1)
    n_timed = n_groups * K_FUSE * BATCH
    # -- segment selection (the printed rule) --
    seg_rule = (
        f"keep segments whose pre- AND post-segment dispatch probes <= "
        f"{SEG_PROBE_FACTOR}x min(all probes); first {SEG_PLAN} healthy "
        f"count ({warm_groups} untimed warm groups precede segment 0)"
    )
    if seg_runs_all:
        floor = min(min(seg_probes), min(seg_probes_after))
        healthy = [
            i for i in range(len(seg_runs_all))
            if seg_probes[i] <= SEG_PROBE_FACTOR * floor
            and seg_probes_after[i] <= SEG_PROBE_FACTOR * floor
        ]
        if not healthy:
            # a uniformly degraded run still needs a headline: fall back
            # to the least-degraded segment rather than reporting nothing
            # (the JSON carries the probes, so the fallback is visible)
            healthy = [
                int(np.argmin(np.maximum(seg_probes, seg_probes_after)))
            ]
        selected = healthy[:SEG_PLAN]
    else:
        floor = None
        selected = []
    seg_runs = [seg_runs_all[i] for i in selected]
    print(
        f"flagship segment rule: {seg_rule}; probes_us={seg_probes} "
        f"probes_after_us={seg_probes_after} floor={floor} "
        f"selected={selected} "
        f"discarded={[i for i in range(len(seg_runs_all)) if i not in selected]}",
        file=sys.stderr,
    )
    flagship_tps = float(np.median(seg_runs)) if seg_runs else 0.0
    flagship_spread = (
        round((max(seg_runs) - min(seg_runs)) / flagship_tps, 4)
        if seg_runs and flagship_tps
        else None
    )
    ledger.state = state
    ledger._xfer_used += done * BATCH + n_timed

    # =========== SECONDARY: host-upload (ingest-limited) path ===========
    with stage("ingest_build"):
        batches = []
        next_id = 1
        remaining = N_INGEST
        while remaining > 0:
            n = min(BATCH, remaining)
            batches.append(build_transfers(rng, next_id, n))
            next_id += n
            remaining -= n

    # warmup: the host-path commit kernel compiles on first dispatch
    with stage("ingest_warmup"):
        n_warm = min(2, len(batches))
        for b in batches[:n_warm]:
            ts += len(b)
            pending = ledger.execute_async(Operation.create_transfers, ts, b)
            code_max = fold_max(code_max, pending.results)
        jax.block_until_ready(code_max)

    t0 = time.perf_counter()
    n_ingest = 0
    for b in batches[n_warm:]:
        ts += len(b)
        pending = ledger.execute_async(Operation.create_transfers, ts, b)
        n_ingest += len(b)
        code_max = fold_max(code_max, pending.results)
    jax.block_until_ready(code_max)
    ingest_dt = time.perf_counter() - t0
    stages["ingest"] = ingest_dt
    ingest_tps = n_ingest / ingest_dt if n_ingest else 0.0
    n_ingest += sum(len(b) for b in batches[:n_warm])  # total for conservation

    # =========== tracked configs (BASELINE.json's five workloads) =======
    # BEFORE verification: the first d2h permanently degrades this
    # runtime's dispatch path (see module docstring), and the configs do no
    # device->host reads themselves.
    configs = bench_tracked_configs(stage)

    # --- verification: the process's FIRST d2h transfers happen here ---
    with stage("verify"):
        # Conservation, reduced on device: every committed transfer moves
        # amount=1, so sum(debits_posted) == sum(credits_posted) == total.
        from tigerbeetle_tpu.models.ledger import unpack_account
        from tigerbeetle_tpu.ops import hashtable as ht

        ids = ids_to_batch(list(range(1, N_ACCOUNTS + 1)), 1 << 14)

        def conservation(state, ids):
            slot, found, res = ht.lookup(
                ids["key4"], state["acct_rows"], process.account_slots_log2
            )
            rows = state["acct_rows"][slot]
            a = unpack_account(rows)
            real = jnp.arange(rows.shape[0]) < N_ACCOUNTS
            w = found & real
            dpo = jnp.sum(jnp.where(w, a["dpo_lo"], jnp.uint64(0)))
            cpo = jnp.sum(jnp.where(w, a["cpo_lo"], jnp.uint64(0)))
            # resolve gated on REQUESTED lanes only (padding probes key 0)
            return dpo, cpo, jnp.sum(w.astype(jnp.int32)), jnp.all(res | ~real)

        dpo, cpo, nfound, resolved = jax.jit(conservation)(ledger.state, ids)
        assert bool(np.asarray(resolved)), "verify lookup probe-window overflow"
        # All committed transfers (compile + latency + timed + ingest), amount=1.
        total = (done + n_groups * K_FUSE) * BATCH + n_ingest
        tmax = int(np.asarray(code_max))
        assert tmax == 0, f"nonzero result code: max {tmax}"
        assert int(np.asarray(nfound)) == N_ACCOUNTS
        assert int(np.asarray(dpo)) == int(np.asarray(cpo)) == total, (
            int(np.asarray(dpo)), int(np.asarray(cpo)), total,
        )
        ledger.check_fault()

    # batch-latency histogram: the registry's snapshot is the quoted
    # artifact (same store the server/spill stats live in)
    h_lat = METRICS.histogram("bench.batch_latency_us")
    for ms in lat_ms:
        h_lat.observe(ms * 1000.0)
    lat_hist = h_lat.snapshot()
    print(f"batch latency histogram (us): {lat_hist}", file=sys.stderr)

    lat = np.percentile(lat_ms if lat_ms else [float("nan")], [0, 25, 50, 75, 100])
    print(
        "stage times (s): "
        + ", ".join(f"{k}={v:.2f}" for k, v in stages.items()),
        file=sys.stderr,
    )
    print(
        f"batch latency ms: p00={lat[0]:.2f} p25={lat[1]:.2f} "
        f"p50={lat[2]:.2f} p75={lat[3]:.2f} p100={lat[4]:.2f}",
        file=sys.stderr,
    )
    # The COMPACT headline (the driver's tail capture parses the LAST stdout
    # line; round 4's nested sub-objects grew it past the capture window and
    # the artifact recorded "parsed": null). Full detail — per-run durable
    # metrics, server stats, tracked configs — goes to BENCH_DETAIL.json
    # next to this script plus stderr.
    server_trace_events = e2e.pop("trace_events", None)
    detail = {"durable": e2e, "ingress": ingress, "failover": failover,
              "frontier": frontier, "cross_ledger": cross_ledger,
              "configs": configs,
              "stages_s": {
                  k: round(v, 2) for k, v in stages.items()
              }}
    detail_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAIL.json")
    with open(detail_path, "w") as f:
        json.dump(detail, f, indent=1)
    print("detail: " + json.dumps(detail), file=sys.stderr)
    if trace_path:
        # ONE Perfetto-loadable file, stitched (tracer.stitch): driver
        # spans (pid 0) + the traced e2e server's commit-pipeline spans
        # (pid 1 — fuse holds, journal writes, commit dispatch/finalize,
        # CDC emits, shadow uploads), with the per-op trace tags turned
        # into cross-pid FLOW events — clicking an op follows it from
        # the bus ingress through reply and device apply.
        from tigerbeetle_tpu.tracer import dump_stitched

        n_events = dump_stitched(
            trace_path,
            [TRACER.events_ordered(), server_trace_events or []],
            labels=["bench driver", "e2e server"],
        )
        print(f"trace: {n_events} events (stitched) -> {trace_path}",
              file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "create_transfers transfers/s, batch=8190, 10k "
                "accounts (TPU commit kernel, device-generated protocol "
                "workload, conservation+codes verified; median of "
                f"{len(seg_runs)} probe-selected segments of "
                f"{len(seg_runs_all)} run; detail in BENCH_DETAIL.json)",
                "value": round(flagship_tps, 1),
                "unit": "transfers/s",
                "vs_baseline": round(flagship_tps / BASELINE_TPS, 4),
                "flagship_runs": [round(x, 1) for x in seg_runs],
                "flagship_spread": flagship_spread,
                # the selection rule is part of the artifact: the headline
                # is reproducible only with the rule that produced it —
                # and EVERY decision input rides along (both probe
                # arrays, the floor, the factor), so the next driver
                # artifact shows whether the rule held
                "flagship_rule": seg_rule,
                "flagship_runs_all": [round(x, 1) for x in seg_runs_all],
                "flagship_probe_us": seg_probes,
                "flagship_probe_after_us": seg_probes_after,
                "flagship_probe_floor_us": floor,
                "flagship_probe_factor": SEG_PROBE_FACTOR,
                "flagship_selected": selected,
                "dispatch_us_per_launch": [
                    dispatch_us_before, dispatch_us_after
                ],
                "latency_ms_p00_p25_p50_p75_p100": [round(x, 2) for x in lat],
                # registry-sourced histogram snapshot (metrics.py buckets)
                "latency_hist_us": lat_hist,
                "ingest_tps": round(ingest_tps, 1),
                "durable_tps": e2e.get("durable_tps", 0.0),
                "durable_spread": e2e.get("durable_spread"),
                "durable_two_phase_tps": e2e.get("durable_two_phase_tps", 0.0),
                "durable_shadow_verified_all": e2e.get("shadow_verified_all"),
                # dual-commit durable mode (`--backend dual`): the device
                # follows the committed stream asynchronously, so the
                # through-stack device number rides the native reply path
                # — parity (fingerprints + digests + per-op hash-log
                # ring) verified per run, after the clock stops
                "durable_device_tps": e2e.get("durable_device_tps", 0.0),
                "durable_device_spread": e2e.get("durable_device_spread"),
                "device_shadow_verified_all": e2e.get(
                    "device_shadow_verified_all"
                ),
                "device_hash_log_ok": e2e.get("device_hash_log_ok"),
                "device_lag_ops": e2e.get("device_lag_ops"),
                "device_apply_overlap": e2e.get("device_apply_overlap"),
                # CDC A/B: live change stream into a deliberately slow
                # sink — throughput must hold vs durable_tps while the
                # pump (not the replica) absorbs the backpressure
                "durable_cdc_tps": e2e.get("durable_cdc_tps", 0.0),
                "cdc_lag_ops": e2e.get("cdc_lag_ops"),
                "cdc_backpressure_pauses": e2e.get("cdc_backpressure_pauses"),
                "group_commit_hit_rate": e2e.get("group_commit_hit_rate", 0.0),
                "group_fuse_width": e2e.get("group_fuse_width"),
                # per-segment fuse diagnostics (hit rate, holds/expired,
                # the window autotune ended at) — the 0.4562-vs-0.85
                # regression's attribution artifact
                "group_hit_rate_by_segment": e2e.get(
                    "group_hit_rate_by_segment"
                ),
                "fuse_window_us": e2e.get("fuse_window_us"),
                "shadow_upload_overlap": e2e.get("shadow_upload_overlap"),
                "loop_us_per_batch": e2e.get("loop_us_per_batch"),
                # conflict-wave scheduler segments (dependent transfers):
                # mixed = chains+posts+fast majority (wave + serial
                # residue), hot = zipfian hot accounts + same-batch
                # pend->post pairs (pure waves); ratios are vs
                # two_phase_tps, the fast-path segment under the same
                # synced per-batch protocol (ROADMAP item 2: >= 0.5x)
                "mixed_split_tps": configs.get("mixed_split_tps", 0.0),
                "mixed_split_spread": configs.get("mixed_split_tps_spread"),
                "mixed_hot_tps": configs.get("mixed_hot_tps", 0.0),
                "mixed_hot_spread": configs.get("mixed_hot_tps_spread"),
                "mixed_vs_fast_ratio": configs.get("mixed_vs_fast_ratio"),
                "mixed_hot_vs_fast_ratio": configs.get(
                    "mixed_hot_vs_fast_ratio"
                ),
                "two_phase_tps": configs.get("two_phase_tps", 0.0),
                "spill_active_tps": configs.get("spill_active_tps", 0.0),
                # overlap accounting: reload gather time hidden behind
                # commits (1.0 = admit never waited on the IO worker) and
                # mean ids per batched LSM multi-point-read
                "spill_overlap": configs.get("spill_overlap"),
                "spill_lookup_batch": configs.get("spill_lookup_batch"),
                # [fresh, post-first-d2h] us/launch: the transport cliff
                # that caps every reply-serving device path on this rig
                "spill_dispatch_cliff_us": [
                    configs.get("spill_transport_probe", {}).get(
                        "dispatch_us_fresh"
                    ),
                    configs.get("spill_transport_probe", {}).get(
                        "dispatch_us_post_first_drain"
                    ),
                ],
                # ingress gateway: 10k live multiplexed sessions — p99
                # vs the 10-session baseline (target <= 2x), and the
                # saturation phase's shed/throughput contract (sheds in
                # ingress.shed, event tps holds vs unshedded)
                "ingress_sessions": ingress.get("sessions", 0),
                "ingress_p99_ms": [
                    ingress.get("p99_baseline_ms"),
                    ingress.get("p99_live_ms"),
                ],
                "ingress_p99_ratio": ingress.get("p99_ratio"),
                "ingress_tps_saturated_ratio": ingress.get(
                    "tps_saturated_ratio"
                ),
                "ingress_shed": ingress.get("ingress_shed"),
                "ingress_busy_replies": ingress.get("busy_replies"),
                # failover: the primary SIGKILLed under live multiplexed
                # load — kill-to-first-reply ms and the throughput ratio
                # after recovery, with zero lost/duplicated transfers
                # proven (conservation + CDC); full report in detail
                "failover_recovery_ms": failover.get(
                    "failover_recovery_ms"
                ),
                "failover_tps_ratio": failover.get(
                    "post_failover_tps_ratio"
                ),
                "failover_lost_events": failover.get("lost_events"),
                # load/latency frontier (run_frontier): per-step offered/
                # achieved/p50/p99/shed/dominant-leg ladder — the compact
                # headline keeps the knee + peak; full steps in detail
                "frontier_peak_tps": frontier.get("peak_achieved_tps"),
                "frontier_knee_tps": frontier.get(
                    "saturation_offered_tps"
                ),
                "frontier_steps": [
                    [s.get("offered_tps"), s.get("achieved_tps"),
                     s.get("p50_ms"), s.get("p99_ms"), s.get("shed_rate"),
                     s.get("dominant_leg"),
                     s.get("dominant_device_subleg")]
                    for s in frontier.get("steps", [])
                ],
                "frontier_accounted_ratio": (
                    frontier.get("breakdown") or {}
                ).get("accounted_ratio"),
                # cross-ledger federation: settled origin pendings per
                # wall second across two live regions (pending + remote
                # mirror + resolve per payment), the settlement lag
                # bound in ops, and the external counterparty audit of
                # each region's commitment stream; full report in detail
                "cross_ledger_tps": cross_ledger.get("cross_ledger_tps"),
                "settlement_lag_ops": cross_ledger.get(
                    "settlement_lag_max_ops"
                ),
                "commitment_verify_ok": cross_ledger.get(
                    "commitment_verify_ok"
                ),
                # device anatomy: commit_wait decomposed on the applier
                # thread — the slowest sampled apply item's sub-legs must
                # account for its span exactly (ratio 1.0 at device
                # granularity), and the knee names the sub-leg to attack
                "frontier_device_accounted_ratio": (
                    frontier.get("device_breakdown") or {}
                ).get("accounted_ratio"),
                "frontier_device_dominant": (
                    frontier.get("device_breakdown") or {}
                ).get("dominant"),
                # compile-sentinel + .jax_cache provenance: recompiles
                # observed in THIS driver process and the cache growth it
                # caused — post-warmup compiles are the pathology signal
                "compile_sentinel": _sentinel_summary(),
                "jax_cache_bytes_start": _JAX_CACHE_BYTES_START,
                "jax_cache_bytes_end": _jax_cache_bytes(),
            }
        )
    )


if __name__ == "__main__":
    main()
