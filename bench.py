"""Benchmark driver: the reference's scripts/benchmark.sh protocol on TPU.

Reference protocol (reference: src/benchmark.zig:23-73, scripts/benchmark.sh):
10_000 accounts, transfers submitted in batches of 8190, measure transfers/s.
Here the state machine is the device ledger (tigerbeetle_tpu/models/ledger.py)
executing whole batches as single jitted commit steps; the host driver plays
the role of the benchmark client (id_order=reversed like the reference default,
two uniform-random distinct accounts per transfer).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "transfers/s", "vs_baseline": N}
vs_baseline is value / 1e6 — the reference's "~1M financial transactions/s"
headline on its own benchmark (reference: README.md:134-135, docs/HISTORY.md:31
800k/s AlphaBeetle; BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np


BASELINE_TPS = 1_000_000.0  # reference headline (BASELINE.md)
N_ACCOUNTS = 10_000
BATCH = 8190  # (1 MiB - 128 B) / 128 B, reference: src/constants.zig:167-168
N_BATCHES_WARMUP = 3
N_BATCHES = 40  # 40 * 8190 = 327_600 transfers measured


def build_account_batch(start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import ACCOUNT_DTYPE

    arr = np.zeros(count, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + count, dtype=np.uint64)
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def build_transfer_batch(rng, start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import TRANSFER_DTYPE

    arr = np.zeros(count, dtype=TRANSFER_DTYPE)
    # id_order=reversed (reference: src/benchmark.zig:66-73 default).
    arr["id_lo"] = np.arange(start_id + count - 1, start_id - 1, -1, dtype=np.uint64)
    dr = rng.integers(1, N_ACCOUNTS + 1, size=count, dtype=np.uint64)
    off = rng.integers(1, N_ACCOUNTS, size=count, dtype=np.uint64)
    cr = (dr - 1 + off) % N_ACCOUNTS + 1  # distinct from dr
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = cr
    arr["amount_lo"] = 1
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def main() -> None:
    import jax

    from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
    from tigerbeetle_tpu.models.ledger import DeviceLedger

    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=24)
    ledger = DeviceLedger(process=process, mode="auto")
    ledger.pad_to = BATCH_PAD

    from tigerbeetle_tpu.types import Operation

    ts = 1 << 40
    rng = np.random.default_rng(42)

    # Load accounts (8190-per-batch like the reference client).
    next_id = 1
    while next_id <= N_ACCOUNTS:
        n = min(BATCH, N_ACCOUNTS - next_id + 1)
        batch = build_account_batch(next_id, n)
        ts += n
        res = ledger.execute(Operation.create_accounts, ts, batch)
        assert res == [], res[:5]
        next_id += n

    # Warmup (compile + cache).
    xfer_id = 1
    for _ in range(N_BATCHES_WARMUP):
        batch = build_transfer_batch(rng, xfer_id, BATCH)
        ts += BATCH
        res = ledger.execute(Operation.create_transfers, ts, batch)
        assert res == [], res[:5]
        xfer_id += BATCH

    # Timed run. execute() blocks on the dense result transfer each batch,
    # which is the same sync point the reference's client ack provides.
    t0 = time.perf_counter()
    for _ in range(N_BATCHES):
        batch = build_transfer_batch(rng, xfer_id, BATCH)
        ts += BATCH
        res = ledger.execute(Operation.create_transfers, ts, batch)
        assert res == [], res[:5]
        xfer_id += BATCH
    jax.block_until_ready(ledger.state["commit_ts"])
    dt = time.perf_counter() - t0

    tps = N_BATCHES * BATCH / dt
    print(
        json.dumps(
            {
                "metric": "create_transfers throughput, batch=8190, 10k accounts",
                "value": round(tps, 1),
                "unit": "transfers/s",
                "vs_baseline": round(tps / BASELINE_TPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
