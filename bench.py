"""Benchmark driver: the reference's scripts/benchmark.sh protocol on TPU.

Reference protocol (reference: src/benchmark.zig:23-73, scripts/benchmark.sh):
10_000 accounts, 10_000_000 transfers submitted in batches of 8190
(id_order=reversed, two uniform-random distinct accounts per transfer,
amount=1), measure transfers/s and batch-latency percentiles p00/p25/p50/
p75/p100 (reference: src/benchmark.zig main loop printout).

Driver structure (the reference keeps 8 prepares in flight,
src/vsr/replica.zig:5102-5186; this driver pipelines the same way):

- batches are prebuilt on host, then dispatched asynchronously through
  DeviceLedger.execute_async — no device->host transfer happens ANYWHERE
  until the timed run is over. On this tunneled-TPU runtime the FIRST d2h
  transfer permanently switches the process into a slow synchronous
  dispatch mode (~12 ms per kernel launch instead of ~30 us — measured,
  see ops/hashtable.py's module note), so replies are reduced on device
  per GROUP of batches and every readback (group maxes, account results,
  the fault word) happens after the clock stops;
- a separate synced phase measures true per-batch commit latency
  (dispatch -> results ready on device via block_until_ready, which does
  not transfer) for the percentile table.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": "transfers/s", "vs_baseline": N, ...}
vs_baseline is value / 10_000_000 — BASELINE.json's target (>= 10M
transfers/s on one v5e chip). The stage-time table goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_TPS = 10_000_000.0  # BASELINE.json north-star target
N_ACCOUNTS = 10_000
BATCH = 8190  # (1 MiB - 128 B) / 128 B, reference: src/constants.zig:167-168
N_TRANSFERS = int(os.environ.get("BENCH_TRANSFERS", 10_000_000))
N_LATENCY = 30  # synced batches for the latency percentiles


def build_accounts(start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import ACCOUNT_DTYPE

    arr = np.zeros(count, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(start_id, start_id + count, dtype=np.uint64)
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def build_transfers(rng, start_id: int, count: int, ledger: int = 1) -> np.ndarray:
    from tigerbeetle_tpu.types import TRANSFER_DTYPE

    arr = np.zeros(count, dtype=TRANSFER_DTYPE)
    # id_order=reversed (reference: src/benchmark.zig:66-73 default).
    arr["id_lo"] = np.arange(start_id + count - 1, start_id - 1, -1, dtype=np.uint64)
    dr = rng.integers(1, N_ACCOUNTS + 1, size=count, dtype=np.uint64)
    off = rng.integers(1, N_ACCOUNTS, size=count, dtype=np.uint64)
    arr["debit_account_id_lo"] = dr
    arr["credit_account_id_lo"] = (dr - 1 + off) % N_ACCOUNTS + 1  # distinct
    arr["amount_lo"] = 1
    arr["ledger"] = ledger
    arr["code"] = 1
    return arr


def main() -> None:
    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
    from tigerbeetle_tpu.models.ledger import DeviceLedger
    from tigerbeetle_tpu.types import Operation

    stages: dict[str, float] = {}

    def stage(name):
        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()

            def __exit__(self, *a):
                stages[name] = time.perf_counter() - self.t0

        return _T()

    # 10M transfers at load factor <= 1/2 needs 2^25 transfer slots (4 GiB
    # of HBM rows); 10k accounts sit comfortably in 2^16.
    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=25)
    ledger = DeviceLedger(process=process, mode="auto")
    ledger.pad_to = BATCH_PAD

    rng = np.random.default_rng(42)
    ts = 1 << 40

    # --- phase 0: prebuild every batch on host ---
    with stage("build"):
        batches = []
        next_id = 1
        remaining = N_TRANSFERS
        while remaining > 0:
            n = min(BATCH, remaining)
            batches.append(build_transfers(rng, next_id, n))
            next_id += n
            remaining -= n

    # Running on-device reply reduction: one fixed-shape op per batch, so
    # verification needs no per-batch readback and no variable-arity jit.
    fold_max = jax.jit(lambda acc, r: jnp.maximum(acc, jnp.max(r)))
    code_max = jnp.uint32(0)

    # --- phase 1: load accounts (async; verified after the timed run) ---
    with stage("accounts"):
        next_id = 1
        while next_id <= N_ACCOUNTS:
            n = min(BATCH, N_ACCOUNTS - next_id + 1)
            ts += n
            pending = ledger.execute_async(
                Operation.create_accounts, ts, build_accounts(next_id, n)
            )
            code_max = fold_max(code_max, pending.results)
            next_id += n
        jax.block_until_ready(code_max)
        acct_code_max = code_max
        code_max = jnp.uint32(0)

    # --- phase 2: warmup (compile) ---
    n_warm = min(2, len(batches))
    with stage("warmup"):
        for b in batches[:n_warm]:
            ts += len(b)
            pending = ledger.execute_async(Operation.create_transfers, ts, b)
            code_max = fold_max(code_max, pending.results)
        jax.block_until_ready(code_max)
        done = n_warm

    # --- phase 3: latency (synced per batch; block only, no transfer) ---
    lat_ms = []
    with stage("latency"):
        for b in batches[done : done + N_LATENCY]:
            ts += len(b)
            t0 = time.perf_counter()
            pending = ledger.execute_async(Operation.create_transfers, ts, b)
            jax.block_until_ready(pending.results)
            lat_ms.append((time.perf_counter() - t0) * 1e3)
            code_max = fold_max(code_max, pending.results)
        done += len(lat_ms)

    # --- phase 4: pipelined throughput over the remaining batches ---
    n_timed = 0
    t0 = time.perf_counter()
    for b in batches[done:]:
        ts += len(b)
        pending = ledger.execute_async(Operation.create_transfers, ts, b)
        n_timed += len(b)
        code_max = fold_max(code_max, pending.results)
    jax.block_until_ready(code_max)
    dt = time.perf_counter() - t0
    stages["throughput"] = dt

    # --- verification: the process's FIRST d2h transfers happen here ---
    with stage("verify"):
        amax = int(np.asarray(acct_code_max))
        assert amax == 0, f"account create failed: max code {amax}"
        tmax = int(np.asarray(code_max))
        assert tmax == 0, f"nonzero transfer result code: max {tmax}"
        ledger.check_fault()

    tps = n_timed / dt if n_timed else 0.0
    lat = np.percentile(lat_ms if lat_ms else [float("nan")], [0, 25, 50, 75, 100])
    print(
        "stage times (s): "
        + ", ".join(f"{k}={v:.2f}" for k, v in stages.items()),
        file=sys.stderr,
    )
    print(
        f"batch latency ms: p00={lat[0]:.2f} p25={lat[1]:.2f} "
        f"p50={lat[2]:.2f} p75={lat[3]:.2f} p100={lat[4]:.2f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "create_transfers throughput, batch=8190, 10k accounts, "
                f"{N_TRANSFERS} transfers",
                "value": round(tps, 1),
                "unit": "transfers/s",
                "vs_baseline": round(tps / BASELINE_TPS, 4),
                "latency_ms_p00_p25_p50_p75_p100": [round(x, 2) for x in lat],
                "pipelined_batches": n_timed // BATCH,
            }
        )
    )


if __name__ == "__main__":
    main()
