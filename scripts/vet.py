#!/usr/bin/env python
"""vet: the repo's static-analysis driver (reference: src/tidy.zig +
src/copyhound.zig run as build steps, not review comments).

Usage:
  python scripts/vet.py                 # all passes, exit 1 on any hit
  python scripts/vet.py --pass tidy,races
  python scripts/vet.py --update        # rewrite baselines (whys kept;
                                        # NEW sites need a human why
                                        # before the run goes green)
  python scripts/vet.py --update --pass copyhound
  python scripts/vet.py --explain races
  python scripts/vet.py --explain copyhound/coerce
  python scripts/vet.py --json          # machine-readable violations

Passes: tidy (source form + named noqa), copyhound (host<->device sync
inducers), races (thread-ownership lint), determinism (sim-reachable
code stays seed-deterministic). Baselines are CLOSED: new sites fail,
vanished baselined sites fail, and every entry needs a `why`.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tigerbeetle_tpu import devtools  # noqa: E402


def explain(topic: str) -> int:
    passes = devtools.make_passes()
    if "/" in topic:
        pass_name, check = topic.split("/", 1)
    else:
        pass_name, check = topic, None
    for p in passes:
        if p.name != pass_name:
            continue
        if check is None:
            print((p.doc or "").strip())
            print("\nchecks:")
            for cid, text in sorted(p.checks.items()):
                print(f"  {p.name}/{cid}: {text}")
            return 0
        if check in p.checks:
            print(f"{p.name}/{check}: {p.checks[check]}")
            return 0
        print(f"no check {check!r} in pass {pass_name!r} "
              f"(have {sorted(p.checks)})")
        return 1
    print(f"no pass {topic!r} (have {[p.name for p in passes]})")
    return 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pass", dest="passes", default=None,
                    help="comma-separated subset of passes to run")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the selected passes' baselines")
    ap.add_argument("--explain", metavar="PASS[/CHECK]",
                    help="print a pass's (or one check's) documentation")
    ap.add_argument("--json", action="store_true",
                    help="emit violations as JSON")
    args = ap.parse_args()
    if args.explain:
        return explain(args.explain)
    names = args.passes.split(",") if args.passes else None
    violations, notes = devtools.run_vet(
        ROOT, pass_names=names, update=args.update
    )
    # --json keeps stdout pure JSON (json.loads(stdout) must work);
    # human-facing notes and the summary go to stderr there
    human = sys.stdout if not args.json else sys.stderr
    for note in notes:
        print(f"vet: {note}", file=human)
    if args.json:
        print(json.dumps(
            [v.__dict__ for v in violations], indent=1, sort_keys=True
        ))
    else:
        for v in violations:
            print(v.render())
    if violations:
        by_pass: dict[str, int] = {}
        for v in violations:
            by_pass[v.pass_name] = by_pass.get(v.pass_name, 0) + 1
        summary = ", ".join(f"{k}={n}" for k, n in sorted(by_pass.items()))
        print(f"vet: {len(violations)} problem(s) ({summary})", file=human)
        return 1
    ran = names or [p.name for p in devtools.make_passes()]
    print(f"vet: clean ({', '.join(ran)})", file=human)
    return 0


if __name__ == "__main__":
    sys.exit(main())
