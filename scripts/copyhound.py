#!/usr/bin/env python
"""Copyhound — now a vet pass (`python scripts/vet.py --pass copyhound`).

This shim keeps the historical entry point (and its --update flow)
alive. v2 scans the whole commit path (ops/ models/ parallel/ vsr/ lsm/
cdc/ ingress/ io/), adds the implicit sync inducers (.item(), device
coercions, numpy-on-jax, device arrays in f-strings), and the baseline
is CLOSED: stale entries fail, and every entry carries a human `why`.
The implementation lives in tigerbeetle_tpu/devtools/copyhound_pass.py.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tigerbeetle_tpu import devtools  # noqa: E402


def main() -> int:
    update = "--update" in sys.argv
    violations, notes = devtools.run_vet(
        ROOT, pass_names=["copyhound"], update=update
    )
    for note in notes:
        print(note)
    for v in violations:
        print(v.render())
    if violations:
        print(f"copyhound: {len(violations)} problem(s)")
        return 1
    print("copyhound: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
