#!/usr/bin/env python
"""Copyhound: find host<->device copy inducers in the device compute path.

The reference's copyhound scans LLVM IR for accidental large memcpys
(reference: src/copyhound.zig:1-9). The TPU analog of an accidental
memcpy is an accidental DEVICE SYNC or host round-trip in the compute
path: `np.asarray(...)` on a device array, `.block_until_ready()`,
`jax.device_get`, `float()/int()` coercions of device scalars, and
`.tobytes()` pulls. Each one stalls dispatch (see ops/hashtable.py on why
dispatch health is the flagship constraint).

This scans ops/, models/, parallel/ for those call sites and compares the
set against `scripts/copyhound_baseline.json`. NEW sites fail the check:
either justify the sync (it is on a cold path) and re-baseline with
--update, or remove it.
"""

from __future__ import annotations

import ast
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
BASELINE = ROOT / "scripts" / "copyhound_baseline.json"
SCAN_DIRS = ("tigerbeetle_tpu/ops", "tigerbeetle_tpu/models",
             "tigerbeetle_tpu/parallel")

SYNC_CALLS = {"asarray", "block_until_ready", "device_get", "tobytes",
              "from_dlpack"}


def scan() -> dict[str, list[str]]:
    sites: dict[str, list[str]] = {}
    for d in SCAN_DIRS:
        for path in sorted((ROOT / d).rglob("*.py")):
            rel = str(path.relative_to(ROOT))
            tree = ast.parse(path.read_text())
            found = []
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = None
                if isinstance(f, ast.Attribute) and f.attr in SYNC_CALLS:
                    name = f.attr
                elif isinstance(f, ast.Name) and f.id in SYNC_CALLS:
                    name = f.id
                if name:
                    # function context for a stable-ish key
                    found.append(f"{name}@{node.lineno}")
            if found:
                sites[rel] = found
    return sites


def main() -> int:
    update = "--update" in sys.argv
    sites = scan()
    counts = {
        rel: sorted({s.split("@")[0] for s in v}) and
        {kind: sum(1 for s in v if s.startswith(kind + "@"))
         for kind in sorted({s.split("@")[0] for s in v})}
        for rel, v in sites.items()
    }
    if update or not BASELINE.exists():
        BASELINE.write_text(json.dumps(counts, indent=1, sort_keys=True) + "\n")
        print(f"baseline written: {BASELINE.name}")
        return 0
    base = json.loads(BASELINE.read_text())
    grew = []
    for rel, kinds in counts.items():
        for kind, n in kinds.items():
            if n > base.get(rel, {}).get(kind, 0):
                grew.append(f"{rel}: {kind} sites {base.get(rel, {}).get(kind, 0)} -> {n}")
    if grew:
        print("copyhound: NEW host-device sync sites in the compute path "
              "(justify + rerun with --update, or remove):")
        for g in grew:
            print(" ", g)
        return 1
    print("copyhound: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
