"""Standalone load/latency frontier sweep driver.

Runs ONLY the frontier segment (benchmark.run_frontier) against a fresh
live server and writes the JSON segment — the quick loop for ROADMAP
item 4 work, without paying for the full bench.py run:

  python scripts/frontier.py out.json
  python scripts/frontier.py --steps 50000,100000,200000 \
      --backend dual --step-s 8 out.json

The segment shape matches bench.py's `frontier` detail section, so a
sweep captured here can be compared against (or spliced into) a driver
artifact directly.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("out", help="write the frontier JSON segment here")
    p.add_argument("--steps", default="25000,50000,100000,200000,400000",
                   help="offered-load ladder, events/s, comma-separated")
    p.add_argument("--step-s", type=float, default=6.0)
    p.add_argument("--batch", type=int, default=2048)
    p.add_argument("--sessions", type=int, default=32)
    p.add_argument("--backend", default="dual",
                   help="server backend (dual | native | native+device)")
    p.add_argument("--sample-every", type=int, default=1,
                   help="server-side latency sampling (1 = every request)")
    p.add_argument("--jax-platform", default="",
                   help="pin the server's JAX platform (e.g. cpu)")
    args = p.parse_args()

    from tigerbeetle_tpu.benchmark import run_frontier

    out = run_frontier(
        steps=tuple(int(x) for x in args.steps.split(",") if x),
        step_s=args.step_s,
        batch=args.batch,
        sessions=args.sessions,
        backend=args.backend,
        sample_every=args.sample_every,
        jax_platform=args.jax_platform or None,
        log=lambda *a: print("[frontier]", *a, file=sys.stderr),
    )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    steps = out.get("steps", [])
    for s in steps:
        print(
            f"offered {s['offered_tps']:>9}/s  achieved "
            f"{s['achieved_tps']:>10}/s  p50 {s['p50_ms']:>8}ms  p99 "
            f"{s['p99_ms']:>8}ms  shed {s['shed_rate']:>6}  "
            f"dominant {s['dominant_leg']}"
        )
    print(f"peak {out.get('peak_achieved_tps')}/s  knee "
          f"{out.get('saturation_offered_tps')}  accounted "
          f"{(out.get('breakdown') or {}).get('accounted_ratio')}")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
