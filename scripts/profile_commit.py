"""Per-stage profiling of the fast-tier commit kernel on the real TPU.

Explains the bench's bimodal batch latency (p25 ~1.7ms vs p50 ~7ms) by timing
(a) back-to-back commits, (b) isolated sub-kernels: account-table lookup,
transfer-table lookup, claim rounds, digit fold + scatters.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")
from bench import build_accounts, build_transfers  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess  # noqa: E402
from tigerbeetle_tpu.models.ledger import DeviceLedger, transfers_to_batch  # noqa: E402
from tigerbeetle_tpu.ops import hashtable as ht  # noqa: E402
from tigerbeetle_tpu.types import Operation  # noqa: E402

N_ACCOUNTS = 10_000
BATCH = 8190


def timeit(fn, n=20, warmup=3):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append((time.perf_counter() - t0) * 1e3)
    ts = np.array(ts)
    return ts


def main():
    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=25)
    ledger = DeviceLedger(process=process, mode="auto")
    ledger.pad_to = BATCH_PAD
    rng = np.random.default_rng(7)
    ts_base = 1 << 40

    next_id = 1
    t = ts_base
    while next_id <= N_ACCOUNTS:
        n = min(BATCH, N_ACCOUNTS - next_id + 1)
        t += n
        ledger.execute_async(Operation.create_accounts, t, build_accounts(next_id, n))
        next_id += n

    # sequential commits, individually timed
    state_holder = {"t": t, "next": 1}

    def commit_once():
        b = build_transfers(rng, state_holder["next"], BATCH)
        state_holder["next"] += BATCH
        state_holder["t"] += BATCH
        p = ledger.execute_async(Operation.create_transfers, state_holder["t"], b)
        return p.results

    lat = timeit(commit_once, n=40)
    print(f"commit e2e ms: min={lat.min():.2f} p25={np.percentile(lat,25):.2f} "
          f"p50={np.percentile(lat,50):.2f} p75={np.percentile(lat,75):.2f} "
          f"max={lat.max():.2f}")
    print("  first 20:", " ".join(f"{x:.1f}" for x in lat[:20]))

    # isolated sub-kernels over the live state
    state = ledger.state
    b = build_transfers(rng, 10_000_000, BATCH)
    rows_b = transfers_to_batch(b, BATCH_PAD)["rows"]
    a_log2, t_log2 = process.account_slots_log2, process.transfer_slots_log2

    both_k4 = jnp.concatenate([rows_b[:, 4:8], rows_b[:, 8:12]], axis=0)

    acct_lookup = jax.jit(lambda rows, k4: ht.lookup(k4, rows, a_log2)[0])
    xfer_lookup = jax.jit(lambda rows, k4: ht.lookup(k4, rows, t_log2)[0])
    lat = timeit(lambda: acct_lookup(state["acct_rows"], both_k4))
    print(f"acct lookup (16384 lanes, W=32): p50={np.percentile(lat,50):.2f}ms")
    lat = timeit(lambda: xfer_lookup(state["xfer_rows"], rows_b[:, :4]))
    print(f"xfer lookup (8192 lanes, W=32):  p50={np.percentile(lat,50):.2f}ms")

    ok = jnp.ones(BATCH_PAD, dtype=bool)
    claim_fn = jax.jit(
        lambda rows, claim, k4: ht.claim_slots(k4, ok, rows, claim, t_log2)[0]
    )
    lat = timeit(lambda: claim_fn(state["xfer_rows"], state["xfer_claim"], rows_b[:, :4]))
    print(f"claim_slots (8192 lanes, 4 rounds): p50={np.percentile(lat,50):.2f}ms")

    # gather+scatter of full rows on the transfer table (the insert write)
    slots = jnp.arange(BATCH_PAD, dtype=jnp.int32) * 97 % (1 << t_log2)
    scatter_fn = jax.jit(lambda rows, s, v: rows.at[s].set(v))
    lat = timeit(lambda: scatter_fn(state["xfer_rows"], slots, rows_b))
    print(f"xfer row scatter (8192x128B): p50={np.percentile(lat,50):.2f}ms")
    gather_fn = jax.jit(lambda rows, s: rows[s])
    lat = timeit(lambda: gather_fn(state["xfer_rows"], slots))
    print(f"xfer row gather  (8192x128B): p50={np.percentile(lat,50):.2f}ms")

    lat = timeit(lambda: scatter_fn(
        state["acct_rows"], slots & jnp.int32((1 << a_log2) - 1), rows_b))
    print(f"acct row scatter (8192x128B): p50={np.percentile(lat,50):.2f}ms")


if __name__ == "__main__":
    main()
