"""Profile the durable e2e path: run the real server under cProfile and
print the top costs of the event loop (where the 62k-TPS ceiling lives).

Usage: python scripts/profile_e2e.py [n_transfers]
"""

import os
import pstats
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tigerbeetle_tpu.benchmark import run_e2e  # noqa: E402


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 300_000
    prof_path = os.path.join(tempfile.gettempdir(), "tb_e2e_server.pstats")
    os.environ["TB_PROFILE"] = prof_path
    result = run_e2e(
        n_accounts=10_000,
        n_transfers=n,
        clients=int(os.environ.get("E2E_CLIENTS", "16")),
        log=lambda *a: print("[e2e]", *a, file=sys.stderr),
    )
    print(result)
    stats = pstats.Stats(prof_path)
    stats.sort_stats("cumulative")
    print("\n==== cumulative ====")
    stats.print_stats(35)
    stats.sort_stats("tottime")
    print("\n==== tottime ====")
    stats.print_stats(35)


if __name__ == "__main__":
    main()
