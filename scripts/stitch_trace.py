#!/usr/bin/env python
"""Stitch per-process trace dumps into ONE cluster-causal Perfetto file.

Each replica of a cluster dumps its own span ring (`start --trace`,
SIGTERM; or SIGQUIT's `<trace>.quit.json`) with local pid 0. This tool
merges N such dumps: input i becomes pid i (named after its file), and
every span tagged with an op's trace id (vsr/header.py trace_id — spans
carry it as args `trace`/`traces`) becomes a Perfetto FLOW, so clicking
one leg of an op in the merged file draws its whole causal tree across
processes: ingress -> fuse/quorum -> journal write -> commit -> reply ->
CDC emit -> device apply.

Usage:
    python scripts/stitch_trace.py --out cluster.json \
        r0.trace.json r1.trace.json r2.trace.json

The output is canonical JSON (sorted keys, fixed separators): stitching
byte-identical inputs — e.g. two same-seed simulator replays — yields
byte-identical output, so stitched traces can be diffed like any other
deterministic artifact.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tigerbeetle_tpu.tracer import stitch  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process trace dumps into one "
        "Perfetto-loadable file with cross-process flow events"
    )
    ap.add_argument("inputs", nargs="+",
                    help="trace dumps, one per process (pid = input order)")
    ap.add_argument("--out", required=True, help="merged output path")
    args = ap.parse_args()

    event_lists = []
    labels = []
    for path in args.inputs:
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        event_lists.append(events)
        labels.append(os.path.basename(path))
    merged = stitch(event_lists, labels=labels)
    with open(args.out, "w") as f:
        json.dump({"traceEvents": merged}, f, sort_keys=True,
                  separators=(",", ":"))
    flows = sum(1 for e in merged if e.get("ph") in ("s", "t", "f"))
    ids = len({e["id"] for e in merged if e.get("ph") in ("s", "t", "f")})
    print(
        f"stitched {len(args.inputs)} dump(s): {len(merged)} events, "
        f"{flows} flow legs across {ids} op trace id(s) -> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
