#!/usr/bin/env python
"""Stitch per-process trace dumps into ONE cluster-causal Perfetto file.

Each replica of a cluster dumps its own span ring (`start --trace`,
SIGTERM; or SIGQUIT's `<trace>.quit.json`) with local pid 0. This tool
merges N such dumps: input i becomes pid i (named after its file), and
every span tagged with an op's trace id (vsr/header.py trace_id — spans
carry it as args `trace`/`traces`) becomes a Perfetto FLOW, so clicking
one leg of an op in the merged file draws its whole causal tree across
processes: ingress -> fuse/quorum -> journal write -> commit -> reply ->
CDC emit -> device apply.

The XLA trace bridge: `--device-trace <dir>` additionally merges a
bounded device-trace window captured on the applier thread
(`start --device-trace <dir>`, or scripts/profile_applier.py). The
jax.profiler dump under `<dir>/plugins/profile/*/` carries device/host
timelines on its own pids with its own timebase; the sidecar
`device_trace_meta.json` written at capture start anchors that window to
the span dumps' clock (perf_counter microseconds), so XLA kernel slices
land at the right offset under the applier's `device.*` sub-leg spans.
Device pids are re-numbered AFTER the span-dump pids — the device
timeline appears as its own process group in the stitched file.

Usage:
    python scripts/stitch_trace.py --out cluster.json \
        r0.trace.json r1.trace.json r2.trace.json \
        [--device-trace /tmp/devtrace]

The output is canonical JSON (sorted keys, fixed separators): stitching
byte-identical inputs — e.g. two same-seed simulator replays — yields
byte-identical output, so stitched traces can be diffed like any other
deterministic artifact.
"""

import argparse
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tigerbeetle_tpu.tracer import stitch  # noqa: E402


def load_device_trace(trace_dir: str, pid_base: int) -> list[dict]:
    """Load a jax.profiler capture directory and return its trace events
    aligned to the span dumps' clock and re-pid'd starting at `pid_base`.

    Alignment: the profiler's Chrome-trace timestamps are microseconds on
    the profiler's own timebase whose zero is (approximately) the
    start_trace call; `device_trace_meta.json` records perf_counter_ns at
    that same moment, so shifting the window's earliest event onto the
    anchor puts device slices on the span dumps' microsecond axis. The
    residual error is the start_trace latency (sub-millisecond) — fine
    for eyeballing which XLA op fills a device_busy span, and flagged in
    the stitched metadata so nobody reads it as nanosecond-exact.
    """
    meta_path = os.path.join(trace_dir, "device_trace_meta.json")
    anchor_us = None
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            anchor_us = json.load(f).get("anchor_perf_ns", 0) / 1000.0
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json.gz"
    )))
    # uncompressed fallback (tests + older plugin versions)
    paths += sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*", "*.trace.json"
    )))
    events: list[dict] = []
    for p in paths:
        opener = gzip.open if p.endswith(".gz") else open
        with opener(p, "rt") as f:
            doc = json.load(f)
        events.extend(
            doc["traceEvents"] if isinstance(doc, dict) else doc
        )
    if not events:
        return []
    ts_vals = [e["ts"] for e in events
               if "ts" in e and e.get("ph") != "M"]
    shift = (anchor_us - min(ts_vals)
             if anchor_us is not None and ts_vals else 0.0)
    pid_map: dict = {}
    out: list[dict] = []
    for e in events:
        e = dict(e)
        pid = e.get("pid", 0)
        if pid not in pid_map:
            pid_map[pid] = pid_base + len(pid_map)
        e["pid"] = pid_map[pid]
        if "ts" in e and e.get("ph") != "M":
            e["ts"] = e["ts"] + shift
        out.append(e)
    out.append({
        "ph": "M", "name": "process_name", "pid": pid_base, "tid": 0,
        "ts": 0, "args": {
            "name": f"xla:{os.path.basename(trace_dir.rstrip('/'))} "
                    f"(clock-aligned, +-start_trace latency)"
        },
    })
    return out


def main() -> int:
    ap = argparse.ArgumentParser(
        description="merge per-process trace dumps into one "
        "Perfetto-loadable file with cross-process flow events"
    )
    ap.add_argument("inputs", nargs="+",
                    help="trace dumps, one per process (pid = input order)")
    ap.add_argument("--out", required=True, help="merged output path")
    ap.add_argument("--device-trace", action="append", default=[],
                    metavar="DIR",
                    help="jax.profiler capture dir (start --device-trace); "
                    "its device timeline is clock-aligned and merged as "
                    "its own pid group")
    args = ap.parse_args()

    event_lists = []
    labels = []
    for path in args.inputs:
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        event_lists.append(events)
        labels.append(os.path.basename(path))
    merged = stitch(event_lists, labels=labels)
    dev_count = 0
    pid_base = len(event_lists)
    for trace_dir in args.device_trace:
        dev = load_device_trace(trace_dir, pid_base)
        if not dev:
            print(f"[stitch] no profiler dump under {trace_dir} "
                  "(plugins/profile/*/)", file=sys.stderr)
            continue
        pid_base = 1 + max(e.get("pid", 0) for e in dev)
        dev_count += len(dev)
        merged.extend(dev)
    with open(args.out, "w") as f:
        json.dump({"traceEvents": merged}, f, sort_keys=True,
                  separators=(",", ":"))
    flows = sum(1 for e in merged if e.get("ph") in ("s", "t", "f"))
    ids = len({e["id"] for e in merged if e.get("ph") in ("s", "t", "f")})
    dev_note = f", {dev_count} device events" if dev_count else ""
    print(
        f"stitched {len(args.inputs)} dump(s): {len(merged)} events, "
        f"{flows} flow legs across {ids} op trace id(s){dev_note} "
        f"-> {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
