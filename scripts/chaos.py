#!/usr/bin/env python
"""chaos: the live-cluster chaos runner (testing/chaos.py as a CLI).

Spawns a real N-replica TCP cluster plus a multiplexed client fleet on
the fault-tolerant client runtime, injects live faults (SIGKILL/restart,
SIGSTOP gray failures, connection resets, a disk-fault flip on restart),
and verifies zero lost / zero duplicated transfers three ways (client
replies vs CDC stream vs wire conservation, plus dual-mode hash-log
parity), reporting time-to-first-commit-after-kill.

  python scripts/chaos.py                      # default: 1 primary kill
  python scripts/chaos.py --sessions 1000 --conns 16 --backend dual \
      --faults kill_primary,gray_primary,kill_backup,reset_conns
  python scripts/chaos.py --json report.json
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def main() -> int:
    from tigerbeetle_tpu.testing.chaos import CHAOS_ACTIONS, run_chaos

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sessions", type=int, default=64)
    ap.add_argument("--conns", type=int, default=4)
    ap.add_argument("--accounts", type=int, default=128)
    ap.add_argument("--events-per-batch", type=int, default=16)
    ap.add_argument("--batches-per-session", type=int, default=6)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--backend", default="native",
                    help="native | dual | native+device | device")
    ap.add_argument("--faults", default="kill_primary",
                    help="comma list of " + "|".join(CHAOS_ACTIONS))
    ap.add_argument("--restart-after", type=float, default=2.0,
                    metavar="S", help="kill -> respawn delay")
    ap.add_argument("--gray", type=float, default=3.0, metavar="S",
                    help="SIGSTOP duration")
    ap.add_argument("--no-disk-fault", action="store_true",
                    help="skip the WAL flip on the first restart")
    ap.add_argument("--ingress", action="store_true",
                    help="front every replica with the ingress gateway")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--deadline", type=float, default=600.0, metavar="S")
    ap.add_argument("--kill-cluster", action="store_true",
                    help="federation mode: spawn --federation-regions "
                         "whole clusters, SIGKILL every replica of one "
                         "region mid-settlement (federation/live.py)")
    ap.add_argument("--federation-regions", type=int, default=2)
    ap.add_argument("--payments", type=int, default=24,
                    help="cross-region origin pendings per region")
    ap.add_argument("--commitment-interval", type=int, default=8)
    ap.add_argument("--jax-platform", default="cpu",
                    help="TB_JAX_PLATFORM for the servers ('' = inherit)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the full report as JSON")
    args = ap.parse_args()

    faults = tuple(f for f in args.faults.split(",") if f)
    for f in faults:
        if f not in CHAOS_ACTIONS:
            ap.error(f"unknown fault {f!r} (have {CHAOS_ACTIONS})")

    def log(*a):
        print("[chaos]", *a, file=sys.stderr, flush=True)

    if args.kill_cluster:
        from tigerbeetle_tpu.federation.live import run_federation_chaos

        report = run_federation_chaos(
            regions=args.federation_regions,
            replica_count=args.replicas,
            payments=args.payments,
            commitment_interval=args.commitment_interval,
            restart_after_s=args.restart_after,
            backend=args.backend,
            seed=args.seed,
            deadline_s=args.deadline,
            jax_platform=args.jax_platform or None,
            log=log,
        )
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
            log(f"report -> {args.json}")
        print(json.dumps(report, indent=1, sort_keys=True))
        ok = (
            report["conservation"]["ok"]
            and all(v["checked"] > 0
                    for v in report["stream_verify"].values())
        )
        log("PASS" if ok else "FAIL")
        return 0 if ok else 1

    report = run_chaos(
        n_sessions=args.sessions,
        conns=args.conns,
        n_accounts=args.accounts,
        events_per_batch=args.events_per_batch,
        batches_per_session=args.batches_per_session,
        replica_count=args.replicas,
        backend=args.backend,
        faults=faults,
        restart_after_s=args.restart_after,
        gray_s=args.gray,
        disk_fault_on_restart=not args.no_disk_fault,
        ingress=args.ingress,
        seed=args.seed,
        deadline_s=args.deadline,
        jax_platform=args.jax_platform or None,
        log=log,
    )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"report -> {args.json}")
    print(json.dumps(report, indent=1, sort_keys=True))
    ok = (
        report["lost_events"] == 0
        and report["conservation_ok"]
        and report["cdc"]["dup_ids"] == 0
    )
    log("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
