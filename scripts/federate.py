#!/usr/bin/env python
"""federate: the declarative N-region federation entry point.

One topology knob (`--regions`), two execution modes over the same
cross-ledger scenario (origin pendings escrowed on the source region, a
settlement agent posting mirror/resolve legs, device-computed commitment
chains verified from the CDC stream by an external consumer):

  sim    the seed-deterministic composite (federation/sim.py): every
         region a full in-process simulated cluster, seeded settlement-
         agent crashes, one region killed wholesale mid-settlement;
         conservation + stream verification proven on recovery. The
         replay contract is the seed alone.

  live   real clusters (federation/live.py): one TCP replica-set per
         region, JSONL CDC tails, the settlement agent on the fault-
         tolerant client runtime; optionally SIGKILL every replica of
         one region mid-settlement and restart from disk.

  python scripts/federate.py sim --seed 7 --regions 2
  python scripts/federate.py live --regions 2 --replicas 3 --kill
  python scripts/federate.py sim --json report.json

Exit 0 iff conservation holds and every region's stream verified with at
least one checkpoint (the same PASS bar as scripts/chaos.py
--kill-cluster and the tier-1 federation tests).
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def _passes(report: dict) -> bool:
    verify = report.get("stream_verify") or {}
    return bool(
        report["conservation"]["ok"]
        and verify
        and all(v["checked"] > 0 for v in verify.values())
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode", choices=("sim", "live"))
    ap.add_argument("--regions", type=int, default=2,
                    help="federation size (each region a full cluster)")
    ap.add_argument("--replicas", type=int, default=3,
                    help="replica count per region")
    ap.add_argument("--commitment-interval", type=int, default=0,
                    help="checkpoint-commitment spacing in ops "
                         "(0 = the mode's default)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="write the full report as JSON")
    sim = ap.add_argument_group("sim mode")
    sim.add_argument("--ticks", type=int, default=2600)
    sim.add_argument("--no-region-kill", action="store_true",
                     help="skip the whole-region mid-settlement kill")
    live = ap.add_argument_group("live mode")
    live.add_argument("--payments", type=int, default=24,
                      help="cross-region origin pendings per region")
    live.add_argument("--kill", action="store_true",
                      help="SIGKILL every replica of one region "
                           "mid-settlement, restart from disk")
    live.add_argument("--restart-after", type=float, default=1.5,
                      metavar="S", help="kill -> respawn delay")
    live.add_argument("--backend", default="native",
                      help="native | dual | native+device | device")
    live.add_argument("--deadline", type=float, default=600.0,
                      metavar="S")
    live.add_argument("--jax-platform", default="cpu",
                      help="TB_JAX_PLATFORM for the servers "
                           "('' = inherit)")
    args = ap.parse_args()

    def log(*a):
        print("[federate]", *a, file=sys.stderr, flush=True)

    if args.mode == "sim":
        sys.path.insert(0, ".")
        import tests.conftest  # noqa: F401 — CPU platform before jax

        from tigerbeetle_tpu.federation.sim import run_federation_sim

        report = run_federation_sim(
            args.seed,
            n_regions=args.regions,
            ticks=args.ticks,
            replica_count=args.replicas,
            region_kill=not args.no_region_kill,
            **({"commitment_interval": args.commitment_interval}
               if args.commitment_interval else {}),
        )
        # JSON-shape parity with live mode: region keys as strings
        report["stream_verify"] = {
            str(k): v for k, v in (report["stream_verify"] or {}).items()
        }
    else:
        from tigerbeetle_tpu.federation.live import run_federation_chaos

        report = run_federation_chaos(
            regions=args.regions,
            replica_count=args.replicas,
            payments=args.payments,
            kill_cluster=args.kill,
            restart_after_s=args.restart_after,
            backend=args.backend,
            seed=args.seed,
            deadline_s=args.deadline,
            jax_platform=args.jax_platform or None,
            log=log,
            **({"commitment_interval": args.commitment_interval}
               if args.commitment_interval else {}),
        )

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
        log(f"report -> {args.json}")
    print(json.dumps(report, indent=1, sort_keys=True))
    ok = _passes(report)
    log("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
