"""Assemble a BENCH_r0N.json driver-shaped artifact from a bench.py run.

The driver's artifacts (`BENCH_r0*.json`) wrap one repeated `python
bench.py` invocation as {n, cmd, rc, tail, parsed}. When a round's
artifact is produced in-session instead (the driver hasn't run since
r05), this script builds the same shape from a captured run and adds the
provenance fields an honest off-rig artifact needs: the platform, the
size-reduction env knobs, and any segment failures — so no number can be
mistaken for a rig number.

Usage:
  python scripts/make_bench_artifact.py OUT.json STDOUT STDERR RC 'ENV...'
"""

import json
import os
import platform
import sys


def _jax_cache_bytes() -> int:
    cache = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    total = 0
    for root, _dirs, files in os.walk(cache):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def main() -> int:
    out_path, stdout_path, stderr_path, rc, env = sys.argv[1:6]
    parsed = None
    with open(stdout_path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    for ln in reversed(lines):  # bench prints the summary JSON last
        try:
            parsed = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    if parsed is None:
        print("no JSON summary in stdout — bench did not finish", file=sys.stderr)
        return 1
    with open(stderr_path) as f:
        tail = f.read()[-8000:]
    # Segment-health summary: a live segment that died mid-run leaves
    # nulls in the summary (r06's failover flake) — name the incomplete
    # segments in the artifact itself so a null reads as "segment
    # failed", never as "measured zero".
    incomplete = []
    if parsed.get("failover_recovery_ms") is None:
        incomplete.append("failover")
    if not parsed.get("frontier_steps"):
        incomplete.append("frontier")
    elif len(parsed["frontier_steps"]) < 4:
        incomplete.append("frontier_short_ladder")
    artifact = {
        "n": 1,
        "cmd": f"env {env} python bench.py",
        "rc": int(rc),
        # Off-rig provenance: r01-r05 ran on the TPU v5e rig via the
        # driver; this round ran in-session on the CPU sandbox (1 core,
        # JAX_PLATFORMS=cpu) with the size knobs recorded in `cmd`/`env`.
        # Absolute tps is NOT comparable to r05; same-run ratios
        # (`*_vs_fast_ratio`, spreads, parity booleans) are the quotable
        # signals. See README "Conflict-wave scheduling".
        "platform": {
            "backend": "cpu",
            "machine": platform.machine(),
            "python": platform.python_version(),
            "note": "in-session CPU sandbox run; not rig-comparable",
        },
        "env": env,
        "tail": tail,
        "segments_incomplete": incomplete,
        # Compile-cache provenance: the run's recompile story. bench.py
        # records .jax_cache size + its in-process compile-sentinel
        # totals in the summary; the artifact also stamps the cache size
        # at assembly time, so cache churn between run and packaging is
        # itself visible (a poisoned .jax_cache is the known pathology —
        # see models/ledger.py and the conftest guard).
        "jax_cache": {
            "bytes_at_artifact": _jax_cache_bytes(),
            "bytes_run_start": parsed.get("jax_cache_bytes_start"),
            "bytes_run_end": parsed.get("jax_cache_bytes_end"),
            "compile_sentinel": parsed.get("compile_sentinel"),
        },
        "parsed": parsed,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
