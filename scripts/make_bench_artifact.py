"""Assemble a BENCH_r0N.json driver-shaped artifact from a bench.py run.

The driver's artifacts (`BENCH_r0*.json`) wrap one repeated `python
bench.py` invocation as {n, cmd, rc, tail, parsed}. When a round's
artifact is produced in-session instead (the driver hasn't run since
r05), this script builds the same shape from a captured run and adds the
provenance fields an honest off-rig artifact needs — the platform, the
size-reduction env knobs, segment failures, the compile-cache story —
all through the shared provenance module (tigerbeetle_tpu/artifact.py,
also the PRODDAY emitter's wrapper, so the two artifacts cannot drift).

Usage:
  python scripts/make_bench_artifact.py OUT.json STDOUT STDERR RC 'ENV...'
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from tigerbeetle_tpu.artifact import wrap_artifact  # noqa: E402


def main() -> int:
    out_path, stdout_path, stderr_path, rc, env = sys.argv[1:6]
    parsed = None
    with open(stdout_path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    for ln in reversed(lines):  # bench prints the summary JSON last
        try:
            parsed = json.loads(ln)
            break
        except json.JSONDecodeError:
            continue
    if parsed is None:
        print("no JSON summary in stdout — bench did not finish", file=sys.stderr)
        return 1
    with open(stderr_path) as f:
        tail = f.read()[-8000:]
    # Segment-health summary: a live segment that died mid-run leaves
    # nulls in the summary (r06's failover flake) — name the incomplete
    # segments in the artifact itself so a null reads as "segment
    # failed", never as "measured zero".
    incomplete = []
    if parsed.get("failover_recovery_ms") is None:
        incomplete.append("failover")
    if not parsed.get("frontier_steps"):
        incomplete.append("frontier")
    elif len(parsed["frontier_steps"]) < 4:
        incomplete.append("frontier_short_ladder")
    if parsed.get("cross_ledger_tps") is None:
        incomplete.append("cross_ledger")
    artifact = wrap_artifact(
        cmd=f"env {env} python bench.py", rc=int(rc), env=env, tail=tail,
        parsed=parsed, segments_incomplete=incomplete,
    )
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    print(f"wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
