#!/usr/bin/env python
"""Source lint (the reference's tidy.zig / TigerStyle lint analog,
reference: src/tidy.zig:1-9, scripts/lint_tigerstyle.zig).

Checks every Python source in the repo:
- no tabs, no trailing whitespace, lines <= 100 columns;
- no unused imports (AST-verified; `# noqa` opts a line out);
- `print()` only in user-facing surfaces (CLI/REPL/scripts/bench) —
  library code logs or returns, it does not print.

Exit code 1 on any violation; run from the repo root.
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

LINE_MAX = 100
# Golden-vector fixture tables transcribed verbatim from the reference's
# test tables keep the reference's own formatting.
LINE_MAX_EXEMPT = {"tests/test_golden.py"}
PRINT_OK = {
    "tigerbeetle_tpu/cli.py", "tigerbeetle_tpu/repl.py",
    "tigerbeetle_tpu/__main__.py", "bench.py", "__graft_entry__.py",
}


def py_files():
    for base in ("tigerbeetle_tpu", "tests", "scripts"):
        yield from sorted((ROOT / base).rglob("*.py"))
    yield ROOT / "bench.py"
    yield ROOT / "__graft_entry__.py"


def used_names(tree: ast.AST) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def check_file(path: pathlib.Path) -> list[str]:
    rel = str(path.relative_to(ROOT))
    text = path.read_text()
    problems = []
    for i, line in enumerate(text.splitlines(), 1):
        if "\t" in line:
            problems.append(f"{rel}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if len(line) > LINE_MAX and rel not in LINE_MAX_EXEMPT:
            problems.append(f"{rel}:{i}: line exceeds {LINE_MAX} columns")
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [f"{rel}: syntax error: {e}"]
    noqa = {
        i for i, line in enumerate(text.splitlines(), 1) if "# noqa" in line
    }
    used = used_names(tree)
    in_init = path.name == "__init__.py"
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) and not in_init:
            if node.lineno in noqa:
                continue
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = (alias.asname or alias.name).split(".")[0]
                if name not in used:
                    problems.append(
                        f"{rel}:{node.lineno}: unused import {name!r}"
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
            and rel.startswith("tigerbeetle_tpu/")
            and rel not in PRINT_OK
            and node.lineno not in noqa
        ):
            problems.append(f"{rel}:{node.lineno}: print() in library code")
    return problems


def main() -> int:
    problems = []
    for path in py_files():
        problems += check_file(path)
    for p in problems:
        print(p)
    if problems:
        print(f"tidy: {len(problems)} problem(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
