#!/usr/bin/env python
"""Source lint — now a vet pass (`python scripts/vet.py --pass tidy`).

This shim keeps the historical entry point alive: same checks (no tabs,
no trailing whitespace, <=100 columns, unused imports, library prints)
plus the v2 rule that `# noqa` must name the check it suppresses. The
implementation lives in tigerbeetle_tpu/devtools/tidy_pass.py.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tigerbeetle_tpu import devtools  # noqa: E402


def main() -> int:
    violations, _ = devtools.run_vet(ROOT, pass_names=["tidy"])
    for v in violations:
        print(v.render())
    if violations:
        print(f"tidy: {len(violations)} problem(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
