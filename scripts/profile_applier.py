#!/usr/bin/env python
"""One-shot applier profiler: drive a follower DualLedger with synthetic
batches, capture a bounded XLA trace window on the apply thread, and
report the commit_wait decomposition.

This is the incident tool the README cookbook ends on: when `inspect
live` / the frontier ladder says commit_wait dominates and the device
sub-leg columns name a sub-leg, this script reproduces the applier in
isolation and hands you (a) the per-sub-leg totals and slowest-apply
breakdown, and (b) a stitched Perfetto file where the jax.profiler
device timeline sits clock-aligned under the applier's spans — so the
sub-leg's interior (which XLA op, h2d vs kernel vs gap) is one click
deep.

Usage:
    python scripts/profile_applier.py --out /tmp/applier_profile
    python scripts/profile_applier.py --out /tmp/p --batches 64 \
        --batch 256 --window-s 2.0 --jax-platform cpu

Writes under --out:
    devtrace/...            the jax.profiler capture + clock-anchor meta
    applier.trace.json      the applier-side span dump (JsonTracer)
    stitched.json           spans + device timeline, one Perfetto file
    report.json             sub-leg totals, dominant, slowest applies,
                            compile-sentinel snapshot

Host+device in ONE process (no server, no sockets): the native engine
computes the reply codes exactly like the dual backend's reply path,
apply_commit feeds the follower queue, and finalize() proves parity
before the report is trusted.
"""

import argparse
import json
import os
import sys
from time import perf_counter_ns

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="capture an XLA trace window on the dual-backend "
        "applier thread and report the commit_wait sub-leg decomposition"
    )
    ap.add_argument("--out", required=True,
                    help="output directory (created)")
    ap.add_argument("--batches", type=int, default=32,
                    help="transfer batches to apply (default 32)")
    ap.add_argument("--batch", type=int, default=256,
                    help="events per batch (default 256)")
    ap.add_argument("--window-s", type=float, default=3.0,
                    help="device-trace window length (default 3.0)")
    ap.add_argument("--stall-s", type=float, default=0.0,
                    help="throttle the apply loop per run (forces queue "
                    "buildup + fused runs, like a real backlog)")
    ap.add_argument("--jax-platform", default=None,
                    help="JAX_PLATFORMS override (e.g. cpu)")
    args = ap.parse_args()

    if args.jax_platform:
        os.environ["JAX_PLATFORMS"] = args.jax_platform
    os.makedirs(args.out, exist_ok=True)

    import numpy as np

    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.latency import device_leg_totals, dominant_leg
    from tigerbeetle_tpu.metrics import Metrics
    from tigerbeetle_tpu.models.dual_ledger import DualLedger
    from tigerbeetle_tpu.models.ledger import COMPILE_SENTINEL
    from tigerbeetle_tpu.tracer import JsonTracer
    from tigerbeetle_tpu.types import Operation

    metrics = Metrics()
    tracer = JsonTracer(metrics=metrics)
    led = DualLedger(12, 14, follower=True, warm_kernels=True)
    led.instrument(metrics, tracer)
    devtrace = os.path.join(args.out, "devtrace")
    led.start_device_trace(devtrace, args.window_s)
    if args.stall_s:
        led._test_apply_delay_s = args.stall_s

    n_accounts = 64
    acc = np.zeros(n_accounts, dtype=types.ACCOUNT_DTYPE)
    acc["id_lo"] = np.arange(1, n_accounts + 1, dtype=np.uint64)
    acc["ledger"] = 1
    acc["code"] = 1

    op_no = 0

    def drive(op, arr):
        # the replica's commit-finalize seam: native reply codes first,
        # then the follower enqueue — every op SAMPLED (lat_ns stamped)
        # so the report sees the full population, not 1-in-16
        nonlocal op_no
        op_no += 1
        led.prepare(op, len(arr))
        ts = led.prepare_timestamp
        p = led.execute_async(op, ts, arr)
        led.drain(p)
        with tracer.span("profile.commit", trace=op_no):
            led.apply_commit(op_no, op, ts, arr, p.codes,
                             prepare_checksum=0xABCD_0000 + op_no,
                             trace=op_no, lat_ns=perf_counter_ns())

    drive(Operation.create_accounts, acc)
    rng = np.random.default_rng(7)
    for b in range(args.batches):
        x = np.zeros(args.batch, dtype=types.TRANSFER_DTYPE)
        x["id_lo"] = np.arange(1000 + b * args.batch,
                               1000 + (b + 1) * args.batch,
                               dtype=np.uint64)
        deb = rng.integers(1, n_accounts + 1, args.batch, dtype=np.uint64)
        cred = deb % n_accounts + 1
        x["debit_account_id_lo"] = deb
        x["credit_account_id_lo"] = cred
        x["amount_lo"] = 1
        x["ledger"] = 1
        x["code"] = 1
        drive(Operation.create_transfers, x)

    led._test_apply_delay_s = 0.0
    snap_before = {}
    report_ok = led.finalize(timeout=600)
    snap = metrics.snapshot()
    totals = device_leg_totals(snap)
    leg, share = dominant_leg(snap_before, totals)
    report = {
        "verified": report_ok.get("verified"),
        "device_subleg_totals_us": {k: round(v["total_us"], 1)
                                    for k, v in totals.items()},
        "dominant_subleg": leg,
        "dominant_share": share,
        "device_slowest": led.device_anatomy.slowest(limit=8),
        "compile_sentinel": COMPILE_SENTINEL.snapshot(),
        "trace_window_dir": devtrace,
    }
    with open(os.path.join(args.out, "report.json"), "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    span_path = os.path.join(args.out, "applier.trace.json")
    tracer.dump(span_path)

    # stitch spans + device timeline into one Perfetto file
    from scripts.stitch_trace import load_device_trace
    from tigerbeetle_tpu.tracer import stitch

    merged = stitch([tracer.events_ordered()], labels=["applier"])
    dev = load_device_trace(devtrace, pid_base=1)
    merged.extend(dev)
    stitched = os.path.join(args.out, "stitched.json")
    with open(stitched, "w") as f:
        json.dump({"traceEvents": merged}, f, sort_keys=True,
                  separators=(",", ":"))

    print(f"verified={report['verified']} "
          f"dominant={leg} ({share:.0%})", file=sys.stderr)
    for k, v in sorted(totals.items(),
                       key=lambda kv: -kv[1]["total_us"]):
        print(f"  {k:<18} {v['total_us'] / 1000.0:9.2f} ms",
              file=sys.stderr)
    sent = report["compile_sentinel"]
    print(f"compiles total={sent['total']} "
          f"post_warmup={sent['post_warmup']}", file=sys.stderr)
    print(f"device events stitched: {len(dev)} -> {stitched}",
          file=sys.stderr)
    return 0 if report["verified"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
