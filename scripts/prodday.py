#!/usr/bin/env python
"""Run a scripted production day against a live cluster and score it.

Composes the existing drivers into one scenario run: a gateway-fronted
`--backend dual` cluster (testing/chaos.py's ChaosServer), an OPEN-LOOP
offered-load schedule derived from the timeline's phase curves (the
frontier driver's due-time discipline: a batch becomes due on the
schedule's clock whether or not the cluster kept up, so latency is
measured from DUE time and queueing delay is visible), the chaos fault
injectors fired at scripted offsets, and the CDC fan-out hub with one
count-throttled slow consumer. Phase boundaries are stamped into every
replica's flight recorder over the wire (`mark`, vsr/header.py), so the
phase-aligned SLO scorer (tigerbeetle_tpu/prodday.py) slices recorder
history per phase and names the dominant critical-path leg for any
violated budget.

Emits the scorecard report to --out and a PRODDAY artifact (the same
provenance discipline as BENCH artifacts: platform block, .jax_cache
sizes, compile-sentinel totals, segments_incomplete) to --artifact.

The same timeline replays seed-deterministically in the simulator:
  python -c "from tigerbeetle_tpu.prodday import *; \\
             print(run_sim_twin(production_day(), seed=1)['scorecard'])"

Example (sandbox-scaled rehearsal of the canonical day):
  python scripts/prodday.py --time-scale 0.25 --rate-scale 0.5 \\
      --artifact PRODDAY_r01.json
"""

import argparse
import json
import os
import random
import socket
import sys
import time
from collections import deque

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

from tigerbeetle_tpu.artifact import jax_cache_bytes, wrap_artifact
from tigerbeetle_tpu.benchmark import (
    REPO,
    _accounts_body,
    _transfers_body,
    free_port,
    kill_process_group,
)
from tigerbeetle_tpu.constants import ConfigCluster
from tigerbeetle_tpu.inspect import inspect_live, send_mark
from tigerbeetle_tpu.metrics import Metrics
from tigerbeetle_tpu.prodday import (
    offered_rate,
    production_day,
    scale_timeline,
    score,
    slice_history,
    smoke_timeline,
)
from tigerbeetle_tpu.testing.chaos import (
    ChaosFleet,
    ChaosServer,
    _parse_cdc_stream,
    inject_wal_fault,
)
from tigerbeetle_tpu.types import Operation


class ProddayFleet(ChaosFleet):
    """Open-loop fleet: batches become due on the timeline's clock and
    are issued on the first free session once due. Latency is ack time
    minus DUE time (not issue time), so a saturated cluster's queueing
    delay lands in the phase's p99 instead of silently stretching the
    schedule — the open-loop discipline run_frontier established."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.due: deque = deque()  # (due_t, phase, events, body)
        self.meta: dict = {}  # session id() -> (due_t, phase, events)
        self.latencies: dict = {}  # phase -> [ack - due, ...] seconds
        self.phase_counts: dict = {}  # phase -> {offered, acked, failed}

    def offer(self, due_t: float, phase: str, body: bytes) -> None:
        ev = len(body) // 128
        self.due.append((due_t, phase, ev, body))
        pc = self.phase_counts.setdefault(
            phase, {"offered": 0, "acked": 0, "failed": 0}
        )
        pc["offered"] += ev
        self.total_events += ev

    def step_open(self, now: float) -> int:
        dispatched = self.pump()
        harvested = 0
        for s in self.sessions:
            s.ticker.advance(now)
            c = s.client
            try:
                c.poll()
            except Exception as e:  # typed errors: count, never hang
                self.errors.append(f"{type(e).__name__}: {e}")
                m = self.meta.pop(id(s), None)
                if m is not None:
                    self.phase_counts[m[1]]["failed"] += m[2]
                s.events_inflight = 0
            if c.reply is not None:
                _h, body = c.take_reply()
                self.max_op = max(self.max_op, _h.op)
                if body != b"":
                    self.errors.append(
                        f"client {c.client_id:#x}: non-empty reply "
                        f"({len(body)} bytes of result structs)"
                    )
                t = time.monotonic()
                self.recovery.observe_reply(t, _h.view, s.issue_seq)
                m = self.meta.pop(id(s), None)
                if m is not None:
                    due_t, phase, ev = m
                    self.latencies.setdefault(phase, []).append(t - due_t)
                    self.phase_counts[phase]["acked"] += ev
                self.acked_events += s.events_inflight
                self.acked_timeline.append((t, s.events_inflight))
                s.acked += s.events_inflight
                s.events_inflight = 0
                harvested += 1
            if (
                c.in_flight is None and c.session != 0
                and id(s) not in self.meta
                and self.due and self.due[0][0] <= now
            ):
                due_t, phase, ev, body = self.due.popleft()
                s.events_inflight = ev
                self._issue_seq += 1
                s.issue_seq = self._issue_seq
                self.meta[id(s)] = (due_t, phase, ev)
                c.request(Operation.create_transfers, body)
        return harvested + dispatched


def build_schedule(timeline, events_per_batch: int, n_accounts: int,
                   seed: int):
    """Precompute the whole day's batches: (due_rel_s, phase_name,
    body). Deterministic in (timeline, seed); disjoint id namespaces
    keep the CDC duplicate audit meaningful. Flash-crowd phases with
    hot_accounts > 0 draw both sides of every transfer from the hot
    subset {1..hot} — the concentrated-contention shape."""
    nrng = np.random.default_rng(seed)
    sched = []
    t, dur, nid = 0.0, timeline.duration_s, 1_000_000
    while t < dur:
        phase, into = timeline.phase_at(t)
        rate = max(0.0, offered_rate(phase, into / phase.duration_s))
        if rate <= 0.0:
            t += 0.1
            continue
        acct = phase.hot_accounts or n_accounts
        sched.append(
            (t, phase.name,
             _transfers_body(nrng, nid, events_per_batch, acct))
        )
        nid += events_per_batch
        t += events_per_batch / rate
    return sched


def run_prodday(
    timeline,
    n_sessions: int = 32,
    conns: int = 4,
    n_accounts: int = 128,
    events_per_batch: int = 16,
    replica_count: int = 3,
    backend: str = "dual",
    restart_after_s: float = 2.0,
    seed: int = 1,
    jax_platform: str | None = "cpu",
    settle_s: float = 1.0,
    drain_grace_s: float = 120.0,
    harvest_every_s: float = 5.0,
    tmpdir: str | None = None,
    log=None,
) -> dict:
    """Drive `timeline` against a live cluster; return the report with
    the phase-aligned scorecard. Raises only on harness failures —
    SLO violations are scorecard rows, not exceptions."""
    import subprocess
    import tempfile

    log = log or (lambda *_: None)
    rng = random.Random(seed)
    timeline.validate()
    own_tmp = tmpdir is None
    if own_tmp:
        tmp = tempfile.TemporaryDirectory(prefix="tb_prodday_")
        tmpdir = tmp.name

    slow_events = [e for e in timeline.events if e.kind == "slow_consumer"]
    schedule = build_schedule(timeline, events_per_batch, n_accounts, seed)
    total_events = len(schedule) * events_per_batch + events_per_batch

    ports = [free_port() for _ in range(replica_count)]
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    clients_max = n_sessions + 64
    reply_slots = 64
    session_args = (
        "--clients-max", str(clients_max),
        "--client-reply-slots", str(reply_slots),
    )
    cluster_cfg = ConfigCluster(
        replica_count=replica_count,
        clients_max=clients_max,
        client_reply_slots=reply_slots,
    )
    slots_log2 = 14
    while total_events * 2 + 4096 > (1 << slots_log2) // 2:
        slots_log2 += 1
    acct_log2 = max(14, (n_accounts * 2 + 2).bit_length())
    start_args = session_args + (
        "--account-slots-log2", str(acct_log2),
        "--transfer-slots-log2", str(slots_log2),
    )
    pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=f"{REPO}:{pp}" if pp else REPO,
               TB_PARENT_WATCHDOG="1")
    if jax_platform:
        env["TB_JAX_PLATFORM"] = jax_platform

    paths = []
    for i in range(replica_count):
        path = os.path.join(tmpdir, f"prodday_{i}.tigerbeetle")
        paths.append(path)
        fmt = subprocess.run(
            [sys.executable, "-m", "tigerbeetle_tpu", "format",
             "--cluster", "7", "--replica", str(i),
             "--replica-count", str(replica_count),
             *session_args, path],
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
        )
        assert fmt.returncode == 0, fmt.stderr

    # The slow CDC consumer rides the fan-out hub so the audit stream
    # (jsonl) keeps full pace while the throttled laggard's position
    # falls behind — its lag is the `ingress.fanout_lag_ops` gauge the
    # cdc_lag SLO reads. The laggard is a UDP sink we also receive.
    udp_rx = None
    cdc_path = os.path.join(tmpdir, "prodday_cdc.jsonl")
    servers = []
    for i in range(replica_count):
        extra: tuple = ("--ingress",)
        if i == 0:
            extra = extra + (
                "--cdc-jsonl", cdc_path,
                "--cdc-cursor", cdc_path + ".cursor",
            )
            if slow_events:
                udp_rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                udp_rx.bind(("127.0.0.1", 0))
                udp_rx.setblocking(False)
                extra = extra + (
                    "--cdc-udp",
                    f"127.0.0.1:{udp_rx.getsockname()[1]}",
                    "--cdc-fanout",
                    "--cdc-slow-every", str(slow_events[0].arg or 4),
                )
        servers.append(ChaosServer(
            i, addresses, paths[i], env, backend, start_args, extra, log,
        ))

    metrics = Metrics()
    fleet = None
    report = {
        "timeline": timeline.name,
        "seed": seed,
        "backend": backend,
        "sessions": n_sessions,
        "conns": conns,
        "replicas": replica_count,
        "scheduled_batches": len(schedule),
        "events": {"kills": 0, "restarts": 0, "gray_stops": 0,
                   "conn_resets": 0, "disk_fault_slots": [],
                   "slow_consumer_every": (slow_events[0].arg or 4)
                   if slow_events else 0},
    }
    # merged flight history: (replica, entry_t) -> entry, harvested
    # periodically because a SIGKILL wipes the victim's in-memory ring
    flight: dict = {}
    slow_datagrams = 0

    def harvest() -> None:
        nonlocal slow_datagrams
        for s in servers:
            if not s.alive or s.stopped or not s.ready.is_set():
                continue
            try:
                live = inspect_live(
                    "127.0.0.1", ports[s.index], timeout=2.0
                )
            except (OSError, RuntimeError, ValueError):
                continue
            for e in live.get("history") or []:
                flight[(s.index, s.spawns, e["t"])] = e
        if udp_rx is not None:
            while True:
                try:
                    udp_rx.recv(65536)
                except (BlockingIOError, OSError):
                    break
                slow_datagrams += 1

    def mark_all(name: str) -> None:
        for s in servers:
            if s.alive and not s.stopped and s.ready.is_set():
                try:
                    send_mark("127.0.0.1", ports[s.index], name,
                              timeout=2.0)
                except (OSError, RuntimeError, ValueError):
                    pass  # a booting replica misses one boundary; the
                    # next mark (or its restart re-mark) catches it up

    try:
        t_boot = time.monotonic()
        for s in servers:
            s.spawn(wait=False)
        for s in servers:
            if not s.ready.wait(300.0):
                raise TimeoutError(f"replica {s.index} never listened")
        log(f"cluster up on {addresses} in "
            f"{time.monotonic() - t_boot:.1f}s")

        fleet = ProddayFleet(ports, n_sessions, conns, metrics)
        report["register_s"] = round(fleet.register_all(), 2)

        next_id = 1
        while next_id <= n_accounts:
            k = min(2048, n_accounts - next_id + 1)
            body = fleet.execute(
                fleet.sessions[0], Operation.create_accounts,
                _accounts_body(next_id, k),
            )
            assert body == b"", "account create failed"
            next_id += k
        warm = _transfers_body(
            np.random.default_rng(seed + 1), 500_000, events_per_batch,
            n_accounts,
        )
        assert fleet.execute(
            fleet.sessions[0], Operation.create_transfers, warm,
            deadline_s=600.0,
        ) == b""
        warm_events = events_per_batch

        # shed/timeout accounting per phase: counter totals sampled at
        # each boundary (one registry serves every session's client)
        def _ctr() -> tuple:
            snap = metrics.snapshot()["counters"]
            return (snap.get("client.busy_sheds", 0),
                    snap.get("client.timeouts", 0))

        starts = timeline.phase_starts_s()
        events_left = sorted(timeline.events, key=lambda e: e.at_s)
        pending_restarts: list = []  # [when, server, flip]
        pending_cont: list = []  # [when, server]
        owe_mark: list = []  # restarted servers owed the current phase
        disk_flip_armed = False
        faults_armed = 0
        boundary_ctr: dict = {}  # phase -> (sheds, timeouts) at entry
        phase_now = None
        sched_i = 0
        next_harvest = 0.0
        fault_log: list = []

        t0 = time.monotonic()
        duration = timeline.duration_s
        deadline = t0 + duration + drain_grace_s
        log(f"driving timeline '{timeline.name}': {duration:.0f}s, "
            f"{len(schedule)} batches, {len(events_left)} events")
        while True:
            now = time.monotonic()
            rel = now - t0
            done_load = sched_i >= len(schedule) and not fleet.due
            if rel >= duration and done_load and not fleet.meta:
                break
            if now > deadline:
                log(f"drain grace expired with "
                    f"{fleet.outstanding()} events outstanding")
                break

            # phase boundaries (stamped BEFORE the load that phase
            # offers: the driver waits for mark acks, so the recorder
            # slices can't smear across the boundary)
            while starts and rel >= starts[0][0]:
                _, p = starts.pop(0)
                phase_now = p.name
                boundary_ctr[p.name] = _ctr()
                mark_all(p.name)
                log(f"phase -> {p.name} at t+{rel:.1f}s")

            # offered load: enqueue every batch now due
            while sched_i < len(schedule) and schedule[sched_i][0] <= rel:
                due_rel, pname, body = schedule[sched_i]
                fleet.offer(t0 + due_rel, pname, body)
                sched_i += 1

            # scheduled faults
            while events_left and rel >= events_left[0].at_s:
                e = events_left.pop(0)
                if e.kind == "kill_primary":
                    victim = servers[fleet.view % replica_count]
                    if victim.alive:
                        victim.sigcont()
                        victim.kill()
                        report["events"]["kills"] += 1
                        fleet.mark_fault(time.monotonic())
                        faults_armed += 1
                        fault_log.append((round(rel, 1), e.kind))
                        log(f"event: SIGKILL replica {victim.index} "
                            f"(primary) at t+{rel:.1f}s")
                        pending_restarts.append([
                            time.monotonic() + restart_after_s, victim,
                        ])
                elif e.kind == "gray_primary":
                    victim = servers[fleet.view % replica_count]
                    if victim.alive and not victim.stopped:
                        victim.sigstop()
                        report["events"]["gray_stops"] += 1
                        fleet.mark_fault(time.monotonic())
                        faults_armed += 1
                        fault_log.append((round(rel, 1), e.kind))
                        log(f"event: SIGSTOP replica {victim.index} "
                            f"for {e.arg or 3}s at t+{rel:.1f}s")
                        pending_cont.append([
                            time.monotonic() + (e.arg or 3), victim,
                        ])
                elif e.kind == "reset_conns":
                    for b in fleet.buses:
                        b.drop_connections()
                    report["events"]["conn_resets"] += 1
                    fleet.mark_fault(time.monotonic())
                    faults_armed += 1
                    fault_log.append((round(rel, 1), e.kind))
                    log(f"event: reset every client connection "
                        f"at t+{rel:.1f}s")
                elif e.kind == "disk_fault_on_restart":
                    disk_flip_armed = True
                    fault_log.append((round(rel, 1), e.kind))
                    log(f"event: next restart boots from a faulted WAL")
                elif e.kind == "slow_consumer":
                    # armed at boot (sink wiring is a start-time flag);
                    # the event timestamp records the scenario beat
                    fault_log.append((round(rel, 1), e.kind))
                    log(f"event: slow CDC consumer in effect "
                        f"(accept every "
                        f"{report['events']['slow_consumer_every']}th)")

            for entry in list(pending_restarts):
                when, srv = entry
                if now >= when and not srv.alive:
                    pending_restarts.remove(entry)
                    if disk_flip_armed:
                        disk_flip_armed = False
                        slots = inject_wal_fault(
                            srv.path, cluster_cfg, rng
                        )
                        report["events"]["disk_fault_slots"] = slots
                        log(f"event: disk-fault flip on replica "
                            f"{srv.index}'s WAL (slots {slots})")
                    srv.spawn(wait=False)
                    report["events"]["restarts"] += 1
                    owe_mark.append(srv)
                    log(f"event: replica {srv.index} restarting")
            for entry in list(pending_cont):
                when, srv = entry
                if now >= when:
                    pending_cont.remove(entry)
                    srv.sigcont()
                    owe_mark.append(srv)  # it slept through boundaries
                    log(f"event: SIGCONT replica {srv.index}")
            for srv in list(owe_mark):
                if srv.alive and not srv.stopped and srv.ready.is_set():
                    owe_mark.remove(srv)
                    if phase_now:
                        try:
                            send_mark("127.0.0.1", ports[srv.index],
                                      phase_now, timeout=2.0)
                        except (OSError, RuntimeError, ValueError):
                            owe_mark.append(srv)

            if rel >= next_harvest:
                next_harvest = rel + harvest_every_s
                harvest()

            if fleet.step_open(now) == 0:
                time.sleep(0.0005)

        drive_wall = time.monotonic() - t0
        log(f"timeline complete: {fleet.acked_events}/"
            f"{fleet.total_events} events acked in {drive_wall:.1f}s; "
            f"recoveries_ms="
            f"{[round(r) for r in fleet.recoveries_ms]}")
        for _w, srv in pending_restarts:  # tail kill: still owed boot
            if not srv.alive:
                srv.spawn(wait=False)
                report["events"]["restarts"] += 1
        for _w, srv in pending_cont:
            srv.sigcont()
        for srv in servers:
            if srv.proc is not None and srv.alive:
                srv.ready.wait(300.0)

        time.sleep(settle_s)
        total = fleet.acked_events + warm_events
        from tigerbeetle_tpu.state_machine import (
            decode_accounts,
            encode_ids,
        )

        dpo = cpo = found = 0
        for i in range(0, n_accounts, 8000):
            ids = list(range(1 + i, 1 + min(i + 8000, n_accounts)))
            body = fleet.execute(
                fleet.sessions[0], Operation.lookup_accounts,
                encode_ids(ids),
            )
            arr = decode_accounts(body)
            found += len(arr)
            dpo += int(arr["debits_posted_lo"].sum())
            cpo += int(arr["credits_posted_lo"].sum())
        conservation_ok = (found == n_accounts and dpo == cpo == total)
        log(f"wire conservation: debits={dpo} credits={cpo} "
            f"acked+warm={total} -> {'OK' if conservation_ok else 'FAIL'}")

        # catch-up barrier before the CDC tail is read: the stream can
        # only carry what replica 0 committed
        target = fleet.max_op
        t_w = time.monotonic()
        for s in servers:
            while True:
                if time.monotonic() - t_w > 300.0:
                    raise TimeoutError(
                        f"replica {s.index} never caught up to {target}"
                    )
                try:
                    live = inspect_live(
                        "127.0.0.1", ports[s.index], timeout=2.0
                    )
                    if live["commit_min"] >= target:
                        break
                except (OSError, RuntimeError, ValueError):
                    pass
                time.sleep(0.25)
        harvest()  # final rings, post-barrier

        parity = {}
        sentinels = {}
        for s in servers:
            stats = s.terminate()
            shadow = stats.get("device_shadow") or {}
            parity[f"r{s.index}"] = {
                "verified": shadow.get("verified"),
                "hash_log_ok": (shadow.get("hash_log") or {}).get("ok"),
            }
            if stats.get("compile_sentinel") is not None:
                sentinels[f"r{s.index}"] = stats["compile_sentinel"]
            if stats.get("phases"):
                report.setdefault("replica_phase_logs", {})[
                    f"r{s.index}"
                ] = stats["phases"]

        cdc = _parse_cdc_stream(cdc_path)
        parity_ok = True
        if backend in ("dual", "native+device"):
            parity_ok = all(
                v["verified"] and v["hash_log_ok"] is not False
                for v in parity.values()
            )
        checks = {
            "conservation_ok": conservation_ok,
            "parity_ok": parity_ok,
            "cdc_dup_free": cdc["dup_ids"] == 0
            and cdc["transfers_bad"] == 0,
            "cdc_complete": cdc["unique_ids"] == total,
        }

        # phase measurements from the driver's own bookkeeping
        measures = {}
        end_ctr = _ctr()
        names = [p.name for p in timeline.phases]
        for i, p in enumerate(timeline.phases):
            pc = fleet.phase_counts.get(p.name)
            if not pc or not pc["offered"]:
                continue
            lat = sorted(fleet.latencies.get(p.name, ()))
            c0 = boundary_ctr.get(p.name)
            c1 = (boundary_ctr.get(names[i + 1])
                  if i + 1 < len(names) else None) or end_ctr
            sheds = (c1[0] - c0[0]) if c0 else 0
            touts = (c1[1] - c0[1]) if c0 else 0
            batches = max(1, pc["offered"] // events_per_batch)
            # client-perceived attempt success rate: every shed, runtime
            # timeout (each retry counts) and failed batch is one failed
            # attempt; each acked batch is one successful attempt.
            # Dividing failures by BATCHES instead would clamp a phase
            # with heavy retries to 0.0 "total outage" even though every
            # event eventually acked.
            attempts = batches + sheds + touts + pc["failed"]
            m = {
                "offered": pc["offered"],
                "acked": pc["acked"],
                "failed": pc["failed"],
                "sheds": sheds,
                "timeouts": touts,
                "availability": round(batches / attempts, 5),
                "shed_rate": round(min(1.0, sheds / attempts), 5),
            }
            if lat:
                m["p99_ms"] = round(
                    lat[min(len(lat) - 1,
                            int(0.99 * len(lat)))] * 1e3, 3
                )
                m["p50_ms"] = round(lat[len(lat) // 2] * 1e3, 3)
            measures[p.name] = m

        entries = [flight[k] for k in sorted(flight)]
        slices = slice_history(entries)
        card = score(
            timeline, slices, measures=measures,
            recoveries_ms=list(fleet.recoveries_ms),
            faults_armed=faults_armed, checks=checks,
        )

        snap = metrics.snapshot()["counters"]
        report.update({
            "wall_s": round(drive_wall, 2),
            "acked_events": fleet.acked_events,
            "offered_events": fleet.total_events,
            "unacked_events": fleet.outstanding(),
            "tps": round(fleet.acked_events / max(drive_wall, 1e-9), 1),
            "recoveries_ms": [
                round(r, 1) for r in fleet.recoveries_ms
            ],
            "fault_log": fault_log,
            "conservation": {"debits": dpo, "credits": cpo,
                             "expected": total},
            "checks": checks,
            "cdc": cdc,
            "slow_consumer_datagrams": slow_datagrams,
            "parity": parity,
            "compile_sentinel": sentinels,
            "phase_measures": measures,
            "flight_entries": len(entries),
            "client_errors": fleet.errors[:8],
            "bus_reconnects": snap.get("bus.reconnects", 0),
            "scorecard": card,
        })
        return report
    finally:
        if fleet is not None:
            fleet.close()
        for s in servers:
            s.sigcont()
            if s.proc is not None:
                kill_process_group(s.proc)
        if udp_rx is not None:
            udp_rx.close()
        if own_tmp:
            tmp.cleanup()


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--timeline", default="production_day",
                    choices=("production_day", "smoke"))
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="shrink phase durations (0.25 = quarter-length"
                         " rehearsal; SLOs and event order unchanged)")
    ap.add_argument("--rate-scale", type=float, default=1.0,
                    help="scale offered rates to the box's frontier")
    ap.add_argument("--sessions", type=int, default=32)
    ap.add_argument("--conns", type=int, default=4)
    ap.add_argument("--accounts", type=int, default=128)
    ap.add_argument("--events-per-batch", type=int, default=16)
    ap.add_argument("--backend", default="dual")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default=None,
                    help="write the full report JSON here")
    ap.add_argument("--artifact", default=None,
                    help="write the PRODDAY artifact here "
                         "(e.g. PRODDAY_r01.json)")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args()

    tl = (production_day() if args.timeline == "production_day"
          else smoke_timeline())
    if args.time_scale != 1.0 or args.rate_scale != 1.0:
        tl = scale_timeline(tl, time=args.time_scale,
                            rate=args.rate_scale)

    log = (lambda *_: None) if args.quiet else (
        lambda *a: print(*a, file=sys.stderr, flush=True)
    )
    cache_start = jax_cache_bytes()
    t0 = time.monotonic()
    report = run_prodday(
        tl,
        n_sessions=args.sessions,
        conns=args.conns,
        n_accounts=args.accounts,
        events_per_batch=args.events_per_batch,
        replica_count=args.replicas,
        backend=args.backend,
        seed=args.seed,
        log=log,
    )
    report["harness_wall_s"] = round(time.monotonic() - t0, 1)
    report["jax_cache_bytes_start"] = cache_start
    report["jax_cache_bytes_end"] = jax_cache_bytes()

    card = report["scorecard"]
    for r in card["rows"]:
        state = {True: "PASS", False: "FAIL", None: "no-data"}[r["pass"]]
        extra = ""
        if r["pass"] is False and r.get("dominant_leg"):
            extra = (f"  dominant={r['dominant_leg']}"
                     f" ({r['dominant_leg_share']:.0%})")
            if r.get("dominant_device_subleg"):
                extra += f" device={r['dominant_device_subleg']}"
        m = r["measured"]
        if isinstance(m, dict):
            m = ",".join(k for k, v in sorted(m.items()) if not v) or "ok"
        print(f"{state:7} {r['phase']:>14} {r['slo']:<14} "
              f"measured={m} budget={r['budget']}{extra}")
    print(f"scorecard: {'PASS' if card['pass'] else 'FAIL'} "
          f"({card['violations']} violations, "
          f"{card['no_data']} no-data rows)")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1, default=str)
            f.write("\n")
        print(f"wrote {args.out}")
    if args.artifact:
        segments_incomplete = []
        if report["unacked_events"]:
            segments_incomplete.append("drive_drain")
        if card["no_data"]:
            segments_incomplete.append("scorecard_no_data_rows")
        parsed = dict(report)
        parsed["compile_sentinel"] = report.get("compile_sentinel")
        artifact = wrap_artifact(
            cmd="python scripts/prodday.py "
                + " ".join(sys.argv[1:]),
            rc=0,
            env=f"TB_JAX_PLATFORM=cpu seed={args.seed}",
            tail="",
            parsed=parsed,
            segments_incomplete=segments_incomplete,
            backend=args.backend,
        )
        with open(args.artifact, "w") as f:
            json.dump(artifact, f, indent=1, default=str)
            f.write("\n")
        print(f"wrote {args.artifact}")
    return 0 if card["pass"] else 2


if __name__ == "__main__":
    sys.exit(main())
