#!/usr/bin/env python
"""Fuzz loop driver (the reference's scripts/fuzz_loop.sh analog).

Usage:
    python scripts/fuzz.py                 # all fuzzers, seeds forever
    python scripts/fuzz.py lsm_tree        # one fuzzer
    python scripts/fuzz.py --seeds 50      # bounded run (CI)
    python scripts/fuzz.py --seed 1234 lsm_tree   # replay one seed

Every failure prints the fuzzer name + seed — rerun with --seed to replay
deterministically (the reference's VOPR seed-replay workflow,
docs/internals/testing.md).
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from tigerbeetle_tpu.testing.fuzz import ALL_FUZZERS  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("fuzzers", nargs="*", choices=[[], *sorted(ALL_FUZZERS)],
                   help="which fuzzers (default: all)")
    p.add_argument("--seeds", type=int, default=0,
                   help="number of seeds per fuzzer (0 = forever)")
    p.add_argument("--seed", type=int, help="replay exactly this seed")
    args = p.parse_args()
    names = args.fuzzers or sorted(ALL_FUZZERS)

    if args.seed is not None:
        for name in names:
            print(f"replay {name} seed={args.seed}")
            ALL_FUZZERS[name](args.seed)
        print("ok")
        return 0

    seed = int(time.time())
    n = 0
    while args.seeds == 0 or n < args.seeds:
        for name in names:
            t0 = time.time()
            try:
                ALL_FUZZERS[name](seed)
            except Exception:
                print(f"FAIL {name} seed={seed}", flush=True)
                raise
            print(f"ok {name} seed={seed} ({time.time() - t0:.1f}s)",
                  flush=True)
        seed += 1
        n += 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
