#!/usr/bin/env python
"""Stage-level profile of the spill-active steady state (bench cfg_spill).

Instruments SpillManager.cycle / _reload_rows / admit and the commit drain
so the ~4k TPS bill (VERDICT r4 weak #3) gets an itemized receipt:
  - cycle.d2h      gather of cold rows device->host
  - cycle.lsm      forest bulk insert (host CPU)
  - cycle.rebuild  device-side table rebuild
  - reload         LSM fetch + h2d reinsert of referenced spilled rows
  - commit         everything else (kernel dispatch + drain)

Usage: python scripts/profile_spill.py [--batches N]
"""

import argparse
import sys
import time
from collections import defaultdict

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

TIMES = defaultdict(float)
COUNTS = defaultdict(int)


def timed(name, fn):
    def wrap(*a, **kw):
        t0 = time.perf_counter()
        try:
            return fn(*a, **kw)
        finally:
            TIMES[name] += time.perf_counter() - t0
            COUNTS[name] += 1
    return wrap


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=12)
    args = ap.parse_args()

    from bench import BATCH, N_ACCOUNTS, build_accounts, build_transfers
    from tigerbeetle_tpu.constants import BATCH_PAD, TEST_CLUSTER, ConfigProcess
    from tigerbeetle_tpu.io.storage import MemoryStorage, ZoneLayout
    from tigerbeetle_tpu.lsm.grid import Grid
    from tigerbeetle_tpu.lsm.groove import Forest
    from tigerbeetle_tpu.models import spill as spill_mod
    from tigerbeetle_tpu.models.ledger import DeviceLedger
    from tigerbeetle_tpu.types import Operation

    # -- instrument the spill internals ---------------------------------
    orig_cycle = spill_mod.SpillManager.cycle
    orig_reload = spill_mod.SpillManager._reload_rows
    orig_fetch = spill_mod.SpillManager._fetch
    spill_mod.SpillManager.cycle = timed("cycle", orig_cycle)
    spill_mod.SpillManager._reload_rows = timed("reload", orig_reload)
    spill_mod.SpillManager._fetch = timed("fetch", orig_fetch)

    rng = np.random.default_rng(7)
    layout = ZoneLayout(TEST_CLUSTER, grid_size=768 * 1024 * 1024)
    forest = Forest(Grid(
        MemoryStorage(layout), offset=0, block_count=5760, cache_blocks=128,
    ), memtable_max=8192)
    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=16)
    ledger = DeviceLedger(process=process, mode="auto", forest=forest)
    ledger.pad_to = BATCH_PAD

    g = forest.transfers
    orig_bulk = type(g).insert_bulk
    type(g).insert_bulk = timed("lsm_insert_bulk", orig_bulk)
    orig_enc = type(forest.grid).encode_free_set
    type(forest.grid).encode_free_set = timed("free_set", orig_enc)

    ts2 = 1 << 41
    next_id = 1
    while next_id <= N_ACCOUNTS:
        k = min(BATCH, N_ACCOUNTS - next_id + 1)
        ts2 += k
        ledger.execute_async(Operation.create_accounts, ts2,
                             build_accounts(next_id, k))
        next_id += k

    # warm (compiles outside the timed loop)
    warm_pend = build_transfers(rng, 4_000_000, BATCH)
    warm_pend["flags"] = 2
    ts2 += BATCH
    ledger.drain(ledger.execute_async(Operation.create_transfers, ts2, warm_pend))
    wg = 0
    while ledger.spill.stats["cycles"] < 1 and wg < 8:
        warm = build_transfers(rng, 4_500_000 + wg * BATCH, BATCH)
        ts2 += BATCH
        ledger.drain(ledger.execute_async(Operation.create_transfers, ts2, warm))
        wg += 1
    warm_post = np.zeros(BATCH, dtype=warm_pend.dtype)
    warm_post["id_lo"] = np.arange(4_900_000, 4_900_000 + BATCH, dtype=np.uint64)
    warm_post["pending_id_lo"] = warm_pend["id_lo"]
    warm_post["flags"] = 4
    ts2 += BATCH
    ledger.drain(ledger.execute_async(Operation.create_transfers, ts2, warm_post))

    TIMES.clear()
    COUNTS.clear()

    nbatches = args.batches
    n_pend = max(2, nbatches // 6)
    n_post = n_pend // 2
    pend_bodies = []
    n_sp = 0
    t0 = time.perf_counter()
    for gi in range(nbatches):
        if gi < n_pend:
            b = build_transfers(rng, 6_000_000 + gi * BATCH, BATCH)
            b["flags"] = 2
            pend_bodies.append(b.copy())
        elif gi >= nbatches - n_post and pend_bodies:
            p = pend_bodies.pop(0)
            b = np.zeros(BATCH, dtype=p.dtype)
            b["id_lo"] = np.arange(8_000_000 + gi * BATCH,
                                   8_000_000 + (gi + 1) * BATCH, dtype=np.uint64)
            b["pending_id_lo"] = p["id_lo"]
            b["flags"] = 4
        else:
            b = build_transfers(rng, 6_000_000 + gi * BATCH, BATCH)
        ts2 += BATCH
        ledger.drain(ledger.execute_async(Operation.create_transfers, ts2, b))
        n_sp += BATCH
        if gi % 4 == 3:  # checkpoint cadence; drain first — the spill-IO
            ledger.spill.io_drain()  # worker mutates the same free-set
            forest.grid.encode_free_set()
    total = time.perf_counter() - t0

    print(f"\n== spill profile: {nbatches} batches, {n_sp} transfers, "
          f"{total:.2f}s total, {n_sp/total:,.0f} TPS ==")
    print(f"spill stats: {ledger.spill.stats}")
    acc = 0.0
    for name in sorted(TIMES, key=lambda k: -TIMES[k]):
        t = TIMES[name]
        if name in ("lsm_insert_bulk", "fetch"):
            continue  # nested inside cycle/reload
        acc += t
        print(f"  {name:16s} {t:8.2f}s  ({100*t/total:5.1f}%)  x{COUNTS[name]}")
    print(f"  {'(nested) lsm':16s} {TIMES['lsm_insert_bulk']:8.2f}s  x{COUNTS['lsm_insert_bulk']}")
    print(f"  {'(nested) fetch':16s} {TIMES['fetch']:8.2f}s  x{COUNTS['fetch']}")
    print(f"  {'commit+drain':16s} {total-acc:8.2f}s  ({100*(total-acc)/total:5.1f}%)")


if __name__ == "__main__":
    main()
