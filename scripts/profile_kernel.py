"""Commit-kernel timing on the live device (no d2h transfers — see
ops/hashtable.py's note: the first device->host copy permanently switches
this process to the slow dispatch path, so this script only uses
block_until_ready and prints timings, never values).

Run from the repo root: python scripts/profile_kernel.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from tigerbeetle_tpu.constants import BATCH_PAD, ConfigProcess
from tigerbeetle_tpu.models import ledger as L
from tigerbeetle_tpu.types import ACCOUNT_DTYPE, TRANSFER_DTYPE

N_ACCOUNTS = 10_000
BATCH = 8190


def main():
    probe = jax.jit(lambda x: x + 1)
    xp = jnp.ones(16384, jnp.uint32)

    def dispatch_ms(n=20):
        jax.block_until_ready(probe(xp))
        t0 = time.perf_counter()
        outs = [probe(xp) for _ in range(n)]
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / n * 1e3

    print(f"dispatch baseline:      {dispatch_ms():8.3f} ms")

    process = ConfigProcess(account_slots_log2=16, transfer_slots_log2=25)
    kern = L.LedgerKernels(process)
    state = L.init_state(process)

    arr = np.zeros(N_ACCOUNTS, dtype=ACCOUNT_DTYPE)
    arr["id_lo"] = np.arange(1, N_ACCOUNTS + 1, dtype=np.uint64)
    arr["ledger"] = 1
    arr["code"] = 1
    ts = 1 << 40
    state, r = kern.commit_accounts(
        state, L.accounts_to_batch(arr, 1 << 14), jnp.int32(N_ACCOUNTS),
        jnp.uint64(ts), mode="fast",
    )
    jax.block_until_ready(r)

    rng = np.random.default_rng(0)
    t = np.zeros(BATCH, dtype=TRANSFER_DTYPE)
    t["id_lo"] = np.arange(1, BATCH + 1, dtype=np.uint64)
    dr = rng.integers(1, N_ACCOUNTS + 1, size=BATCH, dtype=np.uint64)
    off = rng.integers(1, N_ACCOUNTS, size=BATCH, dtype=np.uint64)
    t["debit_account_id_lo"] = dr
    t["credit_account_id_lo"] = (dr - 1 + off) % N_ACCOUNTS + 1
    t["amount_lo"] = 1
    t["ledger"] = 1
    t["code"] = 1
    ev = L.transfers_to_batch(t, BATCH_PAD)
    n = jnp.int32(BATCH)

    # warmup/compile
    state, r = kern.commit_transfers(state, ev, n, jnp.uint64(ts + 10**6), mode="fast")
    jax.block_until_ready(r)

    # synced single-batch latency
    lat = []
    for i in range(10):
        t0 = time.perf_counter()
        state, r = kern.commit_transfers(
            state, ev, n, jnp.uint64(ts + 2 * 10**6 + i * 10**4), mode="fast"
        )
        jax.block_until_ready(r)
        lat.append((time.perf_counter() - t0) * 1e3)
    print(f"commit fast synced:     {np.median(lat):8.3f} ms (median of 10)")

    # async chain throughput
    t0 = time.perf_counter()
    rs = []
    for i in range(50):
        state, r = kern.commit_transfers(
            state, ev, n, jnp.uint64(ts + 3 * 10**6 + i * 10**4), mode="fast"
        )
        rs.append(r)
    jax.block_until_ready(rs)
    dt = (time.perf_counter() - t0) * 1e3
    print(f"commit fast x50 async:  {dt/50:8.3f} ms/batch -> {50*BATCH/dt*1000:,.0f} tps")
    print(f"dispatch after commits: {dispatch_ms():8.3f} ms (poison check)")


if __name__ == "__main__":
    main()
