#!/usr/bin/env python
"""The VOPR hub (reference: src/vopr_hub/ — a service that receives
crashing simulator seeds, dedupes them, replays each to confirm, and
files an issue per unique failure).

This is the single-process form: it ingests the JSONL records a fleet run
emits (`python scripts/vopr.py --seeds N --json fleet.jsonl`), groups
failures by signature (exception type + digit-normalized message — the
same crash at different ops/views is one bug), optionally REPLAYS one
representative seed per group to confirm the failure reproduces from the
seed alone, and files one markdown report per unique failure under
vopr_issues/ with the replay command.

Usage:
  python scripts/vopr.py --seeds 200 --json fleet.jsonl
  python scripts/vopr_hub.py fleet.jsonl --replay --out vopr_issues
"""

import argparse
import hashlib
import json
import re
import sys
from pathlib import Path

sys.path.insert(0, ".")


def signature(error: str) -> str:
    """Stable failure signature: exception type + message with runs of
    digits and hex collapsed (op numbers, views, checksums vary per seed;
    the SHAPE of the failure is the bug)."""
    head = error.split("\n", 1)[0][:200]
    norm = re.sub(r"0x[0-9a-fA-F]+", "0xN", head)
    norm = re.sub(r"\d+", "N", norm)
    return norm


def sig_id(sig: str) -> str:
    return hashlib.sha256(sig.encode()).hexdigest()[:12]


def ingest(path: str) -> dict[str, dict]:
    """JSONL fleet records -> {signature: {sig, records}} for failures."""
    groups: dict[str, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("ok"):
                continue
            sig = signature(rec["error"])
            g = groups.setdefault(sig, {"sig": sig, "records": []})
            g["records"].append(rec)
    return groups


def replay(rec: dict) -> tuple[bool, str | None]:
    """Re-run one failing record's seed with the SAME mode flags the
    fleet used (recorded per seed — the topology draw depends on
    device_fraction/fixed, not the seed alone)."""
    from scripts.vopr import (
        CDC_FRACTION_DEFAULT,
        FEDERATION_FRACTION_DEFAULT,
        INGRESS_FRACTION_DEFAULT,
        VERIFY_FRACTION_DEFAULT,
        run_seed,
    )

    _, _, err = run_seed(
        rec["seed"], rec["ticks"],
        device_fraction=rec.get("device_fraction", 0.0),
        fixed=rec.get(
            "fixed", rec["topology"].startswith("fixed")
        ),
        verify_fraction=rec.get(
            "verify_fraction", VERIFY_FRACTION_DEFAULT
        ),
        cdc_fraction=rec.get("cdc_fraction", CDC_FRACTION_DEFAULT),
        ingress_fraction=rec.get(
            "ingress_fraction", INGRESS_FRACTION_DEFAULT
        ),
        federation_fraction=rec.get(
            "federation_fraction", FEDERATION_FRACTION_DEFAULT
        ),
        # a fleet run with --trace recorded the stitched cluster trace
        # per seed: the replay dumps its own at a SIBLING path (failing
        # seeds dump in the simulator's finally) — never the fleet's
        # path, which is exactly the artifact a diverging replay must
        # still be diffable against
        trace_path=(
            f"{rec['trace']}.replay.json" if rec.get("trace") else None
        ),
    )
    return err is not None, err


def file_report(group: dict, out_dir: Path,
                replay_result: tuple[bool, str | None] | None) -> Path:
    sid = sig_id(group["sig"])
    recs = group["records"]
    path = out_dir / f"{sid}.md"
    lines = [
        f"# VOPR failure {sid}",
        "",
        f"**Signature:** `{group['sig']}`",
        f"**Seeds:** {len(recs)} "
        f"({', '.join(str(r['seed']) for r in recs[:12])}"
        f"{', ...' if len(recs) > 12 else ''})",
        "",
    ]
    if replay_result is not None:
        ok, err = replay_result
        lines += [
            f"**Replay:** {'REPRODUCED' if ok else 'did NOT reproduce'}"
            + (f" — `{(err or '')[:160]}`" if ok else ""),
            "",
        ]
    lines += ["## Per-seed detail", ""]
    for r in recs[:20]:
        extra = ""
        if r.get("device_fraction"):
            extra += f" --device-fraction {r['device_fraction']}"
        vf = r.get("verify_fraction")
        if vf is not None:
            # always explicit: the replay must not depend on the CURRENT
            # default matching the fleet's (the drift this field exists
            # to prevent)
            extra += f" --verify-fraction {vf}"
        if r.get("fixed"):
            extra += " --fixed"
        lines += [
            f"- seed `{r['seed']}` ticks={r['ticks']} "
            f"[{r['topology']}]: `{r['error'][:200]}`",
            f"  replay: `python scripts/vopr.py --start {r['seed']} "
            f"--seeds 1 --ticks {r['ticks']}{extra}`",
        ]
    path.write_text("\n".join(lines) + "\n")
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("fleet_jsonl")
    ap.add_argument("--out", default="vopr_issues")
    ap.add_argument("--replay", action="store_true",
                    help="replay one seed per unique failure to confirm")
    args = ap.parse_args()

    groups = ingest(args.fleet_jsonl)
    if not groups:
        print("no failures in fleet log")
        return 0
    out_dir = Path(args.out)
    out_dir.mkdir(exist_ok=True)
    for sig, group in sorted(groups.items()):
        rr = replay(group["records"][0]) if args.replay else None
        path = file_report(group, out_dir, rr)
        print(f"{sig_id(sig)}: {len(group['records'])} seed(s) -> {path}")
    print(f"{len(groups)} unique failure(s) filed in {out_dir}/")
    return 2  # failures exist

if __name__ == "__main__":
    raise SystemExit(main())
