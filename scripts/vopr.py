#!/usr/bin/env python
"""The VOPR fleet runner (reference: src/vopr.zig): run batches of
simulator seeds, report failures with their replay seed.

Usage: python scripts/vopr.py [--seeds N] [--start S] [--ticks T] [--device]
"""

import argparse
import sys
import time

sys.path.insert(0, ".")
import tests.conftest  # noqa: F401, E402 — CPU platform before jax init

from tigerbeetle_tpu.testing.simulator import run_simulation  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=20)
    ap.add_argument("--start", type=int, default=1)
    ap.add_argument("--ticks", type=int, default=1000)
    ap.add_argument("--device", action="store_true",
                    help="device-ledger backend (slow)")
    args = ap.parse_args()

    failures = []
    t0 = time.time()
    for seed in range(args.start, args.start + args.seeds):
        kw = {}
        if args.device:
            kw["backend_factory"] = None
            kw["n_clients"] = 1
        try:
            stats = run_simulation(seed, ticks=args.ticks, **kw)
            print(
                f"seed {seed:6d} ok: committed={stats['committed_ops']:5d} "
                f"replies={stats['replies']:5d} crashes={stats['crashes']} "
                f"wal_faults={stats['wal_faults']} view={stats['view']}"
            )
        except Exception as e:  # noqa: BLE001 — report and continue the fleet
            failures.append(seed)
            print(f"seed {seed:6d} FAIL: {type(e).__name__}: {str(e)[:160]}")
    dt = time.time() - t0
    print(f"\n{args.seeds - len(failures)}/{args.seeds} passed in {dt:.0f}s")
    if failures:
        print(f"replay failures with: python scripts/vopr.py --start <seed> --seeds 1")
        print(f"failing seeds: {failures}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
