#!/usr/bin/env python
"""The VOPR fleet runner (reference: src/vopr.zig + src/simulator.zig:66-152):
run batches of simulator seeds, each with a seed-derived random topology
(1-6 replicas, 0-2 standbys, 1-8 clients) and fault mix (partitions, torn
writes, WAL/replies/superblock faults combined; a slice of seeds runs the
device backend with grid faults). Failures report their replay seed — the
seed alone reproduces topology, workload, and fault schedule.

Usage: python scripts/vopr.py [--seeds N] [--start S] [--ticks T]
         [--device-fraction F] [--fixed] [--json PATH]

--fixed pins the legacy 3-replica/2-client topology (pre-round-5 behavior)
for bisecting topology-dependent failures; --json appends one record per
seed for the VOPR hub (scripts/vopr_hub.py).
"""

import argparse
import json
import sys
import time
import traceback

sys.path.insert(0, ".")
import tests.conftest  # noqa: F401, E402 — CPU platform before jax init

from tigerbeetle_tpu.testing.simulator import (  # noqa: E402
    describe_options,
    random_options,
    run_simulation,
)


VERIFY_FRACTION_DEFAULT = 0.25
CDC_FRACTION_DEFAULT = 0.2
INGRESS_FRACTION_DEFAULT = 0.15
FEDERATION_FRACTION_DEFAULT = 0.1


def run_seed(seed: int, ticks: int, device_fraction: float,
             fixed: bool,
             verify_fraction: float = VERIFY_FRACTION_DEFAULT,
             cdc_fraction: float = CDC_FRACTION_DEFAULT,
             ingress_fraction: float = INGRESS_FRACTION_DEFAULT,
             federation_fraction: float = FEDERATION_FRACTION_DEFAULT,
             trace_path: str | None = None,
             hash_log: tuple[str, str] | None = None,
             ) -> tuple[dict | None, str, str | None]:
    """(stats, topology-line, error) for one seed. A `verify_fraction`
    slice of seeds runs with the intensive online-verification tier
    (constants.VERIFY — reference src/constants.zig:592): hash-chain
    re-checks at commit, LSM level audits, journal read-after-write,
    oracle conservation audits. A `cdc_fraction` slice runs the
    deterministic CDC consumer (crash/restart schedule seeded, checker
    proves no gaps / no duplicated effects). An `ingress_fraction` slice
    runs the ingress gateway on every replica (busy-shed admission), a
    seeded connect storm, and the 3-consumer CDC fan-out hub with one
    throttled consumer (backpressure isolation under the fault mix).
    A `federation_fraction` slice takes the seed WHOLE: the two-region
    cross-ledger scenario (federation/sim.py — seeded settlement-agent
    crash/restart, one region killed wholesale mid-settlement,
    conservation + commitment-stream verification on recovery)."""
    from tigerbeetle_tpu import constants

    if not fixed and (
        (seed * 3266489917 % 100) < federation_fraction * 100
    ):
        # exclusive slice, distinct multiplier (xxhash PRIME32_3)
        # decorrelating the draw from the VERIFY/CDC/INGRESS ones; the
        # composite runs its own per-region Simulators, so the usual
        # topology draw does not apply
        from tigerbeetle_tpu.federation.sim import run_federation_sim

        desc = "FED 2-region agent-crash region-kill"
        try:
            # the settlement drain needs room: floor the tick budget
            return (
                run_federation_sim(seed, ticks=max(ticks, 1200)),
                desc, None,
            )
        except Exception as e:  # noqa: BLE001 — report, continue fleet
            frame = traceback.extract_tb(e.__traceback__)[-1]
            return None, desc, (
                f"{type(e).__name__}: {e} "
                f"[{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}]"
            )

    if fixed:
        opts: dict = {}
        desc = "fixed r3+s0 c2 oracle"
        verify = cdc = False
    else:
        opts = random_options(seed, device_fraction=device_fraction)
        verify = (seed * 2654435761 % 100) < verify_fraction * 100
        # a distinct multiplier decorrelates the CDC draw from VERIFY's
        cdc = (seed * 2246822519 % 100) < cdc_fraction * 100
        # ...and a third (FNV prime) decorrelates the ingress slice
        ingress = (seed * 2166136261 % 100) < ingress_fraction * 100
        desc = describe_options(opts) + (" VERIFY" if verify else "")
        if cdc:
            desc += " CDC"
            opts["cdc_consumer"] = True
        if ingress and opts.get("backend_factory", "x") is not None:
            # oracle seeds only: the device slice's tick budget is too
            # tight for storm registrations + fan-out draining
            desc += " INGRESS"
            opts["ingress_gateway"] = True
            opts["storm_clients"] = 4 + seed % 8
            opts["cdc_fanout"] = 3
    kw = {"ticks": ticks, **opts}
    if hash_log is not None:
        # record-then-check divergence debugging (testing/hash_log.py;
        # the reference's -Dhash-log-mode): first run of a seed records
        # its committed prepare/reply checksum stream, a replay checks
        # and dies AT the first divergent op
        kw["hash_log"] = hash_log
        desc += f" HASHLOG[{hash_log[0]}]"
    if trace_path is not None:
        # deterministic tick-stamped trace (tracer.SimTracer): the same
        # seed dumps byte-identical files, so two replays of a diverging
        # seed can be diffed span by span
        kw["trace_path"] = trace_path
    prev, constants.VERIFY = constants.VERIFY, verify or constants.VERIFY
    try:
        return run_simulation(seed, **kw), desc, None
    except Exception as e:  # noqa: BLE001 — report and continue the fleet
        frame = traceback.extract_tb(e.__traceback__)[-1]
        return None, desc, (
            f"{type(e).__name__}: {e} "
            f"[{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}]"
        )
    finally:
        constants.VERIFY = prev


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=20)
    ap.add_argument("--start", type=int, default=1)
    ap.add_argument("--ticks", type=int, default=1000)
    ap.add_argument("--device-fraction", type=float, default=0.0,
                    help="fraction of seeds on the DeviceLedger backend "
                         "with grid faults (slow; needs jax)")
    ap.add_argument("--verify-fraction", type=float,
                    default=VERIFY_FRACTION_DEFAULT,
                    help="fraction of seeds run with the intensive "
                         "online-verification tier (constants.VERIFY)")
    ap.add_argument("--cdc-fraction", type=float,
                    default=CDC_FRACTION_DEFAULT,
                    help="fraction of seeds run with the deterministic "
                         "CDC consumer (crash/restart + stream checker)")
    ap.add_argument("--ingress-fraction", type=float,
                    default=INGRESS_FRACTION_DEFAULT,
                    help="fraction of seeds run with the ingress gateway, "
                         "a seeded connect storm, and the CDC fan-out hub "
                         "(throttled-consumer isolation)")
    ap.add_argument("--federation-fraction", type=float,
                    default=FEDERATION_FRACTION_DEFAULT,
                    help="fraction of seeds run as the two-region "
                         "cross-ledger federation scenario (settlement "
                         "agent crash/restart + region-wide kill)")
    ap.add_argument("--fixed", action="store_true",
                    help="legacy fixed topology (3 replicas / 2 clients)")
    ap.add_argument("--json", default=None,
                    help="append one JSON record per seed (vopr_hub input)")
    ap.add_argument("--trace", default=None,
                    help="dump a deterministic tick-stamped Chrome trace "
                         "per seed to PATH.<seed>.json (byte-identical "
                         "across replays of the same seed — diffable)")
    ap.add_argument("--hash-log", default=None, metavar="PREFIX",
                    help="per-seed hash-log at PREFIX.<seed>.jsonl: a "
                         "seed with no recording RECORDS its committed "
                         "prepare/reply checksum stream; a seed whose "
                         "recording exists CHECKS against it and fails "
                         "at the first divergent op (dual-mode parity "
                         "debugging outside the bench harness)")
    args = ap.parse_args()

    failures = []
    sink = open(args.json, "a") if args.json else None
    t0 = time.time()
    for seed in range(args.start, args.start + args.seeds):
        hash_log = None
        if args.hash_log:
            import os

            hl_path = f"{args.hash_log}.{seed}.jsonl"
            hash_log = (
                "check" if os.path.exists(hl_path) else "record", hl_path
            )
        stats, desc, err = run_seed(
            seed, args.ticks, args.device_fraction, args.fixed,
            verify_fraction=args.verify_fraction,
            cdc_fraction=args.cdc_fraction,
            ingress_fraction=args.ingress_fraction,
            federation_fraction=args.federation_fraction,
            trace_path=(
                f"{args.trace}.{seed}.json" if args.trace else None
            ),
            hash_log=hash_log,
        )
        if err is None and "FED" in desc:
            print(
                f"seed {seed:6d} ok [{desc}]: "
                f"committed={stats['committed_ops']} "
                f"issued={stats['issued']} settled={stats['settled']} "
                f"voided={stats['voided']} "
                f"agent_crashes={stats['agent_crashes']} "
                f"killed=r{stats['region_killed']} "
                f"lag={stats['settlement_lag_max_ops']}"
            )
        elif err is None:
            print(
                f"seed {seed:6d} ok [{desc}]: "
                f"committed={stats['committed_ops']:5d} "
                f"replies={stats['replies']:5d} crashes={stats['crashes']} "
                f"wal_faults={stats['wal_faults']} "
                f"torn={stats['torn_writes']} "
                f"grid={stats['grid_faults']} view={stats['view']}"
            )
        else:
            failures.append(seed)
            print(f"seed {seed:6d} FAIL [{desc}]: {err[:240]}")
        if sink:
            rec = {"seed": seed, "ticks": args.ticks, "topology": desc,
                   "device_fraction": args.device_fraction,
                   # the VERIFY/CDC-slice draws depend on their fractions,
                   # not the seed alone: record them so hub replays stay
                   # reproducible if the defaults ever change
                   "verify_fraction": args.verify_fraction,
                   "cdc_fraction": args.cdc_fraction,
                   "ingress_fraction": args.ingress_fraction,
                   "federation_fraction": args.federation_fraction,
                   "fixed": args.fixed, "ok": err is None}
            if args.trace:
                # the hub replay re-records the stitched cluster trace
                # at the same path, so a confirmed failure ships with a
                # diffable trace artifact
                rec["trace"] = f"{args.trace}.{seed}.json"
            rec["error" if err else "stats"] = err or stats
            sink.write(json.dumps(rec) + "\n")
            sink.flush()
    dt = time.time() - t0
    print(f"\n{args.seeds - len(failures)}/{args.seeds} passed in {dt:.0f}s")
    if failures:
        # the replay must carry the SAME mode flags — the seed's topology
        # draw depends on device_fraction/fixed, not the seed alone
        extra = ""
        if args.device_fraction:
            extra += f" --device-fraction {args.device_fraction}"
        if args.verify_fraction != VERIFY_FRACTION_DEFAULT:
            extra += f" --verify-fraction {args.verify_fraction}"
        if args.cdc_fraction != CDC_FRACTION_DEFAULT:
            extra += f" --cdc-fraction {args.cdc_fraction}"
        if args.ingress_fraction != INGRESS_FRACTION_DEFAULT:
            extra += f" --ingress-fraction {args.ingress_fraction}"
        if args.federation_fraction != FEDERATION_FRACTION_DEFAULT:
            extra += f" --federation-fraction {args.federation_fraction}"
        if args.fixed:
            extra += " --fixed"
        print("replay failures with: python scripts/vopr.py "
              f"--start <seed> --seeds 1 --ticks {args.ticks}{extra}")
        print(f"failing seeds: {failures}")
    if sink:
        sink.close()
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
