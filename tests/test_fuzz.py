"""Bounded runs of every per-structure fuzzer (tier 4; reference:
build.zig:508-558 fuzz targets). `scripts/fuzz.py` runs the unbounded
loop; this tier pins a few seeds per structure so regressions surface in
CI time."""

import pytest

from tigerbeetle_tpu.testing.fuzz import ALL_FUZZERS


@pytest.mark.parametrize("name", sorted(ALL_FUZZERS))
@pytest.mark.parametrize("seed", [1, 2])
def test_fuzz(name, seed):
    ALL_FUZZERS[name](seed)
