"""The unified observability layer: metrics registry, batched StatsD,
tracer ring/determinism, and the no-op overhead budget.

Covers the PR-4 contracts:
- histogram bucket/percentile math (fixed power-of-two buckets, clamped
  percentiles);
- batched StatsD datagrams (many metrics per MTU-sized packet, counters
  as deltas) captured via a local UDP socket, plus the --statsd address
  parsing fix (`host`, `:port`, `host:port`);
- JsonTracer ring behavior (overwrite oldest at capacity; open spans
  emitted as incomplete events at dump) and a Chrome trace-event schema
  check;
- deterministic simulator tracer: same VOPR seed twice -> byte-identical
  dumps, and tracing leaves the committed history unchanged;
- CI smoke: a cluster tick loop with the `none` backend plus a measured
  no-op span enter/exit budget, so the hot paths can keep their spans
  permanently.
"""

import hashlib
import json
import socket
import time

import pytest

from tigerbeetle_tpu.metrics import NULL_METRICS, Metrics
from tigerbeetle_tpu.statsd import StatsD, StatsDEmitter, parse_addr
from tigerbeetle_tpu.tracer import NULL_TRACER, JsonTracer


# -- regression: cross-thread metric writes must not lose updates ------
# (vet's races pass found the unguarded `value += v`: the WAL writer
# pool, the spill IO worker, and the device-shadow loop all add into the
# same registry counters the event loop is adding into — a thread switch
# between a counter's read and store silently dropped increments)


def test_counter_and_histogram_survive_concurrent_writers():
    import sys
    import threading

    m = Metrics()
    counter = m.counter("races.counter")
    hist = m.histogram("races.hist")
    threads_n, per_thread = 8, 5_000
    start = threading.Barrier(threads_n)

    def hammer():
        start.wait()
        for i in range(per_thread):
            counter.add()
            hist.observe(float(i % 64))

    threads = [threading.Thread(target=hammer) for _ in range(threads_n)]
    old_interval = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)  # force preemption inside the +=
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sys.setswitchinterval(old_interval)
    expect = threads_n * per_thread
    assert counter.value == expect
    assert hist.count == expect
    assert sum(hist.counts) == expect


# -- satellite: StatsD address parsing ---------------------------------


def test_statsd_addr_parsing():
    assert parse_addr("statsd.example.com") == ("statsd.example.com", 8125)
    assert parse_addr(":9125") == ("127.0.0.1", 9125)
    assert parse_addr("10.0.0.7:9125") == ("10.0.0.7", 9125)
    assert parse_addr("10.0.0.7:") == ("10.0.0.7", 8125)
    assert parse_addr("") == ("127.0.0.1", 8125)
    assert parse_addr(" host ") == ("host", 8125)


# -- histogram bucket / percentile math --------------------------------


def test_histogram_buckets_and_percentiles():
    m = Metrics()
    h = m.histogram("t", unit="us")
    for _ in range(90):
        h.observe(1.0)
    for _ in range(10):
        h.observe(1000.0)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["max"] == 1000.0
    assert snap["mean"] == pytest.approx((90 + 10 * 1000) / 100, rel=1e-6)
    # p50 falls in the bucket holding 1.0 (upper bound 2); p95/p99 fall in
    # the 1000 bucket (upper bound 1024) but clamp to the observed max
    assert snap["p50"] <= 2.0
    assert snap["p95"] == 1000.0
    assert snap["p99"] == 1000.0
    # monotone
    assert snap["p50"] <= snap["p95"] <= snap["p99"] <= snap["max"]
    # empty histogram snapshots cleanly
    assert m.histogram("empty").snapshot()["count"] == 0


def test_stat_group_is_dict_compatible():
    m = Metrics()
    g = m.group("spill", ("cycles", "t_scan"))
    g.add("cycles")
    g.add("t_scan", 0.5)
    assert g["cycles"] == 1
    assert dict(g) == {"cycles": 1, "t_scan": 0.5}
    assert g.get("cycles") == 1
    assert sorted(g.items()) == [("cycles", 1), ("t_scan", 0.5)]
    # the group IS the registry: same counter object
    assert m.counter("spill.cycles").value == 1
    assert m.snapshot()["counters"]["spill.cycles"] == 1


# -- batched StatsD emission -------------------------------------------


def _udp_sink():
    sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sink.bind(("127.0.0.1", 0))
    sink.settimeout(2)
    return sink, sink.getsockname()[1]


def test_batched_statsd_datagrams():
    sink, port = _udp_sink()
    s = StatsD("127.0.0.1", port, prefix="tb")
    m = Metrics()
    for i in range(40):
        m.counter(f"c{i:02d}").add(i + 1)
    m.gauge("commit_min").set(17)
    h = m.histogram("lat")
    h.observe(100.0)
    em = StatsDEmitter(s, m)
    n = em.flush()
    # MANY metrics per datagram: 40 counters + 1 gauge + 4 histogram
    # stats in far fewer packets than metrics
    assert 1 <= n < 10
    lines = []
    for _ in range(n):
        payload = sink.recv(4096).decode()
        assert len(payload) <= 1400
        lines.extend(payload.split("\n"))
    assert "tb.c04:5|c" in lines
    assert "tb.commit_min:17|g" in lines
    assert any(line.startswith("tb.lat.p50:") for line in lines)
    # every line is well-formed statsd
    for line in lines:
        name_val, _, kind = line.rpartition("|")
        assert kind in ("c", "g", "ms"), line
        assert ":" in name_val, line
    # second flush: counters unchanged -> deltas suppressed (only the
    # gauge + histogram stats go out, in one datagram)
    n2 = em.flush()
    assert n2 == 1
    payload = sink.recv(4096).decode()
    assert not any("|c" in ln for ln in payload.split("\n"))
    # counters move again -> delta (not the absolute) is emitted
    m.counter("c00").add(3)
    em.flush()
    payload = sink.recv(4096).decode()
    assert "tb.c00:3|c" in payload.split("\n")
    s.close()
    sink.close()


# -- tracer ring + incomplete spans + schema ---------------------------


def test_json_tracer_ring_overwrites_oldest():
    tr = JsonTracer(capacity=4)
    for i in range(10):
        with tr.span("s", i=i):
            pass
    events = tr.events_ordered()
    assert len(events) == 4
    # the NEWEST events survive, oldest-first order
    assert [e["args"]["i"] for e in events] == [6, 7, 8, 9]


def test_json_tracer_emits_open_spans_as_incomplete(tmp_path):
    tr = JsonTracer()
    tr.start("open_span", op=1)  # never stopped
    with tr.span("closed"):
        pass
    path = str(tmp_path / "trace.json")
    tr.dump(path)
    events = json.load(open(path))["traceEvents"]
    by_name = {e["name"]: e for e in events}
    assert by_name["closed"]["ph"] == "X"
    assert by_name["open_span"]["ph"] == "B"  # incomplete, not dropped
    assert "dur" not in by_name["open_span"]


def _assert_chrome_trace_schema(events):
    assert isinstance(events, list) and events
    for e in events:
        assert isinstance(e["name"], str) and e["name"]
        # X/B: span events; s/t/f: generated flow events (stitched
        # cluster traces); M: process_name metadata
        assert e["ph"] in ("X", "B", "s", "t", "f", "M")
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0
        if e["ph"] in ("s", "t", "f"):
            assert isinstance(e["id"], str) and e["id"]


def test_trace_schema_from_real_pipeline(tmp_path):
    """A cluster commit loop traced end to end dumps valid Chrome
    trace-event JSON containing the commit-pipeline spans, and the
    pipeline stats are sourced from the shared registry."""
    import numpy as np

    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Operation

    metrics = Metrics()
    tracer = JsonTracer(metrics=metrics)
    cluster = Cluster(replica_count=1,
                      backend_factory=OracleStateMachine,
                      metrics=metrics, tracer=tracer)
    client = cluster.add_client()
    acct = np.zeros(2, dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = [1, 2]
    acct["ledger"] = 1
    acct["code"] = 1
    cluster.execute(client, Operation.create_accounts, acct.tobytes())
    for i in range(3):
        t = np.zeros(1, dtype=types.TRANSFER_DTYPE)
        t["id_lo"] = 100 + i
        t["debit_account_id_lo"] = 1
        t["credit_account_id_lo"] = 2
        t["amount_lo"] = 1
        t["ledger"] = 1
        t["code"] = 1
        cluster.execute(client, Operation.create_transfers, t.tobytes())
    cluster.run_ticks(5)
    path = str(tmp_path / "pipeline_trace.json")
    tracer.dump(path)
    events = json.load(open(path))["traceEvents"]
    _assert_chrome_trace_schema(events)
    names = {e["name"] for e in events}
    assert {"replica.commit_dispatch", "replica.commit_finalize",
            "journal.write_prepare"} <= names
    # registry-sourced pipeline stats: the replica's group_stats Mapping
    # IS the registry store
    r = cluster.replicas[0]
    snap = r.metrics.snapshot()
    assert snap["counters"]["commit.group.solo_ops"] == (
        r.group_stats["solo_ops"]
    )
    assert snap["histograms"]["replica.commit_dispatch_us"]["count"] >= 4
    # span durations fed histograms through the tracer's metrics hookup
    assert snap["histograms"]["span.replica.commit_dispatch"]["count"] >= 4
    # name-coverage drift guard: every counter/gauge a real commit
    # pipeline registers must be CATALOG'd (the end-to-end [stats]
    # surface gets the same check against a spawned server in
    # tests/test_inspect.py)
    from tigerbeetle_tpu.metrics import CATALOG

    emitted = set(snap["counters"]) | set(snap["gauges"])
    missing = emitted - set(CATALOG)
    assert not missing, f"registry names missing from CATALOG: {missing}"


def test_trace_and_inspect_metric_names_cataloged():
    """The observability layer's own names follow the same contract
    every subsystem's names do (the cdc.*/ingress.* checks below):
    present in CATALOG with a kind, unit and help string."""
    from tigerbeetle_tpu.metrics import CATALOG

    for name in ("trace.sigquit_dumps", "inspect.live_requests"):
        assert name in CATALOG, name
        kind, unit, help_ = CATALOG[name]
        assert kind == "counter"
        assert help_


def test_client_runtime_and_chaos_metric_names_cataloged():
    """Name-coverage drift guard for the client runtime + live chaos
    harness: every counter a Client binds (the pinned name list the
    runtime exports) and every chaos.*/bus reconnect name the harness
    emits must be CATALOG'd — and the binding itself must stay in sync
    with the pinned list (a renamed counter fails here, not in prod)."""
    from tigerbeetle_tpu.io.network import InProcessNetwork
    from tigerbeetle_tpu.metrics import CATALOG, Metrics
    from tigerbeetle_tpu.vsr.client import CLIENT_METRIC_NAMES, Client

    for name in CLIENT_METRIC_NAMES:
        assert name in CATALOG, name
        kind, _unit, help_ = CATALOG[name]
        assert kind == "counter" and help_
    # the runtime's actual bindings == the pinned list
    m = Metrics()
    Client(0xC0, InProcessNetwork(), 1, metrics=m)
    bound = {n for n in m.snapshot()["counters"] if n.startswith("client.")}
    assert bound == set(CLIENT_METRIC_NAMES)
    for name in ("chaos.kills", "chaos.restarts", "chaos.gray_stops",
                 "chaos.conn_resets", "bus.reconnects",
                 "bus.dial_failures", "ingress.passthrough_backup"):
        assert name in CATALOG, name
        assert CATALOG[name][0] == "counter"
    assert CATALOG["chaos.recovery_ms"][0] == "histogram"


# -- deterministic simulator tracer ------------------------------------


def _histories_digest(sim) -> str:
    out = [
        sorted((op, rec[0]) for op, rec in h.items())
        for h in sim.histories
    ]
    return hashlib.sha256(repr(out).encode()).hexdigest()


def test_sim_tracer_reproducible_and_pure(tmp_path):
    """Same VOPR seed twice -> byte-identical STITCHED trace dumps
    (tick-based timestamps, one pid per replica, canonical JSON incl.
    the generated flow events); enabling tracing leaves the committed
    history unchanged vs an untraced run of the same seed."""
    from tigerbeetle_tpu.testing.simulator import Simulator

    p1 = str(tmp_path / "t1.json")
    p2 = str(tmp_path / "t2.json")
    s1 = Simulator(4242, ticks=300, trace_path=p1)
    s1.run()
    s2 = Simulator(4242, ticks=300, trace_path=p2)
    s2.run()
    b1 = open(p1, "rb").read()
    assert b1 == open(p2, "rb").read()
    events = json.loads(b1)["traceEvents"]
    _assert_chrome_trace_schema(events)
    # tick timestamps, not wall time: every ts is a whole tick count far
    # below any perf_counter_ns value
    assert all(e["ts"] == int(e["ts"]) for e in events)
    # the stitched cluster trace spans multiple replica pids and carries
    # cross-pid flow events linking each op's legs
    span_pids = {e["pid"] for e in events if e["ph"] in ("X", "B")}
    assert len(span_pids) >= 2, span_pids
    flow_ids = {e["id"] for e in events if e["ph"] in ("s", "t", "f")}
    assert flow_ids, "no op flows in the stitched sim trace"
    s3 = Simulator(4242, ticks=300)  # tracing off
    s3.run()
    assert _histories_digest(s1) == _histories_digest(s3)


# -- CI smoke: none-backend overhead budget ----------------------------


def test_noop_span_overhead_budget():
    """The hot paths keep their spans permanently: with the `none`
    backend a span enter/exit must stay well under the ~1us budget
    (measured ~0.5us on the CI box; min-of-5 guards against scheduler
    noise)."""
    tr = NULL_TRACER
    n = 50_000
    per_run = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("x"):
                pass
        per_run.append((time.perf_counter() - t0) / n * 1e6)
    assert min(per_run) < 1.5, f"no-op span enter/exit too slow: {per_run}"
    # the no-op metrics backend allocates nothing per event
    h = NULL_METRICS.histogram("x")
    assert h is NULL_METRICS.histogram("y")
    c = NULL_METRICS.counter("x")
    assert c is NULL_METRICS.counter("y")
    t0 = time.perf_counter()
    for _ in range(n):
        with h.time():
            pass
        c.add()
    per = (time.perf_counter() - t0) / n * 1e6
    assert per < 3.0, f"no-op metrics too slow: {per}"


def test_none_backend_commit_loop_smoke():
    """A short bench-segment-shaped commit loop (oracle cluster, default
    `none` tracer + per-replica registry) runs with instrumentation
    permanently wired and commits everything."""
    import numpy as np

    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Operation

    cluster = Cluster(replica_count=1, backend_factory=OracleStateMachine)
    client = cluster.add_client()
    acct = np.zeros(2, dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = [1, 2]
    acct["ledger"] = 1
    acct["code"] = 1
    cluster.execute(client, Operation.create_accounts, acct.tobytes())
    n_batches, batch = 8, 16
    for g in range(n_batches):
        t = np.zeros(batch, dtype=types.TRANSFER_DTYPE)
        t["id_lo"] = np.arange(1000 + g * batch, 1000 + (g + 1) * batch,
                               dtype=np.uint64)
        t["debit_account_id_lo"] = 1
        t["credit_account_id_lo"] = 2
        t["amount_lo"] = 1
        t["ledger"] = 1
        t["code"] = 1
        _, body = cluster.execute(
            client, Operation.create_transfers, t.tobytes()
        )
        assert body == b""  # all events succeeded
    cluster.run_ticks(10)
    r = cluster.replicas[0]
    assert r.commit_min >= n_batches + 2  # register + accounts + batches
    # the default tracer is the none backend: no spans were recorded,
    # but the always-on registry counted the pipeline
    assert r.tracer is NULL_TRACER or not r.tracer.enabled
    assert r.metrics.histogram("replica.commit_dispatch_us").count >= (
        n_batches
    )


# -- CDC metric names are cataloged (units included) -------------------


def test_cdc_metric_names_all_cataloged():
    """Every metric a CdcPump run creates must be in metrics.CATALOG so
    the [stats] line and --statsd emit them without unknown-metric
    fallbacks (the pump's names are the CATALOG's cdc.* section)."""
    import numpy as np

    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.cdc import CdcPump, MemoryCursor, MemorySink
    from tigerbeetle_tpu.metrics import CATALOG
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Operation

    cluster = Cluster(replica_count=1, backend_factory=OracleStateMachine)
    r = cluster.replicas[0]
    # a resuming pump with a poisoned cursor also creates the
    # resume-fork counter — exercise that path too
    cursor = MemoryCursor()
    pump = CdcPump(r, MemorySink(), cursor, ack_interval=1)
    pump.attach()
    client = cluster.add_client()
    acct = np.zeros(2, dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = [1, 2]
    acct["ledger"] = 1
    acct["code"] = 1
    cluster.execute(client, Operation.create_accounts, acct.tobytes())
    while pump.pump():
        pass
    pump.detach()
    cursor.checksum = 0xBAD  # checksum that cannot match the log
    pump2 = CdcPump(r, MemorySink(), cursor)
    pump2.attach()
    import io
    import sys as _sys

    err = io.StringIO()
    orig, _sys.stderr = _sys.stderr, err
    try:
        pump2.pump()
    finally:
        _sys.stderr = orig
    assert "mismatch" in err.getvalue()
    snap = r.metrics.snapshot()
    cdc_names = {
        n
        for section in ("counters", "gauges", "histograms")
        for n in snap[section]
        if n.startswith("cdc.")
    }
    assert cdc_names  # the pump really reported here
    missing = cdc_names - set(CATALOG)
    assert not missing, f"cdc metrics missing from CATALOG: {missing}"
    # and the catalog entries carry units + kinds like the rest
    for name in cdc_names:
        kind, unit, help_ = CATALOG[name]
        assert kind in ("counter", "gauge", "histogram")
        assert isinstance(unit, str) and help_


# -- ingress metric names are cataloged (units included) ---------------


def test_ingress_metric_names_all_cataloged():
    """Every metric the ingress gateway + fan-out hub create must be in
    metrics.CATALOG (the name-coverage contract the cdc.* test enforces,
    extended to the ingress.* section). The bus-side ingress names
    (accepts, shed_conn, shed_pool, disconnect_wedged) are asserted
    statically — they are emitted by the TCP front door, which this
    in-process run does not exercise."""
    import numpy as np

    from tigerbeetle_tpu import types
    from tigerbeetle_tpu.cdc import MemoryCursor, MemorySink
    from tigerbeetle_tpu.ingress import CdcFanoutHub, IngressGateway
    from tigerbeetle_tpu.metrics import CATALOG, Metrics
    from tigerbeetle_tpu.models.oracle import OracleStateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Operation

    m = Metrics()
    cluster = Cluster(
        replica_count=1, backend_factory=OracleStateMachine, metrics=m
    )
    r = cluster.replicas[0]
    gw = IngressGateway(cluster.network, r, sessions_max=4)
    gw.install()
    hub = CdcFanoutHub(r, window=16)
    hub.add_consumer("a", MemorySink(), MemoryCursor())
    hub.add_consumer("b", MemorySink(), MemoryCursor())
    hub.attach()
    client = cluster.add_client()
    acct = np.zeros(2, dtype=types.ACCOUNT_DTYPE)
    acct["id_lo"] = [1, 2]
    acct["ledger"] = 1
    acct["code"] = 1
    cluster.execute(client, Operation.create_accounts, acct.tobytes())
    hub.pump(budget_ops=16)
    # exercise the shed + retransmit counters too
    orig = r.ingress_occupancy
    r.ingress_occupancy = lambda: (99, 8)
    gw.regulator.drain()
    t = np.zeros(1, dtype=types.TRANSFER_DTYPE)
    t["id_lo"] = 9
    t["debit_account_id_lo"] = 1
    t["credit_account_id_lo"] = 2
    t["amount_lo"] = 1
    t["ledger"] = 1
    t["code"] = 1
    client.request(Operation.create_transfers, t.tobytes())
    cluster.network.run()
    r.ingress_occupancy = orig
    gw.regulator.drain()
    client.resend()
    cluster.network.run()
    client.take_reply()

    snap = m.snapshot()
    ingress_names = {
        n
        for section in ("counters", "gauges", "histograms")
        for n in snap[section]
        if n.startswith("ingress.")
    }
    assert ingress_names  # the gateway + hub really reported here
    missing = ingress_names - set(CATALOG)
    assert not missing, f"ingress metrics missing from CATALOG: {missing}"
    # the TCP front door's names (not exercised in-process) are cataloged
    for name in ("ingress.accepts", "ingress.shed_conn",
                 "ingress.shed_pool", "ingress.disconnect_wedged"):
        assert name in CATALOG, name
    for name in ingress_names | {"ingress.accepts"}:
        kind, unit, help_ = CATALOG[name]
        assert kind in ("counter", "gauge", "histogram")
        assert isinstance(unit, str) and help_


# -- flight recorder: time-series snapshot ring ------------------------


def test_flight_recorder_deltas_and_windowed_percentiles():
    """Each record() entry carries counter DELTAS (zero deltas dropped),
    raw gauges, and WINDOWED histogram percentiles computed from the
    bucket-count deltas — the per-interval evidence a cumulative
    snapshot cannot give (a one-interval p99 spike must show in that
    interval's entry, not be diluted into the lifetime percentile)."""
    from tigerbeetle_tpu.metrics import FlightRecorder

    m = Metrics()
    c = m.counter("ops")
    h = m.histogram("lat")
    fr = FlightRecorder(m, capacity=4)

    c.add(10)
    for _ in range(100):
        h.observe(10.0)
    e1 = fr.record(1.0)
    assert e1["dt"] is None  # first entry has no previous interval
    assert e1["counters"]["ops"] == 10
    assert e1["histograms"]["lat"]["count"] == 100
    assert e1["histograms"]["lat"]["p99"] <= 16.0  # all ~10us

    # interval 2: a stall — few, huge observations. The WINDOWED p50
    # must reflect only this interval, not the 100 fast ones before.
    for _ in range(4):
        h.observe(40_000.0)
    e2 = fr.record(2.0)
    assert e2["dt"] == 1.0
    assert "ops" not in e2["counters"]  # unchanged -> dropped
    w = e2["histograms"]["lat"]
    assert w["count"] == 4
    assert w["p50"] >= 32_768.0, w  # the stall dominates ITS window
    # cumulative snapshot would bury it: lifetime p50 is still fast
    assert m.snapshot()["histograms"]["lat"]["p50"] <= 16.0

    # idle interval: no counter moves, no new observations
    e3 = fr.record(3.0)
    assert e3["counters"] == {} and e3["histograms"] == {}

    # ring: capacity 4, oldest overwritten, history oldest-first
    for t in range(4, 9):
        c.add(1)
        fr.record(float(t))
    hist = fr.history()
    assert len(hist) == 4
    assert [e["t"] for e in hist] == [5.0, 6.0, 7.0, 8.0]
    assert fr.history(last=2)[-1]["t"] == 8.0
    # the recorder counts its own passes (CATALOG'd)
    from tigerbeetle_tpu.metrics import CATALOG

    assert m.snapshot()["counters"]["flight.records"] == 8
    assert "flight.records" in CATALOG


def test_statsd_histogram_percentiles_and_count_deltas():
    """The emitter ships histogram percentile snapshots (p50/p95/p99/max
    as gauges) plus the observation-count DELTA as a counter — and a
    histogram with no new observations since the last flush emits
    nothing (an idle server used to re-send every percentile forever)."""
    sink, port = _udp_sink()
    s = StatsD("127.0.0.1", port, prefix="tb")
    m = Metrics()
    h = m.histogram("commit_us")
    for v in (100.0, 200.0, 400.0):
        h.observe(v)
    em = StatsDEmitter(s, m)
    n = em.flush()
    lines = []
    for _ in range(n):
        lines.extend(sink.recv(4096).decode().split("\n"))
    assert "tb.commit_us.count:3|c" in lines
    for stat in ("p50", "p95", "p99", "max"):
        assert any(
            ln.startswith(f"tb.commit_us.{stat}:") and ln.endswith("|g")
            for ln in lines
        ), (stat, lines)
    # unchanged histogram -> fully suppressed (nothing else registered,
    # so the flush sends zero datagrams)
    assert em.flush() == 0
    # new observations -> the count DELTA (not the absolute) goes out
    h.observe(800.0)
    n = em.flush()
    lines = []
    for _ in range(n):
        lines.extend(sink.recv(4096).decode().split("\n"))
    assert "tb.commit_us.count:1|c" in lines
    s.close()
    sink.close()


def test_federation_metric_names_all_cataloged():
    """Every metric the settlement agent's core registers must be in
    metrics.CATALOG (the federation.* section) so [stats] and --statsd
    emit them without unknown-metric fallbacks — the same drift guard
    the cdc.*/chaos.* names have."""
    import json

    from tigerbeetle_tpu.federation.agent import SettlementCore
    from tigerbeetle_tpu.federation.topology import (
        FEDERATION_LEDGER,
        SETTLE_CODE,
        FederationTopology,
        escrow_account_id,
        origin_id,
    )
    from tigerbeetle_tpu.metrics import CATALOG, Metrics
    from tigerbeetle_tpu.types import TransferFlags

    m = Metrics()
    core = SettlementCore(FederationTopology.of(2), region=0, window=1,
                          metrics=m)
    line = json.dumps({
        "kind": "transfer", "op": 2, "ix": 0, "ts": 1002, "result": 0,
        "id": origin_id(0, 1), "debit_account_id": 7,
        "credit_account_id": escrow_account_id(0, 1), "amount": 5,
        "ledger": FEDERATION_LEDGER, "code": SETTLE_CODE,
        "flags": int(TransferFlags.pending), "user_data_128": 9,
    })
    assert core.emit_lines([line])
    # window full -> the next op is refused (registers the refusal
    # counter), then drive the staged leg through to posted
    assert not core.emit_lines([line.replace('"op": 2', '"op": 3')])
    legs = core.next_mirror_batch(1)
    core.on_mirror_replies(legs, [0])
    core.on_resolve_replies(core.next_resolve_batch(), [0])
    snap = m.snapshot()
    emitted = set(snap["counters"]) | set(snap["gauges"])
    fed = {n for n in emitted if n.startswith("federation.")}
    assert fed, "the core registered no federation.* metrics"
    missing = fed - set(CATALOG)
    assert not missing, f"federation names missing from CATALOG: {missing}"
    for name in fed:
        kind, _unit, help_ = CATALOG[name]
        assert help_, name
