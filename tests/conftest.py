"""Test env: force JAX onto a virtual 8-device CPU mesh.

The session image pins jax_platforms to the tunneled real-TPU platform at the
config level (env JAX_PLATFORMS is ignored), so this must be overridden via
jax.config after import — BEFORE any backend initialization. Real-TPU
execution is exercised by bench.py / the driver, not the unit suite
(SURVEY.md §4: deterministic in-process testing is the primary harness).
"""

import os

# -- .jax_cache size guard (the PR-10 mitigation for the rotating
# native-abort class): an ACCUMULATED persistent compilation cache
# correlates strongly with mid-run native aborts/corruption on this
# sandbox (PR 10: 1/10 full-suite completions with a ~17 MB cache vs 3/3
# after clearing). Clear it at session start once it grows past ~16 MB so
# every tier-1 run starts from the known-good cache state. Runs BEFORE
# jax import (tigerbeetle_tpu/__init__ points jax at this directory).
# TB_JAX_CACHE_GUARD=0 disables (e.g. to bisect the cache itself).
# TB_JAX_CACHE_GUARD_MB overrides the threshold (default 16 — unchanged;
# raise it to study an accumulated cache, lower it to force a clear).
_CACHE_GUARD_MAX_BYTES = int(
    float(os.environ.get("TB_JAX_CACHE_GUARD_MB", 16)) * 1024 * 1024
)
_CACHE_GUARD_TRIPPED = False

if os.environ.get("TB_JAX_CACHE_GUARD", "1") != "0":
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    if os.path.isdir(_cache_dir):
        _size = 0
        _entries = []
        for _root, _dirs, _files in os.walk(_cache_dir):
            for _f in _files:
                _p = os.path.join(_root, _f)
                try:
                    _size += os.path.getsize(_p)
                except OSError:
                    continue
                _entries.append(_p)
        if _size > _CACHE_GUARD_MAX_BYTES:
            import sys as _sys

            _CACHE_GUARD_TRIPPED = True
            for _p in _entries:
                try:
                    os.remove(_p)
                except OSError:
                    pass
            print(
                f"[conftest] cleared .jax_cache ({_size / 1e6:.1f} MB > "
                f"{_CACHE_GUARD_MAX_BYTES / 1e6:.0f} MB guard; see PR 10 "
                "native-abort mitigation)",
                file=_sys.stderr,
            )

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# -- CI tiering (VERDICT r5 weak #6): the whole suite runs in the default
# `pytest -q` — hiding the consensus/e2e surface behind an opt-in tier let
# a replica regression ship default-green. The modules below still carry
# the `nightly` marker so `pytest -m nightly` keeps selecting the heavy
# slice, but nothing deselects it by default; only `slow` (the 8190-batch
# CPU tests) stays opt-in (pytest.ini addopts).

import pytest  # noqa: E402

NIGHTLY_MODULES = {
    "test_process.py",        # real server processes over TCP
    "test_cluster.py",        # 3-replica in-process clusters
    "test_cluster_spill.py",
    "test_mesh_replica.py",   # 8-device mesh behind a replica
    "test_simulator.py",      # long-seed VOPR runs
    "test_wal_grid_repair.py",  # device-backend sim seeds (compile-bound)
    "test_dual_backend.py",   # dual-commit e2e servers
    "test_async_client.py",   # async ABI e2e servers
    "test_adversarial_replies.py",
    "test_c_abi_sequence.py",
    "test_go_client.py",
    "test_durability.py",     # kill-9 / crash-restart server cycles
    "test_fuzz.py",
    "test_production_scale.py",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.basename in NIGHTLY_MODULES:
            item.add_marker(pytest.mark.nightly)


def pytest_sessionfinish(session, exitstatus):
    # The guard runs before jax import, so the cost of a clear — every
    # kernel recompiled from scratch — can only be counted at session
    # end, via the compile sentinel (models/ledger.py). A tripped guard
    # followed by a big compile count IS the PR-10 pathology made
    # visible; a tripped guard with few compiles means the suite slice
    # barely touched the device stack.
    if not _CACHE_GUARD_TRIPPED:
        return
    import sys as _sys

    _mod = _sys.modules.get("tigerbeetle_tpu.models.ledger")
    if _mod is None:
        return
    _snap = _mod.COMPILE_SENTINEL.snapshot()
    print(
        f"\n[conftest] cache guard tripped this session: "
        f"{_snap['total']} fresh compile(s) observed by the sentinel "
        f"({', '.join(f'{k}x{v}' for k, v in sorted(_snap['per_fn'].items())) or 'none'})",
        file=_sys.stderr,
    )
