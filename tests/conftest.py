"""Test env: force JAX onto a virtual 8-device CPU mesh.

The session image pins jax_platforms to the tunneled real-TPU platform at the
config level (env JAX_PLATFORMS is ignored), so this must be overridden via
jax.config after import — BEFORE any backend initialization. Real-TPU
execution is exercised by bench.py / the driver, not the unit suite
(SURVEY.md §4: deterministic in-process testing is the primary harness).
"""

import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
