"""Test env: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import (hence top-level in conftest). Real-TPU
execution is exercised by bench.py / the driver, not the unit suite
(SURVEY.md §4: deterministic in-process testing is the primary harness).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
