"""Binding generation: one schema source of truth (types.py) emits every
language's types (reference: build.zig:687-924 generated bindings).
The committed files must match regeneration exactly, and the schema must
cover the full wire surface."""

import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_bindings_in_sync():
    r = subprocess.run(
        [sys.executable, "scripts/bindgen.py", "--check"],
        cwd=ROOT, capture_output=True, text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr


def test_schema_covers_wire_surface():
    sys.path.insert(0, str(ROOT / "scripts"))
    import bindgen

    from tigerbeetle_tpu import types

    sizes = {"u128": 16, "u64": 8, "u32": 4, "u16": 2}
    for name in ("Account", "Transfer"):
        assert sum(sizes[k] for _, k in bindgen.SCHEMA[name]) == 128, name
    assert sum(sizes[k] for _, k in bindgen.SCHEMA["CreateAccountsResult"]) == 8
    assert len(bindgen.ENUMS["CreateAccountResult"]) == len(
        types.CreateAccountResult
    )
    assert len(bindgen.ENUMS["CreateTransferResult"]) == len(
        types.CreateTransferResult
    )
    # every generated file carries every result-code name
    go = (ROOT / "clients/go/types.go").read_text()
    ts = (ROOT / "clients/node/types.ts").read_text()
    for m in types.CreateTransferResult:
        assert str(m.value) in go
        assert m.name in ts
