"""Load/latency frontier sweep (benchmark.run_frontier) — tier-1 smoke.

A tiny two-step ladder against a real spawned server (native backend,
CPU-pinned): every step must carry offered/achieved tps, p50/p95/p99,
the typed-shed rate, and a dominant-leg attribution sourced from the
server's per-request latency anatomy over the wire — and the slowest
sampled request's breakdown must ACCOUNT for its end-to-end latency
(legs are consecutive stamp intervals; the acceptance bound is 20%).
The full ladder (`--backend dual`, 4+ steps) runs in bench.py's
frontier segment / scripts/frontier.py.
"""

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu.latency import LEGS


def test_frontier_smoke_two_steps():
    from tigerbeetle_tpu.benchmark import run_frontier

    out = run_frontier(
        steps=(2_000, 6_000),
        step_s=1.5,
        batch=256,
        sessions=8,
        conns=2,
        n_accounts=64,
        backend="native",
        jax_platform="cpu",
    )
    steps = out["steps"]
    assert len(steps) == 2
    for s in steps:
        assert s["offered_tps"] in (2_000, 6_000)
        assert s["achieved_tps"] > 0
        assert s["acked_events_in_window"] > 0
        assert s["p50_ms"] > 0 and s["p99_ms"] >= s["p50_ms"]
        assert s["failures"] == 0
        assert 0.0 <= s["shed_rate"] <= 1.0
        assert s["dominant_leg"] in LEGS
        assert 0.0 < s["dominant_leg_share"] <= 1.0
    assert out["peak_achieved_tps"] >= steps[0]["achieved_tps"]
    # the decomposition accounts for the slowest request's time: legs
    # are consecutive intervals, so their sum must be within 20% of the
    # measured e2e (in practice it is exact minus rounding)
    b = out["breakdown"]
    assert b is not None, "no sampled breakdown from the live server"
    assert b["e2e_us"] > 0
    assert abs(b["accounted_ratio"] - 1.0) <= 0.2, b
    assert b["dominant"] in b["legs"]
    assert set(b["legs"]) <= set(LEGS)
