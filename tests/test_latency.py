"""Per-request critical-path latency attribution (tigerbeetle_tpu/latency.py).

Contracts under test:

- legs are CONSECUTIVE stamp intervals, so a finished record's legs sum
  to its end-to-end latency exactly (the decomposition accounts for all
  of the time);
- sampling: one request in `sample_every` opens a record, the rest pay
  only the countdown — and the amortized no-op-backend cost of the full
  stamp sequence stays under the 1us/request budget at the default rate;
- the stamps ride the DETERMINISTIC time seam: a seeded simulator run
  with stamping forced on commits a byte-identical history AND folds
  identical latency histograms across runs;
- a real in-process pipeline (Simulator, crashes off) produces slowest-
  request breakdowns whose legs account for the measured e2e;
- eviction/discard never leak open records.
"""

import time

import tests.conftest  # noqa: F401 — CPU platform before jax init
from tigerbeetle_tpu.latency import (
    LEG_DISPATCH,
    LEG_FINALIZE,
    LEG_FUSE,
    LEG_QUORUM,
    LEG_WAIT,
    LEG_WAL,
    LEGS,
    LatencyAnatomy,
    dominant_leg,
    leg_totals,
)
from tigerbeetle_tpu.metrics import CATALOG, NULL_METRICS, Metrics

_ALL_LEGS = (LEG_WAL, LEG_QUORUM, LEG_FUSE, LEG_DISPATCH, LEG_WAIT,
             LEG_FINALIZE)


class _FakeClock:
    """Deterministic ns clock: each read advances by the next scripted
    delta (cycled)."""

    def __init__(self, deltas=(1000,)):
        self.t = 0
        self.deltas = list(deltas)
        self.i = 0

    def __call__(self):
        self.t += self.deltas[self.i % len(self.deltas)]
        self.i += 1
        return self.t


def _run_one(anatomy: LatencyAnatomy, tid: int) -> int:
    """Drive one request through the full stamp protocol; returns the
    token (0 if unsampled)."""
    anatomy.arrive()
    tok = anatomy.open(tid) if anatomy.want() else 0
    if tok:
        for leg in _ALL_LEGS:
            anatomy.stamp(tok, leg)
        anatomy.egress(tok, client=tid, context=tid * 7)
    return tok


def test_legs_partition_e2e_exactly():
    m = Metrics()
    a = LatencyAnatomy(metrics=m, clock=_FakeClock([1000, 3000, 500]),
                       sample_every=1)
    assert _run_one(a, 0xABC)
    rec = a.slowest()[0]
    assert rec["trace"] == f"{0xABC:016x}"
    assert abs(sum(rec["legs"].values()) - rec["e2e_us"]) < 1e-6, rec
    assert rec["dominant"] in rec["legs"]
    snap = m.snapshot()
    assert snap["counters"]["latency.samples"] == 1
    assert snap["histograms"]["latency.e2e_us"]["count"] == 1
    # per-leg histograms observed exactly once each
    for leg in LEGS:
        h = snap["histograms"][f"latency.{leg}_us"]
        assert h["count"] == 1, leg


def test_every_leg_and_lane_is_cataloged():
    for leg in LEGS:
        assert f"latency.{leg}_us" in CATALOG, leg
    for name in ("latency.e2e_us", "latency.samples", "latency.dropped",
                 "latency.device_apply_lag_us", "latency.wal_lane_us",
                 "flight.records"):
        assert name in CATALOG, name


def test_sampling_takes_one_in_n():
    a = LatencyAnatomy(metrics=NULL_METRICS, clock=_FakeClock(),
                       sample_every=4)
    sampled = sum(1 for i in range(100) if _run_one(a, 1000 + i))
    assert sampled == 25
    # 0 disables entirely
    off = LatencyAnatomy(metrics=NULL_METRICS, clock=_FakeClock(),
                         sample_every=0)
    assert sum(1 for i in range(50) if _run_one(off, i + 1)) == 0
    # ... including when turned off at RUNTIME with `_take` still armed
    # from construction (the --latency-sample-every 0 server path)
    late_off = LatencyAnatomy(metrics=NULL_METRICS, clock=_FakeClock())
    late_off.sample_every = 0
    assert sum(1 for i in range(50) if _run_one(late_off, i + 1)) == 0


def test_capacity_eviction_never_leaks_open_records():
    a = LatencyAnatomy(metrics=NULL_METRICS, clock=_FakeClock(),
                       sample_every=1, capacity=8)
    for i in range(100):  # open without ever finishing
        if a.want():
            a.open(1 + i)
    assert len(a._recs) <= 8
    # discard is a no-op for unknown/zero tokens
    a.discard(0)
    a.discard(None)
    a.discard(123456)


def test_deferred_egress_parks_and_finishes_by_reply_key():
    m = Metrics()
    a = LatencyAnatomy(metrics=m, clock=_FakeClock(), sample_every=1)
    a.defer_egress = True
    assert a.want()
    tok = a.open(77)
    a.stamp(tok, LEG_FINALIZE)
    a.egress(tok, client=0xC1, context=0xBEEF)
    assert a.pending_egress[(0xC1, 0xBEEF)] == tok
    assert m.snapshot()["counters"].get("latency.samples", 0) == 0
    # the bus pops the key and finishes at flush
    got = a.pending_egress.pop((0xC1, 0xBEEF))
    a.finish(got)
    assert m.snapshot()["counters"]["latency.samples"] == 1


def test_stale_gateway_arrival_is_discarded():
    clk = _FakeClock([0])  # manual control below
    a = LatencyAnatomy(metrics=NULL_METRICS, clock=lambda: clk.t,
                       sample_every=1)
    clk.t = 1_000
    a.arrive()
    clk.t = 1_000 + 200_000_000  # 200ms later: the arrival is stale
    assert a.want()
    tok = a.open(5)
    assert a._recs[tok][0] == clk.t  # fresh clock, not the stale arrival
    a.finish(tok)
    # a FRESH arrival is used as t0
    clk.t += 1_000
    a.arrive()
    clk.t += 50_000  # 50us of admission work
    tok = a.open(6)
    assert a._recs[tok][0] == clk.t - 50_000


def test_dominant_leg_delta_math():
    before = {"commit_finalize": {"count": 10, "total_us": 1000.0}}
    after = {
        "commit_finalize": {"count": 20, "total_us": 5000.0},
        "wal_write": {"count": 20, "total_us": 1000.0},
    }
    leg, share = dominant_leg(before, after)
    assert leg == "commit_finalize"
    assert share == 0.8
    assert dominant_leg({}, {}) == (None, 0.0)
    # leg_totals extracts count * mean from a registry snapshot shape
    snap = {"histograms": {
        "latency.wal_write_us": {"count": 4, "mean": 2.5},
        "latency.e2e_us": {"count": 4, "mean": 10.0},  # not a leg
    }}
    t = leg_totals(snap)
    assert t == {"wal_write": {"count": 4, "total_us": 10.0}}


def test_stamp_budget_under_1us_per_request_noop_backend():
    """The ISSUE's budget: amortized per-request stamping cost < 1us
    with the no-op metrics backend at the DEFAULT sampling rate. Best
    of 5 passes so a scheduler hiccup on a loaded CI core cannot flake
    the bound (the true cost is ~0.7us on this class of machine)."""
    from tigerbeetle_tpu.vsr.header import Command, Header

    req = Header(command=int(Command.request), client=0xABC, request=7)
    req.set_checksum_body(b"x" * 128)
    req.set_checksum()
    a = LatencyAnatomy(metrics=NULL_METRICS)  # default sample_every
    assert a.sample_every == 16

    def one_request():
        a.arrive()
        tok = a.open(req.trace()) if a.want() else 0
        if tok:
            for leg in _ALL_LEGS:
                a.stamp(tok, leg)
            a.egress(tok, 0xABC, 123)

    n = 20_000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter_ns()
        for _i in range(n):
            one_request()
        best = min(best, (time.perf_counter_ns() - t0) / n)
    assert best < 1000.0, f"amortized stamp cost {best:.0f}ns >= 1us"


def test_simulator_determinism_with_stamping_enabled():
    """Same seed, stamping forced on EVERY request: byte-identical
    committed histories AND identical latency histogram folds across
    runs (the stamps ride the DeterministicTime seam, so they are part
    of the reproducible surface, not noise on top of it)."""
    from tigerbeetle_tpu.testing.simulator import Simulator

    def run():
        sim = Simulator(11, ticks=400, latency_sample_every=1)
        sim.run()
        hists = [sorted(h.items()) for h in sim.histories]
        lat = {
            k: v
            for k, v in sim.replicas[0].metrics.snapshot()[
                "histograms"
            ].items()
            if k.startswith("latency.")
        }
        return hists, lat

    h1, l1 = run()
    h2, l2 = run()
    assert h1 == h2, "stamping perturbed the committed history"
    assert l1 == l2, "latency folds diverged across identical runs"


def test_pipeline_breakdown_accounts_for_e2e():
    """A real in-process consensus pipeline (3 replicas, oracle
    backend, crashes off) folds sampled requests whose slowest-request
    breakdowns account for the measured end-to-end latency — the same
    invariant the live frontier asserts over TCP."""
    from tigerbeetle_tpu.testing.simulator import Simulator

    sim = Simulator(3, ticks=400, crash_probability=0.0,
                    latency_sample_every=1)
    sim.run()
    primary = next(r for r in sim.replicas if r.is_primary)
    snap = primary.metrics.snapshot()
    assert snap["counters"]["latency.samples"] > 0
    recs = primary.latency.slowest()
    assert recs, "no breakdown records on the primary"
    for rec in recs:
        total = sum(rec["legs"].values())
        assert abs(total - rec["e2e_us"]) <= max(0.02, 0.2 * rec["e2e_us"]), rec
        assert rec["dominant"] in rec["legs"]
    # quorum_wait must appear for a 3-replica quorum (acks cross ticks)
    assert snap["histograms"]["latency.quorum_wait_us"]["count"] > 0
